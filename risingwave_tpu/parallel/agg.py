"""Vnode-sharded grouped aggregation over a device mesh.

Reference parity: N parallel HashAggExecutor actors fed by a HASH
dispatcher (SURVEY §2.12 data parallelism; hash_agg.rs:67 +
dispatch.rs:582). TPU re-design: ONE SPMD program under ``shard_map`` —
each mesh shard owns a contiguous vnode range (VnodeMapping semantics)
and a private slice of the hash-table/accumulator arrays; rows hop to
their owner via the bucketized all_to_all (parallel/exchange.py) and are
then aggregated with the exact same kernel math as the single-chip path
(ops/hash_agg._update_call — one code path, two launch shapes).

State is the single-chip ``AggState`` with a leading [n_dev] axis,
sharded ``P('d')``. The barrier flush gathers per-shard dirty slots the
same way the single-chip kernel does; shards never share groups because
ownership is a function of the key hash.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from risingwave_tpu.common.chunk import next_pow2
from risingwave_tpu.common.hash import VNODE_COUNT
from risingwave_tpu.ops import hash_table as ht
from risingwave_tpu.ops import lanes
from risingwave_tpu.ops.hash_agg import (
    AggSpec, AggState, FlushResult, _call_slices, _rebuild_live,
    _update_call, advance_state, decode_flush_data, decode_outputs,
    dev_layout, encode_host_accs, gather_packed, make_agg_state,
    n_input_lanes, pack_chunk, packed_layout, retire_state,
)
from risingwave_tpu.parallel.exchange import (
    bucketize_by_owner, exchange, owners_host, skew_bucket,
    vnodes_from_lanes,
)
from risingwave_tpu.utils import jaxtools, spans
from risingwave_tpu.utils.ledger import LEDGER

AXIS = "d"

# Compiled SPMD programs shared ACROSS kernel instances (fresh
# sessions, twin MVs and bench re-runs reuse traces instead of paying
# warmup compiles on the p99 tail — the join's _STEP_CACHE scheme).
# Keyed by (mesh device ids, program kind + statics, key_width,
# specs); jit shape-keys per state capacity internally. A CompileCache
# (stream/costs.py) so hits/misses bill the pulling MV.
from risingwave_tpu.stream.costs import CompileCache as _CompileCache

_PROG_CACHE: Dict[tuple, object] = _CompileCache("agg_prog")


def _note_dispatch(rows: float) -> None:
    """Real-SPMD-dispatch accounting at the jit sites (the sharded agg
    counts its own launches — one per backlog flush / barrier gather —
    so the executor layer must not also count per-chunk requests;
    exactly one site counts each dispatch and the registry totals
    stay launch-for-launch honest)."""
    from risingwave_tpu.utils.metrics import STREAMING
    STREAMING.device_dispatch.inc(1, kernel="sharded_agg")
    STREAMING.rows_per_dispatch.observe(float(rows),
                                        kernel="sharded_agg")


class _ShardedCounters:
    """Per-shard sync-free occupancy accounting + deferred overflow.

    The vector twin of jaxtools.PendingCounters: each SPMD apply returns
    int32[n_dev] insert counts and a bucket-overflow flag; both ride the
    async DMA and are folded in when they land, so the hot path never
    blocks on the tunnel. Overflow raises when observed (barrier at the
    latest) — the barrier rolls back, same contract as the reference's
    error channel.
    """

    def __init__(self, n_dev: int):
        self._count = np.zeros(n_dev, dtype=np.int64)
        self._pending: List[tuple] = []   # (ins[n_dev], overflow, rows)
        self._rows = 0

    def push(self, ins, overflow, n_rows: int) -> None:
        jaxtools.start_fetch(ins, overflow)
        self._pending.append((ins, overflow, n_rows))
        self._rows += n_rows

    def _fold(self, ins, overflow, n_rows: int) -> None:
        if bool(np.asarray(overflow).any()):
            raise RuntimeError(
                "bucket overflow: routed rows dropped — raise `bucket`")
        self._count += np.asarray(ins, dtype=np.int64)
        self._rows -= n_rows

    def drain_ready(self) -> None:
        while self._pending and self._pending[0][0].is_ready() \
                and self._pending[0][1].is_ready():
            self._fold(*self._pending.pop(0))

    def drain_all(self) -> None:
        pending, self._pending = self._pending, []
        for entry in pending:
            jaxtools.fetch(entry[0], entry[1])
            self._fold(*entry)

    def bound(self) -> int:
        """Upper bound on the FULLEST shard's occupancy: every pending
        row could in principle route to one shard."""
        return int(self._count.max(initial=0)) + self._rows

    def worst_exact(self) -> int:
        return int(self._count.max(initial=0))

    def reset(self, per_shard_counts: np.ndarray) -> None:
        self._count = np.asarray(per_shard_counts, dtype=np.int64)
        self._pending = []
        self._rows = 0


def _pad_rows(a: np.ndarray, m: int) -> np.ndarray:
    """Zero/False-pad the leading axis to m rows (pad rows are routed
    nowhere: the caller pads `vis` with False)."""
    out = np.zeros((m,) + a.shape[1:], dtype=a.dtype)
    out[:a.shape[0]] = a
    return out


def _stack_state(n_dev: int, capacity: int, key_width: int,
                 specs: Sequence[AggSpec]) -> AggState:
    """AggState with a leading device axis on every leaf."""
    one = make_agg_state(capacity, key_width, specs)
    return jax.tree.map(
        lambda a: jnp.broadcast_to(a[None], (n_dev,) + a.shape), one)


class ShardedAggKernel:
    """Multi-chip grouped aggregation (fixed capacity v1 — growth and
    elastic resharding land with the reschedule path).

    apply(): one jitted SPMD step — vnode routing, all_to_all, local
    probe+scatter per shard. snapshot(): host-side decode of all live
    groups (test/flush support).
    """

    # one inc per shard_map launch, at the launch (metrics contract
    # shared with the fused kernels): the executor layer checks this
    # and skips its per-chunk request counting
    counts_own_dispatches = True

    # epoch batch bound, mirroring GroupedAggKernel.BATCH_ROWS: the
    # backlog dispatches at this many rows mid-epoch (bounds host
    # buffering and the int32 limb math), else once at the barrier
    # flush — O(1) SPMD dispatches per epoch instead of one per chunk
    # (each shard_map host dispatch costs ~100ms through the 4-virtual-
    # device CPU mesh, BENCH_r09's whole ad-ctr tail). The FIXED batch
    # shape also means one compiled program instead of per-chunk-shape
    # churn — the RecompileGuard's sharded contract.
    BATCH_ROWS = 1 << 15

    def __init__(self, mesh: Mesh, key_width: int,
                 specs: Sequence[AggSpec], capacity: int = 1 << 12,
                 bucket: Optional[int] = None,
                 flush_capacity: int = 1 << 10,
                 epoch_batch: bool = True):
        self.mesh = mesh
        self.n_dev = mesh.devices.size
        self.specs = tuple(specs)
        self.key_width = key_width
        self.capacity = capacity
        self.bucket = bucket
        # epoch_batch=False is the per-chunk oracle arm (one SPMD
        # dispatch per apply — the pre-ISSUE-10 behavior)
        self.epoch_batch = bool(epoch_batch)
        self._backlog: List[np.ndarray] = []
        self._backlog_owners: List[Optional[np.ndarray]] = []
        self._backlog_rows = 0
        self._backlog_vis = 0
        self._stage_pending: List = []
        # fused-fragment mode (ops/fused.py build_agg_prelude): set via
        # set_prelude BEFORE any data; the absorbed filter/project run
        # traces ahead of the vnode routing inside the same SPMD step
        self._prelude = None
        self._raw_width: Optional[int] = None
        self.metrics_label: Optional[str] = None
        self._span_label = "ShardedAggKernel"
        self._touched = False
        # vnode → owning shard: contiguous even split (VnodeMapping)
        owners = np.repeat(np.arange(self.n_dev, dtype=np.int32),
                           VNODE_COUNT // self.n_dev)
        pad = VNODE_COUNT - len(owners)
        if pad:
            owners = np.concatenate(
                [owners, np.full(pad, self.n_dev - 1, np.int32)])
        self.owner_map = jnp.asarray(owners)
        self._owner_map_host = owners
        sharding = NamedSharding(mesh, P(AXIS))
        self.state: AggState = jax.tree.map(
            lambda a: jax.device_put(a, sharding),
            _stack_state(self.n_dev, capacity, key_width, self.specs))
        self._step_cache: Dict[Tuple[int, int], object] = {}
        self._fills = tuple(f for _dt, f in dev_layout(self.specs))
        self._flush_cap = next_pow2(flush_capacity)
        self._flush_idx: Optional[List[np.ndarray]] = None
        self._counters = _ShardedCounters(self.n_dev)
        self._state_spec = jax.tree.map(lambda _: P(AXIS), self.state)
        self._advance_jit = self._shardwise(advance_state, donate=True,
                                            cache_key=("advance",))
        self._retire_jit = None        # built lazily (lane_off static)
        self._patch_step = None        # built lazily (col count static)
        self._gather_cache: Dict[int, object] = {}

    def _prog_key(self, *parts) -> tuple:
        return (tuple(int(d.id) for d in self.mesh.devices.flat),
                self.key_width, self.specs) + parts

    def _shardwise(self, fn, donate: bool, out_spec=None,
                   extra_specs=(), cache_key=None):
        """Wrap a single-chip traced state transform in shard_map: each
        shard applies `fn` to its slice (leading axis dropped/restored).
        The single-chip and sharded kernels literally share programs.
        ``cache_key`` (structural statics) shares the COMPILED program
        across kernel instances via the module cache."""
        key = None
        if cache_key is not None:
            key = self._prog_key(*cache_key)
            step = _PROG_CACHE.get(key)
            if step is not None:
                return step

        def local(state, *args):
            state = jax.tree.map(lambda a: a[0], state)
            out = fn(state, *args)
            return jax.tree.map(lambda a: a[None], out)

        mapped = jaxtools.shard_map(
            local, mesh=self.mesh,
            in_specs=(self._state_spec,) + tuple(extra_specs),
            out_specs=out_spec if out_spec is not None
            else self._state_spec,
            check_vma=False)
        step = jaxtools.instrumented_jit(
            mapped, "parallel_agg.sharded",
            donate_argnums=(0,) if donate else ())
        if key is not None:
            _PROG_CACHE[key] = step
        return step

    # -- fused-fragment prelude (ops/fused.py) ----------------------------
    @property
    def supports_prelude(self) -> bool:
        """Fusion eligibility hook (opt/fusion.agg_ineligible_reason):
        the sharded apply traces an absorbed filter/project run BEFORE
        vnode routing inside the same SPMD step — but only a kernel
        that has not yet seen data can adopt one."""
        return not self._touched

    def set_prelude(self, prelude, raw_width: int,
                    metrics_label: Optional[str] = None,
                    prelude_key: Optional[str] = None) -> None:
        """Install the fused-input prelude (build_agg_prelude). Must
        run before any data touches the kernel — the raw codec changes
        the upload layout. ``prelude_key`` is the run's STRUCTURAL
        identity (FusedStages.trace_key): equal runs share compiled
        steps across kernel instances and sessions."""
        assert not self._touched, "set_prelude after data flowed"
        self._prelude = prelude
        self._raw_width = int(raw_width)
        self._prelude_key = prelude_key or f"id:{id(prelude)}"
        self.metrics_label = metrics_label
        if metrics_label:
            self._span_label = metrics_label

    # -- the SPMD step ----------------------------------------------------
    # The step consumes the single-chip PACKED chunk matrix
    # (ops/hash_agg.pack_chunk: keys | sign | vis | per call lanes +
    # valid) — ONE routed payload through the all_to_all instead of a
    # flat array per lane, and the same host codec as the single-chip
    # kernel (no drifting twin). With a prelude, the upload is the RAW
    # int64 matrix and the absorbed run traces ahead of the routing.
    def _build_packed_step(self, bucket: int):
        specs = self.specs
        slices = _call_slices(specs)
        call_cols = packed_layout(self.key_width, specs)
        n_dev = self.n_dev
        kw = self.key_width

        def local_step(state: AggState, packed, owner_map):
            # shard_map hands each shard a [1, ...] block: drop the axis
            state = jax.tree.map(lambda a: a[0], state)
            key_lanes = packed[:, :kw]
            vis = packed[:, kw + 1].astype(bool)
            vn = vnodes_from_lanes(key_lanes)
            owner = owner_map[vn]
            buckets, bvalid, overflow = bucketize_by_owner(
                owner, vis, [packed], n_dev, bucket)
            recv, rvalid = exchange(buckets, bvalid, AXIS)
            m = n_dev * bucket
            rp = recv[0].reshape(m, packed.shape[1])
            rvis = rvalid.reshape(m)
            rkeys = rp[:, :kw]
            table, slots, ins = ht.probe_insert(state.table, rkeys,
                                                rvis)
            cap = state.table.capacity
            scat = jnp.where(rvis, slots, cap)
            s32 = rp[:, kw]
            group_rows = state.group_rows.at[scat].add(s32, mode="drop")
            dirty = state.dirty.at[scat].set(True, mode="drop")
            accs = list(state.accs)
            for spec, sl, (lc, vc) in zip(specs, slices, call_cols):
                if spec.is_float_sum:
                    in_lanes = tuple(jax.lax.bitcast_convert_type(
                        rp[:, i], jnp.float32) for i in lc)
                else:
                    in_lanes = tuple(rp[:, i] for i in lc)
                val_ok = jnp.ones(m, dtype=bool) if vc is None \
                    else rp[:, vc].astype(bool)
                _update_call(spec, accs, sl, in_lanes, val_ok, slots,
                             rvis, s32, cap)
            new = AggState(table, group_rows, dirty, tuple(accs),
                           state.emitted_valid, state.emitted_rows,
                           state.emitted_accs)
            new = jax.tree.map(lambda a: a[None], new)
            return new, ins[None], overflow[None]

        state_spec = jax.tree.map(lambda _: P(AXIS), self.state)
        mapped = jaxtools.shard_map(
            local_step, mesh=self.mesh,
            in_specs=(state_spec, P(AXIS), P()),
            out_specs=(state_spec, P(AXIS), P(AXIS)),
            check_vma=False)
        return jaxtools.instrumented_jit(
            mapped, "parallel_agg.step", donate_argnums=(0,))

    def _build_raw_step(self, bucket: int):
        """The prelude (fused) twin: raw int64 rows → the absorbed
        filter/project run → key/lane encode — all traced BEFORE the
        vnode routing, per shard, in the same SPMD step (ISSUE 10:
        `fusion_grouping` stops refusing mesh plans)."""
        specs = self.specs
        slices = _call_slices(specs)
        n_dev = self.n_dev
        prelude = self._prelude

        def local_step(state: AggState, raw, owner_map):
            state = jax.tree.map(lambda a: a[0], state)
            key_lanes, s32, vis, call_inputs, stage_rows = prelude(raw)
            local_n = key_lanes.shape[0]
            vn = vnodes_from_lanes(key_lanes)
            owner = owner_map[vn]
            payloads = [key_lanes, s32.astype(jnp.int32)]
            for spec, (in_lanes, val_ok) in zip(specs, call_inputs):
                payloads.extend(in_lanes)
                payloads.append(
                    jnp.ones(local_n, dtype=bool) if val_ok is None
                    else val_ok)
            buckets, bvalid, overflow = bucketize_by_owner(
                owner, vis, payloads, n_dev, bucket)
            recv, rvalid = exchange(buckets, bvalid, AXIS)
            m = n_dev * bucket
            rkeys = recv[0].reshape(m, key_lanes.shape[1])
            rsigns = recv[1].reshape(m)
            rflat = [r.reshape(m) for r in recv[2:]]
            rvis = rvalid.reshape(m)
            table, slots, ins = ht.probe_insert(state.table, rkeys,
                                                rvis)
            cap = state.table.capacity
            scat = jnp.where(rvis, slots, cap)
            group_rows = state.group_rows.at[scat].add(rsigns,
                                                       mode="drop")
            dirty = state.dirty.at[scat].set(True, mode="drop")
            accs = list(state.accs)
            k = 0
            for spec, sl in zip(specs, slices):
                n_in = n_input_lanes(spec)
                in_lanes = tuple(rflat[k:k + n_in])
                val_ok = rflat[k + n_in]
                k += n_in + 1
                _update_call(spec, accs, sl, in_lanes, val_ok, slots,
                             rvis, rsigns, cap)
            new = AggState(table, group_rows, dirty, tuple(accs),
                           state.emitted_valid, state.emitted_rows,
                           state.emitted_accs)
            new = jax.tree.map(lambda a: a[None], new)
            return (new, ins[None], overflow[None],
                    stage_rows[None])

        state_spec = jax.tree.map(lambda _: P(AXIS), self.state)
        mapped = jaxtools.shard_map(
            local_step, mesh=self.mesh,
            in_specs=(state_spec, P(AXIS), P()),
            out_specs=(state_spec, P(AXIS), P(AXIS), P(AXIS)),
            check_vma=False)
        return jaxtools.instrumented_jit(
            mapped, "parallel_agg.step_fused", donate_argnums=(0,))

    def apply(self, key_lanes: np.ndarray, signs: np.ndarray,
              vis: np.ndarray,
              inputs: Sequence[Tuple[Sequence[np.ndarray], np.ndarray]]
              ) -> None:
        """Buffer one host chunk for the epoch's SPMD step.

        ISSUE 10: chunks accumulate host-side (the single-chip packed
        codec) and the whole epoch ships as ONE routed SPMD dispatch at
        the barrier flush (or per BATCH_ROWS slab mid-epoch) — signs
        and visibility ride the packed aux columns, and the adds
        commute across the epoch fold (limb/count adds exactly;
        MIN/MAX idempotently), so the batched application equals the
        per-chunk one. `inputs` is per call (value lanes, valid mask).
        With epoch_batch=False every apply dispatches immediately (the
        per-chunk oracle arm).
        """
        assert self._prelude is None, \
            "fused kernel takes raw chunks (apply_raw)"
        self._touched = True
        with LEDGER.phase("host_pack", kernel=self._span_label):
            packed = pack_chunk(self.key_width, self.specs,
                                np.asarray(key_lanes),
                                np.asarray(signs),
                                np.asarray(vis), inputs)
        n = packed.shape[0]
        if self._backlog_rows + n > self.BATCH_ROWS:
            self._dispatch_backlog()
        self._backlog.append(packed)
        self._backlog_rows += n
        # growth decisions run per buffered chunk (pessimistic bound
        # over the whole backlog): the rehash happens off the dispatch
        # path, and a table sized for its stream never re-checks
        self._reserve(self._backlog_rows)
        if not self.epoch_batch or \
                self._backlog_rows >= self.BATCH_ROWS:
            self._dispatch_backlog()

    def owners_of(self, key_lanes: np.ndarray) -> np.ndarray:
        """Host twin of the device vnode routing (the executor feeds
        per-row owners back for the skew-exact bucket on the fused
        path, where the trace alone holds the derived lanes) — the
        shared exchange helper, one copy with the join kernel."""
        return owners_host(key_lanes, self._owner_map_host)

    def apply_raw(self, raw: np.ndarray, n_visible: int,
                  owners: Optional[np.ndarray] = None) -> None:
        """Fused-fragment hot path: backlog one RAW int64 chunk matrix
        (ops/fused.encode_raw_chunk) plus an always-invisible separator
        row — the traced chain's shifted compares must never marry rows
        across chunk boundaries (the separator-row codec of
        ops/fused.py, reused as the epoch buffer's chunk-boundary aux
        marker). ``owners`` (host-derived when the group keys map to
        raw columns) rides along for the skew-exact routing bucket —
        a PRE-filter superset of the routed rows, so the bound stays
        safe when the traced filter drops rows."""
        assert self._prelude is not None, \
            "apply_raw needs a fused (set_prelude) kernel"
        self._touched = True
        n = raw.shape[0] + 1
        if self._backlog_rows + n > self.BATCH_ROWS:
            self._dispatch_backlog()
        self._backlog.append(raw)
        self._backlog.append(np.zeros((1, raw.shape[1]),
                                      dtype=np.int64))   # separator
        if owners is not None:
            ow = np.full(n, -1, dtype=np.int64)
            vis = raw[:, 1] != 0
            ow[:n - 1][vis] = np.asarray(owners)[vis]
            self._backlog_owners.append(ow)
        else:
            self._backlog_owners.append(None)
        self._backlog_rows += n
        self._backlog_vis += int(n_visible)
        self._reserve(self._backlog_rows)
        if not self.epoch_batch or \
                self._backlog_rows >= self.BATCH_ROWS:
            self._dispatch_backlog()

    def _dispatch_backlog(self) -> None:
        """Ship the buffered epoch rows as ONE SPMD dispatch: pad to
        the fixed batch shape (one compiled program; pad rows are
        invisible and route nowhere), route every row to its vnode
        owner, apply locally."""
        if not self._backlog:
            return
        mats, n = self._backlog, self._backlog_rows
        n_vis = self._backlog_vis
        owner_chunks = self._backlog_owners
        self._backlog, self._backlog_rows = [], 0
        self._backlog_owners = []
        self._backlog_vis = 0
        raw_mode = self._prelude is not None
        # per-shard post-exchange batch is n_dev*bucket rows in ONE
        # traced step; limb sums stay exact past MAX_CHUNK_ROWS
        # because _update_call slices the batch and carry-normalizes
        # per slab (the single-chip 32K backlog rides the same path)
        self._reserve(n)
        # epoch staging + routing-bucket computation is host_pack (the
        # ledger's phase taxonomy); the sharded upload below is h2d
        with LEDGER.phase("host_pack", kernel=self._span_label):
            # pow2-bucketed batch shape (the join epoch path's
            # convention): steady-state epochs repeat a handful of
            # shapes — the RecompileGuard's sharded contract — without
            # padding every small epoch to the full 32K slab
            cap_rows = max(next_pow2(n), self.n_dev)
            if cap_rows % self.n_dev:
                cap_rows += self.n_dev - (cap_rows % self.n_dev)
            w = mats[0].shape[1]
            packed = np.zeros((cap_rows, w),
                              dtype=np.int64 if raw_mode else np.int32)
            at = 0                   # pad rows: vis=0
            for m_ in mats:
                packed[at:at + m_.shape[0]] = m_
                at += m_.shape[0]
            local = cap_rows // self.n_dev
            bucket = self.bucket or local
            if raw_mode and self.bucket is None and owner_chunks and \
                    all(o is not None for o in owner_chunks):
                ow = np.full(cap_rows, -1, dtype=np.int64)
                ow[:n] = np.concatenate(owner_chunks)
                bucket = skew_bucket(ow, ow >= 0, self.n_dev, local)
            if not raw_mode and self.bucket is None:
                # skew-exact routing bucket (the join's stage_epoch
                # scheme): the default (= local rows) makes every shard
                # process the WHOLE batch post-exchange — n_dev× the
                # single-chip compute; exact per-(sender, target)
                # counts from the host key lanes collapse it to the
                # real skew, pow2-quantized for shape stability. The
                # fused raw path keeps the worst case (its lanes only
                # exist in-trace).
                kw_ = self.key_width
                vis_col = packed[:, kw_ + 1] != 0
                owner = owners_host(packed[:, :kw_],
                                    self._owner_map_host)
                bucket = skew_bucket(owner, vis_col, self.n_dev, local)
        key = (cap_rows, bucket, raw_mode)
        step = self._step_cache.get(key)
        if step is None:
            if raw_mode:
                # structural prelude key (set_prelude): equal fused
                # runs share the compiled step across instances
                mkey = self._prog_key("step_fused", bucket,
                                      self._prelude_key)
                step = _PROG_CACHE.get(mkey)
                if step is None:
                    step = self._build_raw_step(bucket)
                    _PROG_CACHE[mkey] = step
            else:
                mkey = self._prog_key("step", bucket)
                step = _PROG_CACHE.get(mkey)
                if step is None:
                    step = self._build_packed_step(bucket)
                    _PROG_CACHE[mkey] = step
            self._step_cache[key] = step
        from risingwave_tpu.utils.ledger import note_backlog
        # same kernel label as the phase scopes/transfer bytes above,
        # so one kernel's series correlate across families
        note_backlog(self._span_label, n)
        up = jaxtools.upload(packed, NamedSharding(self.mesh, P(AXIS)),
                             kernel=self._span_label)
        _note_dispatch(n_vis if raw_mode else n)
        if raw_mode:
            with spans.dispatch_span(self._span_label, n_vis,
                                     batch_rows=n):
                self.state, ins, overflow, stage_rows = step(
                    self.state, up, self.owner_map)
            jaxtools.start_fetch(stage_rows)
            self._stage_pending.append(stage_rows)
        else:
            with spans.dispatch_span(self._span_label, n,
                                     batch_rows=n):
                self.state, ins, overflow = step(self.state, up,
                                                 self.owner_map)
        # overflow/insert counters fold in asynchronously — a blocking
        # read per dispatch costs 70ms-1s on the tunneled chip
        self._counters.push(ins, overflow, n)

    def drain_stage_rows(self) -> Optional[np.ndarray]:
        """Sum of per-stage visible-row counts since the last drain
        (fused mode; per-shard vectors sum across the mesh — each raw
        row is counted by exactly one shard pre-routing)."""
        if not self._stage_pending:
            return None
        total = None
        for v in self._stage_pending:
            a = np.asarray(jaxtools.fetch1(v)).sum(axis=0)
            total = a if total is None else total + a
        self._stage_pending = []
        return np.asarray(total)

    def _reserve(self, n: int) -> None:
        """Grow (per-shard rehash) until the fullest shard keeps room
        for `n` pessimistic inserts — the fatal-on-overflow contract of
        v1 is gone (VERDICT r3 #5): state may exceed the initial device
        capacity by any factor; each doubling costs one SPMD rebuild +
        a retrace, amortized like the single-chip growth ladder."""
        self._counters.drain_ready()
        if self._counters.bound() + n <= ht.MAX_LOAD * self.capacity:
            return
        self._counters.drain_all()
        worst = self._counters.worst_exact()
        if worst + n > ht.MAX_LOAD * self.capacity:
            self.grow(next_pow2(int((worst + n) / ht.MAX_LOAD) + 1))

    def grow(self, new_capacity: int) -> None:
        """Per-shard same-membership rehash into larger tables — ONE
        SPMD step reusing the single-chip rebuild (_rebuild_live with
        every occupied slot live), preserving dirty flags and emitted
        snapshots so in-epoch growth never disturbs flush diffs."""
        new_capacity = next_pow2(max(new_capacity, self.capacity * 2))
        fills = self._fills
        step = self._shardwise(
            lambda st: _rebuild_live(st, st.table.occ, new_capacity,
                                     fills),
            donate=True, out_spec=(self._state_spec, P(AXIS)))
        self.state, n_live = step(self.state)
        self.capacity = new_capacity
        # exact per-shard occupancy falls out of the rebuild for free
        self._counters.reset(
            np.asarray(jaxtools.fetch1(n_live)).reshape(self.n_dev))

    # -- barrier flush (GroupedAggKernel surface) -------------------------
    def flush(self) -> FlushResult:
        """Gather every shard's dirty groups — ONE [n_dev, 1+fc, W]
        fetch — and decode the concatenation. Keys never span shards
        (ownership is a function of the key hash), so the merged result
        is a disjoint union and HashAggExecutor's emission/persistence
        logic runs unchanged on it."""
        # the epoch's buffered rows ship as ONE SPMD dispatch here —
        # the barrier IS the sharded batch boundary (ISSUE 10)
        self._dispatch_backlog()
        # drain next: reset() would discard pending bucket-overflow
        # flags, and an overflow MUST surface before this barrier's
        # results are treated as complete
        self._counters.drain_all()
        fc = self._flush_cap
        while True:
            if fc not in self._gather_cache:
                self._gather_cache[fc] = self._shardwise(
                    partial(gather_packed, flush_cap=fc), donate=False,
                    out_spec=P(AXIS), cache_key=("gather", fc))
            with spans.dispatch_span(f"{self._span_label}.flush",
                                     self._counters.bound()):
                mats = jaxtools.fetch1(
                    self._gather_cache[fc](self.state))
            ps = mats[:, 0, 0]
            _note_dispatch(float(ps.sum()))
            self._counters.reset(mats[:, 0, 1])
            worst = int(ps.max())
            if worst <= fc:
                break
            fc = max(fc * 2, next_pow2(worst))
        self._flush_cap = fc
        if int(ps.sum()) == 0:
            self._flush_idx = [np.zeros(0, dtype=np.int32)
                               for _ in range(self.n_dev)]
            return FlushResult.empty(self.specs, self.key_width)
        with LEDGER.phase("host_emit", kernel=self._span_label):
            segs = [mats[d, 1:1 + int(ps[d])]
                    for d in range(self.n_dev)]
            self._flush_idx = [np.ascontiguousarray(s[:, 0])
                               for s in segs]
            data = np.concatenate(segs, axis=0)
            return decode_flush_data(self.specs, self.key_width, data)

    def advance(self) -> None:
        assert self._flush_idx is not None, "flush() first"
        self._flush_idx = None
        self.state = self._advance_jit(self.state)

    def patch_accs(self, decoded, raw_accs=None) -> None:
        """Overwrite flushed groups' accumulators across all shards
        (retractable MIN/MAX minput recompute — the single-chip
        patch_accs, shard-mapped). The flush's per-shard slot indices
        (self._flush_idx) route each corrected row back to its owning
        shard; untouched calls pass their raw gathered columns through
        bit-for-bit."""
        idxs = self._flush_idx
        assert idxs is not None and any(len(ix) for ix in idxs), \
            "flush() first"
        from risingwave_tpu.ops.hash_agg import encode_patch_cols
        dev_cols = encode_patch_cols(self.specs, decoded, raw_accs)
        counts = [len(ix) for ix in idxs]
        m = next_pow2(max(counts))
        bidx = np.full((self.n_dev, m), self.capacity, dtype=np.int32)
        bcols = [np.zeros((self.n_dev, m), dtype=c.dtype)
                 for c in dev_cols]
        at = 0
        for d_i, ix in enumerate(idxs):
            c = len(ix)
            bidx[d_i, :c] = ix
            for bc, col in zip(bcols, dev_cols):
                bc[d_i, :c] = col[at:at + c]
            at += c

        if self._patch_step is None:
            from risingwave_tpu.ops.hash_agg import build_patch
            patch = build_patch(self.specs)
            n_cols = len(dev_cols)
            self._patch_step = self._shardwise(
                lambda st, ix, *cols: patch(st, ix, tuple(cols)),
                donate=True,
                extra_specs=(P(AXIS),) * (1 + n_cols),
                cache_key=("patch", n_cols))
        self.state = self._patch_step(
            self.state, jnp.asarray(bidx),
            *(jnp.asarray(b) for b in bcols))

    def retire_below(self, group_pos: int, wm_i64: int) -> None:
        """Watermark state cleaning, every shard in one SPMD step.
        Runs post-flush only — a buffered epoch batch here would apply
        rows to already-retired groups out of order."""
        if self._backlog_rows:
            raise RuntimeError("retire_below with undispatched backlog")
        if self._retire_jit is None:
            fills = self._fills
            off = group_pos * 3
            self._retire_jit = self._shardwise(
                lambda st, hi, lo: retire_state(st, hi, lo, off, fills),
                donate=True,
                out_spec=(self._state_spec, P(AXIS)),
                extra_specs=(P(), P()),
                cache_key=("retire", off))
            self._retire_off = off
        assert self._retire_off == group_pos * 3, \
            "one watermark column per kernel"
        hi, lo = lanes.split_i64(np.asarray([wm_i64], dtype=np.int64))
        self.state, _n_live = self._retire_jit(
            self.state, jnp.int32(hi[0]), jnp.int32(lo[0]))

    def rebuild(self, keys: np.ndarray, group_rows: np.ndarray,
                acc_cols: Sequence[np.ndarray]) -> None:
        """Reload committed value-state rows (recovery), routing each
        group to its owning shard on the host (recovery is cold path;
        the steady-state exchange stays on device)."""
        n = len(group_rows)
        self._backlog = []
        self._backlog_owners = []
        self._backlog_rows = 0
        self._backlog_vis = 0
        self._stage_pending = []
        self.state = jax.tree.map(
            lambda a: jax.device_put(
                a, NamedSharding(self.mesh, P(AXIS))),
            _stack_state(self.n_dev, self.capacity, self.key_width,
                         self.specs))
        self._counters.reset(np.zeros(self.n_dev, dtype=np.int64))
        if n == 0:
            return
        dev_cols = encode_host_accs(self.specs, acc_cols)
        vn = np.asarray(vnodes_from_lanes(jnp.asarray(keys)))
        owner = np.asarray(self.owner_map)[vn]
        per_shard = np.bincount(owner, minlength=self.n_dev)
        worst = int(per_shard.max(initial=0))
        if worst > ht.MAX_LOAD * self.capacity:
            # probe_insert's free-slot contract: an over-full shard
            # would scatter rows into other groups' slots silently —
            # size the fresh state to fit instead
            self.capacity = next_pow2(int(worst / ht.MAX_LOAD) + 1)
            self.state = jax.tree.map(
                lambda a: jax.device_put(
                    a, NamedSharding(self.mesh, P(AXIS))),
                _stack_state(self.n_dev, self.capacity, self.key_width,
                             self.specs))
        m = next_pow2(int(per_shard.max(initial=1)))
        # stack into [n_dev, m, ...] padded blocks
        order = np.argsort(owner, kind="stable")
        pos_in_shard = np.empty(n, dtype=np.int64)
        at = 0
        for d in range(self.n_dev):
            c = int(per_shard[d])
            pos_in_shard[order[at:at + c]] = np.arange(c)
            at += c

        def blocks(col, fill=0):
            out = np.full((self.n_dev, m) + col.shape[1:], fill,
                          dtype=col.dtype)
            out[owner, pos_in_shard] = col
            return out

        bkeys = blocks(keys)
        brows = blocks(group_rows.astype(np.int32))
        baccs = [blocks(np.asarray(c)) for c in dev_cols]
        bvalid = np.zeros((self.n_dev, m), dtype=bool)
        bvalid[owner, pos_in_shard] = True

        def local(state, keys_b, rows_b, valid_b, *accs_b):
            state = jax.tree.map(lambda a: a[0], state)
            keys_l, rows_l, valid_l = keys_b[0], rows_b[0], valid_b[0]
            table, slots, _ins = ht.probe_insert(
                state.table, keys_l, valid_l)
            scat = jnp.where(valid_l, slots, state.table.capacity)
            accs = tuple(
                a.at[scat].set(c[0], mode="drop")
                for a, c in zip(state.accs, accs_b))
            rows_dev = state.group_rows.at[scat].set(rows_l, mode="drop")
            new = AggState(
                table=table, group_rows=rows_dev, dirty=state.dirty,
                accs=accs,
                emitted_valid=state.emitted_valid.at[scat].set(
                    True, mode="drop"),
                emitted_rows=jnp.copy(rows_dev),
                emitted_accs=tuple(jnp.copy(a) for a in accs),
            )
            return jax.tree.map(lambda a: a[None], new)

        mapped = jaxtools.shard_map(
            local, mesh=self.mesh,
            in_specs=(self._state_spec,) + (P(AXIS),) * (3 + len(baccs)),
            out_specs=self._state_spec, check_vma=False)
        self.state = jax.jit(mapped, donate_argnums=(0,))(
            self.state, bkeys, brows, bvalid, *baccs)
        self._counters.reset(per_shard.astype(np.int64))

    # -- elastic resharding (scale.rs:174 / Mutation::Update analog) ------
    def reshard(self, new_owner_map: np.ndarray) -> None:
        """Move device state to a new vnode→shard mapping at a barrier.

        The reference reschedules by swapping vnode bitmaps and lazily
        reloading state from Hummock (state_table.rs:650); the TPU-
        native equivalent moves the HBM-resident groups directly: one
        SPMD step routes every live slot's (key, counters, accs,
        emitted snapshot) to its new owner via the bucketized
        all_to_all, then rebuilds each shard's table with the same
        probe-insert kernel. No host round-trip for the state itself.
        """
        new_map = jnp.asarray(np.asarray(new_owner_map, dtype=np.int32))
        n_dev = self.n_dev
        cap = self.capacity
        specs = self.specs
        key_width = self.key_width

        def local(state: AggState, owner_map):
            state = jax.tree.map(lambda a: a[0], state)
            live = state.table.occ & ((state.group_rows != 0)
                                      | state.dirty | state.emitted_valid)
            owner = owner_map[vnodes_from_lanes(state.table.keys)]
            payloads = [state.table.keys, state.group_rows,
                        state.dirty.astype(jnp.int32),
                        state.emitted_valid.astype(jnp.int32),
                        state.emitted_rows,
                        *state.accs, *state.emitted_accs]
            # bucket = cap: a shard can never receive more rows than
            # fit in one table, so routing is overflow-free
            buckets, bvalid, _overflow = bucketize_by_owner(
                owner, live, payloads, n_dev, cap)
            recv, rvalid = exchange(buckets, bvalid, AXIS)
            m = n_dev * cap
            rvis = rvalid.reshape(m)
            n_received = jnp.sum(rvis, dtype=jnp.int32)
            rkeys = recv[0].reshape(m, key_width)
            fresh = make_agg_state(cap, key_width, specs)
            table, slots, _ins = ht.probe_insert(fresh.table, rkeys,
                                                 rvis)
            scat = jnp.where(rvis, slots, cap)

            def put(dst, src, cast=None):
                v = src.reshape(m)
                if cast is not None:
                    v = v.astype(cast)
                return dst.at[scat].set(v, mode="drop")

            na = len(state.accs)
            new = AggState(
                table=table,
                group_rows=put(fresh.group_rows, recv[1]),
                dirty=put(fresh.dirty, recv[2], jnp.bool_),
                accs=tuple(put(f, r) for f, r in
                           zip(fresh.accs, recv[5:5 + na])),
                emitted_valid=put(fresh.emitted_valid, recv[3],
                                  jnp.bool_),
                emitted_rows=put(fresh.emitted_rows, recv[4]),
                emitted_accs=tuple(put(f, r) for f, r in
                                   zip(fresh.emitted_accs,
                                       recv[5 + na:])),
            )
            return jax.tree.map(lambda a: a[None], new), n_received[None]

        state_spec = jax.tree.map(lambda _: P(AXIS), self.state)
        mapped = jaxtools.shard_map(
            local, mesh=self.mesh,
            in_specs=(state_spec, P()), out_specs=(state_spec, P(AXIS)),
            check_vma=False)
        step = jax.jit(mapped, donate_argnums=(0,))
        new_state, received = step(self.state, new_map)
        # destination-table contract: probe_insert needs a free slot
        # per routed row; an overfull shard would silently corrupt
        # accumulators — fail loudly instead
        worst = int(np.asarray(received).max())
        if worst > ht.MAX_LOAD * cap:
            raise RuntimeError(
                f"reshard overfills a shard: {worst} live groups vs "
                f"{cap} slots — raise capacity before rescaling")
        self.state = new_state
        self.owner_map = new_map   # apply steps take it as a runtime arg
        # host twin follows (the skew-exact bucket counts against it)
        self._owner_map_host = np.asarray(new_owner_map,
                                          dtype=np.int32)

    # -- host-side full decode (tests + dryrun assertions) ---------------
    def snapshot(self) -> Dict[tuple, tuple]:
        """group key lanes tuple → decoded outputs, across all shards."""
        self._dispatch_backlog()
        self._counters.drain_all()
        st = jax.device_get(self.state)
        out: Dict[tuple, tuple] = {}
        for d in range(self.n_dev):
            occ = st.table.occ[d]
            live = occ & (st.group_rows[d] > 0)
            idx = np.flatnonzero(live)
            if not len(idx):
                continue
            keys = st.table.keys[d][idx]
            accs = [a[d][idx] for a in st.accs]
            outs, nulls = decode_outputs(self.specs, accs)
            for r in range(len(idx)):
                kt = tuple(keys[r].tolist())
                out[kt] = tuple(
                    None if nulls[c][r] else outs[c][r].item()
                    for c in range(len(self.specs)))
        return out
