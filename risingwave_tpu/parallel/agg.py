"""Vnode-sharded grouped aggregation over a device mesh.

Reference parity: N parallel HashAggExecutor actors fed by a HASH
dispatcher (SURVEY §2.12 data parallelism; hash_agg.rs:67 +
dispatch.rs:582). TPU re-design: ONE SPMD program under ``shard_map`` —
each mesh shard owns a contiguous vnode range (VnodeMapping semantics)
and a private slice of the hash-table/accumulator arrays; rows hop to
their owner via the bucketized all_to_all (parallel/exchange.py) and are
then aggregated with the exact same kernel math as the single-chip path
(ops/hash_agg._update_call — one code path, two launch shapes).

State is the single-chip ``AggState`` with a leading [n_dev] axis,
sharded ``P('d')``. The barrier flush gathers per-shard dirty slots the
same way the single-chip kernel does; shards never share groups because
ownership is a function of the key hash.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from risingwave_tpu.common.chunk import next_pow2
from risingwave_tpu.common.hash import VNODE_COUNT
from risingwave_tpu.ops import hash_table as ht
from risingwave_tpu.ops import lanes
from risingwave_tpu.ops.hash_agg import (
    AggSpec, AggState, FlushResult, _call_slices, _rebuild_live,
    _update_call, advance_state, decode_flush_data, decode_outputs,
    dev_layout, encode_host_accs, gather_packed, make_agg_state,
    n_input_lanes, retire_state,
)
from risingwave_tpu.parallel.exchange import (
    bucketize_by_owner, exchange, vnodes_from_lanes,
)
from risingwave_tpu.utils import jaxtools

AXIS = "d"


class _ShardedCounters:
    """Per-shard sync-free occupancy accounting + deferred overflow.

    The vector twin of jaxtools.PendingCounters: each SPMD apply returns
    int32[n_dev] insert counts and a bucket-overflow flag; both ride the
    async DMA and are folded in when they land, so the hot path never
    blocks on the tunnel. Overflow raises when observed (barrier at the
    latest) — the barrier rolls back, same contract as the reference's
    error channel.
    """

    def __init__(self, n_dev: int):
        self._count = np.zeros(n_dev, dtype=np.int64)
        self._pending: List[tuple] = []   # (ins[n_dev], overflow, rows)
        self._rows = 0

    def push(self, ins, overflow, n_rows: int) -> None:
        jaxtools.start_fetch(ins, overflow)
        self._pending.append((ins, overflow, n_rows))
        self._rows += n_rows

    def _fold(self, ins, overflow, n_rows: int) -> None:
        if bool(np.asarray(overflow).any()):
            raise RuntimeError(
                "bucket overflow: routed rows dropped — raise `bucket`")
        self._count += np.asarray(ins, dtype=np.int64)
        self._rows -= n_rows

    def drain_ready(self) -> None:
        while self._pending and self._pending[0][0].is_ready() \
                and self._pending[0][1].is_ready():
            self._fold(*self._pending.pop(0))

    def drain_all(self) -> None:
        pending, self._pending = self._pending, []
        for entry in pending:
            jaxtools.fetch(entry[0], entry[1])
            self._fold(*entry)

    def bound(self) -> int:
        """Upper bound on the FULLEST shard's occupancy: every pending
        row could in principle route to one shard."""
        return int(self._count.max(initial=0)) + self._rows

    def worst_exact(self) -> int:
        return int(self._count.max(initial=0))

    def reset(self, per_shard_counts: np.ndarray) -> None:
        self._count = np.asarray(per_shard_counts, dtype=np.int64)
        self._pending = []
        self._rows = 0


def _pad_rows(a: np.ndarray, m: int) -> np.ndarray:
    """Zero/False-pad the leading axis to m rows (pad rows are routed
    nowhere: the caller pads `vis` with False)."""
    out = np.zeros((m,) + a.shape[1:], dtype=a.dtype)
    out[:a.shape[0]] = a
    return out


def _stack_state(n_dev: int, capacity: int, key_width: int,
                 specs: Sequence[AggSpec]) -> AggState:
    """AggState with a leading device axis on every leaf."""
    one = make_agg_state(capacity, key_width, specs)
    return jax.tree.map(
        lambda a: jnp.broadcast_to(a[None], (n_dev,) + a.shape), one)


class ShardedAggKernel:
    """Multi-chip grouped aggregation (fixed capacity v1 — growth and
    elastic resharding land with the reschedule path).

    apply(): one jitted SPMD step — vnode routing, all_to_all, local
    probe+scatter per shard. snapshot(): host-side decode of all live
    groups (test/flush support).
    """

    def __init__(self, mesh: Mesh, key_width: int,
                 specs: Sequence[AggSpec], capacity: int = 1 << 12,
                 bucket: Optional[int] = None,
                 flush_capacity: int = 1 << 10):
        self.mesh = mesh
        self.n_dev = mesh.devices.size
        self.specs = tuple(specs)
        self.key_width = key_width
        self.capacity = capacity
        self.bucket = bucket
        # vnode → owning shard: contiguous even split (VnodeMapping)
        owners = np.repeat(np.arange(self.n_dev, dtype=np.int32),
                           VNODE_COUNT // self.n_dev)
        pad = VNODE_COUNT - len(owners)
        if pad:
            owners = np.concatenate(
                [owners, np.full(pad, self.n_dev - 1, np.int32)])
        self.owner_map = jnp.asarray(owners)
        sharding = NamedSharding(mesh, P(AXIS))
        self.state: AggState = jax.tree.map(
            lambda a: jax.device_put(a, sharding),
            _stack_state(self.n_dev, capacity, key_width, self.specs))
        self._step_cache: Dict[Tuple[int, int], object] = {}
        self._fills = tuple(f for _dt, f in dev_layout(self.specs))
        self._flush_cap = next_pow2(flush_capacity)
        self._flush_idx: Optional[List[np.ndarray]] = None
        self._counters = _ShardedCounters(self.n_dev)
        self._state_spec = jax.tree.map(lambda _: P(AXIS), self.state)
        self._advance_jit = self._shardwise(advance_state, donate=True)
        self._retire_jit = None        # built lazily (lane_off static)
        self._patch_step = None        # built lazily (col count static)
        self._gather_cache: Dict[int, object] = {}

    def _shardwise(self, fn, donate: bool, out_spec=None, extra_specs=()):
        """Wrap a single-chip traced state transform in shard_map: each
        shard applies `fn` to its slice (leading axis dropped/restored).
        The single-chip and sharded kernels literally share programs."""
        def local(state, *args):
            state = jax.tree.map(lambda a: a[0], state)
            out = fn(state, *args)
            return jax.tree.map(lambda a: a[None], out)

        mapped = jaxtools.shard_map(
            local, mesh=self.mesh,
            in_specs=(self._state_spec,) + tuple(extra_specs),
            out_specs=out_spec if out_spec is not None
            else self._state_spec,
            check_vma=False)
        return jaxtools.instrumented_jit(
            mapped, "parallel_agg.sharded",
            donate_argnums=(0,) if donate else ())

    # -- the SPMD step ----------------------------------------------------
    def _build_step(self, n_rows: int, bucket: int):
        specs = self.specs
        slices = _call_slices(specs)
        n_dev = self.n_dev

        def local_step(state: AggState, key_lanes, signs, vis, flat_in,
                       owner_map):
            # shard_map hands each shard a [1, ...] block: drop the axis
            state = jax.tree.map(lambda a: a[0], state)
            vn = vnodes_from_lanes(key_lanes)
            owner = owner_map[vn]
            # payload layout: keys, signs, then per call: lanes* + valid
            payloads = [key_lanes, signs] + list(flat_in)
            buckets, bvalid, overflow = bucketize_by_owner(
                owner, vis, payloads, n_dev, bucket)
            recv, rvalid = exchange(buckets, bvalid, AXIS)
            m = n_dev * bucket
            rkeys = recv[0].reshape(m, key_lanes.shape[1])
            rsigns = recv[1].reshape(m)
            rflat = [r.reshape(m) for r in recv[2:]]
            rvis = rvalid.reshape(m)
            table, slots, ins = ht.probe_insert(state.table, rkeys, rvis)
            cap = state.table.capacity
            scat = jnp.where(rvis, slots, cap)
            s32 = rsigns.astype(jnp.int32)
            group_rows = state.group_rows.at[scat].add(s32, mode="drop")
            dirty = state.dirty.at[scat].set(True, mode="drop")
            accs = list(state.accs)
            k = 0
            for spec, sl in zip(specs, slices):
                n_in = n_input_lanes(spec)
                in_lanes = tuple(rflat[k:k + n_in])
                val_ok = rflat[k + n_in]
                k += n_in + 1
                _update_call(spec, accs, sl, in_lanes, val_ok, slots,
                             rvis, s32, cap)
            new = AggState(table, group_rows, dirty, tuple(accs),
                           state.emitted_valid, state.emitted_rows,
                           state.emitted_accs)
            new = jax.tree.map(lambda a: a[None], new)
            return new, ins[None], overflow[None]

        state_spec = jax.tree.map(lambda _: P(AXIS), self.state)
        mapped = jaxtools.shard_map(
            local_step, mesh=self.mesh,
            in_specs=(state_spec, P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                      P()),
            out_specs=(state_spec, P(AXIS), P(AXIS)),
            check_vma=False)
        return jaxtools.instrumented_jit(
            mapped, "parallel_agg.step", donate_argnums=(0,))

    def apply(self, key_lanes: np.ndarray, signs: np.ndarray,
              vis: np.ndarray,
              inputs: Sequence[Tuple[Sequence[np.ndarray], np.ndarray]]
              ) -> None:
        """One SPMD step over a host batch.

        Rows are split evenly across shards (row-sharded upload); the
        all_to_all then moves each row to its vnode owner. `inputs` is
        per call (value lanes, valid mask) — the single-chip layout;
        lanes AND validity travel through the exchange. Batch rows must
        divide n_dev.
        """
        n = key_lanes.shape[0]
        if n % self.n_dev:
            m = (n + self.n_dev - 1) // self.n_dev * self.n_dev
            key_lanes = _pad_rows(np.asarray(key_lanes), m)
            signs = _pad_rows(np.asarray(signs), m)
            vis = _pad_rows(np.asarray(vis), m)   # pad rows invisible
            inputs = [
                (tuple(_pad_rows(np.asarray(a), m) for a in in_lanes),
                 None if valid is None
                 else _pad_rows(np.asarray(valid), m))
                for in_lanes, valid in inputs]
            n = m
        # per-shard post-exchange batch is n_dev*bucket rows in ONE
        # scatter step — same int32 limb bound as the single-chip kernel
        if n > lanes.MAX_CHUNK_ROWS:
            raise RuntimeError(
                f"batch {n} > {lanes.MAX_CHUNK_ROWS} breaks limb math")
        self._reserve(n)
        flat: List[jnp.ndarray] = []
        for in_lanes, valid in inputs:
            flat.extend(jnp.asarray(a) for a in in_lanes)
            if valid is None:            # count(*) — same API as the
                valid = np.ones(n, dtype=bool)   # single-chip kernel
            flat.append(jnp.asarray(valid))
        # each shard holds n/n_dev local rows, so no owner can receive
        # more than that: bucket = n/n_dev is overflow-free by
        # construction AND keeps the exchanged tensor at n rows/shard
        bucket = self.bucket or n // self.n_dev
        key = (n, bucket)
        if key not in self._step_cache:
            self._step_cache[key] = self._build_step(n, bucket)
        step = self._step_cache[key]
        self.state, ins, overflow = step(
            self.state, jnp.asarray(key_lanes), jnp.asarray(signs),
            jnp.asarray(vis), tuple(flat), self.owner_map)
        # overflow/insert counters fold in asynchronously — a blocking
        # read per apply costs 70ms-1s on the tunneled chip
        self._counters.push(ins, overflow, n)

    def _reserve(self, n: int) -> None:
        """Grow (per-shard rehash) until the fullest shard keeps room
        for `n` pessimistic inserts — the fatal-on-overflow contract of
        v1 is gone (VERDICT r3 #5): state may exceed the initial device
        capacity by any factor; each doubling costs one SPMD rebuild +
        a retrace, amortized like the single-chip growth ladder."""
        self._counters.drain_ready()
        if self._counters.bound() + n <= ht.MAX_LOAD * self.capacity:
            return
        self._counters.drain_all()
        worst = self._counters.worst_exact()
        if worst + n > ht.MAX_LOAD * self.capacity:
            self.grow(next_pow2(int((worst + n) / ht.MAX_LOAD) + 1))

    def grow(self, new_capacity: int) -> None:
        """Per-shard same-membership rehash into larger tables — ONE
        SPMD step reusing the single-chip rebuild (_rebuild_live with
        every occupied slot live), preserving dirty flags and emitted
        snapshots so in-epoch growth never disturbs flush diffs."""
        new_capacity = next_pow2(max(new_capacity, self.capacity * 2))
        fills = self._fills
        step = self._shardwise(
            lambda st: _rebuild_live(st, st.table.occ, new_capacity,
                                     fills),
            donate=True, out_spec=(self._state_spec, P(AXIS)))
        self.state, n_live = step(self.state)
        self.capacity = new_capacity
        # exact per-shard occupancy falls out of the rebuild for free
        self._counters.reset(
            np.asarray(jaxtools.fetch1(n_live)).reshape(self.n_dev))

    # -- barrier flush (GroupedAggKernel surface) -------------------------
    def flush(self) -> FlushResult:
        """Gather every shard's dirty groups — ONE [n_dev, 1+fc, W]
        fetch — and decode the concatenation. Keys never span shards
        (ownership is a function of the key hash), so the merged result
        is a disjoint union and HashAggExecutor's emission/persistence
        logic runs unchanged on it."""
        # drain first: reset() would discard pending bucket-overflow
        # flags, and an overflow MUST surface before this barrier's
        # results are treated as complete
        self._counters.drain_all()
        fc = self._flush_cap
        while True:
            if fc not in self._gather_cache:
                self._gather_cache[fc] = self._shardwise(
                    partial(gather_packed, flush_cap=fc), donate=False,
                    out_spec=P(AXIS))
            mats = jaxtools.fetch1(self._gather_cache[fc](self.state))
            ps = mats[:, 0, 0]
            self._counters.reset(mats[:, 0, 1])
            worst = int(ps.max())
            if worst <= fc:
                break
            fc = max(fc * 2, next_pow2(worst))
        self._flush_cap = fc
        if int(ps.sum()) == 0:
            self._flush_idx = [np.zeros(0, dtype=np.int32)
                               for _ in range(self.n_dev)]
            return FlushResult.empty(self.specs, self.key_width)
        segs = [mats[d, 1:1 + int(ps[d])] for d in range(self.n_dev)]
        self._flush_idx = [np.ascontiguousarray(s[:, 0]) for s in segs]
        data = np.concatenate(segs, axis=0)
        return decode_flush_data(self.specs, self.key_width, data)

    def advance(self) -> None:
        assert self._flush_idx is not None, "flush() first"
        self._flush_idx = None
        self.state = self._advance_jit(self.state)

    def patch_accs(self, decoded, raw_accs=None) -> None:
        """Overwrite flushed groups' accumulators across all shards
        (retractable MIN/MAX minput recompute — the single-chip
        patch_accs, shard-mapped). The flush's per-shard slot indices
        (self._flush_idx) route each corrected row back to its owning
        shard; untouched calls pass their raw gathered columns through
        bit-for-bit."""
        idxs = self._flush_idx
        assert idxs is not None and any(len(ix) for ix in idxs), \
            "flush() first"
        from risingwave_tpu.ops.hash_agg import encode_patch_cols
        dev_cols = encode_patch_cols(self.specs, decoded, raw_accs)
        counts = [len(ix) for ix in idxs]
        m = next_pow2(max(counts))
        bidx = np.full((self.n_dev, m), self.capacity, dtype=np.int32)
        bcols = [np.zeros((self.n_dev, m), dtype=c.dtype)
                 for c in dev_cols]
        at = 0
        for d_i, ix in enumerate(idxs):
            c = len(ix)
            bidx[d_i, :c] = ix
            for bc, col in zip(bcols, dev_cols):
                bc[d_i, :c] = col[at:at + c]
            at += c

        if self._patch_step is None:
            from risingwave_tpu.ops.hash_agg import build_patch
            patch = build_patch(self.specs)
            n_cols = len(dev_cols)
            self._patch_step = self._shardwise(
                lambda st, ix, *cols: patch(st, ix, tuple(cols)),
                donate=True,
                extra_specs=(P(AXIS),) * (1 + n_cols))
        self.state = self._patch_step(
            self.state, jnp.asarray(bidx),
            *(jnp.asarray(b) for b in bcols))

    def retire_below(self, group_pos: int, wm_i64: int) -> None:
        """Watermark state cleaning, every shard in one SPMD step."""
        if self._retire_jit is None:
            fills = self._fills
            off = group_pos * 3
            self._retire_jit = self._shardwise(
                lambda st, hi, lo: retire_state(st, hi, lo, off, fills),
                donate=True,
                out_spec=(self._state_spec, P(AXIS)),
                extra_specs=(P(), P()))
            self._retire_off = off
        assert self._retire_off == group_pos * 3, \
            "one watermark column per kernel"
        hi, lo = lanes.split_i64(np.asarray([wm_i64], dtype=np.int64))
        self.state, _n_live = self._retire_jit(
            self.state, jnp.int32(hi[0]), jnp.int32(lo[0]))

    def rebuild(self, keys: np.ndarray, group_rows: np.ndarray,
                acc_cols: Sequence[np.ndarray]) -> None:
        """Reload committed value-state rows (recovery), routing each
        group to its owning shard on the host (recovery is cold path;
        the steady-state exchange stays on device)."""
        n = len(group_rows)
        self.state = jax.tree.map(
            lambda a: jax.device_put(
                a, NamedSharding(self.mesh, P(AXIS))),
            _stack_state(self.n_dev, self.capacity, self.key_width,
                         self.specs))
        self._counters.reset(np.zeros(self.n_dev, dtype=np.int64))
        if n == 0:
            return
        dev_cols = encode_host_accs(self.specs, acc_cols)
        vn = np.asarray(vnodes_from_lanes(jnp.asarray(keys)))
        owner = np.asarray(self.owner_map)[vn]
        per_shard = np.bincount(owner, minlength=self.n_dev)
        worst = int(per_shard.max(initial=0))
        if worst > ht.MAX_LOAD * self.capacity:
            # probe_insert's free-slot contract: an over-full shard
            # would scatter rows into other groups' slots silently —
            # size the fresh state to fit instead
            self.capacity = next_pow2(int(worst / ht.MAX_LOAD) + 1)
            self.state = jax.tree.map(
                lambda a: jax.device_put(
                    a, NamedSharding(self.mesh, P(AXIS))),
                _stack_state(self.n_dev, self.capacity, self.key_width,
                             self.specs))
        m = next_pow2(int(per_shard.max(initial=1)))
        # stack into [n_dev, m, ...] padded blocks
        order = np.argsort(owner, kind="stable")
        pos_in_shard = np.empty(n, dtype=np.int64)
        at = 0
        for d in range(self.n_dev):
            c = int(per_shard[d])
            pos_in_shard[order[at:at + c]] = np.arange(c)
            at += c

        def blocks(col, fill=0):
            out = np.full((self.n_dev, m) + col.shape[1:], fill,
                          dtype=col.dtype)
            out[owner, pos_in_shard] = col
            return out

        bkeys = blocks(keys)
        brows = blocks(group_rows.astype(np.int32))
        baccs = [blocks(np.asarray(c)) for c in dev_cols]
        bvalid = np.zeros((self.n_dev, m), dtype=bool)
        bvalid[owner, pos_in_shard] = True

        def local(state, keys_b, rows_b, valid_b, *accs_b):
            state = jax.tree.map(lambda a: a[0], state)
            keys_l, rows_l, valid_l = keys_b[0], rows_b[0], valid_b[0]
            table, slots, _ins = ht.probe_insert(
                state.table, keys_l, valid_l)
            scat = jnp.where(valid_l, slots, state.table.capacity)
            accs = tuple(
                a.at[scat].set(c[0], mode="drop")
                for a, c in zip(state.accs, accs_b))
            rows_dev = state.group_rows.at[scat].set(rows_l, mode="drop")
            new = AggState(
                table=table, group_rows=rows_dev, dirty=state.dirty,
                accs=accs,
                emitted_valid=state.emitted_valid.at[scat].set(
                    True, mode="drop"),
                emitted_rows=jnp.copy(rows_dev),
                emitted_accs=tuple(jnp.copy(a) for a in accs),
            )
            return jax.tree.map(lambda a: a[None], new)

        mapped = jaxtools.shard_map(
            local, mesh=self.mesh,
            in_specs=(self._state_spec,) + (P(AXIS),) * (3 + len(baccs)),
            out_specs=self._state_spec, check_vma=False)
        self.state = jax.jit(mapped, donate_argnums=(0,))(
            self.state, bkeys, brows, bvalid, *baccs)
        self._counters.reset(per_shard.astype(np.int64))

    # -- elastic resharding (scale.rs:174 / Mutation::Update analog) ------
    def reshard(self, new_owner_map: np.ndarray) -> None:
        """Move device state to a new vnode→shard mapping at a barrier.

        The reference reschedules by swapping vnode bitmaps and lazily
        reloading state from Hummock (state_table.rs:650); the TPU-
        native equivalent moves the HBM-resident groups directly: one
        SPMD step routes every live slot's (key, counters, accs,
        emitted snapshot) to its new owner via the bucketized
        all_to_all, then rebuilds each shard's table with the same
        probe-insert kernel. No host round-trip for the state itself.
        """
        new_map = jnp.asarray(np.asarray(new_owner_map, dtype=np.int32))
        n_dev = self.n_dev
        cap = self.capacity
        specs = self.specs
        key_width = self.key_width

        def local(state: AggState, owner_map):
            state = jax.tree.map(lambda a: a[0], state)
            live = state.table.occ & ((state.group_rows != 0)
                                      | state.dirty | state.emitted_valid)
            owner = owner_map[vnodes_from_lanes(state.table.keys)]
            payloads = [state.table.keys, state.group_rows,
                        state.dirty.astype(jnp.int32),
                        state.emitted_valid.astype(jnp.int32),
                        state.emitted_rows,
                        *state.accs, *state.emitted_accs]
            # bucket = cap: a shard can never receive more rows than
            # fit in one table, so routing is overflow-free
            buckets, bvalid, _overflow = bucketize_by_owner(
                owner, live, payloads, n_dev, cap)
            recv, rvalid = exchange(buckets, bvalid, AXIS)
            m = n_dev * cap
            rvis = rvalid.reshape(m)
            n_received = jnp.sum(rvis, dtype=jnp.int32)
            rkeys = recv[0].reshape(m, key_width)
            fresh = make_agg_state(cap, key_width, specs)
            table, slots, _ins = ht.probe_insert(fresh.table, rkeys,
                                                 rvis)
            scat = jnp.where(rvis, slots, cap)

            def put(dst, src, cast=None):
                v = src.reshape(m)
                if cast is not None:
                    v = v.astype(cast)
                return dst.at[scat].set(v, mode="drop")

            na = len(state.accs)
            new = AggState(
                table=table,
                group_rows=put(fresh.group_rows, recv[1]),
                dirty=put(fresh.dirty, recv[2], jnp.bool_),
                accs=tuple(put(f, r) for f, r in
                           zip(fresh.accs, recv[5:5 + na])),
                emitted_valid=put(fresh.emitted_valid, recv[3],
                                  jnp.bool_),
                emitted_rows=put(fresh.emitted_rows, recv[4]),
                emitted_accs=tuple(put(f, r) for f, r in
                                   zip(fresh.emitted_accs,
                                       recv[5 + na:])),
            )
            return jax.tree.map(lambda a: a[None], new), n_received[None]

        state_spec = jax.tree.map(lambda _: P(AXIS), self.state)
        mapped = jaxtools.shard_map(
            local, mesh=self.mesh,
            in_specs=(state_spec, P()), out_specs=(state_spec, P(AXIS)),
            check_vma=False)
        step = jax.jit(mapped, donate_argnums=(0,))
        new_state, received = step(self.state, new_map)
        # destination-table contract: probe_insert needs a free slot
        # per routed row; an overfull shard would silently corrupt
        # accumulators — fail loudly instead
        worst = int(np.asarray(received).max())
        if worst > ht.MAX_LOAD * cap:
            raise RuntimeError(
                f"reshard overfills a shard: {worst} live groups vs "
                f"{cap} slots — raise capacity before rescaling")
        self.state = new_state
        self.owner_map = new_map   # apply steps take it as a runtime arg

    # -- host-side full decode (tests + dryrun assertions) ---------------
    def snapshot(self) -> Dict[tuple, tuple]:
        """group key lanes tuple → decoded outputs, across all shards."""
        st = jax.device_get(self.state)
        out: Dict[tuple, tuple] = {}
        for d in range(self.n_dev):
            occ = st.table.occ[d]
            live = occ & (st.group_rows[d] > 0)
            idx = np.flatnonzero(live)
            if not len(idx):
                continue
            keys = st.table.keys[d][idx]
            accs = [a[d][idx] for a in st.accs]
            outs, nulls = decode_outputs(self.specs, accs)
            for r in range(len(idx)):
                kt = tuple(keys[r].tolist())
                out[kt] = tuple(
                    None if nulls[c][r] else outs[c][r].item()
                    for c in range(len(self.specs)))
        return out
