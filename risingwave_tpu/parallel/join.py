"""Vnode-sharded join matcher over a device mesh (multi-chip q8).

Reference parity: N parallel HashJoinExecutor actors fed by HASH
dispatchers on both inputs (dispatch.rs:582; hash_join.rs:227). TPU
re-design: each mesh shard owns the join-key vnode range's slice of
BOTH sides' key tables and row chains; a chunk routes to owners via the
bucketized all_to_all (parallel/exchange.py) and then runs the exact
single-chip kernels (ops/hash_join.py probe_pairs / link_rows) locally
— one code path, two launch shapes, matching ShardedAggKernel's
construction so the whole q8 plan shards the same way the q7 plan does.

Host contract: row refs are GLOBAL (the host arena's); each shard's
chains store the global refs routed to it, so probe results need no
re-translation. Probe outputs return per-shard packed pair matrices
with the probing row's global id as the left column.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from risingwave_tpu.common.hash import VNODE_COUNT
from risingwave_tpu.ops import hash_table as ht
from risingwave_tpu.ops.hash_join import (
    I32_MAX, ChainState, link_rows, probe_pairs,
)
from risingwave_tpu.parallel.exchange import (
    bucketize_by_owner, exchange, vnodes_from_lanes,
)

AXIS = "d"


class ShardedJoinSide:
    """One join side's matcher sharded over a mesh (fixed capacity v1)."""

    def __init__(self, mesh: Mesh, key_width: int,
                 key_capacity: int = 1 << 12,
                 row_capacity: int = 1 << 12,
                 probe_capacity: int = 1 << 12):
        self.mesh = mesh
        self.n_dev = mesh.devices.size
        self.key_width = key_width
        self.key_capacity = key_capacity
        self.row_capacity = row_capacity
        self.probe_capacity = probe_capacity
        owners = np.repeat(np.arange(self.n_dev, dtype=np.int32),
                           VNODE_COUNT // self.n_dev)
        pad = VNODE_COUNT - len(owners)
        if pad:
            owners = np.concatenate(
                [owners, np.full(pad, self.n_dev - 1, np.int32)])
        self.owner_map = jnp.asarray(owners)
        sharding = NamedSharding(mesh, P(AXIS))

        def stack(a):
            return jax.device_put(
                jnp.broadcast_to(a[None], (self.n_dev,) + a.shape),
                sharding)

        table = ht.make_state(key_capacity, key_width)
        self.table = ht.TableState(stack(table.keys), stack(table.occ))
        self.chains = ChainState(
            head=stack(jnp.full(key_capacity, -1, dtype=jnp.int32)),
            next=stack(jnp.full(row_capacity, -1, dtype=jnp.int32)),
            ins_seq=stack(jnp.full(row_capacity, I32_MAX,
                                   dtype=jnp.int32)),
            del_seq=stack(jnp.full(row_capacity, I32_MAX,
                                   dtype=jnp.int32)))
        self._insert_cache: Dict[Tuple[int, int], object] = {}
        self._probe_cache: Dict[Tuple[int, int, int], object] = {}
        self._keys_upper = 0      # distinct-key upper bound (host)

    # -- SPMD steps -------------------------------------------------------
    def _build_insert(self, n: int, bucket: int):
        n_dev = self.n_dev
        cap = self.key_capacity

        def local(table, chains, key_lanes, refs, vis, owner_map):
            table = jax.tree.map(lambda a: a[0], table)
            chains = jax.tree.map(lambda a: a[0], chains)
            owner = owner_map[vnodes_from_lanes(key_lanes)]
            buckets, bvalid, overflow = bucketize_by_owner(
                owner, vis, [key_lanes, refs], n_dev, bucket)
            recv, rvalid = exchange(buckets, bvalid, AXIS)
            m = n_dev * bucket
            rkeys = recv[0].reshape(m, key_lanes.shape[1])
            rrefs = recv[1].reshape(m)
            rvis = rvalid.reshape(m)
            table, slots, _ins = ht.probe_insert(table, rkeys, rvis)
            chains = link_rows(chains, slots, rrefs, rvis, cap,
                               jnp.int32(0))
            return (jax.tree.map(lambda a: a[None], table),
                    jax.tree.map(lambda a: a[None], chains),
                    overflow[None])

        tspec = jax.tree.map(lambda _: P(AXIS), self.table)
        cspec = jax.tree.map(lambda _: P(AXIS), self.chains)
        mapped = jax.shard_map(
            local, mesh=self.mesh,
            in_specs=(tspec, cspec, P(AXIS), P(AXIS), P(AXIS), P()),
            out_specs=(tspec, cspec, P(AXIS)),
            check_vma=False)
        return jax.jit(mapped, donate_argnums=(0, 1))

    def _build_probe(self, n: int, bucket: int, out_cap: int):
        n_dev = self.n_dev

        def local(table, chains, key_lanes, row_ids, vis, owner_map):
            table = jax.tree.map(lambda a: a[0], table)
            chains = jax.tree.map(lambda a: a[0], chains)
            owner = owner_map[vnodes_from_lanes(key_lanes)]
            buckets, bvalid, overflow = bucketize_by_owner(
                owner, vis, [key_lanes, row_ids], n_dev, bucket)
            recv, rvalid = exchange(buckets, bvalid, AXIS)
            m = n_dev * bucket
            rkeys = recv[0].reshape(m, key_lanes.shape[1])
            rids = recv[1].reshape(m)
            rvis = rvalid.reshape(m)
            mat = probe_pairs(table, chains, rkeys, rvis,
                              jnp.int32(I32_MAX), out_cap)
            # rewrite probe-row indices (local post-exchange positions)
            # to the routed global row ids; -1 stays -1
            pairs = mat[1 + m:]
            safe = jnp.maximum(pairs[:, 0], 0)
            gprobe = jnp.where(pairs[:, 0] >= 0, rids[safe], -1)
            pairs = jnp.stack([gprobe, pairs[:, 1]], axis=1)
            out = jnp.concatenate([mat[:1], pairs], axis=0)
            return out[None], overflow[None]

        tspec = jax.tree.map(lambda _: P(AXIS), self.table)
        cspec = jax.tree.map(lambda _: P(AXIS), self.chains)
        mapped = jax.shard_map(
            local, mesh=self.mesh,
            in_specs=(tspec, cspec, P(AXIS), P(AXIS), P(AXIS), P()),
            out_specs=(P(AXIS), P(AXIS)),
            check_vma=False)
        return jax.jit(mapped)

    # -- host API ---------------------------------------------------------
    def insert(self, key_lanes: np.ndarray, refs: np.ndarray,
               vis: np.ndarray) -> None:
        n = key_lanes.shape[0]
        assert n % self.n_dev == 0, (n, self.n_dev)
        # fixed-capacity v1 guards: overfilling a shard's key table
        # would make probe_insert link rows under wrong keys, and a
        # ref >= row_capacity would be silently dropped by the chain
        # scatter — both must fail loudly until growth lands here.
        # key-table occupancy grows with DISTINCT keys (duplicates
        # chain in the row arena). The host tracks an UPPER BOUND
        # (per-batch unique keys, which over-counts keys recurring
        # across batches); when the bound crosses the load limit it is
        # collapsed to the true worst-shard occupancy with one device
        # sync — same scheme as GroupedAggKernel._reserve.
        kv = np.asarray(key_lanes)[np.asarray(vis)]
        self._keys_upper += len(np.unique(kv, axis=0)) if len(kv) else 0
        limit = ht.MAX_LOAD * self.key_capacity
        if self._keys_upper > limit:
            per_shard = np.asarray(jnp.sum(self.table.occ, axis=1))
            self._keys_upper = int(per_shard.max())
            if self._keys_upper + len(kv) > limit:
                raise RuntimeError(
                    f"sharded join side over capacity: "
                    f"{self._keys_upper} keys on the fullest shard vs "
                    f"{self.key_capacity} slots — raise key_capacity "
                    "(growth TBD)")
        if len(refs) and int(np.max(refs)) >= self.row_capacity:
            raise RuntimeError(
                f"row ref {int(np.max(refs))} >= row_capacity "
                f"{self.row_capacity} — raise row_capacity (growth TBD)")
        bucket = n // self.n_dev
        key = (n, bucket)
        if key not in self._insert_cache:
            self._insert_cache[key] = self._build_insert(n, bucket)
        step = self._insert_cache[key]
        self.table, self.chains, overflow = step(
            self.table, self.chains, jnp.asarray(key_lanes),
            jnp.asarray(refs.astype(np.int32)), jnp.asarray(vis),
            self.owner_map)
        if bool(np.asarray(overflow).any()):
            raise RuntimeError("bucket overflow inserting join rows")

    def probe(self, key_lanes: np.ndarray, vis: np.ndarray
              ) -> Tuple[np.ndarray, np.ndarray]:
        """(probe global row ids, matched refs) across all shards.
        Doubles the per-shard pair buffer and retries on overflow."""
        n = key_lanes.shape[0]
        assert n % self.n_dev == 0, (n, self.n_dev)
        bucket = n // self.n_dev
        row_ids = np.arange(n, dtype=np.int32)
        while True:
            key = (n, bucket, self.probe_capacity)
            if key not in self._probe_cache:
                self._probe_cache[key] = self._build_probe(
                    n, bucket, self.probe_capacity)
            step = self._probe_cache[key]
            mats, overflow = step(self.table, self.chains,
                                  jnp.asarray(key_lanes),
                                  jnp.asarray(row_ids), jnp.asarray(vis),
                                  self.owner_map)
            if bool(np.asarray(overflow).any()):
                raise RuntimeError("bucket overflow routing probe rows")
            mats = np.asarray(mats)      # [n_dev, 1 + out_cap, 2]
            worst = int(mats[:, 0, 0].max())
            if worst <= self.probe_capacity:
                break
            while self.probe_capacity < worst:
                self.probe_capacity *= 2
        probes, refs = [], []
        for d in range(self.n_dev):
            total = int(mats[d, 0, 0])
            pairs = mats[d, 1:1 + total]
            probes.append(pairs[:, 0])
            refs.append(pairs[:, 1])
        return (np.concatenate(probes) if probes else
                np.zeros(0, np.int32),
                np.concatenate(refs) if refs else np.zeros(0, np.int32))
