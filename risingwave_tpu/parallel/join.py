"""Vnode-sharded join matcher over a device mesh (multi-chip q8).

Reference parity: N parallel HashJoinExecutor actors fed by HASH
dispatchers on both inputs (dispatch.rs:582; hash_join.rs:227). TPU
re-design: each mesh shard owns the join-key vnode range's slice of
BOTH sides' key tables and row chains; a chunk routes to owners via the
bucketized all_to_all (parallel/exchange.py) and then runs the exact
single-chip kernels (ops/hash_join.py probe_pairs / link_rows /
tombstone_rows, sequence-versioned) locally — one code path, two
launch shapes, matching ShardedAggKernel's construction so the whole
q8 plan shards the same way the q7 plan does.

Host contract: row refs are GLOBAL (the host arena's); a ref lives
only on its key's owner shard, so each shard's chain arrays index by
global ref directly and probe results need no re-translation. The
executor (stream/executors/hash_join.py) cannot tell this kernel from
the single-chip JoinSideKernel — same apply_and_probe / probe /
delete / rebuild / rebase_seq API, same async PendingProbe contract.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from risingwave_tpu.common.chunk import next_pow2
from risingwave_tpu.common.hash import VNODE_COUNT
from risingwave_tpu.ops import hash_table as ht
from risingwave_tpu.ops.hash_join import (
    AUX_DEL_REF, AUX_FLAGS, AUX_INS_REF, AUX_SEQ, FLAG_DEL, FLAG_INS,
    FLAG_PROBE, I32_MAX, ChainState, _remap_head, link_rows,
    probe_pairs, tombstone_rows,
)
from risingwave_tpu.parallel.exchange import (
    bucketize_by_owner, exchange, owners_host, skew_bucket,
    vnodes_from_lanes,
)
from risingwave_tpu.utils import jaxtools
from risingwave_tpu.utils.ledger import LEDGER

AXIS = "d"

# Compiled SPMD steps, shared ACROSS kernel instances (both sides of a
# join share shapes; capacity growth keys fresh entries instead of
# clearing): keyed by (mesh device ids, program kind, every static the
# closure bakes in). Before this cache, each _JoinSide's kernel rebuilt
# — and re-traced — its own steps on any shape churn, which the
# RecompileGuard now polices on the sharded path too. A CompileCache
# (stream/costs.py) so hits/misses bill the pulling MV: the first MV
# to trace an entry pays the compile, later tenants record shared hits.
from risingwave_tpu.stream.costs import CompileCache as _CompileCache

_STEP_CACHE: Dict[tuple, object] = _CompileCache("join_step")


def _step_key(mesh: Mesh, kind: str, *statics) -> tuple:
    return ((kind,) + tuple(int(d.id) for d in mesh.devices.flat)
            + statics)


def _note_dispatch(rows: float, kernel: str) -> None:
    """Real-SPMD-dispatch accounting at the jit sites (the sharded
    twin of the fused kernels' metrics_label counting): one inc per
    `shard_map` launch, with true row density — the executor layer
    does NOT count for sharded kernels, so totals never double."""
    from risingwave_tpu.utils.metrics import STREAMING
    STREAMING.device_dispatch.inc(1, kernel=kernel)
    STREAMING.rows_per_dispatch.observe(float(rows), kernel=kernel)


class ShardedPendingProbe:
    """In-flight sharded probe (DMA started at dispatch).

    Mirrors ops/hash_join.PendingProbe: sequence versioning makes
    collect() exact however late it runs, and an overflowed per-shard
    pair buffer re-dispatches a probe-only step at the recorded seq."""

    def __init__(self, kernel: "ShardedJoinKernel", mats, key_lanes,
                 vis, seq: int, out_cap: int, n: int, overflow=None):
        self.kernel = kernel
        self.mats = mats
        self.key_lanes = key_lanes      # host arrays (padded)
        self.vis = vis
        self.seq = seq
        self.out_cap = out_cap
        self.n = n                      # caller rows (pre-padding)
        # routing-overflow flag, checked lazily at collect: a sync here
        # would block the dispatch hot path, and the condition is
        # impossible by construction (bucket = local row count) — this
        # is an assertion, not a retry point
        self.overflow = overflow

    def collect(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(degrees[n], probe_idx[pairs], refs[pairs]) — pairs sorted
        by probe row so same-pk delete/insert halves stay ordered."""
        k = self.kernel
        with LEDGER.kernel_scope("sharded_join"):
            while True:
                if self.overflow is not None and \
                        bool(np.asarray(self.overflow).any()):
                    raise RuntimeError(
                        "bucket overflow routing join rows")
                mats = np.asarray(jaxtools.fetch1(self.mats))
                worst = int(mats[:, 0, 0].max())
                if worst <= self.out_cap:
                    break
                while k.probe_capacity < worst:
                    k.probe_capacity *= 2
                self.out_cap = k.probe_capacity
                self.mats, self.overflow = k._dispatch_probe(
                    self.key_lanes, self.vis, self.seq, self.out_cap)
        m = mats.shape[1] - 1 - self.out_cap
        deg = np.zeros(self.n, dtype=np.int32)
        probes, refs = [], []
        for d in range(mats.shape[0]):
            blk = mats[d, 1:1 + m]
            rid, dg = blk[:, 1], blk[:, 0]
            sel = rid >= 0
            deg[rid[sel]] = dg[sel]
            total = int(mats[d, 0, 0])
            pairs = mats[d, 1 + m:1 + m + total]
            probes.append(pairs[:, 0])
            refs.append(pairs[:, 1])
        probe_idx = np.concatenate(probes) if probes else \
            np.zeros(0, np.int32)
        ref_arr = np.concatenate(refs) if refs else np.zeros(0, np.int32)
        order = np.argsort(probe_idx, kind="stable")
        return deg, probe_idx[order], ref_arr[order]


class ShardedPendingEpochProbe:
    """In-flight sharded EPOCH probe (ops/hash_join.PendingEpochProbe
    parity over the per-shard packed matrices).

    collect() parses each shard's [1 + (m) + out_cap, 2] block —
    header, per-routed-row degree rows (with_degrees only), then
    (global probe row, ref) pairs — scatters degrees back to the
    global epoch row space and concatenates pairs sorted stably by
    probe row. A probe row's key routes to exactly ONE owner shard, so
    per-row match order is that shard's chain walk, preserved by the
    stable sort. Payload lanes and device old-degrees are None: the
    sharded path materializes rows from the host arena and keeps
    degrees in the executor's host arrays."""

    def __init__(self, kernel: "ShardedJoinKernel", mats, n_rows: int,
                 out_cap: int, with_degrees: bool, redispatch,
                 overflow=None):
        self.kernel = kernel
        self.mats = mats
        self.n = n_rows               # padded epoch rows
        self.out_cap = out_cap
        self.with_degrees = with_degrees
        self.redispatch = redispatch
        self.overflow = overflow

    def collect(self):
        """(degrees | None, probe_idx, refs, None, None) over the
        CONCATENATED epoch row space, pairs sorted by probe row."""
        k = self.kernel
        k.drain_overflows()
        with LEDGER.kernel_scope("sharded_join"):
            while True:
                if self.overflow is not None and \
                        bool(np.asarray(jaxtools.fetch1(
                            self.overflow)).any()):
                    raise RuntimeError(
                        "bucket overflow routing epoch join probes")
                mats = np.asarray(jaxtools.fetch1(self.mats))
                worst = int(mats[:, 0, 0].max())
                if worst <= self.out_cap:
                    break
                while k.probe_capacity < worst:
                    k.probe_capacity *= 2
                self.out_cap = k.probe_capacity
                self.mats, self.overflow = self.redispatch(self.out_cap)
        m = mats.shape[1] - 1 - self.out_cap
        deg = None
        if self.with_degrees:
            deg = np.zeros(self.n, dtype=np.int32)
        probes, refs = [], []
        for d in range(mats.shape[0]):
            if self.with_degrees:
                blk = mats[d, 1:1 + m]
                rid, dg = blk[:, 1], blk[:, 0]
                sel = rid >= 0
                deg[rid[sel]] = dg[sel]
            total = int(mats[d, 0, 0])
            pairs = mats[d, 1 + m:1 + m + total]
            probes.append(pairs[:, 0])
            refs.append(pairs[:, 1])
        probe_idx = np.concatenate(probes) if probes else \
            np.zeros(0, np.int32)
        ref_arr = np.concatenate(refs) if refs else np.zeros(0, np.int32)
        order = np.argsort(probe_idx, kind="stable")
        return (deg, probe_idx[order].astype(np.int64),
                ref_arr[order], None, None)


class ShardedJoinKernel:
    """JoinSideKernel's API over a device mesh (multi-chip join side).

    Fixed-capacity v1: over-capacity is a loud error, growth is future
    work. Key-table occupancy is tracked as an upper bound (per-batch
    unique keys over-count keys recurring across batches); when the
    bound crosses the load limit it collapses to the true worst-shard
    occupancy with one device sync — GroupedAggKernel._reserve's
    scheme. The bound is GLOBAL while the limit is PER-SHARD, so it is
    conservative: a false trip costs one sync, never a false pass."""

    # pre-sized like JoinSideKernel.DEFAULT_CAPACITY: every growth
    # doubling rehashes AND re-keys every compiled SPMD step (a fresh
    # trace per program — multi-second stalls on the p99 tail), so the
    # defaults absorb typical runs and growth multiplies by 4x
    def __init__(self, mesh: Mesh, key_width: int,
                 key_capacity: int = 1 << 15,
                 row_capacity: int = 1 << 17,
                 probe_capacity: int = 1 << 13):
        self.mesh = mesh
        self.n_dev = mesh.devices.size
        self.key_width = key_width
        self.key_capacity = key_capacity
        self._row_capacity = row_capacity
        self.probe_capacity = probe_capacity
        owners = np.repeat(np.arange(self.n_dev, dtype=np.int32),
                           VNODE_COUNT // self.n_dev)
        pad = VNODE_COUNT - len(owners)
        if pad:
            owners = np.concatenate(
                [owners, np.full(pad, self.n_dev - 1, np.int32)])
        self.owner_map = jnp.asarray(owners)
        self._owner_map_host = owners
        self._sharding = NamedSharding(mesh, P(AXIS))
        self._fresh_state()
        # per-shard distinct-key upper bound (host)
        self._keys_upper = np.zeros(self.n_dev, dtype=np.int64)
        # apply-step overflow flags, checked lazily at the next probe
        # collect (impossible by construction — an assertion, never a
        # retry point; a sync here would block the dispatch hot path)
        self._apply_overflows: list = []
        # fused-input preludes by key (the epoch jits bake them in)
        self._preludes: Dict[str, object] = {}
        # epoch-trace identity stamped on dispatch metrics
        self._span_label = "ShardedJoinKernel"

    @property
    def row_capacity(self) -> int:
        return self._row_capacity

    def _stack(self, a):
        return jax.device_put(
            jnp.broadcast_to(a[None], (self.n_dev,) + a.shape),
            self._sharding)

    def _fresh_state(self) -> None:
        table = ht.make_state(self.key_capacity, self.key_width)
        self.table = ht.TableState(self._stack(table.keys),
                                   self._stack(table.occ))
        self.chains = ChainState(
            head=self._stack(jnp.full(self.key_capacity, -1,
                                      dtype=jnp.int32)),
            next=self._stack(jnp.full(self._row_capacity, -1,
                                      dtype=jnp.int32)),
            ins_seq=self._stack(jnp.full(self._row_capacity, I32_MAX,
                                         dtype=jnp.int32)),
            del_seq=self._stack(jnp.full(self._row_capacity, I32_MAX,
                                         dtype=jnp.int32)))

    # -- capacity management (state > device: grows, never fatal) ---------
    def _owners_host(self, key_lanes: np.ndarray) -> np.ndarray:
        """Host twin of the device routing (same hash → same owner) —
        the shared exchange helper, so device and host routing live in
        one place."""
        return owners_host(key_lanes, self._owner_map_host)

    def _guard_keys(self, key_lanes: np.ndarray, vis: np.ndarray) -> None:
        """PER-SHARD distinct-key upper bound; grows the key tables
        when the fullest shard runs out (VERDICT r3 #5: the fatal
        contract is gone). Growth is SEQ-PRESERVING — the chain arrays
        are row-indexed and untouched; only the key table + head remap
        — so it is safe mid-epoch with probes in flight."""
        kv = key_lanes[vis]
        if len(kv):
            uniq, idx = np.unique(kv, axis=0, return_index=True)
            add = np.bincount(self._owners_host(kv[idx]),
                              minlength=self.n_dev)
            self._keys_upper = self._keys_upper + add
        limit = ht.MAX_LOAD * self.key_capacity
        if int(self._keys_upper.max()) <= limit:
            return
        # collapse the bound to exact occupancy (one sync), then grow
        per_shard = np.asarray(jnp.sum(self.table.occ, axis=1)) \
            .astype(np.int64)
        headroom = 0 if not len(kv) else np.bincount(
            self._owners_host(kv), minlength=self.n_dev)
        need = per_shard + headroom
        self._keys_upper = need
        worst = int(need.max())
        if worst > limit:
            self._grow_keys(next_pow2(int(worst / ht.MAX_LOAD) + 1))

    def _grow_keys(self, new_capacity: int) -> None:
        # 4x, not 2x: each growth re-traces every step at the new
        # capacity statics (see _STEP_CACHE) — same amortization as
        # JoinSideKernel.reserve_rows
        new_capacity = max(new_capacity, self.key_capacity * 4)
        key_width = self.key_width
        n_dev = self.n_dev

        def local(t, c):
            t = jax.tree.map(lambda a: a[0], t)
            c = jax.tree.map(lambda a: a[0], c)
            nt = ht.make_state(new_capacity, key_width)
            nt, slots, _ins = ht.probe_insert(nt, t.keys, t.occ)
            head = _remap_head(c.head, jnp.where(t.occ, slots, -1),
                               new_capacity)
            nc = ChainState(head=head, next=c.next,
                            ins_seq=c.ins_seq, del_seq=c.del_seq)
            return (jax.tree.map(lambda a: a[None], nt),
                    jax.tree.map(lambda a: a[None], nc))

        tspec, cspec = self._specs()
        mapped = jaxtools.shard_map(
            local, mesh=self.mesh, in_specs=(tspec, cspec),
            out_specs=(tspec, cspec), check_vma=False)
        step = jax.jit(mapped, donate_argnums=(0, 1))
        self.table, self.chains = step(self.table, self.chains)
        self.key_capacity = new_capacity
        # no jit-cache clearing: the module-level _STEP_CACHE keys on
        # the capacities, so the grown shapes simply compile fresh
        # entries while the old ones stay valid for other kernels

    def _guard_refs(self, refs: np.ndarray, mask: np.ndarray) -> None:
        if mask.any():
            mx = int(refs[mask].max())
            if mx >= self._row_capacity:
                self._grow_rows(next_pow2(mx + 1))

    def _grow_rows(self, new_capacity: int) -> None:
        """Row-array growth: concat padding along the per-shard axis
        (refs index rows directly; nothing remaps)."""
        new_capacity = max(new_capacity, self._row_capacity * 4)
        pad = new_capacity - self._row_capacity

        def padded(a, fill):
            p = jax.device_put(
                jnp.broadcast_to(
                    jnp.full(pad, fill, dtype=a.dtype)[None],
                    (self.n_dev, pad)), self._sharding)
            return jnp.concatenate([a, p], axis=1)

        self.chains = self.chains._replace(
            next=padded(self.chains.next, -1),
            ins_seq=padded(self.chains.ins_seq, I32_MAX),
            del_seq=padded(self.chains.del_seq, I32_MAX))
        self._row_capacity = new_capacity

    def reserve_rows(self, max_ref: int) -> None:
        if max_ref >= self._row_capacity:
            self._grow_rows(next_pow2(max_ref + 1))

    # -- SPMD step builders ----------------------------------------------
    def _specs(self):
        tspec = jax.tree.map(lambda _: P(AXIS), self.table)
        cspec = jax.tree.map(lambda _: P(AXIS), self.chains)
        return tspec, cspec

    @staticmethod
    def _route(owner_map, lanes, payloads, valid, n_dev, bucket):
        """Shared bucketize+exchange prologue of every local step.

        `lanes` etc. are the LOCAL shard's slice (bucket rows); after
        the all_to_all each shard holds up to n_dev*bucket routed rows
        (worst case: every row keyed to one shard)."""
        owner = owner_map[vnodes_from_lanes(lanes)]
        buckets, bvalid, overflow = bucketize_by_owner(
            owner, valid, [lanes] + payloads, n_dev, bucket)
        recv, rvalid = exchange(buckets, bvalid, AXIS)
        m = n_dev * bucket
        rlanes = recv[0].reshape(m, lanes.shape[1])
        flat = [r.reshape(m) for r in recv[1:]]
        return rlanes, flat, rvalid.reshape(m), overflow

    def _statics(self) -> tuple:
        """The closure-baked shape statics every step key carries."""
        return (self.key_width, self.key_capacity, self._row_capacity)

    def _build_apply_probe(self, bucket: int, out_cap: int):
        key = _step_key(self.mesh, "apply_probe", bucket, out_cap,
                        *self._statics())
        step = _STEP_CACHE.get(key)
        if step is not None:
            return step
        n_dev = self.n_dev
        cap = self.key_capacity

        def local(my_t, my_c, o_t, o_c, lanes, rowids, refs, drefs,
                  pvis, imask, dmask, seq, owner_map):
            my_t = jax.tree.map(lambda a: a[0], my_t)
            my_c = jax.tree.map(lambda a: a[0], my_c)
            o_t = jax.tree.map(lambda a: a[0], o_t)
            o_c = jax.tree.map(lambda a: a[0], o_c)
            valid = pvis | imask | dmask
            rlanes, (rids, rrefs, rdrefs, rpv, rim, rdm), rvalid, ovf = \
                ShardedJoinKernel._route(
                    owner_map, lanes,
                    [rowids, refs, drefs, pvis.astype(jnp.int32),
                     imask.astype(jnp.int32), dmask.astype(jnp.int32)],
                    valid, n_dev, bucket)
            rpv = rvalid & (rpv == 1)
            rim = rvalid & (rim == 1)
            rdm = rvalid & (rdm == 1)
            m = n_dev * bucket
            mat = probe_pairs(o_t, o_c, rlanes, rpv, seq, out_cap)
            my_t2, slots, _ins = ht.probe_insert(my_t, rlanes, rim)
            ch = link_rows(my_c, slots, rrefs, rim, cap, seq)
            ch = tombstone_rows(ch, rdrefs, rdm, seq)
            # output [1 + m + out_cap, 2]: header; (deg, rid) block;
            # (global probe row, ref) pairs
            deg_blk = jnp.stack(
                [mat[1:1 + m, 0],
                 jnp.where(rvalid, rids, jnp.int32(-1))], axis=1)
            pairs = mat[1 + m:]
            safe = jnp.maximum(pairs[:, 0], 0)
            gprobe = jnp.where(pairs[:, 0] >= 0, rids[safe],
                               jnp.int32(-1))
            out = jnp.concatenate(
                [mat[:1], deg_blk,
                 jnp.stack([gprobe, pairs[:, 1]], axis=1)], axis=0)
            return (jax.tree.map(lambda a: a[None], my_t2),
                    jax.tree.map(lambda a: a[None], ch),
                    out[None], ovf[None])

        tspec, cspec = self._specs()
        mapped = jaxtools.shard_map(
            local, mesh=self.mesh,
            in_specs=(tspec, cspec, tspec, cspec, P(AXIS), P(AXIS),
                      P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                      P(), P()),
            out_specs=(tspec, cspec, P(AXIS), P(AXIS)),
            check_vma=False)
        step = jaxtools.instrumented_jit(
            mapped, "parallel_join.apply_probe", donate_argnums=(0, 1))
        _STEP_CACHE[key] = step
        return step

    def _build_probe_only(self, bucket: int, out_cap: int):
        key = _step_key(self.mesh, "probe_only", bucket, out_cap,
                        *self._statics())
        step = _STEP_CACHE.get(key)
        if step is not None:
            return step
        n_dev = self.n_dev

        def local(t, c, lanes, rowids, vis, seq, owner_map):
            t = jax.tree.map(lambda a: a[0], t)
            c = jax.tree.map(lambda a: a[0], c)
            rlanes, (rids,), rvalid, ovf = ShardedJoinKernel._route(
                owner_map, lanes, [rowids], vis, n_dev, bucket)
            m = n_dev * bucket
            mat = probe_pairs(t, c, rlanes, rvalid, seq, out_cap)
            deg_blk = jnp.stack(
                [mat[1:1 + m, 0],
                 jnp.where(rvalid, rids, jnp.int32(-1))], axis=1)
            pairs = mat[1 + m:]
            safe = jnp.maximum(pairs[:, 0], 0)
            gprobe = jnp.where(pairs[:, 0] >= 0, rids[safe],
                               jnp.int32(-1))
            out = jnp.concatenate(
                [mat[:1], deg_blk,
                 jnp.stack([gprobe, pairs[:, 1]], axis=1)], axis=0)
            return out[None], ovf[None]

        tspec, cspec = self._specs()
        mapped = jaxtools.shard_map(
            local, mesh=self.mesh,
            in_specs=(tspec, cspec, P(AXIS), P(AXIS), P(AXIS), P(),
                      P()),
            out_specs=(P(AXIS), P(AXIS)),
            check_vma=False)
        step = jaxtools.instrumented_jit(mapped,
                                         "parallel_join.probe")
        _STEP_CACHE[key] = step
        return step

    def _build_delete(self, bucket: int):
        key = _step_key(self.mesh, "delete", bucket, *self._statics())
        step = _STEP_CACHE.get(key)
        if step is not None:
            return step
        n_dev = self.n_dev

        def local(c, lanes, drefs, dmask, seq, owner_map):
            c = jax.tree.map(lambda a: a[0], c)
            _rl, (rdrefs,), rvalid, ovf = ShardedJoinKernel._route(
                owner_map, lanes, [drefs], dmask, n_dev, bucket)
            ch = tombstone_rows(c, rdrefs, rvalid, seq)
            return jax.tree.map(lambda a: a[None], ch), ovf[None]

        tspec, cspec = self._specs()
        mapped = jaxtools.shard_map(
            local, mesh=self.mesh,
            in_specs=(cspec, P(AXIS), P(AXIS), P(AXIS), P(), P()),
            out_specs=(cspec, P(AXIS)),
            check_vma=False)
        step = jaxtools.instrumented_jit(
            mapped, "parallel_join.delete", donate_argnums=(0,))
        _STEP_CACHE[key] = step
        return step

    def _build_insert(self, bucket: int):
        """Insert-only step (rebuild/insert): route+probe_insert+link."""
        key = _step_key(self.mesh, "insert", bucket, *self._statics())
        step = _STEP_CACHE.get(key)
        if step is not None:
            return step
        n_dev = self.n_dev
        cap = self.key_capacity

        def local(t, c, lanes, refs, vis, seq, owner_map):
            t = jax.tree.map(lambda a: a[0], t)
            c = jax.tree.map(lambda a: a[0], c)
            rlanes, (rrefs,), rvalid, ovf = ShardedJoinKernel._route(
                owner_map, lanes, [refs], vis, n_dev, bucket)
            t2, slots, _ins = ht.probe_insert(t, rlanes, rvalid)
            ch = link_rows(c, slots, rrefs, rvalid, cap, seq)
            return (jax.tree.map(lambda a: a[None], t2),
                    jax.tree.map(lambda a: a[None], ch), ovf[None])

        tspec, cspec = self._specs()
        mapped = jaxtools.shard_map(
            local, mesh=self.mesh,
            in_specs=(tspec, cspec, P(AXIS), P(AXIS), P(AXIS), P(),
                      P()),
            out_specs=(tspec, cspec, P(AXIS)),
            check_vma=False)
        step = jaxtools.instrumented_jit(
            mapped, "parallel_join.insert", donate_argnums=(0, 1))
        _STEP_CACHE[key] = step
        return step

    # -- epoch batching (ISSUE 10 tentpole) -------------------------------
    # One SPMD dispatch per side per epoch instead of one per chunk:
    # the executor concatenates every chunk of the epoch into the same
    # [key_lanes] + aux matrices the single-chip epoch path ships, and
    # the apply/probe steps below route the WHOLE epoch's rows to their
    # vnode owners in one all_to_all, then run the exact single-chip
    # kernels locally with PER-ROW sequences (sequence visibility makes
    # the batched application order-equivalent to per-chunk applies).
    # On the 4-virtual-device CPU mesh each shard_map host dispatch
    # costs ~100ms (BENCH_r09: the whole ad-ctr p99 tail) — this drops
    # the count by the chunks-per-epoch factor.

    def _guard_keys_blind(self, n_ins: int) -> None:
        """Conservative key guard when host key lanes are unavailable
        (fused raw uploads: lanes derive in-trace). Every insert could
        route to one shard; a false trip costs one exact-occupancy
        sync, never a false pass — same contract as _guard_keys."""
        if n_ins == 0:
            return
        self._keys_upper = self._keys_upper + n_ins
        limit = ht.MAX_LOAD * self.key_capacity
        if int(self._keys_upper.max()) <= limit:
            return
        per_shard = np.asarray(jnp.sum(self.table.occ, axis=1)) \
            .astype(np.int64)
        need = per_shard + n_ins
        self._keys_upper = need
        worst = int(need.max())
        if worst > limit:
            self._grow_keys(next_pow2(int(worst / ht.MAX_LOAD) + 1))

    def owners_of(self, key_lanes: np.ndarray) -> np.ndarray:
        """Host twin of the device routing, public (the executor
        computes per-epoch owner counts for the skew-exact bucket)."""
        return self._owners_host(np.asarray(key_lanes))

    def stage_epoch(self, up: np.ndarray, aux: np.ndarray, total: int,
                    max_ins_ref: int,
                    owners: Optional[np.ndarray] = None) -> tuple:
        """Host→device staging of one side's epoch batch: run the
        growth guards against the HOST matrices (the device steps are
        fixed-capacity programs), pad rows to a multiple of n_dev
        (pad rows carry flags=0 — routed nowhere, probed never), and
        upload row-sharded. Returns (up_dev, aux_dev, bucket) — the
        arrays feed BOTH this side's apply_epoch and the probe_epoch
        against the other side, exactly two uploads per side per
        epoch.

        ``owners`` (per-row owner shard, from owners_of) makes the
        routing bucket SKEW-EXACT instead of worst-case: the receive
        shape per shard is n_dev*bucket rows, and the default bucket
        (= local rows) has every shard process the WHOLE epoch — n_dev
        times the single-chip compute, which on the CPU virtual mesh
        (devices share one host) was the post-dispatch-tax half of the
        ad-ctr tail. With exact per-(sender, target) counts the bucket
        collapses to ~local/n_dev·(1+skew), pow2-quantized so steady
        state reuses a handful of compiled shapes. Overflow stays
        impossible: the bound is computed, not guessed."""
        n = up.shape[0]
        ins_mask = (aux[:, AUX_FLAGS] & FLAG_INS) != 0
        if up.dtype == np.int64:
            # fused raw matrix: key lanes only exist in-trace
            self._guard_keys_blind(int(ins_mask.sum()))
        else:
            self._guard_keys(up[:, :self.key_width], ins_mask)
        if max_ins_ref >= 0:
            self.reserve_rows(max_ins_ref)
        # mesh-width padding + the skew-exact routing bucket are epoch
        # staging (host_pack); the row-sharded upload below is h2d
        with LEDGER.phase("host_pack", kernel="sharded_join"):
            m = max(n, self.n_dev)
            if m % self.n_dev:
                m += self.n_dev - (m % self.n_dev)
            if m != n:
                up2 = np.zeros((m, up.shape[1]), dtype=up.dtype)
                up2[:n] = up
                aux2 = np.zeros((m, 4), dtype=np.int32)
                aux2[:n] = aux
                up, aux = up2, aux2
            local = m // self.n_dev
            bucket = local
            if owners is not None:
                ow = np.full(m, -1, dtype=np.int64)
                routed = aux[:total, AUX_FLAGS] != 0
                ow[:total][routed] = np.asarray(owners)[:total][routed]
                bucket = skew_bucket(ow, ow >= 0, self.n_dev, local)
        from risingwave_tpu.utils.ledger import note_backlog
        note_backlog("sharded_join", total)
        return (jaxtools.upload(up, self._sharding,
                                kernel="sharded_join"),
                jaxtools.upload(aux, self._sharding,
                                kernel="sharded_join"), bucket)

    def _prelude_for(self, prelude, prelude_key: str):
        """Pin the prelude under its key so cached steps stay valid
        (the step cache closes over the callable via the key)."""
        if prelude is not None:
            self._preludes[prelude_key] = prelude
        return self._preludes.get(prelude_key)

    def _build_epoch_apply(self, bucket: int, width: int, raw: bool,
                           prelude=None, prelude_key: str = ""):
        key = _step_key(self.mesh, "epoch_apply", bucket, width, raw,
                        prelude_key, *self._statics())
        step = _STEP_CACHE.get(key)
        if step is not None:
            return step
        n_dev = self.n_dev
        cap = self.key_capacity
        kw = self.key_width

        def local(t, c, up, aux, owner_map):
            t = jax.tree.map(lambda a: a[0], t)
            c = jax.tree.map(lambda a: a[0], c)
            # the prelude (ops/fused.build_join_prelude) traces the
            # absorbed filter/project run BEFORE vnode routing: the
            # raw local rows become key lanes here, inside the same
            # SPMD step that routes and applies them
            lanes = up[:, :kw] if prelude is None else \
                prelude(up)[:, :kw]
            flags = aux[:, AUX_FLAGS]
            valid = (flags & (FLAG_INS | FLAG_DEL)) != 0
            rlanes, (rins, rdel, rflags, rseq), rvalid, ovf = \
                ShardedJoinKernel._route(
                    owner_map, lanes,
                    [aux[:, AUX_INS_REF], aux[:, AUX_DEL_REF], flags,
                     aux[:, AUX_SEQ]],
                    valid, n_dev, bucket)
            rim = rvalid & ((rflags & FLAG_INS) != 0)
            rdm = rvalid & ((rflags & FLAG_DEL) != 0)
            t2, slots, _ins = ht.probe_insert(t, rlanes, rim)
            ch = link_rows(c, slots, rins, rim, cap, rseq)
            ch = tombstone_rows(ch, rdel, rdm, rseq)
            return (jax.tree.map(lambda a: a[None], t2),
                    jax.tree.map(lambda a: a[None], ch), ovf[None])

        tspec, cspec = self._specs()
        mapped = jaxtools.shard_map(
            local, mesh=self.mesh,
            in_specs=(tspec, cspec, P(AXIS), P(AXIS), P()),
            out_specs=(tspec, cspec, P(AXIS)),
            check_vma=False)
        step = jaxtools.instrumented_jit(
            mapped, "parallel_join.epoch_apply", donate_argnums=(0, 1))
        _STEP_CACHE[key] = step
        return step

    def apply_epoch(self, up_dev, aux_dev, n_rows: int,
                    max_ins_ref: int, prelude=None,
                    prelude_key: str = "", bucket=None) -> None:
        """Apply a whole epoch's concatenated inserts/tombstones in ONE
        SPMD dispatch (JoinSideKernel.apply_epoch parity; growth guards
        already ran in stage_epoch). Rows carry their message sequence
        in aux[:, AUX_SEQ]; link_rows/tombstone_rows take it per-row.
        ``bucket`` is stage_epoch's skew-exact routing bound (None →
        the overflow-free worst case)."""
        del n_rows, max_ins_ref       # guards ran at stage_epoch
        prelude = self._prelude_for(prelude, prelude_key)
        m = int(up_dev.shape[0])
        if bucket is None:
            bucket = m // self.n_dev
        step = self._build_epoch_apply(
            bucket, int(up_dev.shape[1]), up_dev.dtype == jnp.int64,
            prelude=prelude, prelude_key=prelude_key)
        _note_dispatch(m, "sharded_join")
        with LEDGER.phase("device_compute", kernel="sharded_join"):
            self.table, self.chains, ovf = step(
                self.table, self.chains, up_dev, aux_dev,
                self.owner_map)
        jaxtools.start_fetch(ovf)
        self._apply_overflows.append(ovf)

    def _build_epoch_probe(self, bucket: int, width: int,
                           out_cap: int, with_degrees: bool,
                           prelude=None, prelude_key: str = ""):
        key = _step_key(self.mesh, "epoch_probe", bucket, width,
                        out_cap, with_degrees, prelude_key,
                        *self._statics())
        step = _STEP_CACHE.get(key)
        if step is not None:
            return step
        n_dev = self.n_dev
        kw = self.key_width

        def local(t, c, up, aux, owner_map):
            t = jax.tree.map(lambda a: a[0], t)
            c = jax.tree.map(lambda a: a[0], c)
            lanes = up[:, :kw] if prelude is None else \
                prelude(up)[:, :kw]
            local_n = lanes.shape[0]
            # global epoch row ids: the executor slices results back
            # into per-chunk order by these (rows are row-sharded
            # before routing, so id = shard offset + local position)
            rowids = (jax.lax.axis_index(AXIS) * local_n
                      + jnp.arange(local_n, dtype=jnp.int32)) \
                .astype(jnp.int32)
            flags = aux[:, AUX_FLAGS]
            pvis = (flags & FLAG_PROBE) != 0
            rlanes, (rids, rseq), rvalid, ovf = \
                ShardedJoinKernel._route(
                    owner_map, lanes, [rowids, aux[:, AUX_SEQ]],
                    pvis, n_dev, bucket)
            m = n_dev * bucket
            mat = probe_pairs(t, c, rlanes, rvalid, rseq, out_cap,
                              with_degrees=with_degrees)
            if with_degrees:
                deg_blk = jnp.stack(
                    [mat[1:1 + m, 0],
                     jnp.where(rvalid, rids, jnp.int32(-1))], axis=1)
                pairs = mat[1 + m:]
            else:
                deg_blk = None
                pairs = mat[1:]
            safe = jnp.maximum(pairs[:, 0], 0)
            gprobe = jnp.where(pairs[:, 0] >= 0, rids[safe],
                               jnp.int32(-1))
            gpairs = jnp.stack([gprobe, pairs[:, 1]], axis=1)
            parts = [mat[:1], gpairs] if deg_blk is None else \
                [mat[:1], deg_blk, gpairs]
            return jnp.concatenate(parts, axis=0)[None], ovf[None]

        tspec, cspec = self._specs()
        mapped = jaxtools.shard_map(
            local, mesh=self.mesh,
            in_specs=(tspec, cspec, P(AXIS), P(AXIS), P()),
            out_specs=(P(AXIS), P(AXIS)),
            check_vma=False)
        step = jaxtools.instrumented_jit(
            mapped, "parallel_join.epoch_probe")
        _STEP_CACHE[key] = step
        return step

    def probe_epoch(self, up_dev, aux_dev, with_degrees: bool,
                    sink=None, prelude=None, prelude_key: str = "",
                    bucket=None) -> "ShardedPendingEpochProbe":
        """Probe a whole epoch's rows against THIS side — each row at
        its aux sequence — in one SPMD dispatch. `sink` is accepted for
        JoinSideKernel API parity and unused: the sharded path keeps
        degrees host-side (the executor's replay arrays), so the probe
        only RETURNS per-row degrees, it maintains no device store.
        ``bucket`` is the PROBING side's stage_epoch bound (the same
        rows route by the same keys)."""
        del sink
        prelude = self._prelude_for(prelude, prelude_key)
        m = int(up_dev.shape[0])
        if bucket is None:
            bucket = m // self.n_dev
        out_cap = self.probe_capacity
        width = int(up_dev.shape[1])

        def dispatch(cap):
            step = self._build_epoch_probe(
                bucket, width, cap, with_degrees,
                prelude=prelude, prelude_key=prelude_key)
            _note_dispatch(m, "sharded_join")
            with LEDGER.phase("device_compute",
                              kernel="sharded_join"):
                mats, ovf = step(self.table, self.chains, up_dev,
                                 aux_dev, self.owner_map)
            jaxtools.start_fetch(mats)
            return mats, ovf

        mats, ovf = dispatch(out_cap)
        return ShardedPendingEpochProbe(self, mats, m, out_cap,
                                        with_degrees, dispatch,
                                        overflow=ovf)

    def drain_overflows(self) -> None:
        """Fold in the lazily-checked apply-step overflow flags (the
        condition is impossible by construction — bucket = local rows
        — so this is an assertion, surfaced at the barrier)."""
        flags, self._apply_overflows = self._apply_overflows, []
        for f in flags:
            if bool(np.asarray(jaxtools.fetch1(f)).any()):
                raise RuntimeError(
                    "bucket overflow routing epoch join rows")

    # -- host API (JoinSideKernel parity) ---------------------------------
    def _pad(self, arrs, n: int):
        """Pad host arrays to a multiple of n_dev rows."""
        m = max(self.n_dev, n)
        if m % self.n_dev:
            m += self.n_dev - (m % self.n_dev)
        if m == n:
            return arrs, n
        out = []
        for a in arrs:
            a = np.asarray(a)
            pad_shape = (m - n,) + a.shape[1:]
            out.append(np.concatenate(
                [a, np.zeros(pad_shape, dtype=a.dtype)]))
        return out, m

    def apply_and_probe(self, other: "ShardedJoinKernel",
                        key_lanes: np.ndarray, probe_vis: np.ndarray,
                        ins_refs: np.ndarray, ins_mask: np.ndarray,
                        del_refs: np.ndarray, del_mask: np.ndarray,
                        seq: int) -> ShardedPendingProbe:
        """One fused dispatch per chunk (executor hot path). All args
        are HOST arrays — a device round-trip here would re-serialize
        the async pipeline this kernel exists to keep non-blocking."""
        key_lanes = np.asarray(key_lanes)
        n = int(key_lanes.shape[0])
        self._guard_keys(key_lanes, ins_mask)
        self._guard_refs(ins_refs, ins_mask)
        (lanes, rowids, refs, drefs, pv, im, dm), m = self._pad(
            [key_lanes, np.arange(n, dtype=np.int32),
             ins_refs.astype(np.int32), del_refs.astype(np.int32),
             probe_vis, ins_mask, del_mask], n)
        bucket = m // self.n_dev
        out_cap = other.probe_capacity
        step = self._build_apply_probe(bucket, out_cap)
        _note_dispatch(m, "sharded_join")
        with LEDGER.phase("device_compute", kernel="sharded_join"):
            self.table, self.chains, mats, overflow = step(
                self.table, self.chains, other.table, other.chains,
                jnp.asarray(lanes), jnp.asarray(rowids),
                jnp.asarray(refs), jnp.asarray(drefs),
                jnp.asarray(pv), jnp.asarray(im),
                jnp.asarray(dm), jnp.int32(seq), self.owner_map)
        jaxtools.start_fetch(mats)
        return ShardedPendingProbe(other, mats, lanes, pv, seq,
                                   out_cap, n, overflow=overflow)

    def _dispatch_probe(self, lanes: np.ndarray, vis: np.ndarray,
                        seq: int, out_cap: int):
        m = int(lanes.shape[0])
        bucket = m // self.n_dev
        step = self._build_probe_only(bucket, out_cap)
        _note_dispatch(m, "sharded_join")
        with LEDGER.phase("device_compute", kernel="sharded_join"):
            mats, overflow = step(self.table, self.chains,
                                  jnp.asarray(lanes),
                                  jnp.arange(m, dtype=jnp.int32),
                                  jnp.asarray(vis), jnp.int32(seq),
                                  self.owner_map)
        # overflow is impossible by construction (bucket = local rows)
        # but still checked lazily at collect — never synced here
        jaxtools.start_fetch(mats)
        return mats, overflow

    def probe_submit(self, key_lanes, vis,
                     seq: Optional[int] = None) -> ShardedPendingProbe:
        n = int(np.asarray(key_lanes).shape[0])
        s = I32_MAX if seq is None else seq
        (lanes, pv), _m = self._pad(
            [np.asarray(key_lanes), np.asarray(vis)], n)
        mats, overflow = self._dispatch_probe(lanes, pv, s,
                                              self.probe_capacity)
        return ShardedPendingProbe(self, mats, lanes, pv, s,
                                   self.probe_capacity, n,
                                   overflow=overflow)

    def probe(self, key_lanes, vis, seq: Optional[int] = None):
        return self.probe_submit(key_lanes, vis, seq).collect()

    def insert(self, key_lanes: np.ndarray, refs: np.ndarray,
               vis: np.ndarray, seq: int = 0) -> None:
        """Routed batch insert (recovery/rebuild; tests)."""
        key_lanes = np.asarray(key_lanes)
        vis = np.asarray(vis)
        n = int(key_lanes.shape[0])
        self._guard_keys(key_lanes, vis)
        self._guard_refs(np.asarray(refs), vis)
        (lanes, refs_, mask), m = self._pad(
            [key_lanes, np.asarray(refs, np.int32), vis], n)
        bucket = m // self.n_dev
        step = self._build_insert(bucket)
        _note_dispatch(m, "sharded_join")
        self.table, self.chains, overflow = step(
            self.table, self.chains, jnp.asarray(lanes),
            jnp.asarray(refs_), jnp.asarray(mask), jnp.int32(seq),
            self.owner_map)
        if bool(np.asarray(overflow).any()):
            raise RuntimeError("bucket overflow inserting join rows")

    def delete(self, del_refs: np.ndarray, vis,
               seq: int = 0, key_lanes=None) -> None:
        """Tombstone by ref. Sharded routing needs the refs' KEY lanes
        (the owner shard is a function of the key) — callers pass them
        (the single-chip kernel ignores its optional param)."""
        assert key_lanes is not None, \
            "sharded delete requires key_lanes for routing"
        vis = np.asarray(vis)
        n = int(np.asarray(key_lanes).shape[0])
        (lanes, drefs, dm), m = self._pad(
            [np.asarray(key_lanes), np.asarray(del_refs, np.int32),
             vis], n)
        bucket = m // self.n_dev
        step = self._build_delete(bucket)
        _note_dispatch(m, "sharded_join")
        self.chains, overflow = step(
            self.chains, jnp.asarray(lanes), jnp.asarray(drefs),
            jnp.asarray(dm), jnp.int32(seq), self.owner_map)
        if bool(np.asarray(overflow).any()):
            raise RuntimeError("bucket overflow routing join deletes")

    def rebase_seq(self) -> None:
        mx = jnp.int32(I32_MAX)
        self.chains = self.chains._replace(
            ins_seq=jnp.where(self.chains.ins_seq == mx, mx,
                              jnp.int32(0)),
            del_seq=jnp.where(self.chains.del_seq == mx, mx,
                              jnp.int32(0)))

    def rebuild(self, key_lanes: np.ndarray,
                row_refs: np.ndarray) -> None:
        """Reload all live rows (recovery/compaction): fresh sharded
        state + one routed batch insert at seq 0.

        Per-shard key capacity is sized to hold ALL n keys (worst-case
        skew: one shard owns every key) — a per-shard table that only
        fits n/n_dev keys would corrupt chains under adversarial key
        distributions, and the capacity guard compares a GLOBAL unique
        bound against the per-shard limit anyway."""
        n = len(row_refs)
        while n and int(np.max(row_refs)) >= self._row_capacity:
            self._row_capacity *= 2
        need_keys = ht.MIN_CAPACITY if n == 0 else 1 << int(np.ceil(
            np.log2(max(n / ht.MAX_LOAD, 1))))
        self.key_capacity = max(self.key_capacity, need_keys,
                                ht.MIN_CAPACITY)
        self._fresh_state()
        self._keys_upper = np.zeros(self.n_dev, dtype=np.int64)
        if n == 0:
            return
        self.insert(key_lanes, row_refs, np.ones(n, dtype=bool), seq=0)
