"""Multi-chip execution: vnode-sharded operators over a jax.sharding.Mesh.

Reference parity: the data-parallel axis of SURVEY §2.12 — the reference
routes rows by Crc32(dist key) → vnode → actor (dispatch.rs:582-690, one
gRPC exchange per edge). TPU-native re-design: vnodes map to mesh shards,
and the hash dispatch becomes an on-device bucketized ``all_to_all`` over
ICI inside ``shard_map`` — no host hops on the data plane.

    exchange     vnode bucketize + all_to_all (the DispatchExecutor core)
    agg          vnode-sharded grouped aggregation (multi-chip HashAgg)
"""

from risingwave_tpu.parallel.exchange import bucketize_by_owner
from risingwave_tpu.parallel.agg import ShardedAggKernel

__all__ = ["bucketize_by_owner", "ShardedAggKernel"]
