"""On-device hash dispatch: vnode bucketize + all_to_all.

Reference parity: DispatcherType::HASH (src/stream/src/executor/
dispatch.rs:582-690) — rows route by hash(dist key) → vnode → owner. The
reference serializes per-downstream chunks onto gRPC; here the exchange is
a single ``jax.lax.all_to_all`` over ICI: each shard bucketizes its rows
by target shard into a fixed [n_dev, bucket] send tensor, the collective
transposes it, and every shard receives exactly the rows it owns.

Static shapes (XLA contract): `bucket` bounds rows-per-target per step.
The default bucket (local row count) makes overflow impossible by
construction; a caller shrinking it trades bandwidth for a fatal-on-skew
contract — the overflow flag fires AFTER the step has applied the
surviving rows, so it is an assertion, not a retry point. All lanes are
int32 (ops/lanes.py rationale).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import next_pow2
from risingwave_tpu.common.hash import VNODE_COUNT
from risingwave_tpu.ops.hash_table import hash_key_lanes


def vnodes_from_lanes(key_lanes: jnp.ndarray) -> jnp.ndarray:
    """int32 vnode in [0, 256) from int32 key lanes (device twin of
    common.hash.vnodes_of for pre-split lanes)."""
    return (hash_key_lanes(key_lanes)
            & jnp.uint32(VNODE_COUNT - 1)).astype(jnp.int32)


def owners_host(key_lanes: np.ndarray,
                owner_map_host: np.ndarray) -> np.ndarray:
    """HOST twin of the device routing above (same hash → same owner)
    — the ONE copy both sharded kernels use for capacity guards and
    the skew-exact bucket; drifting from `vnodes_from_lanes` would
    silently break the overflow-impossible contract."""
    from risingwave_tpu.common.hash import hash_columns_host
    lanes = np.asarray(key_lanes)
    h = hash_columns_host([lanes[:, i] for i in range(lanes.shape[1])])
    return owner_map_host[
        (h & np.uint32(VNODE_COUNT - 1)).astype(np.int64)]


def skew_bucket(owner: np.ndarray, mask: np.ndarray, n_dev: int,
                local: int) -> int:
    """Skew-exact per-(sender, target) routing bound for one staged
    batch of n_dev*local row-sharded rows: the all_to_all receive
    shape is n_dev*bucket rows per shard, and the conservative
    default (bucket = local) makes every shard process the WHOLE
    batch — n_dev× the single-chip compute. Exact bincounts collapse
    it to the real skew; the result is pow2-quantized on a coarse
    3-step ladder (local/n_dev … local) so steady state reuses a
    handful of compiled shapes. Overflow stays impossible: the bound
    is computed, not guessed."""
    worst = 1
    for s in range(n_dev):
        sl = owner[s * local:(s + 1) * local]
        sl = sl[mask[s * local:(s + 1) * local]]
        if len(sl):
            worst = max(worst, int(np.bincount(
                sl, minlength=n_dev).max()))
    return min(local, max(local // n_dev, next_pow2(worst)))


def bucketize_by_owner(owner: jnp.ndarray, valid: jnp.ndarray,
                       payloads: Sequence[jnp.ndarray], n_dev: int,
                       bucket: int
                       ) -> Tuple[List[jnp.ndarray], jnp.ndarray,
                                  jnp.ndarray]:
    """Pack rows into per-target buckets for an all_to_all.

    owner: int32[N] target shard per row; valid: bool[N].
    payloads: arrays [N] or [N, K] to route alongside.
    Returns (bucketized payloads each [n_dev, bucket, ...],
             valid [n_dev, bucket], overflowed bool scalar).
    Row order within a bucket preserves input order (determinism).
    """
    n = owner.shape[0]
    onehot = (owner[:, None] == jnp.arange(n_dev, dtype=jnp.int32)[None, :]
              ) & valid[:, None]                          # [N, n_dev]
    pos_all = jnp.cumsum(onehot.astype(jnp.int32), axis=0) - 1
    row_pos = jnp.sum(jnp.where(onehot, pos_all, 0), axis=1)   # [N]
    fits = valid & (row_pos < bucket)
    dest = jnp.where(fits, owner * bucket + row_pos, n_dev * bucket)
    out = []
    for p in payloads:
        flat_shape = (n_dev * bucket,) + p.shape[1:]
        buf = jnp.zeros(flat_shape, dtype=p.dtype).at[dest].set(
            p, mode="drop")
        out.append(buf.reshape((n_dev, bucket) + p.shape[1:]))
    vbuf = jnp.zeros(n_dev * bucket, dtype=bool).at[dest].set(
        valid, mode="drop").reshape(n_dev, bucket)
    overflowed = jnp.any(valid & ~fits)
    return out, vbuf, overflowed


def exchange(bucketized: Sequence[jnp.ndarray], valid: jnp.ndarray,
             axis_name: str
             ) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
    """The ICI collective: transpose [n_dev, bucket, ...] buckets so
    shard i receives every shard's bucket-for-i (dispatch.rs's gRPC
    exchange as one all_to_all)."""
    out = [jax.lax.all_to_all(p, axis_name, split_axis=0, concat_axis=0)
           for p in bucketized]
    v = jax.lax.all_to_all(valid, axis_name, split_axis=0, concat_axis=0)
    return out, v
