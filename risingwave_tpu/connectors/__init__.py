"""Connectors: sources that feed pipelines and sinks that drain them.

Reference parity: src/connector/ (source framework src/connector/src/source/
base.rs:86,282) — here re-designed around vectorized chunk generation: a
split reader produces whole numpy/JAX column batches, never per-row Python
(SURVEY.md §7 hard part 6: 1M ev/s dies if ingest is row-bound).
"""

from risingwave_tpu.connectors.nexmark import (
    AUCTION_SCHEMA,
    BID_SCHEMA,
    PERSON_SCHEMA,
    NexmarkConfig,
    NexmarkSplitReader,
)

__all__ = [
    "AUCTION_SCHEMA",
    "BID_SCHEMA",
    "PERSON_SCHEMA",
    "NexmarkConfig",
    "NexmarkSplitReader",
]
