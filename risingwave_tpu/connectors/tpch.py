"""TPC-H table generators (streaming form): customer/orders/lineitem
plus the q5 dimension tables supplier/nation/region.

Reference parity: the role of the TPC-H corpus the reference streams in
e2e_test/streaming/tpch/ (tables loaded as append-only streams). The
generators are deterministic, whole-chunk vectorized, and replayable by
absolute offset (split recovery contract shared with nexmark/datagen).
Columns cover the streaming q3/q5 baseline shapes; scale is controlled
by row counts, not SF files — no external dbgen needed.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from risingwave_tpu.common.chunk import Column, Op, StreamChunk, next_pow2
from risingwave_tpu.common.types import (
    DataType, Field, Schema, decimal_to_scaled,
)

CUSTOMER_SCHEMA = Schema([
    Field("c_custkey", DataType.INT64),
    Field("c_name", DataType.VARCHAR),
    Field("c_mktsegment", DataType.VARCHAR),
    Field("c_nationkey", DataType.INT64),
])

ORDERS_SCHEMA = Schema([
    Field("o_orderkey", DataType.INT64),
    Field("o_custkey", DataType.INT64),
    Field("o_orderdate", DataType.DATE),
    Field("o_shippriority", DataType.INT32),
])

LINEITEM_SCHEMA = Schema([
    Field("l_orderkey", DataType.INT64),
    Field("l_extendedprice", DataType.DECIMAL),
    Field("l_discount", DataType.DECIMAL),
    Field("l_shipdate", DataType.DATE),
    Field("l_suppkey", DataType.INT64),
    Field("l_quantity", DataType.INT64),
    Field("l_tax", DataType.DECIMAL),
    Field("l_returnflag", DataType.VARCHAR),
    Field("l_linestatus", DataType.VARCHAR),
])

SUPPLIER_SCHEMA = Schema([
    Field("s_suppkey", DataType.INT64),
    Field("s_name", DataType.VARCHAR),
    Field("s_nationkey", DataType.INT64),
])

NATION_SCHEMA = Schema([
    Field("n_nationkey", DataType.INT64),
    Field("n_name", DataType.VARCHAR),
    Field("n_regionkey", DataType.INT64),
])

REGION_SCHEMA = Schema([
    Field("r_regionkey", DataType.INT64),
    Field("r_name", DataType.VARCHAR),
])

_RETURNFLAGS = np.array(["R", "A", "N"], dtype=object)
_LINESTATUS = np.array(["O", "F"], dtype=object)

# the 25 nations / 5 regions of the TPC-H spec (nation → region)
NATION_NAMES = np.array([
    "ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT", "ETHIOPIA",
    "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN", "IRAQ",
    "JAPAN", "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE", "PERU",
    "CHINA", "ROMANIA", "SAUDI ARABIA", "VIETNAM", "RUSSIA",
    "UNITED KINGDOM", "UNITED STATES"], dtype=object)
NATION_REGIONS = np.array([0, 1, 1, 1, 4, 0, 3, 3, 2, 2, 4, 4, 2, 4,
                           0, 0, 0, 1, 2, 3, 4, 2, 3, 3, 1],
                          dtype=np.int64)
REGION_NAMES = np.array(["AFRICA", "AMERICA", "ASIA", "EUROPE",
                         "MIDDLE EAST"], dtype=object)
SUPPLIERS = 100                     # matches l_suppkey ∈ 1..100

TABLE_SCHEMAS = {
    "customer": CUSTOMER_SCHEMA,
    "orders": ORDERS_SCHEMA,
    "lineitem": LINEITEM_SCHEMA,
    "supplier": SUPPLIER_SCHEMA,
    "nation": NATION_SCHEMA,
    "region": REGION_SCHEMA,
}

SEGMENTS = np.array(["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY",
                     "HOUSEHOLD"], dtype=object)

# date domain: 1992-01-01 .. 1998-08-02 as days-since-epoch int32
DATE_LO = 8035      # 1992-01-01
DATE_HI = 10440     # 1998-08-02
LINES_PER_ORDER = 4


@dataclass
class TpchConfig:
    table: str = "lineitem"
    customers: int = 1500           # SF0.01-ish proportions
    orders: int = 15000
    row_count: Optional[int] = None  # rows of THIS table to emit
    max_chunk_size: int = 1024
    seed: int = 0x7C9

    @property
    def total_rows(self) -> int:
        if self.row_count is not None:
            return self.row_count
        if self.table == "customer":
            return self.customers
        if self.table == "orders":
            return self.orders
        if self.table == "supplier":
            return SUPPLIERS
        if self.table == "nation":
            return len(NATION_NAMES)
        if self.table == "region":
            return len(REGION_NAMES)
        return self.orders * LINES_PER_ORDER


def _mix(k: np.ndarray, seed: int) -> np.ndarray:
    x = (k.astype(np.uint64)
         + np.uint64((seed * 0x9E3779B97F4A7C15) & (2**64 - 1)))
    with np.errstate(over="ignore"):
        x ^= x >> np.uint64(30)
        x *= np.uint64(0xBF58476D1CE4E5B9)
        x ^= x >> np.uint64(27)
        x *= np.uint64(0x94D049BB133111EB)
        x ^= x >> np.uint64(31)
    return x


def gen_customer(k: np.ndarray, cfg: TpchConfig) -> Dict[str, np.ndarray]:
    return {
        "c_custkey": k + 1,
        "c_name": np.array([f"Customer#{i + 1:09d}" for i in k.tolist()],
                           dtype=object),
        "c_mktsegment": SEGMENTS[
            (_mix(k, cfg.seed + 1) % 5).astype(np.int64)],
        "c_nationkey": (_mix(k, cfg.seed + 2) % 25).astype(np.int64),
    }


def gen_orders(k: np.ndarray, cfg: TpchConfig) -> Dict[str, np.ndarray]:
    return {
        "o_orderkey": k + 1,
        "o_custkey": (_mix(k, cfg.seed + 3)
                      % cfg.customers).astype(np.int64) + 1,
        "o_orderdate": (DATE_LO + _mix(k, cfg.seed + 4)
                        % (DATE_HI - DATE_LO)).astype(np.int32),
        "o_shippriority": np.zeros(len(k), dtype=np.int32),
    }


def gen_lineitem(k: np.ndarray, cfg: TpchConfig) -> Dict[str, np.ndarray]:
    order_k = k // LINES_PER_ORDER
    price_cents = (_mix(k, cfg.seed + 5) % 104949).astype(np.int64) + 10001
    discount_pct = (_mix(k, cfg.seed + 6) % 11).astype(np.int64)  # 0..0.10
    ship_delay = (_mix(k, cfg.seed + 7) % 122).astype(np.int64)
    odate = (DATE_LO + _mix(order_k, cfg.seed + 4)
             % (DATE_HI - DATE_LO)).astype(np.int64)
    return {
        "l_orderkey": order_k + 1,
        # DECIMAL physical = scaled int64 (4 frac digits)
        "l_extendedprice": price_cents * 100,     # cents → 4-digit scale
        "l_discount": discount_pct * 100,         # 0.00..0.10 scaled
        "l_shipdate": (odate + 1 + ship_delay).astype(np.int32),
        "l_suppkey": (_mix(k, cfg.seed + 8) % 100).astype(np.int64) + 1,
        "l_quantity": (_mix(k, cfg.seed + 9) % 50).astype(np.int64) + 1,
        "l_tax": (_mix(k, cfg.seed + 10) % 9).astype(np.int64) * 100,
        "l_returnflag": _RETURNFLAGS[
            (_mix(k, cfg.seed + 11) % 3).astype(np.int64)],
        "l_linestatus": _LINESTATUS[
            (_mix(k, cfg.seed + 12) % 2).astype(np.int64)],
    }


def gen_supplier(k: np.ndarray, cfg: TpchConfig) -> Dict[str, np.ndarray]:
    return {
        "s_suppkey": k + 1,
        "s_name": np.array([f"Supplier#{i + 1:09d}" for i in k.tolist()],
                           dtype=object),
        "s_nationkey": (_mix(k, cfg.seed + 13) % 25).astype(np.int64),
    }


def gen_nation(k: np.ndarray, cfg: TpchConfig) -> Dict[str, np.ndarray]:
    return {
        "n_nationkey": k.astype(np.int64),
        "n_name": NATION_NAMES[k],
        "n_regionkey": NATION_REGIONS[k],
    }


def gen_region(k: np.ndarray, cfg: TpchConfig) -> Dict[str, np.ndarray]:
    return {
        "r_regionkey": k.astype(np.int64),
        "r_name": REGION_NAMES[k],
    }


_GENERATORS = {"customer": gen_customer, "orders": gen_orders,
               "lineitem": gen_lineitem, "supplier": gen_supplier,
               "nation": gen_nation, "region": gen_region}


class TpchSplitReader:
    """Replayable split reader (SplitReader protocol)."""

    def __init__(self, cfg: TpchConfig, offset: int = 0):
        assert cfg.table in _GENERATORS, cfg.table
        self.cfg = cfg
        self.schema = TABLE_SCHEMAS[cfg.table]
        self.split_id = f"tpch-{cfg.table}-0"
        self.offset = offset

    def seek(self, offset: int) -> None:
        self.offset = offset

    def next_chunk(self) -> Optional[StreamChunk]:
        n = min(self.cfg.max_chunk_size,
                self.cfg.total_rows - self.offset)
        if n <= 0:
            return None
        k = np.arange(self.offset, self.offset + n, dtype=np.int64)
        self.offset += n
        data = _GENERATORS[self.cfg.table](k, self.cfg)
        cap = next_pow2(n)
        cols = []
        for f in self.schema:
            arr = data[f.name]
            if f.data_type.is_device:
                full = np.zeros(cap, dtype=f.data_type.np_dtype)
            else:
                full = np.empty(cap, dtype=object)
            full[:n] = arr
            cols.append(Column(f.data_type, full, None))
        vis = np.zeros(cap, dtype=bool)
        vis[:n] = True
        ops = np.full(cap, int(Op.INSERT), dtype=np.int8)
        return StreamChunk(self.schema, cols, vis, ops)
