"""File-log source: Kafka-shaped ingestion from append-only log files.

Reference parity: the Kafka source family
(src/connector/src/source/kafka/ — enumerator.rs lists partitions,
source/reader.rs consumes one partition from an offset). The external
system here is a DIRECTORY of append-only partition files
``<topic>-<partition>.log`` (newline-delimited records) — the same
protocol shape without a broker: partitions are discovered by the
enumerator, each split tails one file from a BYTE offset, and the
offset is the exact recovery cursor (a restarted reader re-emits
precisely the suffix the last checkpoint had not committed).
Producers append records (optionally fsync) with any tool — the
framework finally ingests bytes it did not generate itself.

SQL surface::

    CREATE SOURCE t (a INT, b VARCHAR)
    WITH (connector='filelog', path='/data/logs', topic='t',
          format='json')
"""

from __future__ import annotations

import os
import re
from typing import List, Optional

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.common.types import Schema
from risingwave_tpu.connectors.base import SourceSplit, SplitEnumerator
from risingwave_tpu.connectors.parser import RowParser, make_parser

_PART_RE = re.compile(r"^(?P<topic>.+)-(?P<part>\d+)\.log$")


def partition_path(path: str, topic: str, partition: int) -> str:
    return os.path.join(path, f"{topic}-{partition}.log")


class FileLogEnumerator(SplitEnumerator):
    """Lists ``<topic>-<N>.log`` partition files (enumerator.rs)."""

    def __init__(self, path: str, topic: str):
        self.path = path
        self.topic = topic

    def list_splits(self) -> List[SourceSplit]:
        out = []
        try:
            names = sorted(os.listdir(self.path))
        except FileNotFoundError:
            return []
        for name in names:
            m = _PART_RE.match(name)
            if m and m.group("topic") == self.topic:
                out.append(SourceSplit(
                    split_id=f"filelog-{self.topic}-"
                             f"{int(m.group('part'))}"))
        return out


class FileLogSplitReader:
    """Tails one partition file from a byte offset (SplitReader).

    The offset is the BYTE position after the last fully-consumed
    record — torn trailing writes (no newline yet) stay unconsumed
    until the producer completes them, so a record is never half-read.
    """

    # log sources never finish: None from next_chunk means "idle",
    # not "exhausted" (SourceExecutor parks on the barrier channel)
    unbounded = True

    def __init__(self, path: str, topic: str, partition: int,
                 schema: Schema, fmt: str = "json",
                 max_chunk_size: int = 1024, offset: int = 0,
                 options=None):
        self.path = path
        self.topic = topic
        self.partition = partition
        self.schema = schema
        self.parser: RowParser = make_parser(fmt, schema, options)
        self.max_chunk_size = int(max_chunk_size)
        self.offset = int(offset)

    @property
    def split_id(self) -> str:
        return f"filelog-{self.topic}-{self.partition}"

    @property
    def file_path(self) -> str:
        return partition_path(self.path, self.topic, self.partition)

    def seek(self, offset: int) -> None:
        self.offset = int(offset)

    def next_chunk(self) -> Optional[StreamChunk]:
        """Read up to max_chunk_size complete records from the offset.

        Returns None when no complete record is available (the stream
        idles until the producer appends more — unlike the bounded
        generators, a log source never 'finishes')."""
        try:
            with open(self.file_path, "rb") as f:
                f.seek(self.offset)
                payloads: List[bytes] = []
                consumed = 0
                while len(payloads) < self.max_chunk_size:
                    line = f.readline()
                    if not line.endswith(b"\n"):
                        break              # EOF or torn trailing write
                    consumed += len(line)
                    rec = line.rstrip(b"\r\n")
                    if rec:
                        payloads.append(rec)
        except FileNotFoundError:
            return None
        if not payloads:
            return None
        chunk = self.parser.build_chunk(payloads)
        # advance past malformed records too (they are counted by the
        # parser) — re-reading them forever would wedge the split
        self.offset += consumed
        return chunk
