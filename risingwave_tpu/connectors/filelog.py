"""File-log source: Kafka-shaped ingestion from append-only log files.

Reference parity: the Kafka source family
(src/connector/src/source/kafka/ — enumerator.rs lists partitions,
source/reader.rs consumes one partition from an offset). The external
system here is a DIRECTORY of append-only partition files
``<topic>-<partition>.log`` (newline-delimited records) — the same
protocol shape without a broker: partitions are discovered by the
enumerator, each split tails one file from a BYTE offset, and the
offset is the exact recovery cursor (a restarted reader re-emits
precisely the suffix the last checkpoint had not committed).
Producers append records (optionally fsync) with any tool — the
framework finally ingests bytes it did not generate itself.

SQL surface::

    CREATE SOURCE t (a INT, b VARCHAR)
    WITH (connector='filelog', path='/data/logs', topic='t',
          format='json')
"""

from __future__ import annotations

import os
import re
from typing import List, Optional

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.common.types import Schema
from risingwave_tpu.connectors.base import SourceSplit, SplitEnumerator
from risingwave_tpu.connectors.parser import RowParser, make_parser

_PART_RE = re.compile(r"^(?P<topic>.+)-(?P<part>\d+)\.log$")


# block size for the bulk read path: big enough that a typical chunk's
# records arrive in one read, small enough that an over-read past the
# line limit stays cheap (the tail re-seeks by returned `consumed`)
_READ_BLOCK = 1 << 20


def _read_complete_records(f, payloads: List[bytes],
                           limit: int) -> int:
    """Append up to `limit` COMPLETE newline-terminated records from an
    open file handle; returns bytes consumed. A trailing line without
    its newline is a torn write (or segment end) and stays unconsumed —
    the one 'complete record' protocol both readers share.

    Reads in blocks and splits at C speed (ISSUE 12): the old
    readline-per-record loop was ~1s of the ad-ctr ingest profile at
    200K records. Callers advance their offset by the returned byte
    count, so over-reading past `limit` lines costs nothing — the
    unconsumed suffix is simply not counted."""
    consumed = 0
    pending = b""
    while len(payloads) < limit:
        block = f.read(_READ_BLOCK)
        if not block:
            break
        data = pending + block
        # only COMPLETE lines: the suffix after the last newline is
        # torn (or mid-write) and carries over / stays unconsumed
        cut = data.rfind(b"\n")
        if cut < 0:
            pending = data
            continue
        lines = data[:cut].split(b"\n")
        rest = limit - len(payloads)
        if len(lines) > rest:
            lines = lines[:rest]
            # consumed bytes = the kept lines + their newlines (any
            # carried-over pending prefix is part of the first line)
            consumed += sum(map(len, lines)) + len(lines)
            payloads.extend(
                ln.rstrip(b"\r") for ln in lines if ln.rstrip(b"\r"))
            return consumed
        consumed += cut + 1          # includes the pending prefix
        # the partial line past the last newline carries into the
        # next block — dropping it would corrupt any record that
        # straddles a read-block boundary
        pending = data[cut + 1:]
        payloads.extend(
            ln.rstrip(b"\r") for ln in lines if ln.rstrip(b"\r"))
    return consumed



def partition_path(path: str, topic: str, partition: int) -> str:
    return os.path.join(path, f"{topic}-{partition}.log")


class FileLogEnumerator(SplitEnumerator):
    """Lists ``<topic>-<N>.log`` partition files (enumerator.rs)."""

    def __init__(self, path: str, topic: str):
        self.path = path
        self.topic = topic

    def list_splits(self) -> List[SourceSplit]:
        out = []
        try:
            names = sorted(os.listdir(self.path))
        except FileNotFoundError:
            return []
        for name in names:
            m = _PART_RE.match(name)
            if m and m.group("topic") == self.topic:
                out.append(SourceSplit(
                    split_id=f"filelog-{self.topic}-"
                             f"{int(m.group('part'))}"))
        return out


class FileLogSplitReader:
    """Tails one partition file from a byte offset (SplitReader).

    The offset is the BYTE position after the last fully-consumed
    record — torn trailing writes (no newline yet) stay unconsumed
    until the producer completes them, so a record is never half-read.
    """

    # log sources never finish: None from next_chunk means "idle",
    # not "exhausted" (SourceExecutor parks on the barrier channel)
    unbounded = True

    def __init__(self, path: str, topic: str, partition: int,
                 schema: Schema, fmt: str = "json",
                 max_chunk_size: int = 1024, offset: int = 0,
                 options=None):
        self.path = path
        self.topic = topic
        self.partition = partition
        self.schema = schema
        self.parser: RowParser = make_parser(fmt, schema, options)
        self.max_chunk_size = int(max_chunk_size)
        self.offset = int(offset)
        # exact emitted-row counter: the offset is BYTES (the recovery
        # cursor); throughput accounting needs rows
        self.rows_read = 0

    @property
    def split_id(self) -> str:
        return f"filelog-{self.topic}-{self.partition}"

    @property
    def file_path(self) -> str:
        return partition_path(self.path, self.topic, self.partition)

    def seek(self, offset: int) -> None:
        self.offset = int(offset)

    def next_chunk(self) -> Optional[StreamChunk]:
        """Read up to max_chunk_size complete records from the offset.

        Returns None when no complete record is available (the stream
        idles until the producer appends more — unlike the bounded
        generators, a log source never 'finishes')."""
        try:
            with open(self.file_path, "rb") as f:
                f.seek(self.offset)
                payloads: List[bytes] = []
                consumed = _read_complete_records(
                    f, payloads, self.max_chunk_size)
        except FileNotFoundError:
            return None
        if not payloads:
            return None
        chunk = self.parser.build_chunk(payloads)
        # advance past malformed records too (they are counted by the
        # parser) — re-reading them forever would wedge the split
        self.offset += consumed
        self.rows_read += chunk.cardinality()
        return chunk


class FileLogMultiReader:
    """One source actor driving SEVERAL partition splits (the split-
    rebalancing contract, ISSUE 15): the scheduler assigns each source
    actor a partition subset and stamps it into the shipped plan; this
    reader round-robins over per-partition ``FileLogSplitReader``s so
    no split starves, and exposes the per-split byte offsets —
    ``splits()`` / ``seek_split()`` — that the SourceExecutor persists
    one row per split. On rescale, each split's offset row migrates to
    its new owner's namespace and the new reader resumes from exactly
    that byte: no record lost, none re-read.

    An EMPTY partition set is legal (scale-out past the partition
    count): the reader idles forever and the actor just forwards
    barriers."""

    unbounded = True

    def __init__(self, path: str, topic: str, partitions,
                 schema: Schema, fmt: str = "json",
                 max_chunk_size: int = 1024, options=None):
        self.path = path
        self.topic = topic
        self.partitions = [int(p) for p in partitions]
        self.schema = schema
        self.readers = [FileLogSplitReader(
            path, topic, p, schema, fmt=fmt,
            max_chunk_size=max_chunk_size, options=options)
            for p in self.partitions]
        self._rr = 0

    @property
    def split_id(self) -> str:
        parts = "+".join(str(p) for p in self.partitions) or "none"
        return f"filelog-{self.topic}-p{parts}"

    @property
    def offset(self) -> int:
        """Aggregate byte position (throughput accounting only — the
        recovery cursors are the PER-SPLIT offsets)."""
        return sum(r.offset for r in self.readers)

    @property
    def rows_read(self) -> int:
        return sum(r.rows_read for r in self.readers)

    # -- the per-split offset contract ---------------------------------
    def splits(self) -> List[tuple]:
        """[(split_id, byte offset)] — one durable row per split."""
        return [(r.split_id, r.offset) for r in self.readers]

    def seek_split(self, split_id: str, offset: int) -> None:
        for r in self.readers:
            if r.split_id == split_id:
                r.seek(offset)
                return

    def seek(self, offset: int) -> None:
        """Aggregate seek is meaningless across splits — recovery goes
        through ``seek_split`` (SourceExecutor's multi-split path); a
        fresh deployment starts every split at 0 anyway."""

    def next_chunk(self) -> Optional[StreamChunk]:
        """Round-robin the splits, starting after the last producer so
        a hot partition cannot starve its siblings."""
        n = len(self.readers)
        for i in range(n):
            r = self.readers[(self._rr + i) % n]
            chunk = r.next_chunk()
            if chunk is not None:
                self._rr = (self._rr + i + 1) % n
                return chunk
        return None


def segment_path(path: str, topic: str, partition: int,
                 start: int) -> str:
    """Segment file for the records beginning at STREAM POSITION
    `start` (record index since topic birth). Position-named segments
    are monotone by construction — epoch numbers are not stable
    across recovery, so naming by epoch would let a post-crash
    segment sort before an orphaned pre-crash one."""
    return os.path.join(path, f"{topic}-{partition}.seg-{start:016x}.log")


def list_segments(path: str, topic: str, partition: int):
    """Committed segment files in stream order (immutable once named:
    the sink publishes each batch by atomic rename; names are the
    zero-padded start position, so lexicographic = stream order)."""
    pre = f"{topic}-{partition}.seg-"
    try:
        names = [n for n in os.listdir(path)
                 if n.startswith(pre) and n.endswith(".log")]
    except FileNotFoundError:
        return []
    return sorted(os.path.join(path, n) for n in names)


class SegmentedFileLogReader:
    """SplitReader over a SEGMENTED topic (one immutable file per
    committed epoch — the exactly-once sink's output). The offset is
    the cumulative byte position across segments in epoch order;
    segments never mutate after publication, so the mapping is stable
    across restarts and new segments only extend it."""

    unbounded = True

    def __init__(self, path: str, topic: str, partition: int,
                 schema: Schema, fmt: str = "json",
                 max_chunk_size: int = 1024, offset: int = 0,
                 options=None):
        self.path = path
        self.topic = topic
        self.partition = partition
        self.schema = schema
        self.parser: RowParser = make_parser(fmt, schema, options)
        self.max_chunk_size = int(max_chunk_size)
        self.offset = int(offset)
        # cached (path, size, cum_end) — segments are IMMUTABLE after
        # publication, so sizes and cumulative offsets never change;
        # the directory is re-listed only when the cached tail is
        # exhausted (O(new segments) per poll, not O(all segments))
        self._segs: List[tuple] = []

    @property
    def split_id(self) -> str:
        return f"filelog-seg-{self.topic}-{self.partition}"

    def seek(self, offset: int) -> None:
        self.offset = int(offset)

    def _refresh_segments(self) -> None:
        known = {p for p, _sz, _cum in self._segs}
        cum = self._segs[-1][2] if self._segs else 0
        for seg in list_segments(self.path, self.topic,
                                 self.partition):
            if seg in known:
                continue
            size = os.path.getsize(seg)
            cum += size
            self._segs.append((seg, size, cum))

    def next_chunk(self) -> Optional[StreamChunk]:
        if not self._segs or self.offset >= self._segs[-1][2]:
            self._refresh_segments()
        payloads: List[bytes] = []
        consumed = 0
        # binary search the segment holding the current offset
        import bisect
        ends = [cum for _p, _sz, cum in self._segs]
        at = bisect.bisect_right(ends, self.offset)
        for seg, size, cum_end in self._segs[at:]:
            with open(seg, "rb") as f:
                f.seek(self.offset + consumed - (cum_end - size))
                consumed += _read_complete_records(
                    f, payloads, self.max_chunk_size)
            if len(payloads) >= self.max_chunk_size:
                break
        if not payloads:
            return None
        chunk = self.parser.build_chunk(payloads)
        self.offset += consumed
        return chunk
