"""Source-connector framework: splits, enumerators, readers, formats.

Reference parity: src/connector/src/source/base.rs — SplitEnumerator
(:86, discovers the current split set of an external system) and
SplitReader (:282, consumes one split from a seekable offset). The
in-tree generators (nexmark/datagen/tpch) already satisfy the READER
shape structurally (split_id / offset / seek / next_chunk / schema);
this module gives the contract a name, adds the enumerator half, and
defines the parser seam (src/connector/src/parser/) that turns
external BYTES into typed StreamChunks — the boundary where data the
system did not generate itself enters the dataflow.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import List, Optional, Protocol, runtime_checkable

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.common.types import Schema


@dataclass(frozen=True)
class SourceSplit:
    """One unit of parallel consumption (base.rs SplitMetaData)."""

    split_id: str
    # connector-specific restart position for a FRESH reader; a
    # recovered reader seeks to its persisted offset instead
    start_offset: int = 0


class SplitEnumerator(abc.ABC):
    """Discovers splits (base.rs:86). Called at CREATE SOURCE and by
    future split-rebalance ticks."""

    @abc.abstractmethod
    def list_splits(self) -> List[SourceSplit]:
        ...


@runtime_checkable
class SplitReader(Protocol):
    """The reader contract every source implements (base.rs:282).

    offset is the EXACT recovery cursor: after seek(offset) the reader
    re-emits precisely the rows that were not yet offset-committed —
    with the source executor's split-state persistence this yields
    exactly-once ingestion into MVs.
    """

    schema: Schema
    offset: int

    @property
    def split_id(self) -> str: ...

    def seek(self, offset: int) -> None: ...

    def next_chunk(self) -> Optional[StreamChunk]: ...
