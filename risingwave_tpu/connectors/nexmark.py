"""Nexmark in-process event generator, fully vectorized.

Reference parity: src/connector/src/source/nexmark/mod.rs:31 (properties:
event.num, table.type, max.chunk.size, min.event.gap.in.ns, hot ratios,
active people / in-flight auctions) and the upstream `nexmark` crate's
generator semantics: a single global event sequence interleaving
1 person : 3 auctions : 46 bids per 50 events, with hot-key skew on
sellers/auctions/bidders and event-time pacing.

TPU re-design (NOT a port of the per-event generator loop): events are a
*pure function of the event index*. A counter-based RNG (splitmix64 over the
index) lets us materialize any range of events as whole numpy columns in one
vectorized pass — no generator state, no per-row Python, trivially split by
striding the index space. That is what feeds a 1M ev/s device pipeline and
it makes every split reader deterministic and seekable by construction
(offset = event index, recovery is `seek(offset)`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

from risingwave_tpu.common.chunk import StreamChunk, next_pow2
from risingwave_tpu.common.types import DataType, Field, Schema

# Standard Nexmark interleave: out of every 50 events, 1 person then
# 3 auctions then 46 bids (nexmark crate config.rs PROPORTION constants).
PERSON_PROPORTION = 1
AUCTION_PROPORTION = 3
BID_PROPORTION = 46
PROPORTION_DENOMINATOR = 50

FIRST_PERSON_ID = 1000
FIRST_AUCTION_ID = 1000
FIRST_CATEGORY_ID = 10

# Event-time origin: 2015-07-15 00:00:00 UTC in ms, like the nexmark crate.
BASE_TIME_MS = 1_436_918_400_000


BID_SCHEMA = Schema([
    Field("auction", DataType.INT64),
    Field("bidder", DataType.INT64),
    Field("price", DataType.INT64),          # cents
    Field("channel", DataType.VARCHAR),
    Field("url", DataType.VARCHAR),
    Field("date_time", DataType.TIMESTAMP),  # µs
    Field("extra", DataType.VARCHAR),
])

AUCTION_SCHEMA = Schema([
    Field("id", DataType.INT64),
    Field("item_name", DataType.VARCHAR),
    Field("description", DataType.VARCHAR),
    Field("initial_bid", DataType.INT64),
    Field("reserve", DataType.INT64),
    Field("date_time", DataType.TIMESTAMP),
    Field("expires", DataType.TIMESTAMP),
    Field("seller", DataType.INT64),
    Field("category", DataType.INT64),
    Field("extra", DataType.VARCHAR),
])

PERSON_SCHEMA = Schema([
    Field("id", DataType.INT64),
    Field("name", DataType.VARCHAR),
    Field("email_address", DataType.VARCHAR),
    Field("credit_card", DataType.VARCHAR),
    Field("city", DataType.VARCHAR),
    Field("state", DataType.VARCHAR),
    Field("date_time", DataType.TIMESTAMP),
    Field("extra", DataType.VARCHAR),
])

TABLE_SCHEMAS = {
    "bid": BID_SCHEMA,
    "auction": AUCTION_SCHEMA,
    "person": PERSON_SCHEMA,
}


@dataclass
class NexmarkConfig:
    """Knobs mirroring nexmark.* source properties (mod.rs:31)."""

    event_num: int = 1 << 62           # effectively unbounded
    max_chunk_size: int = 1024
    table_type: str = "bid"            # bid | auction | person
    min_event_gap_in_ns: int = 100_000  # event-time pacing: 10K ev/s default
    active_people: int = 1000
    in_flight_auctions: int = 100
    hot_seller_ratio: int = 4
    hot_auction_ratio: int = 2
    hot_bidder_ratio: int = 4
    num_categories: int = 5
    seed: int = 0x5EED0                # deterministic stream identity
    generate_strings: bool = True       # False: constant-pool-only varchar


# -- counter-based RNG ------------------------------------------------------

_SM_GAMMA = np.uint64(0x9E3779B97F4A7C15)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer: uint64 counter → uint64 random bits."""
    with np.errstate(over="ignore"):
        z = (x + _SM_GAMMA) * np.uint64(1)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


def _rng_u64(idx: np.ndarray, stream: int, seed: int) -> np.ndarray:
    """Independent random stream per (event index, stream id)."""
    with np.errstate(over="ignore"):
        x = idx.astype(np.uint64) * np.uint64(PROPORTION_DENOMINATOR + 7) \
            + np.uint64(stream) + (np.uint64(seed) << np.uint64(20))
    return _splitmix64(x)


def _uniform(idx: np.ndarray, stream: int, seed: int) -> np.ndarray:
    """float64 uniform [0, 1) per event."""
    return (_rng_u64(idx, stream, seed) >> np.uint64(11)).astype(
        np.float64) / float(1 << 53)


# -- id bookkeeping (pure functions of the global event index) --------------


def _epoch_offset(event_idx: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    return (event_idx // PROPORTION_DENOMINATOR,
            event_idx % PROPORTION_DENOMINATOR)


def _max_person_base0(event_idx: np.ndarray) -> np.ndarray:
    """Highest base-0 person id that exists as of this event (inclusive)."""
    ep, off = _epoch_offset(event_idx)
    return ep * PERSON_PROPORTION + np.minimum(off, PERSON_PROPORTION - 1)


def _max_auction_base0(event_idx: np.ndarray) -> np.ndarray:
    """Highest base-0 auction id that exists as of this event (inclusive)."""
    ep, off = _epoch_offset(event_idx)
    return (ep * AUCTION_PROPORTION
            + np.clip(off - PERSON_PROPORTION, 0, AUCTION_PROPORTION - 1))


def _event_timestamp_us(event_idx: np.ndarray,
                        cfg: NexmarkConfig) -> np.ndarray:
    ns = event_idx.astype(np.int64) * np.int64(cfg.min_event_gap_in_ns)
    return np.int64(BASE_TIME_MS) * 1000 + ns // 1000


# nth event of a type → global event index (closed forms, no filtering)


def person_event_index(k: np.ndarray) -> np.ndarray:
    return (k // PERSON_PROPORTION) * PROPORTION_DENOMINATOR \
        + k % PERSON_PROPORTION


def auction_event_index(k: np.ndarray) -> np.ndarray:
    return (k // AUCTION_PROPORTION) * PROPORTION_DENOMINATOR \
        + PERSON_PROPORTION + k % AUCTION_PROPORTION


def bid_event_index(k: np.ndarray) -> np.ndarray:
    return (k // BID_PROPORTION) * PROPORTION_DENOMINATOR \
        + PERSON_PROPORTION + AUCTION_PROPORTION + k % BID_PROPORTION


# -- string pools (fancy-indexed: vectorized varchar generation) ------------

_CHANNELS = np.asarray(["Google", "Facebook", "Baidu", "Apple"], dtype=object)
_FIRST_NAMES = np.asarray(
    ["Peter", "Paul", "Luke", "John", "Saul", "Vicky", "Kate", "Julie",
     "Sarah", "Deiter", "Walter"], dtype=object)
_LAST_NAMES = np.asarray(
    ["Shultz", "Abrams", "Spencer", "White", "Bartels", "Walton", "Smith",
     "Jones", "Noris"], dtype=object)
_CITIES = np.asarray(
    ["Phoenix", "Los Angeles", "San Francisco", "Boise", "Portland",
     "Bend", "Redmond", "Seattle", "Kent", "Cheyenne"], dtype=object)
_STATES = np.asarray(["AZ", "CA", "ID", "OR", "WA", "WY"], dtype=object)
_ITEMS = np.asarray(
    ["toaster", "chair", "sofa", "bicycle", "kettle", "lamp", "drill",
     "camera", "guitar", "skates"], dtype=object)


def _pool_pick(pool: np.ndarray, u: np.ndarray) -> np.ndarray:
    # uint64 fancy indexing is legal: skip the int64 astype temporary
    return pool[u % np.uint64(len(pool))]


def _prefixed_int_str(prefix: str, vals: np.ndarray) -> np.ndarray:
    """``prefix + str(v)`` per row, built once per DISTINCT value
    through the unique pool (the bid-url shape: a constant prefix
    over a bounded id window). Nexmark numeric string columns draw
    from bounded windows (in-flight auctions, active people), so a 4K
    chunk holds a few hundred uniques at most — the per-row
    str()/np.char fixed-width materializations this replaces were the
    dominant generator cost (the r11 q1 host_ingest residual)."""
    uniq, inv = np.unique(vals, return_inverse=True)
    pool = np.array([prefix + str(v) for v in uniq.tolist()],
                    dtype=object)
    return pool[inv]


# -- column generators ------------------------------------------------------


def gen_bids(k: np.ndarray, cfg: NexmarkConfig) -> Dict[str, np.ndarray]:
    """k: bid ordinals (int64). Returns named columns, all vectorized."""
    idx = bid_event_index(k)
    s = cfg.seed
    max_auction = _max_auction_base0(idx)
    max_person = _max_person_base0(idx)

    # auction choice: hot auction with prob 1-1/ratio, else uniform over the
    # last `in_flight_auctions` (nexmark NUM_IN_FLIGHT_AUCTIONS analog)
    hot_a = _uniform(idx, 1, s) < 1.0 - 1.0 / max(cfg.hot_auction_ratio, 1)
    hot_auction = (max_auction // cfg.in_flight_auctions) \
        * cfg.in_flight_auctions
    window_a = np.minimum(max_auction + 1, cfg.in_flight_auctions)
    cold_auction = max_auction - (
        _rng_u64(idx, 2, s) % window_a.astype(np.uint64)).astype(np.int64)
    auction = np.where(hot_a, hot_auction, cold_auction) + FIRST_AUCTION_ID

    # bidder choice: hot bidder, else uniform over last `active_people`
    hot_b = _uniform(idx, 3, s) < 1.0 - 1.0 / max(cfg.hot_bidder_ratio, 1)
    hot_bidder = (max_person // cfg.active_people) * cfg.active_people + 1
    window_p = np.minimum(max_person + 1, cfg.active_people)
    cold_bidder = max_person - (
        _rng_u64(idx, 4, s) % window_p.astype(np.uint64)).astype(np.int64)
    bidder = np.where(hot_b, np.minimum(hot_bidder, max_person),
                      cold_bidder) + FIRST_PERSON_ID

    # price: lognormal-ish cents in [1, 10^8) — 10^(u*6)*100
    price = np.maximum(
        1, (np.power(10.0, _uniform(idx, 5, s) * 6.0) * 100.0)).astype(
        np.int64)

    out: Dict[str, np.ndarray] = {
        "auction": auction,
        "bidder": bidder,
        "price": price,
        "date_time": _event_timestamp_us(idx, cfg),
    }
    if cfg.generate_strings:
        out["channel"] = _pool_pick(_CHANNELS, _rng_u64(idx, 6, s))
        out["url"] = _prefixed_int_str(
            "https://www.nexmark.com/item.htm?query=1&id=", auction)
        out["extra"] = _pool_pick(_CITIES, _rng_u64(idx, 7, s))
    else:
        const = np.full(len(k), "", dtype=object)
        out["channel"] = _pool_pick(_CHANNELS, _rng_u64(idx, 6, s))
        out["url"] = const
        out["extra"] = const
    return out


def gen_auctions(k: np.ndarray, cfg: NexmarkConfig) -> Dict[str, np.ndarray]:
    idx = auction_event_index(k)
    s = cfg.seed
    auction_id = k + FIRST_AUCTION_ID
    max_person = _max_person_base0(idx)

    # seller: hot seller (recent person) with prob 1-1/ratio else uniform
    hot = _uniform(idx, 11, s) < 1.0 - 1.0 / max(cfg.hot_seller_ratio, 1)
    hot_seller = (max_person // cfg.active_people) * cfg.active_people + 1
    window_p = np.minimum(max_person + 1, cfg.active_people)
    cold_seller = max_person - (
        _rng_u64(idx, 12, s) % window_p.astype(np.uint64)).astype(np.int64)
    seller = np.where(hot, np.minimum(hot_seller, max_person),
                      cold_seller) + FIRST_PERSON_ID

    initial_bid = np.maximum(
        1, (np.power(10.0, _uniform(idx, 13, s) * 6.0) * 100.0)).astype(
        np.int64)
    reserve = initial_bid + np.maximum(
        1, (np.power(10.0, _uniform(idx, 14, s) * 6.0) * 100.0)).astype(
        np.int64)
    date_time = _event_timestamp_us(idx, cfg)
    # expires: 1..12s of event time later (scaled by the event gap so a
    # window of auctions is always open, like NEXT_AUCTION_LENGTH)
    lifetime_us = ((_rng_u64(idx, 15, s) % np.uint64(11) + np.uint64(1))
                   .astype(np.int64)
                   * np.int64(max(cfg.min_event_gap_in_ns, 1))
                   * PROPORTION_DENOMINATOR // 1000 * 20)
    expires = date_time + np.maximum(lifetime_us, 1_000_000)
    category = FIRST_CATEGORY_ID + (
        _rng_u64(idx, 16, s) % np.uint64(cfg.num_categories)).astype(np.int64)

    out: Dict[str, np.ndarray] = {
        "id": auction_id,
        "initial_bid": initial_bid,
        "reserve": reserve,
        "date_time": date_time,
        "expires": expires,
        "seller": seller,
        "category": category,
    }
    item = _pool_pick(_ITEMS, _rng_u64(idx, 17, s))
    out["item_name"] = item
    if cfg.generate_strings:
        # pool-to-pool map: "Nice <item>" exists once per pool entry
        nice = np.array(["Nice " + str(i) for i in _ITEMS.tolist()],
                        dtype=object)
        out["description"] = _pool_pick(nice, _rng_u64(idx, 17, s))
        out["extra"] = _pool_pick(_CITIES, _rng_u64(idx, 18, s))
    else:
        const = np.full(len(k), "", dtype=object)
        out["description"] = const
        out["extra"] = const
    return out


# first×last cross pools: every "First Last" / "First.Last@nexmark.com"
# combination exists exactly once (99 entries); rows fancy-index into
# them — zero per-row string work for names/emails
_NAME_POOL = np.array(
    [f + " " + l for f in _FIRST_NAMES.tolist()
     for l in _LAST_NAMES.tolist()], dtype=object)
_EMAIL_POOL = np.array(
    [f + "." + l + "@nexmark.com" for f in _FIRST_NAMES.tolist()
     for l in _LAST_NAMES.tolist()], dtype=object)


def gen_persons(k: np.ndarray, cfg: NexmarkConfig) -> Dict[str, np.ndarray]:
    idx = person_event_index(k)
    s = cfg.seed
    person_id = k + FIRST_PERSON_ID
    # same (first, last) draws as the per-part pools, combined into
    # one cross-pool index
    fi = _rng_u64(idx, 21, s) % np.uint64(len(_FIRST_NAMES))
    li = _rng_u64(idx, 22, s) % np.uint64(len(_LAST_NAMES))
    combo = fi * np.uint64(len(_LAST_NAMES)) + li
    out: Dict[str, np.ndarray] = {
        "id": person_id,
        "date_time": _event_timestamp_us(idx, cfg),
        "city": _pool_pick(_CITIES, _rng_u64(idx, 23, s)),
        "state": _pool_pick(_STATES, _rng_u64(idx, 24, s)),
    }
    out["name"] = _NAME_POOL[combo]
    if cfg.generate_strings:
        out["email_address"] = _EMAIL_POOL[combo]
        cc = _rng_u64(idx, 25, s) % np.uint64(10 ** 16)
        out["credit_card"] = np.char.mod(
            "%016d", cc.astype(np.int64)).astype(object)
        out["extra"] = _pool_pick(_CITIES, _rng_u64(idx, 26, s))
    else:
        const = np.full(len(k), "", dtype=object)
        out["email_address"] = const
        out["credit_card"] = const
        out["extra"] = const
    return out


_GENERATORS = {"bid": gen_bids, "auction": gen_auctions,
               "person": gen_persons}

_TYPE_PROPORTION = {"bid": BID_PROPORTION, "auction": AUCTION_PROPORTION,
                    "person": PERSON_PROPORTION}


class NexmarkSplitReader:
    """One split of the nexmark event stream (SplitReader analog,
    src/connector/src/source/base.rs:282; nexmark reader
    src/connector/src/source/nexmark/source/reader.rs).

    Split `i` of `m` reads type-ordinals {i, i+m, i+2m, …} — striding the
    ordinal space gives disjoint, load-balanced, seekable splits. `offset`
    (the recovery cursor persisted in split state) counts chunks of this
    split's own ordinal subsequence.
    """

    def __init__(self, cfg: NexmarkConfig, split_index: int = 0,
                 split_num: int = 1, offset: int = 0):
        assert cfg.table_type in _GENERATORS, cfg.table_type
        assert 0 <= split_index < split_num
        self.cfg = cfg
        self.split_index = split_index
        self.split_num = split_num
        self.offset = int(offset)   # ordinals consumed within this split
        self.schema = TABLE_SCHEMAS[cfg.table_type]
        self._gen = _GENERATORS[cfg.table_type]
        # total ordinals of this type available to this split
        share = cfg.event_num * _TYPE_PROPORTION[cfg.table_type] \
            // PROPORTION_DENOMINATOR
        self._split_total = share // split_num \
            + (1 if split_index < share % split_num else 0)
        self._capacity = next_pow2(cfg.max_chunk_size)

    @property
    def split_id(self) -> str:
        return f"nexmark-{self.split_index}"

    def seek(self, offset: int) -> None:
        self.offset = int(offset)

    def next_chunk(self) -> Optional[StreamChunk]:
        """Generate up to max_chunk_size events as one StreamChunk.

        Returns None when the split is exhausted (event_num reached).
        """
        remaining = self._split_total - self.offset
        if remaining <= 0:
            return None
        n = int(min(self.cfg.max_chunk_size, remaining))
        local = np.arange(self.offset, self.offset + n, dtype=np.int64)
        k = local * self.split_num + self.split_index  # global type ordinal
        cols = self._gen(k, self.cfg)
        self.offset += n
        data = {f.name: cols[f.name] for f in self.schema}
        return StreamChunk.from_pydict(self.schema, data,
                                       capacity=self._capacity)
