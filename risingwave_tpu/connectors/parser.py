"""Record parsers: external bytes → typed rows/columns.

Reference parity: src/connector/src/parser/ — the parser layer between
raw connector payloads and typed rows (json_parser.rs, csv_parser.rs;
the Debezium/Avro family is future work). Values land in the PHYSICAL
representation the rest of the system uses (timestamps as µs ints,
DECIMAL as scaled int64 — common/types.py), so chunks built from parsed
records are indistinguishable from generated ones.

Two parse paths share the coercion rules (ISSUE 12 tentpole):

- **Columnar batch path** (``build_chunk``, the source hot path): the
  whole payload batch decodes in ONE pass (JSON: one combined
  ``json.loads`` over a synthesized array; CSV: one decode + split) and
  each field coerces as ONE vectorized numpy column — no per-record
  tuples ever materialize, and the resulting ``StreamChunk`` carries
  ready numpy columns the fused preludes encode straight into raw
  int64 matrices. Malformed records are ISOLATED, not tolerated-by-
  abandoning-the-batch: a failed combined decode re-parses record-wise
  (skip-and-count, the reference's parser error tolerance) and a failed
  column coercion re-coerces that column row-wise, dropping exactly the
  offending records.
- **Row path** (``parse_records``/``parse_batch``, and the batch path's
  isolation fallback): one tuple per record via per-field coercers —
  the bit-identity oracle's off arm (``batch=False``).
"""

from __future__ import annotations

import abc
import json
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu.common.chunk import Column, Op, StreamChunk, next_pow2
from risingwave_tpu.common.types import DataType, Schema, decimal_to_scaled
from risingwave_tpu.utils.ledger import LEDGER

_USECS = 1_000_000


def _parse_timestamp(v) -> int:
    """ISO-8601 string or epoch number → µs since epoch."""
    if isinstance(v, (int, float)):
        # heuristic: values up to ~2100 in seconds; larger ones are
        # already µs (matches the bench generators' physical encoding)
        return int(v * _USECS) if abs(v) < 5_000_000_000 else int(v)
    import datetime
    s = str(v).replace("Z", "+00:00")
    dt = datetime.datetime.fromisoformat(s)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return int(dt.timestamp() * _USECS)


def _parse_date(v) -> int:
    import datetime
    if isinstance(v, (int, float)):
        return int(v)
    return (datetime.date.fromisoformat(str(v))
            - datetime.date(1970, 1, 1)).days


def _parse_bytea(v) -> bytes:
    if isinstance(v, dict) and "__b" in v:
        # the filelog sink's explicit bytes envelope — guessing
        # hex from a bare string would corrupt hex-LOOKING text
        return bytes.fromhex(v["__b"])
    if isinstance(v, str):
        return v.encode()
    return bytes(v)


def _parse_decimal(v) -> int:
    from decimal import Decimal
    return decimal_to_scaled(Decimal(str(v)))


def _coerce(v, dt: DataType):
    """One JSON value → physical value for `dt` (None passes through)."""
    if v is None:
        return None
    if dt in (DataType.INT16, DataType.INT32, DataType.INT64,
              DataType.SERIAL):
        return int(v)
    if dt in (DataType.FLOAT32, DataType.FLOAT64):
        return float(v)
    if dt == DataType.BOOLEAN:
        return bool(v)
    if dt == DataType.DECIMAL:
        return _parse_decimal(v)
    if dt in (DataType.TIMESTAMP, DataType.TIMESTAMPTZ):
        return _parse_timestamp(v)
    if dt == DataType.DATE:
        return _parse_date(v)
    if dt == DataType.BYTEA:
        return _parse_bytea(v)
    return str(v)


# -- vectorized column coercion (the batch path's per-field pass) ----------

_INT_DTS = frozenset({DataType.INT16, DataType.INT32, DataType.INT64,
                      DataType.SERIAL})
_TS_DTS = frozenset({DataType.TIMESTAMP, DataType.TIMESTAMPTZ})


def _batch_coerce(dt: DataType, nn: np.ndarray) -> np.ndarray:
    """Non-null decoded values (object array) → physical value array,
    one vectorized pass. Raises exactly where the row path's per-value
    coercer would (the caller isolates by re-coercing row-wise), and
    produces the same physical values where it wouldn't:

    - int/float/bool columns go through numpy's object cast, which
      applies ``int()``/``float()``/truth-testing per element at C
      speed — including the row path's string parses (``int("3")``)
      and its ``ValueError`` on ``int("3.5")``.
    - timestamp columns take the numeric seconds-vs-µs heuristic as
      one ``where``; string timestamps fall to ``_parse_timestamp``
      per element (the slow shapes stay row-wise by nature).
    - DECIMAL/DATE-from-string/BYTEA coerce per element (exact Decimal
      arithmetic and envelope handling have no vector form) but still
      build the column directly — no row tuples.
    """
    if dt in _INT_DTS:
        return nn.astype(np.int64)
    if dt in (DataType.FLOAT32, DataType.FLOAT64):
        return nn.astype(np.float64)
    if dt == DataType.BOOLEAN:
        return nn.astype(bool)
    if dt in _TS_DTS:
        a = np.asarray(nn.tolist())
        if a.dtype.kind in "iu":
            a = a.astype(np.int64)
            return np.where(np.abs(a) < 5_000_000_000, a * _USECS, a)
        if a.dtype.kind == "f":
            if np.isnan(a).any():
                raise ValueError("NaN timestamp")   # rowwise isolates
            with np.errstate(over="ignore", invalid="ignore"):
                return np.where(np.abs(a) < 5e9,
                                a * _USECS, a).astype(np.int64)
        return np.fromiter((_parse_timestamp(v) for v in nn.tolist()),
                           dtype=np.int64, count=len(nn))
    if dt == DataType.DATE:
        a = np.asarray(nn.tolist())
        if a.dtype.kind in "iuf":
            return a.astype(np.int64)
        return np.fromiter((_parse_date(v) for v in nn.tolist()),
                           dtype=np.int64, count=len(nn))
    if dt == DataType.DECIMAL:
        return np.fromiter((_parse_decimal(v) for v in nn.tolist()),
                           dtype=np.int64, count=len(nn))
    if dt == DataType.BYTEA:
        out = np.empty(len(nn), dtype=object)
        out[:] = [_parse_bytea(v) for v in nn.tolist()]
        return out
    out = np.empty(len(nn), dtype=object)
    out[:] = [str(v) for v in nn.tolist()]
    return out


def _coerce_column(dt: DataType, vals: List
                   ) -> Tuple[np.ndarray, Optional[np.ndarray],
                              Optional[np.ndarray]]:
    """One decoded column (python values, None = NULL) → (physical
    values[n], validity[n] or None, bad-record mask or None).

    The vectorized pass runs first; if ANY value refuses to coerce the
    whole column re-coerces row-wise so only the offending records are
    marked bad (skip-and-count isolation) — the batch path's answer to
    the row path's per-record try/except."""
    n = len(vals)
    obj = np.empty(n, dtype=object)
    obj[:] = vals
    nulls = obj == None                    # noqa: E711  (elementwise)
    has_null = bool(nulls.any())
    nn = obj[~nulls] if has_null else obj
    if len(nn):
        try:
            phys = _batch_coerce(dt, nn)
        except (ValueError, TypeError, KeyError):
            return _coerce_column_rowwise(dt, obj, nulls)
    else:
        phys = np.zeros(0, dtype=np.dtype(dt.np_dtype)
                        if dt.is_device else object)
    if not has_null:
        return phys, None, None
    out = np.zeros(n, dtype=phys.dtype) if phys.dtype != object \
        else np.empty(n, dtype=object)
    out[~nulls] = phys
    return out, ~nulls, None


def _coerce_column_rowwise(dt: DataType, obj: np.ndarray,
                           nulls: np.ndarray
                           ) -> Tuple[np.ndarray, Optional[np.ndarray],
                                      Optional[np.ndarray]]:
    """Row-wise isolation arm: same coercions, bad values marked."""
    n = len(obj)
    vals = np.empty(n, dtype=object)
    bad = np.zeros(n, dtype=bool)
    for i, v in enumerate(obj.tolist()):
        if v is None:
            continue
        try:
            vals[i] = _coerce(v, dt)
        except (ValueError, TypeError, KeyError):
            bad[i] = True
    ok = ~nulls & ~bad
    if dt.is_device:
        out = np.zeros(n, dtype=np.dtype(dt.np_dtype))
        if ok.any():
            out[ok] = vals[ok].astype(out.dtype)
    else:
        out = np.empty(n, dtype=object)
        out[ok] = vals[ok]
    return out, ok, (bad if bad.any() else None)


def _physical_column(dt: DataType, vals: List) -> Tuple[
        np.ndarray, Optional[np.ndarray]]:
    """Already-physical per-record values (row-path fallback) → padded-
    free (values[n], validity[n] or None) arrays."""
    obj = np.empty(len(vals), dtype=object)
    obj[:] = vals
    nulls = obj == None                    # noqa: E711
    if not nulls.any():
        return (obj.astype(np.dtype(dt.np_dtype))
                if dt.is_device else obj), None
    ok = ~nulls
    if dt.is_device:
        out = np.zeros(len(vals), dtype=np.dtype(dt.np_dtype))
        out[ok] = obj[ok].astype(out.dtype)
    else:
        out = obj.copy()
        out[nulls] = None
    return out, ok


def _chunk_from_columns(schema: Schema,
                        cols: Sequence[Tuple[np.ndarray,
                                             Optional[np.ndarray]]],
                        deletes: Optional[np.ndarray],
                        n: int) -> StreamChunk:
    """Physical column arrays → StreamChunk, padded to pow2 capacity.

    The direct constructor the batch path uses instead of
    ``from_pydict``'s list transposition — values here are PHYSICAL
    (scaled DECIMAL ints, µs timestamps), which ``from_pydict`` would
    re-scale (its contract is logical values; feeding it parsed rows
    double-scaled DECIMAL — the bug this constructor fixes for the row
    fallback too)."""
    cap = next_pow2(max(n, 1))
    out_cols: List[Column] = []
    for f, (vals, ok) in zip(schema, cols):
        dt = f.data_type
        if dt.is_device:
            arr = np.zeros(cap, dtype=np.dtype(dt.np_dtype))
        else:
            arr = np.empty(cap, dtype=object)
        arr[:n] = vals
        validity = None
        if ok is not None and not ok.all():
            validity = np.ones(cap, dtype=bool)
            validity[:n] = ok
        out_cols.append(Column(dt, arr, validity))
    vis = np.zeros(cap, dtype=bool)
    vis[:n] = True
    ops = np.full(cap, int(Op.INSERT), dtype=np.int8)
    if deletes is not None and deletes.any():
        ops[:n] = np.where(deletes, np.int8(int(Op.DELETE)),
                           np.int8(int(Op.INSERT)))
    return StreamChunk(schema, out_cols, vis, ops)


class RowParser(abc.ABC):
    """bytes-per-record → typed records (parser/ analog).

    Malformed records are SKIPPED and counted (the reference's parser
    error tolerance) — a poisoned message must not wedge the stream.
    ``batch=False`` forces the row-at-a-time path everywhere (the
    oracle's off arm; sources pass ``parse.batch`` through options).
    """

    def __init__(self, schema: Schema, batch: bool = True):
        self.schema = schema
        self.errors = 0
        self.batch = batch

    @abc.abstractmethod
    def parse_one(self, payload: bytes) -> Optional[tuple]:
        ...

    def parse_record(self, payload: bytes
                     ) -> Optional[Tuple[bool, tuple]]:
        """(is_insert, row) — formats with an op envelope (the filelog
        sink's __op) override this; plain formats are inserts."""
        row = self.parse_one(payload)
        return None if row is None else (True, row)

    def parse_records(self, payloads: Sequence[bytes]
                      ) -> List[Tuple[bool, tuple]]:
        # the connector-decode half of the epoch phase ledger's
        # host_ingest: per-record parse/coerce work, timed per batch
        with LEDGER.phase("host_ingest"):
            out = []
            for p in payloads:
                try:
                    rec = self.parse_record(p)
                except (ValueError, TypeError, KeyError,
                        json.JSONDecodeError):
                    rec = None
                if rec is None:
                    self.errors += 1
                else:
                    out.append(rec)
            return out

    def parse_batch(self, payloads: Sequence[bytes]) -> List[tuple]:
        """Rows only (op envelope dropped) — the plain-source shape."""
        return [r for _ins, r in self.parse_records(payloads)]

    # -- columnar batch path (ISSUE 12) --------------------------------
    def _parse_columns(self, payloads: Sequence[bytes]) -> Optional[
            Tuple[List[Tuple[np.ndarray, Optional[np.ndarray]]],
                  Optional[np.ndarray], int]]:
        """Batch-capable subclasses return (columns, delete-mask, n);
        None means 'no batch path' and build_chunk falls back to the
        row path."""
        return None

    def build_chunk(self, payloads: Sequence[bytes]
                    ) -> Optional[StreamChunk]:
        with LEDGER.phase("host_ingest"):
            if self.batch:
                parsed = self._parse_columns(payloads)
                if parsed is not None:
                    cols, deletes, n = parsed
                    if n == 0:
                        return None
                    return _chunk_from_columns(self.schema, cols,
                                               deletes, n)
        recs = self.parse_records(payloads)
        if not recs:
            return None
        with LEDGER.phase("host_ingest"):
            n = len(recs)
            cols = [
                _physical_column(f.data_type,
                                 [r[i] for _ins, r in recs])
                for i, f in enumerate(self.schema)]
            deletes = None
            if not all(ins for ins, _r in recs):
                deletes = np.fromiter((not ins for ins, _r in recs),
                                      dtype=bool, count=n)
            return _chunk_from_columns(self.schema, cols, deletes, n)


class JsonRowParser(RowParser):
    """One JSON object per record (parser/json_parser.rs analog);
    missing keys read as NULL, unknown keys are ignored. A ``__op``
    envelope field ("I"/"D" — the filelog sink's changelog wire
    format) maps to the chunk op so retractions survive the wire."""

    # per-type coercers BOUND AT CONSTRUCTION: _coerce's type-dispatch
    # chain ran per field per record (1.3M calls in one ad-ctr bench
    # window — the r10 ingestion profile); a prebuilt (name, coercer)
    # list keeps the per-record work at one dict.get + one call per
    # field, with the common int/float cases as bare builtins
    _FAST = {DataType.INT16: int, DataType.INT32: int,
             DataType.INT64: int, DataType.SERIAL: int,
             DataType.FLOAT32: float, DataType.FLOAT64: float,
             DataType.BOOLEAN: bool,
             DataType.TIMESTAMP: _parse_timestamp,
             DataType.TIMESTAMPTZ: _parse_timestamp}

    def __init__(self, schema: Schema, batch: bool = True):
        super().__init__(schema, batch=batch)
        self._fields = [
            (f.name,
             self._FAST.get(f.data_type)
             or (lambda v, _dt=f.data_type: _coerce(v, _dt)))
            for f in schema]

    def parse_one(self, payload: bytes) -> Optional[tuple]:
        rec = self.parse_record(payload)
        return None if rec is None else rec[1]

    @staticmethod
    def _decode_payload(payload):
        # decode BEFORE json.loads: loads on bytes runs
        # detect_encoding per record — ~1s/MM records of pure
        # overhead on the ingestion hot path (r10 ad-ctr profile).
        # Rare shapes keep the old behavior: a UTF-8 BOM strips
        # (json.loads(bytes) tolerated it) and non-UTF-8 payloads
        # (UTF-16/32) fall back to loads' own encoding detection.
        if isinstance(payload, (bytes, bytearray)):
            try:
                s = payload.decode("utf-8")
                if s.startswith("\ufeff"):
                    s = s[1:]
            except UnicodeDecodeError:
                s = payload          # loads(bytes) auto-detects
        else:
            s = payload
        return s

    def parse_record(self, payload: bytes
                     ) -> Optional[Tuple[bool, tuple]]:
        obj = json.loads(self._decode_payload(payload))
        if not isinstance(obj, dict):
            return None
        get = obj.get
        row = tuple(
            None if (v := get(name)) is None else coerce(v)
            for name, coerce in self._fields)
        return (get("__op", "I") != "D", row)

    # -- batch path -----------------------------------------------------
    def _decode_objs(self, payloads: Sequence[bytes]) -> List[dict]:
        """Whole batch → list of record dicts, ONE json.loads in the
        common case (payloads joined into a synthesized JSON array —
        the array parse IS the per-record parse, at C speed with no
        per-record Python). Any malformed/odd-encoding record fails
        the combined parse; the fallback re-parses record-wise so only
        the offenders are skipped and counted."""
        try:
            text = b"[" + b",".join(payloads) + b"]"
            objs = json.loads(text.decode("utf-8"))
            if len(objs) != len(payloads):
                # a malformed payload that PARSES as several values
                # ('{..},{..}') would mint phantom records — the row
                # path counts it as one error; isolate record-wise
                raise ValueError("record/payload count mismatch")
        except (UnicodeDecodeError, ValueError):
            objs = []
            for p in payloads:
                try:
                    obj = json.loads(self._decode_payload(p))
                except (ValueError, TypeError):
                    self.errors += 1
                    continue
                objs.append(obj)
        good = [o for o in objs if isinstance(o, dict)]
        self.errors += len(objs) - len(good)    # non-object records
        return good

    def _parse_columns(self, payloads: Sequence[bytes]):
        objs = self._decode_objs(payloads)
        if not objs:
            return [], None, 0
        n = len(objs)
        cols: List[Tuple[np.ndarray, Optional[np.ndarray]]] = []
        bad: Optional[np.ndarray] = None
        for f in self.schema:
            name = f.name
            vals = [o.get(name) for o in objs]
            v, ok, b = _coerce_column(f.data_type, vals)
            cols.append((v, ok))
            if b is not None:
                bad = b if bad is None else (bad | b)
        deletes = None
        if any("__op" in o for o in objs):
            deletes = np.fromiter(
                (o.get("__op", "I") == "D" for o in objs),
                dtype=bool, count=n)
        if bad is not None:
            # drop the records whose coercion failed (skip-and-count);
            # earlier columns already built — one gather fixes them up
            self.errors += int(bad.sum())
            keep = ~bad
            n = int(keep.sum())
            cols = [(v[keep], None if ok is None else ok[keep])
                    for v, ok in cols]
            if deletes is not None:
                deletes = deletes[keep]
        return cols, deletes, n


class CsvRowParser(RowParser):
    """Positional delimited records (parser/csv_parser.rs analog);
    empty fields read as NULL. Coercers are PREBOUND per column (the
    PR 10 JSON fast path, ported): one call per field per record on
    the row path, one vectorized pass per column on the batch path."""

    _FAST = {DataType.INT16: int, DataType.INT32: int,
             DataType.INT64: int, DataType.SERIAL: int,
             DataType.FLOAT32: float, DataType.FLOAT64: float,
             DataType.BOOLEAN: bool,
             DataType.TIMESTAMP: _parse_timestamp,
             DataType.TIMESTAMPTZ: _parse_timestamp}

    def __init__(self, schema: Schema, delimiter: str = ",",
                 batch: bool = True):
        super().__init__(schema, batch=batch)
        self.delimiter = delimiter
        self._fields: List[Tuple[int, DataType, Callable]] = [
            (i, f.data_type,
             self._FAST.get(f.data_type)
             or (lambda v, _dt=f.data_type: _coerce(v, _dt)))
            for i, f in enumerate(self.schema)]

    def parse_one(self, payload: bytes) -> Optional[tuple]:
        parts = payload.decode().rstrip("\r\n").split(self.delimiter)
        if len(parts) < len(self.schema):
            return None
        return tuple(
            None if parts[i] == "" else coerce(parts[i])
            for i, _dt, coerce in self._fields)

    def _parse_columns(self, payloads: Sequence[bytes]):
        try:
            lines = [p.decode().rstrip("\r\n").split(self.delimiter)
                     for p in payloads]
        except UnicodeDecodeError:
            # some record isn't decodable: isolate it record-wise
            lines = []
            for p in payloads:
                try:
                    lines.append(p.decode().rstrip("\r\n")
                                 .split(self.delimiter))
                except UnicodeDecodeError:
                    self.errors += 1
        width = len(self.schema)
        short = [ln for ln in lines if len(ln) < width]
        if short:
            self.errors += len(short)
            lines = [ln for ln in lines if len(ln) >= width]
        if not lines:
            return [], None, 0
        n = len(lines)
        cols: List[Tuple[np.ndarray, Optional[np.ndarray]]] = []
        bad: Optional[np.ndarray] = None
        for i, f in enumerate(self.schema):
            vals = [None if (v := ln[i]) == "" else v for ln in lines]
            v, ok, b = _coerce_column(f.data_type, vals)
            cols.append((v, ok))
            if b is not None:
                bad = b if bad is None else (bad | b)
        if bad is not None:
            self.errors += int(bad.sum())
            keep = ~bad
            n = int(keep.sum())
            cols = [(v[keep], None if ok is None else ok[keep])
                    for v, ok in cols]
        return cols, None, n


def make_parser(fmt: str, schema: Schema, options=None) -> RowParser:
    fmt = (fmt or "json").lower()
    opts = options or {}
    batch = str(opts.get("parse.batch", "true")).lower() not in (
        "false", "0", "off")
    if fmt == "json":
        return JsonRowParser(schema, batch=batch)
    if fmt == "csv":
        delim = opts.get("csv.delimiter", ",")
        return CsvRowParser(schema, delim, batch=batch)
    raise ValueError(f"unknown source format {fmt!r}")
