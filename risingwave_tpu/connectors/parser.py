"""Record parsers: external bytes → typed row values.

Reference parity: src/connector/src/parser/ — the parser layer between
raw connector payloads and typed rows (json_parser.rs, csv_parser.rs;
the Debezium/Avro family is future work). Parsing is vectorized per
batch of records; values land in the PHYSICAL representation the rest
of the system uses (timestamps as µs ints, DECIMAL as scaled int64 —
common/types.py), so chunks built from parsed rows are
indistinguishable from generated ones.
"""

from __future__ import annotations

import abc
import json
from typing import Dict, List, Optional, Sequence

import numpy as np

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.common.types import DataType, Schema, decimal_to_scaled
from risingwave_tpu.utils.ledger import LEDGER

_USECS = 1_000_000


def _parse_timestamp(v) -> int:
    """ISO-8601 string or epoch number → µs since epoch."""
    if isinstance(v, (int, float)):
        # heuristic: values up to ~2100 in seconds; larger ones are
        # already µs (matches the bench generators' physical encoding)
        return int(v * _USECS) if abs(v) < 5_000_000_000 else int(v)
    import datetime
    s = str(v).replace("Z", "+00:00")
    dt = datetime.datetime.fromisoformat(s)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return int(dt.timestamp() * _USECS)


def _coerce(v, dt: DataType):
    """One JSON value → physical value for `dt` (None passes through)."""
    if v is None:
        return None
    if dt in (DataType.INT16, DataType.INT32, DataType.INT64,
              DataType.SERIAL):
        return int(v)
    if dt in (DataType.FLOAT32, DataType.FLOAT64):
        return float(v)
    if dt == DataType.BOOLEAN:
        return bool(v)
    if dt == DataType.DECIMAL:
        from decimal import Decimal
        return decimal_to_scaled(Decimal(str(v)))
    if dt in (DataType.TIMESTAMP, DataType.TIMESTAMPTZ):
        return _parse_timestamp(v)
    if dt == DataType.DATE:
        import datetime
        if isinstance(v, (int, float)):
            return int(v)
        return (datetime.date.fromisoformat(str(v))
                - datetime.date(1970, 1, 1)).days
    if dt == DataType.BYTEA:
        if isinstance(v, dict) and "__b" in v:
            # the filelog sink's explicit bytes envelope — guessing
            # hex from a bare string would corrupt hex-LOOKING text
            return bytes.fromhex(v["__b"])
        if isinstance(v, str):
            return v.encode()
        return bytes(v)
    return str(v)


class RowParser(abc.ABC):
    """bytes-per-record → row tuples in schema order (parser/ analog).

    Malformed records are SKIPPED and counted (the reference's parser
    error tolerance) — a poisoned message must not wedge the stream.
    """

    def __init__(self, schema: Schema):
        self.schema = schema
        self.errors = 0

    @abc.abstractmethod
    def parse_one(self, payload: bytes) -> Optional[tuple]:
        ...

    def parse_record(self, payload: bytes
                     ) -> Optional[Tuple[bool, tuple]]:
        """(is_insert, row) — formats with an op envelope (the filelog
        sink's __op) override this; plain formats are inserts."""
        row = self.parse_one(payload)
        return None if row is None else (True, row)

    def parse_records(self, payloads: Sequence[bytes]
                      ) -> List[Tuple[bool, tuple]]:
        # the connector-decode half of the epoch phase ledger's
        # host_ingest: per-record parse/coerce work, timed per batch
        with LEDGER.phase("host_ingest"):
            out = []
            for p in payloads:
                try:
                    rec = self.parse_record(p)
                except (ValueError, TypeError, KeyError,
                        json.JSONDecodeError):
                    rec = None
                if rec is None:
                    self.errors += 1
                else:
                    out.append(rec)
            return out

    def parse_batch(self, payloads: Sequence[bytes]) -> List[tuple]:
        """Rows only (op envelope dropped) — the plain-source shape."""
        return [r for _ins, r in self.parse_records(payloads)]

    def build_chunk(self, payloads: Sequence[bytes]
                    ) -> Optional[StreamChunk]:
        recs = self.parse_records(payloads)
        if not recs:
            return None
        # column transposition + chunk building is still ingest-side
        # decode work (rows exist only after this lands)
        with LEDGER.phase("host_ingest"):
            data: Dict[str, list] = {
                f.name: [r[i] for _ins, r in recs]
                for i, f in enumerate(self.schema)}
            ops = None
            if not all(ins for ins, _r in recs):
                from risingwave_tpu.common.chunk import Op
                ops = [Op.INSERT if ins else Op.DELETE
                       for ins, _r in recs]
            return StreamChunk.from_pydict(self.schema, data, ops=ops)


class JsonRowParser(RowParser):
    """One JSON object per record (parser/json_parser.rs analog);
    missing keys read as NULL, unknown keys are ignored. A ``__op``
    envelope field ("I"/"D" — the filelog sink's changelog wire
    format) maps to the chunk op so retractions survive the wire."""

    # per-type coercers BOUND AT CONSTRUCTION: _coerce's type-dispatch
    # chain ran per field per record (1.3M calls in one ad-ctr bench
    # window — the r10 ingestion profile); a prebuilt (name, coercer)
    # list keeps the per-record work at one dict.get + one call per
    # field, with the common int/float cases as bare builtins
    _FAST = {DataType.INT16: int, DataType.INT32: int,
             DataType.INT64: int, DataType.SERIAL: int,
             DataType.FLOAT32: float, DataType.FLOAT64: float,
             DataType.BOOLEAN: bool,
             DataType.TIMESTAMP: _parse_timestamp,
             DataType.TIMESTAMPTZ: _parse_timestamp}

    def __init__(self, schema: Schema):
        super().__init__(schema)
        self._fields = [
            (f.name,
             self._FAST.get(f.data_type)
             or (lambda v, _dt=f.data_type: _coerce(v, _dt)))
            for f in schema]

    def parse_one(self, payload: bytes) -> Optional[tuple]:
        rec = self.parse_record(payload)
        return None if rec is None else rec[1]

    def parse_record(self, payload: bytes
                     ) -> Optional[Tuple[bool, tuple]]:
        # decode BEFORE json.loads: loads on bytes runs
        # detect_encoding per record — ~1s/MM records of pure
        # overhead on the ingestion hot path (r10 ad-ctr profile).
        # Rare shapes keep the old behavior: a UTF-8 BOM strips
        # (json.loads(bytes) tolerated it) and non-UTF-8 payloads
        # (UTF-16/32) fall back to loads' own encoding detection.
        if isinstance(payload, (bytes, bytearray)):
            try:
                s = payload.decode("utf-8")
                if s.startswith("\ufeff"):
                    s = s[1:]
            except UnicodeDecodeError:
                s = payload          # loads(bytes) auto-detects
        else:
            s = payload
        obj = json.loads(s)
        if not isinstance(obj, dict):
            return None
        get = obj.get
        row = tuple(
            None if (v := get(name)) is None else coerce(v)
            for name, coerce in self._fields)
        return (get("__op", "I") != "D", row)


class CsvRowParser(RowParser):
    """Positional delimited records (parser/csv_parser.rs analog);
    empty fields read as NULL."""

    def __init__(self, schema: Schema, delimiter: str = ","):
        super().__init__(schema)
        self.delimiter = delimiter

    def parse_one(self, payload: bytes) -> Optional[tuple]:
        parts = payload.decode().rstrip("\r\n").split(self.delimiter)
        if len(parts) < len(self.schema):
            return None
        return tuple(
            None if parts[i] == "" else _coerce(parts[i], f.data_type)
            for i, f in enumerate(self.schema))


def make_parser(fmt: str, schema: Schema, options=None) -> RowParser:
    fmt = (fmt or "json").lower()
    if fmt == "json":
        return JsonRowParser(schema)
    if fmt == "csv":
        delim = (options or {}).get("csv.delimiter", ",")
        return CsvRowParser(schema, delim)
    raise ValueError(f"unknown source format {fmt!r}")
