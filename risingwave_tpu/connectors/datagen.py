"""Datagen source: deterministic synthetic rows from WITH options.

Reference parity: src/connector/src/source/datagen/ — per-field
sequence/random generators configured via `fields.<name>.*` WITH
options (the reference reads field types from DDL columns; here the
type rides in `fields.<name>.type`, keeping CREATE SOURCE one
statement). Generation is whole-chunk vectorized numpy keyed by the
absolute row offset, so a seek makes replay exact (split recovery
contract, same as the nexmark reader).

Options:
    connector = 'datagen'
    datagen.rows.per.chunk  (default 1024)
    datagen.event.num       (default unbounded)
    fields.<name>.type      bigint | double | varchar | timestamp
    fields.<name>.kind      sequence | random       (default sequence)
    fields.<name>.start / .end      sequence bounds (wraps at end)
    fields.<name>.min / .max        random bounds
    fields.<name>.seed              per-field seed offset
    fields.<name>.length            varchar length (random strings)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from risingwave_tpu.common.chunk import Column, StreamChunk, next_pow2
from risingwave_tpu.common.types import DataType, Field, Schema

_TYPES = {
    "bigint": DataType.INT64, "int": DataType.INT32,
    "integer": DataType.INT32, "smallint": DataType.INT16,
    "double": DataType.FLOAT64, "real": DataType.FLOAT32,
    "varchar": DataType.VARCHAR, "timestamp": DataType.TIMESTAMP,
    "boolean": DataType.BOOLEAN,
}


@dataclass
class FieldSpec:
    name: str
    data_type: DataType
    kind: str = "sequence"               # sequence | random
    start: int = 0
    end: int = (1 << 62)
    vmin: float = 0
    vmax: float = 100
    seed: int = 0
    length: int = 8


@dataclass
class DatagenConfig:
    fields: List[FieldSpec] = field(default_factory=list)
    rows_per_chunk: int = 1024
    event_num: int = 1 << 62
    seed: int = 0xDA7A

    @property
    def schema(self) -> Schema:
        return Schema([Field(f.name, f.data_type) for f in self.fields])

    @staticmethod
    def from_options(opts: Dict[str, str]) -> "DatagenConfig":
        cfg = DatagenConfig(
            rows_per_chunk=int(opts.get("datagen.rows.per.chunk", 1024)),
            event_num=int(opts.get("datagen.event.num", 1 << 62)),
            seed=int(opts.get("datagen.seed", 0xDA7A)),
        )
        specs: Dict[str, FieldSpec] = {}
        order: List[str] = []
        for key, val in opts.items():
            if not key.startswith("fields."):
                continue
            _prefix, name, prop = key.split(".", 2)
            if name not in specs:
                specs[name] = FieldSpec(name, DataType.INT64)
                order.append(name)
            s = specs[name]
            if prop == "type":
                s.data_type = _TYPES[val.lower()]
            elif prop == "kind":
                s.kind = val.lower()
            elif prop == "start":
                s.start = int(val)
            elif prop == "end":
                s.end = int(val)
            elif prop == "min":
                s.vmin = float(val)
            elif prop == "max":
                s.vmax = float(val)
            elif prop == "seed":
                s.seed = int(val)
            elif prop == "length":
                s.length = int(val)
            else:
                raise ValueError(f"unknown datagen option {key!r}")
        if not order:
            raise ValueError("datagen needs at least one fields.<name>.*")
        cfg.fields = [specs[n] for n in order]
        return cfg


def _mix(k: np.ndarray, seed: int) -> np.ndarray:
    """splitmix-style stateless mix of row offsets (uint64)."""
    gamma = (seed * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    x = (k.astype(np.uint64) + np.uint64(gamma)) \
        & np.uint64(0xFFFFFFFFFFFFFFFF)
    with np.errstate(over="ignore"):
        x ^= x >> np.uint64(30)
        x *= np.uint64(0xBF58476D1CE4E5B9)
        x ^= x >> np.uint64(27)
        x *= np.uint64(0x94D049BB133111EB)
        x ^= x >> np.uint64(31)
    return x


def gen_rows(k: np.ndarray, cfg: DatagenConfig) -> Dict[str, np.ndarray]:
    """Absolute offsets → column arrays (vectorized, replayable)."""
    out: Dict[str, np.ndarray] = {}
    for f in cfg.fields:
        if f.kind == "sequence":
            span = max(1, f.end - f.start)
            vals = f.start + (k % span)
            if f.data_type == DataType.FLOAT64 or \
                    f.data_type == DataType.FLOAT32:
                out[f.name] = vals.astype(f.data_type.np_dtype)
            elif f.data_type == DataType.VARCHAR:
                out[f.name] = np.array(
                    [f"{f.name}_{v}" for v in vals.tolist()], dtype=object)
            else:
                out[f.name] = vals.astype(f.data_type.np_dtype)
        elif f.kind == "random":
            bits = _mix(k, cfg.seed + f.seed + hash(f.name) % (1 << 31))
            u = (bits >> np.uint64(11)).astype(np.float64) / float(1 << 53)
            if f.data_type == DataType.VARCHAR:
                letters = np.array(list("abcdefghijklmnopqrstuvwxyz"))
                idx = np.stack([
                    (_mix(k, cfg.seed + f.seed + i) % 26).astype(np.int64)
                    for i in range(f.length)], axis=1)
                out[f.name] = np.array(
                    ["".join(letters[row]) for row in idx], dtype=object)
            elif f.data_type in (DataType.FLOAT64, DataType.FLOAT32):
                out[f.name] = (f.vmin + u * (f.vmax - f.vmin)).astype(
                    f.data_type.np_dtype)
            elif f.data_type == DataType.BOOLEAN:
                out[f.name] = (bits & np.uint64(1)).astype(bool)
            else:
                vals = (f.vmin + u * (f.vmax - f.vmin + 1)).astype(np.int64)
                out[f.name] = np.minimum(
                    vals, int(f.vmax)).astype(f.data_type.np_dtype)
        else:
            raise ValueError(f"unknown datagen kind {f.kind!r}")
    return out


class DatagenSplitReader:
    """Replayable split reader (SplitReader protocol)."""

    def __init__(self, cfg: DatagenConfig, offset: int = 0):
        self.cfg = cfg
        self.schema = cfg.schema
        self.split_id = "datagen-0"
        self.offset = offset

    def seek(self, offset: int) -> None:
        self.offset = offset

    def next_chunk(self) -> Optional[StreamChunk]:
        n = min(self.cfg.rows_per_chunk, self.cfg.event_num - self.offset)
        if n <= 0:
            return None
        k = np.arange(self.offset, self.offset + n, dtype=np.int64)
        self.offset += n
        data = gen_rows(k, self.cfg)
        cap = next_pow2(n)
        cols = []
        for f in self.schema:
            arr = data[f.name]
            if f.data_type.is_device:
                full = np.zeros(cap, dtype=f.data_type.np_dtype)
            else:
                full = np.empty(cap, dtype=object)
            full[:n] = arr
            cols.append(Column(f.data_type, full, None))
        vis = np.zeros(cap, dtype=bool)
        vis[:n] = True
        from risingwave_tpu.common.chunk import Op
        ops = np.full(cap, int(Op.INSERT), dtype=np.int8)
        return StreamChunk(self.schema, cols, vis, ops)
