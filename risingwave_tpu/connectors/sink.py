"""Epoch-segment sink targets: the exactly-once N-writer storage
format (ISSUE 20).

Reference parity: the coordinated two-phase sink commit
(src/connector/src/sink/mod.rs:156 SinkCommitCoordinator +
src/meta/src/manager/sink_coordination/) — N writers STAGE their
epoch's rows concurrently as per-(epoch, writer) segment objects; the
meta-side coordinator then commits ONE manifest object per checkpoint
epoch. Visibility is manifest-existence: an epoch's rows are in the
sink iff ``manifest/<epoch>.json`` exists. The concurrency stance is
arxiv 1904.03800's — writers never coordinate with each other, the
only serialized decision is the single manifest PUT.

Layout (under one object-store root per sink)::

    seg/<epoch:016x>/w<writer:04d>.seg    staged segment (atomic PUT)
    manifest/<epoch:016x>.json            commit record (atomic PUT)

The commit protocol's two crash-window invariants (enforced by WHERE
the hooks live, storage/uploader.py):

  1. manifest strictly AFTER the checkpoint floor covers the epoch —
     else a crash before the floor advanced would replay rows that
     are already visible (duplicates);
  2. floor advance strictly AFTER all the epoch's staging is durable —
     else a crash after the floor advanced would lose rows the
     upstream will never replay (they are ≤ the recovery point).

Together: floor ≥ E  ⟹  every segment of E is durable, so recovery
can PROMOTE any unmanifested epoch ≤ floor (complete its manifest
from the staged segments) and must TRUNCATE any epoch > floor (its
rows replay under fresh epochs). Commit authority is the object-store
LISTING, never drained pre-commit RPCs — a lost drain can delay a
commit but never lose one.

Record encodings (newline-delimited JSON, filelog-compatible):

  append  ``{"__op": "I", <col>: <val>, ...}`` — inserts only; the
          planner proves the input append-only before choosing this.
  upsert  ``{"__op": "U"|"D", "__k": [key vals], <col>: <val>, ...}``
          — retractions FOLD per key within the epoch (last write
          wins); a D that survives folding is a tombstone.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from risingwave_tpu.utils.failpoint import fail_point

SEG_PREFIX = "seg/"
MANIFEST_PREFIX = "manifest/"

# every local-FS sink root this process built a target over — the
# tier-1 conftest orphan guard sweeps these at test teardown: staged
# segments without a manifest that outlive the test are exactly the
# uncommitted-epoch leakage the protocol exists to prevent
_TOUCHED_ROOTS: set = set()


def touched_roots() -> List[str]:
    return sorted(_TOUCHED_ROOTS)


def reset_touched_roots() -> None:
    _TOUCHED_ROOTS.clear()


def _jsonable(v):
    """Physical value → JSON-safe, recursively (Decimal → str).
    Bytes ride an explicit ``{"__b": hex}`` envelope — a bare hex
    string would be indistinguishable from a real string that merely
    looks like hex on the consuming side."""
    if isinstance(v, bytes):
        return {"__b": v.hex()}
    if isinstance(v, (tuple, list)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (int, float, str, bool)) or v is None:
        return v
    return str(v)                           # Decimal and friends


def seg_key(epoch: int, writer: int) -> str:
    return f"{SEG_PREFIX}{epoch:016x}/w{writer:04d}.seg"


def manifest_key(epoch: int) -> str:
    return f"{MANIFEST_PREFIX}{epoch:016x}.json"


def _parse_seg_key(key: str) -> Optional[Tuple[int, int]]:
    """seg/<epoch>/w<writer>.seg → (epoch, writer); None for garbage
    (mkstemp residue from a writer SIGKILLed mid-PUT, stray files)."""
    if not key.startswith(SEG_PREFIX) or not key.endswith(".seg"):
        return None
    body = key[len(SEG_PREFIX):-len(".seg")]
    parts = body.split("/")
    if len(parts) != 2 or not parts[1].startswith("w"):
        return None
    try:
        return int(parts[0], 16), int(parts[1][1:], 10)
    except ValueError:
        return None


class EpochSegmentTarget:
    """One sink's staging + manifest namespace over an ObjectStore.

    Writer side (any process): ``stage``. Coordinator side:
    ``commit_upto`` / ``recover`` / the read surface. Both sides are
    listing-driven — no shared in-memory state, so worker processes
    and the meta coordinator agree by construction."""

    def __init__(self, store, mode: str = "append",
                 field_names: Optional[List[str]] = None):
        assert mode in ("append", "upsert"), mode
        self.store = store
        self.mode = mode
        self.field_names = field_names

    # -- writer side ----------------------------------------------------
    def stage(self, epoch: int, writer: int,
              records: List[bytes]) -> dict:
        """Durably stage one writer's epoch payload (atomic PUT).
        Empty payloads stage nothing — the listing-driven commit does
        not require a segment per writer. Returns the pre-commit
        handle (telemetry only; commit never depends on it)."""
        if not records:
            return {"epoch": epoch, "writer": writer, "rows": 0,
                    "bytes": 0, "key": None}
        data = b"".join(r + b"\n" for r in records)
        # the SIGKILL-mid-stage chaos window: rows are folded and
        # serialized but NOT yet durable while this point sleeps
        fail_point("sink.stage.mid")
        key = seg_key(epoch, writer)
        self.store.upload(key, data)
        return {"epoch": epoch, "writer": writer,
                "rows": len(records), "bytes": len(data), "key": key}

    # -- coordinator side -----------------------------------------------
    def committed_epoch(self) -> int:
        ms = self.store.list(MANIFEST_PREFIX)
        best = 0
        for m in ms:
            name = m[len(MANIFEST_PREFIX):]
            if name.endswith(".json"):
                try:
                    best = max(best, int(name[:-len(".json")], 16))
                except ValueError:
                    pass
        return best

    def staged_epochs(self) -> Dict[int, List[Tuple[int, str]]]:
        """epoch → [(writer, key)] for every staged segment (garbage
        keys — torn tmp files — excluded; ``recover`` sweeps them)."""
        out: Dict[int, List[Tuple[int, str]]] = {}
        for key in self.store.list(SEG_PREFIX):
            parsed = _parse_seg_key(key)
            if parsed is not None:
                out.setdefault(parsed[0], []).append((parsed[1], key))
        return out

    def uncommitted_epochs(self) -> Dict[int, List[Tuple[int, str]]]:
        return {e: segs for e, segs in self.staged_epochs().items()
                if not self.store.exists(manifest_key(e))}

    def commit(self, epoch: int, segs: List[Tuple[int, str]]) -> dict:
        """The ONE serialized commit decision: write the epoch's
        manifest from the staged listing (atomic PUT; idempotent —
        re-deriving from the same durable listing yields the same
        manifest, and existence is checked first)."""
        mkey = manifest_key(epoch)
        if self.store.exists(mkey):
            return json.loads(self.store.read(mkey).decode())
        manifest = {"epoch": epoch, "mode": self.mode,
                    "segments": [
                        {"writer": w, "key": k,
                         "bytes": self.store.size(k)}
                        for w, k in sorted(segs)]}
        # the storage-fault-during-commit chaos point: an epoch whose
        # manifest PUT fails stays invisible until recovery re-derives
        # and re-PUTs it from the (durable) staging listing
        fail_point("sink.manifest_commit")
        self.store.upload(mkey, json.dumps(
            manifest, sort_keys=True).encode())
        return manifest

    def commit_upto(self, floor: int) -> List[int]:
        """Commit every staged-but-unmanifested epoch ≤ the checkpoint
        floor (invariant 1: never past the floor). Listing-driven:
        robust to lost pre-commit drains and to zero-row writers."""
        done = []
        for epoch, segs in sorted(self.uncommitted_epochs().items()):
            if epoch <= floor:
                self.commit(epoch, segs)
                done.append(epoch)
        return done

    def recover(self, floor: int) -> Tuple[List[int], List[int]]:
        """Post-crash reconciliation: PROMOTE unmanifested epochs ≤
        floor (their staging is provably complete — invariant 2),
        TRUNCATE epochs > floor (their rows replay under fresh
        epochs), and sweep torn tmp garbage. Idempotent."""
        promoted, truncated = [], []
        staged = self.staged_epochs()
        known = {k for segs in staged.values() for _w, k in segs}
        for key in self.store.list(SEG_PREFIX):
            if key not in known:
                self.store.delete(key)      # mkstemp residue
        for epoch, segs in sorted(staged.items()):
            if self.store.exists(manifest_key(epoch)):
                continue
            if epoch <= floor:
                self.commit(epoch, segs)
                promoted.append(epoch)
            else:
                for _w, key in segs:
                    self.store.delete(key)
                truncated.append(epoch)
        return promoted, truncated

    # -- read surface -----------------------------------------------------
    def manifests(self) -> List[dict]:
        out = []
        for key in sorted(self.store.list(MANIFEST_PREFIX)):
            out.append(json.loads(self.store.read(key).decode()))
        return sorted(out, key=lambda m: m["epoch"])

    def committed_records(self):
        """Yield decoded records of every committed epoch in commit
        order (within an epoch: writer order — writers hold disjoint
        key partitions, so the order is not load-bearing)."""
        for m in self.manifests():
            for seg in m["segments"]:
                data = self.store.read(seg["key"])
                for line in data.splitlines():
                    if line:
                        yield json.loads(line.decode())

    def canonical_rows(self) -> List[str]:
        """The canonical (replay-invariant) content view. Epoch
        numbering is an artifact of one execution — a recovered run
        re-stages replayed rows under fresh epochs — so bit-identity
        across runs is defined on this view, not on raw manifests:
        append → every committed record, sorted; upsert → the folded
        final key→row state, sorted by key."""
        if self.mode == "append":
            return sorted(json.dumps(r, sort_keys=True)
                          for r in self.committed_records())
        state: Dict[str, dict] = {}
        for r in self.committed_records():
            k = json.dumps(r.get("__k"), sort_keys=True)
            if r.get("__op") == "D":
                state.pop(k, None)
            else:
                state[k] = r
        return [json.dumps(state[k], sort_keys=True)
                for k in sorted(state)]

    def canonical_bytes(self) -> bytes:
        return "\n".join(self.canonical_rows()).encode()


class AppendSegmentSink:
    """Append-only record encoder over an EpochSegmentTarget: inserts
    serialize 1:1; a retraction reaching this sink is a planner bug
    (the mode was PROVEN append-only), never silently dropped."""

    mode = "append"

    def __init__(self, target: EpochSegmentTarget):
        self.target = target

    def encode(self, records) -> List[bytes]:
        names = self.target.field_names
        out = []
        for op, row in records:
            if not op.is_insert:
                raise RuntimeError(
                    "retraction reached an append-only sink — the "
                    "append-only derivation admitted a retracting "
                    "plan")
            obj = {"__op": "I"}
            for i, v in enumerate(row):
                obj[names[i] if names else f"f{i}"] = _jsonable(v)
            out.append(json.dumps(obj, sort_keys=True).encode())
        return out

    def stage(self, epoch: int, writer: int, records) -> dict:
        return self.target.stage(epoch, writer, self.encode(records))


class UpsertSegmentSink:
    """Keyed upsert encoder: retractions FOLD per key within the
    epoch (last write wins; a surviving delete is a tombstone), so
    the staged segment carries one record per touched key."""

    mode = "upsert"

    def __init__(self, target: EpochSegmentTarget,
                 pk_indices: List[int]):
        assert pk_indices, "upsert sink needs a primary key"
        self.target = target
        self.pk_indices = list(pk_indices)

    def encode(self, records) -> List[bytes]:
        names = self.target.field_names
        folded: "Dict[tuple, Tuple[str, tuple]]" = {}
        for op, row in records:
            key = tuple(row[i] for i in self.pk_indices)
            folded[key] = ("U" if op.is_insert else "D", row)
        out = []
        for key in sorted(folded, key=lambda k: json.dumps(
                _jsonable(list(k)), sort_keys=True)):
            kind, row = folded[key]
            obj = {"__op": kind, "__k": _jsonable(list(key))}
            if kind == "U":
                for i, v in enumerate(row):
                    obj[names[i] if names else f"f{i}"] = _jsonable(v)
            out.append(json.dumps(obj, sort_keys=True).encode())
        return out

    def stage(self, epoch: int, writer: int, records) -> dict:
        return self.target.stage(epoch, writer, self.encode(records))


def make_sink_target(options: Dict[str, str], mode: str,
                     field_names: Optional[List[str]] = None
                     ) -> EpochSegmentTarget:
    """connector='epochlog' → EpochSegmentTarget over a local-FS
    object store at ``path`` (atomic temp+rename PUTs — the staging
    and manifest protocol requires atomic publication)."""
    from risingwave_tpu.storage.object_store import LocalFsObjectStore
    path = options.get("path")
    if not path:
        raise ValueError("epochlog sink needs path='...'")
    import os
    _TOUCHED_ROOTS.add(os.path.abspath(path))
    return EpochSegmentTarget(LocalFsObjectStore(path), mode=mode,
                              field_names=field_names)
