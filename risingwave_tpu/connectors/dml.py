"""In-process DML channel: the table-write path.

Reference parity: src/source/src/dml_manager.rs + the DmlExecutor —
batch INSERT/DELETE/UPDATE statements hand their chunks to the
table's streaming fragment through a registered channel, so table
writes flow through the SAME barrier/checkpoint pipeline as connector
data (exactly-once, MV chains see them as ordinary deltas).

TPU re-design: the reader side implements the SplitReader protocol
(stream/executors/source.py), so a plain SourceExecutor drives it;
``unbounded=True`` parks the source on its barrier channel while no
DML is pending instead of declaring the stream exhausted.

Replay: none. A DML statement only returns once its chunk's
checkpoint commits, so after recovery the committed table state IS
the statement's effect — seek() has nothing to do.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.common.types import Schema

_SEQ_BITS = 12            # row-id epoch window (row_id_gen.py scheme)


class DmlReader:
    """SplitReader over an in-process deque of DML chunks."""

    unbounded = True

    def __init__(self, schema: Schema):
        self.schema = schema
        self.split_id = "dml"
        self.offset = 0
        self._pending: deque = deque()

    def seek(self, offset: int) -> None:
        pass                       # nothing to replay (module docstring)

    def push(self, chunk: StreamChunk) -> None:
        self._pending.append(chunk)

    def next_chunk(self) -> Optional[StreamChunk]:
        if not self._pending:
            return None
        self.offset += 1
        return self._pending.popleft()


class RowIdSeq:
    """Hidden-_row_id allocator for tables without a PRIMARY KEY.
    Same epoch-rebase scheme as RowIdGenExecutor: ids from after a
    recovery start above every id allocated before it (the committed
    epoch is monotone), without persisting a counter."""

    def __init__(self) -> None:
        self._next = 0

    def take(self, committed_epoch: int, n: int) -> range:
        floor = (committed_epoch >> 16) << _SEQ_BITS
        if self._next < floor:
            self._next = floor
        start = self._next
        self._next += n
        return range(start, start + n)
