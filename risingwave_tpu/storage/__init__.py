"""Persistent state backend (hummock-lite).

Reference parity: src/storage/ — the Hummock LSM over object storage
(store.rs:72 traits, sstable/builder.rs:91 SST format, event_handler/
uploader.rs:567 checkpoint upload, compactor/). Re-designed small:
same *semantics* (epoch-MVCC keys, snapshot reads at a committed epoch,
shared-buffer → SST upload at checkpoint, version deltas, compaction),
different encoding details.
"""

from risingwave_tpu.storage.object_store import (
    DelayedObjectStore, LocalFsObjectStore, MemObjectStore, ObjectStore,
)
from risingwave_tpu.storage.hummock import HummockLite
from risingwave_tpu.storage.uploader import CheckpointUploader

__all__ = [
    "ObjectStore", "MemObjectStore", "LocalFsObjectStore",
    "DelayedObjectStore", "HummockLite", "CheckpointUploader",
]
