"""HummockLite: LSM state store over an object store.

Reference parity (semantics, not format):
- shared buffer / imms / upload-at-checkpoint:
  src/storage/src/hummock/event_handler/uploader.rs:567 — unsealed
  writes buffer per epoch; seal turns them immutable; ``sync(epoch)``
  builds one SST from all imms ≤ epoch and uploads it (the barrier
  commit's durability point, meta commit_epoch analog).
- version: L0 (time-ordered whole SSTs, newest last) + L1
  (key-disjoint sorted runs), persisted as a JSON version snapshot in
  the object store with a CURRENT pointer (HummockVersion/-Delta,
  src/meta/src/hummock/manager/mod.rs:1335). Restart loads CURRENT —
  recovery reads resume at the committed epoch.
- reads: merge shared-buffer → imms → L0 (newest first) → L1 with
  bloom-filter pruning for point gets (hummock_storage.rs read path).
- compaction: when L0 grows past a threshold, a merge of L0 with the
  overlapping L1 runs rewrites key-disjoint L1 runs, dropping versions
  shadowed below the committed epoch (compactor/compactor_runner.rs).
  Two arms, ``compaction_mode``:
    * ``"inline"`` (default): ``commit_ssts``/``commit_through`` call
      ``compact()`` synchronously — the single-process/test arm.
    * ``"dedicated"``: commits NEVER compact; a CompactionManager
      (meta/compaction.py) picks tasks off level snapshots, a
      compactor role executes the merge off the serving path
      (storage/compactor.py), and the result lands here as a
      compare-and-commit **version delta** (``reserve_task`` →
      ``apply_version_delta``/``abort_task``).
- GC: replaced objects are RETIRED, not deleted — a vacuum pass frees
  them only once no pinned version still references them
  (``pin_version``/``unpin_version``; every ``iter()`` pins at first
  next()). This is exact pin-counting (vacuum.rs analog), replacing
  the old "one compaction cycle of grace" heuristic.
"""

from __future__ import annotations

import heapq
import json
from collections import OrderedDict
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

from risingwave_tpu.state.store import StateStore, Value
from risingwave_tpu.utils.failpoint import fail_point
from risingwave_tpu.utils.metrics import STORAGE as _METRICS
from risingwave_tpu.storage.object_store import ObjectStore
from risingwave_tpu.storage.sst import (
    EPOCH_MASK, LazySst, Sst, SstBuilder, build_sst, full_key,
    split_full_key,
)
from risingwave_tpu.storage.value_codec import decode_row, encode_row

L0_COMPACT_THRESHOLD = 4
L1_TARGET_SST_BYTES = 4 * 1024 * 1024


def _user_prefix(hex_key: str) -> bytes:
    """SST-info boundary (hex) → table+user-key prefix: strips the
    8-byte inverted-epoch suffix, which would mis-order comparisons
    (shared by the level picker and the L1 binary search)."""
    return bytes.fromhex(hex_key)[:-8]


class HummockLite(StateStore):
    """Single-process LSM store: StateStore for every table id.

    ``two_phase=True`` (cluster workers): ``sync(epoch)`` only STAGES
    the uploaded SST in a durable side manifest; the version advances
    when the coordinator's commit decision arrives via
    ``commit_through(epoch)`` — the HummockManager::commit_epoch split
    (src/meta/src/hummock/manager/mod.rs:1335): compute nodes upload,
    meta owns the version. This is what makes a cluster checkpoint
    atomic: a worker that crashed after staging an epoch the
    coordinator never committed discards it on recovery
    (``discard_staged_above``) instead of resurrecting half an epoch.
    Staged SSTs stay readable (they are the newest layer) so the
    in-flight epoch's reads see the data it just flushed.
    """

    def __init__(self, obj: ObjectStore, two_phase: bool = False) -> None:
        self.obj = obj
        self.two_phase = two_phase
        self._staged: List[dict] = []   # [{"epoch": e, "sst": info}]
        # built-but-not-yet-committed checkpoint SSTs (the async
        # uploader's in-flight window): each entry carries the SST's
        # BYTES so reads keep seeing the flushed data between
        # ``build_ssts`` and ``commit_ssts`` without touching the
        # object store. In-memory only — a crash here loses nothing
        # the manifest ever referenced (recovery resumes at the last
        # committed version). Newest last, like L0.
        self._uploading: List[dict] = []
        # unsealed writes: epoch → table → key → (tombstone, row)
        self._mem: Dict[int, Dict[int, Dict[bytes, Value]]] = {}
        # sealed, not yet synced: newest last
        self._imms: List[Tuple[int, Dict[int, Dict[bytes, Value]]]] = []
        self._sealed_epoch = 0
        self._committed_epoch = 0
        self._version_id = 0
        self._next_sst_id = 1
        self._l0: List[dict] = []       # SST infos, newest LAST
        self._l1: List[dict] = []       # key-disjoint, sorted by smallest
        # block-granular cache (sstable_store.rs block_cache analog):
        # reads fetch byte ranges per block; hot blocks stay resident
        # under a byte budget. SST HANDLES (index+bloom only) cache
        # separately — they are small and bound the metadata round
        # trips. Compaction's one-shot sequential scans bypass both.
        from risingwave_tpu.storage.block_cache import BlockCache
        self._blocks = BlockCache()
        self._handles: OrderedDict[int, LazySst] = OrderedDict()
        self._handles_max = 256
        # -- compaction arms + pin-exact GC -----------------------------
        # "inline": commits compact synchronously (test/oracle arm);
        # "dedicated": commits never compact — the compactor subsystem
        # applies version deltas through reserve/apply/abort below.
        self.compaction_mode = "inline"
        # version pins: pin id → version_id the reader opened against.
        # A retired object is deletable only when every live pin is at
        # or past the version that replaced it.
        self._pins: Dict[int, int] = {}
        self._next_pin = 1
        # retired-but-not-deleted objects: {"id", "size", "since"}
        # (since = first version_id that no longer references the id)
        self._retired: List[dict] = []
        # in-flight dedicated tasks: frozenset(input ids) → reserved
        # output id block (base, cap)
        self._reservations: Dict[frozenset, Tuple[int, int]] = {}
        self._load_current()

    # -- manifest ---------------------------------------------------------
    def _load_current(self) -> None:
        if self.obj.exists("meta/STAGED.json"):
            self._staged = json.loads(
                self.obj.read("meta/STAGED.json").decode())
            # staged maxima apply even with no committed version yet:
            # a worker that crashed before its FIRST commit_through
            # must not reuse a staged SST's id or re-seal its epoch
            self._sealed_epoch = max(
                (s["epoch"] for s in self._staged), default=0)
            self._next_sst_id = max(
                (s["sst"]["id"] + 1 for s in self._staged),
                default=self._next_sst_id)
        if not self.obj.exists("meta/CURRENT"):
            return
        vid = int(self.obj.read("meta/CURRENT").decode())
        v = json.loads(self.obj.read(f"meta/v{vid}.json").decode())
        self._version_id = v["version_id"]
        self._committed_epoch = v["committed_epoch"]
        self._sealed_epoch = max(v["committed_epoch"],
                                 self._sealed_epoch)
        self._next_sst_id = max(v["next_sst_id"], self._next_sst_id)
        self._l0 = v["l0"]
        self._l1 = v["l1"]

    def _persist_staged(self) -> None:
        self.obj.upload("meta/STAGED.json",
                        json.dumps(self._staged).encode())

    def _commit_version(self) -> None:
        self._version_id += 1
        v = {
            "version_id": self._version_id,
            "committed_epoch": self._committed_epoch,
            "next_sst_id": self._next_sst_id,
            "l0": self._l0,
            "l1": self._l1,
        }
        self.obj.upload(f"meta/v{self._version_id}.json",
                        json.dumps(v).encode())
        self.obj.upload("meta/CURRENT", str(self._version_id).encode())
        old = f"meta/v{self._version_id - 2}.json"
        if self.obj.exists(old):
            self.obj.delete(old)

    # -- write path -------------------------------------------------------
    def ingest_batch(self, table_id: int,
                     batch: Iterable[Tuple[bytes, Value]],
                     epoch: int) -> int:
        if epoch <= self._sealed_epoch:
            raise ValueError(
                f"write at epoch {epoch} <= sealed {self._sealed_epoch}")
        t = self._mem.setdefault(epoch, {}).setdefault(table_id, {})
        n = 0
        for key, value in batch:
            t[key] = value
            n += 1
        return n

    def seal_epoch(self, epoch: int, is_checkpoint: bool = True) -> None:
        assert epoch >= self._sealed_epoch, (epoch, self._sealed_epoch)
        self._sealed_epoch = epoch
        for e in sorted(self._mem):
            if e <= epoch:
                self._imms.append((e, self._mem.pop(e)))
        self._imms.sort(key=lambda t: t[0])

    def sync(self, epoch: int) -> dict:
        """Make all data ≤ epoch durable: build → upload → commit,
        inline. The async checkpoint pipeline (storage/uploader.py)
        calls the three phases separately so only the build mutates
        loop-confined state and the upload runs off the event loop."""
        payloads = self.build_ssts(epoch)
        for p in payloads:
            self.upload_payload(p)
        return self.commit_ssts(epoch, payloads)

    def build_ssts(self, epoch: int) -> List[dict]:
        """CPU half of a checkpoint flush: drain every imm ≤ epoch into
        built-but-unpublished SSTs. The built SSTs join the in-memory
        ``_uploading`` read layer (newest above L0), so the flushed
        data stays readable while its upload is in flight. Returns the
        payloads to hand to ``upload_payload`` then ``commit_ssts``.

        Builds MUST run in epoch order (the imm drain is cumulative:
        a younger epoch's build would swallow an older epoch's imms) —
        the CheckpointUploader chains them."""
        fail_point("hummock.sync")
        take = [im for im in self._imms if im[0] <= epoch]
        self._imms = [im for im in self._imms if im[0] > epoch]
        entries: List[Tuple[bytes, bool, bytes]] = []
        for e, tables in take:
            for table_id, kv in tables.items():
                for key, value in kv.items():
                    fk = full_key(table_id, key, e)
                    tomb = value is None
                    entries.append(
                        (fk, tomb, b"" if tomb else encode_row(value)))
        if not entries:
            return []
        entries.sort(key=lambda t: t[0])
        sst_id = self._next_sst_id
        self._next_sst_id += 1
        data, info = build_sst(sst_id, entries)
        payload = {"epoch": epoch, "sst": info, "data": data}
        self._uploading.append(payload)
        return [payload]

    def upload_payload(self, payload: dict) -> None:
        """Durably store one built SST. Object-store I/O only — no
        store state is touched, so the uploader may run this in a
        worker thread (and retry it) while the event loop proceeds."""
        data = payload["data"]
        self.obj.upload(f"data/{payload['sst']['id']}.sst", data)
        _METRICS.sst_upload_count.inc(source="sync")
        _METRICS.sst_upload_bytes.inc(len(data), source="sync")

    def commit_ssts(self, epoch: int, payloads: List[dict]) -> dict:
        """Manifest-publish half: adopt the uploaded SSTs into the
        version (or the durable staged manifest in two-phase mode) and
        advance the committed epoch. Must be called in epoch order,
        only after every payload's upload durably landed — the
        version must never reference an object that may not exist."""
        ids = {p["sst"]["id"] for p in payloads}
        self._uploading = [u for u in self._uploading
                           if u["sst"]["id"] not in ids]
        info = None
        for p in payloads:
            info = p["sst"]
            if self.two_phase:
                self._staged.append({"epoch": p["epoch"], "sst": info})
            else:
                self._l0.append(info)
        if self.two_phase:
            if payloads:
                self._persist_staged()
            return {"sst": info}
        self._committed_epoch = max(self._committed_epoch, epoch)
        if (self.compaction_mode == "inline"
                and len(self._l0) >= L0_COMPACT_THRESHOLD):
            self.compact()
        else:
            self._commit_version()
        return {"sst": info}

    # -- two-phase commit plane (coordinator-driven) ----------------------
    def commit_through(self, epoch: int) -> None:
        """Adopt every staged SST ≤ epoch into the committed version —
        the commit decision the coordinator pipelines on the next
        barrier (HummockManager::commit_epoch)."""
        if epoch <= self._committed_epoch and not any(
                s["epoch"] <= epoch for s in self._staged):
            return
        adopt = [s for s in self._staged if s["epoch"] <= epoch]
        self._staged = [s for s in self._staged if s["epoch"] > epoch]
        for s in adopt:
            self._l0.append(s["sst"])
        self._committed_epoch = max(self._committed_epoch, epoch)
        if (self.compaction_mode == "inline"
                and len(self._l0) >= L0_COMPACT_THRESHOLD):
            self.compact()
        else:
            self._commit_version()
        if adopt:
            self._persist_staged()

    def discard_staged_above(self, epoch: int) -> int:
        """Recovery: drop staged SSTs the coordinator never committed
        (a crashed cluster's half-epoch must not resurrect)."""
        drop = [s for s in self._staged if s["epoch"] > epoch]
        self._staged = [s for s in self._staged if s["epoch"] <= epoch]
        for s in drop:
            self.obj.delete(f"data/{s['sst']['id']}.sst")
            self._handles.pop(s["sst"]["id"], None)
            self._blocks.drop_sst(s["sst"]["id"])
        if drop:
            self._persist_staged()
        # writes restart above what remains
        self._sealed_epoch = max(self._committed_epoch,
                                 max((s["epoch"] for s in self._staged),
                                     default=0))
        return len(drop)

    def committed_epoch(self) -> int:
        return self._committed_epoch

    def vacuum_orphans(self) -> int:
        """Recovery-time GC: delete data objects no manifest layer
        references — the async pipeline's crash residue (a kill with
        uploads in flight can strand up to max_uploading
        uploaded-but-uncommitted SSTs per generation, plus any
        deferred-vacuum garbage the dead generation never deleted).
        Single-writer assumption: call ONLY when this instance owns
        the namespace (the session recovery path; ctl inspects
        in-memory snapshot clones, where this is harmless). Returns
        the number of objects deleted."""
        live = {info["id"] for info in self._l0 + self._l1}
        live |= {s["sst"]["id"] for s in self._staged}
        live |= {u["sst"]["id"] for u in self._uploading}
        # retired objects vacuum through maybe_vacuum (pin-gated);
        # reserved output blocks belong to in-flight compaction tasks
        live |= {ent["id"] for ent in self._retired}
        for base, cap in self._reservations.values():
            live |= set(range(base, base + cap))
        dropped = 0
        for path in self.obj.list("data/"):
            name = path[len("data/"):]
            if not name.endswith(".sst"):
                continue
            try:
                sst_id = int(name[:-4])
            except ValueError:
                continue
            if sst_id not in live:
                self.obj.delete(path)
                self._handles.pop(sst_id, None)
                self._blocks.drop_sst(sst_id)
                dropped += 1
        return dropped

    # -- version pins + exact-count vacuum --------------------------------
    def pin_version(self) -> int:
        """Pin the CURRENT version: objects it references stay on disk
        until ``unpin_version``. Every ``iter()`` takes one at its
        first next(); the uploader window and staged layers are
        protected structurally (they are in the live set)."""
        pid = self._next_pin
        self._next_pin += 1
        self._pins[pid] = self._version_id
        return pid

    def unpin_version(self, pin: int) -> None:
        self._pins.pop(pin, None)
        self.maybe_vacuum()

    def pinned_versions(self) -> List[int]:
        return sorted(self._pins.values())

    def _retire(self, infos: List[dict], since: int) -> None:
        """Mark replaced objects for the pin-gated vacuum. ``since`` is
        the first version_id that no longer references them."""
        for info in infos:
            self._retired.append({"id": info["id"],
                                  "size": info.get("size", 0),
                                  "since": since})

    def maybe_vacuum(self) -> int:
        """Delete retired objects no pinned version can still read:
        deletable iff every live pin is ≥ the retiring version. A
        storage fault here only DELAYS GC (the entry stays retired and
        the next pass retries) — vacuum must never fail a commit or a
        version-delta apply."""
        if not self._retired:
            return 0
        floor = min(self._pins.values(), default=None)
        keep: List[dict] = []
        dropped = 0
        for ent in self._retired:
            if floor is not None and floor < ent["since"]:
                keep.append(ent)
                continue
            try:
                fail_point("hummock.vacuum")
                self.obj.delete(f"data/{ent['id']}.sst")
            except FileNotFoundError:
                pass               # already gone (recovery vacuumed it)
            except OSError:
                keep.append(ent)
                continue
            self._handles.pop(ent["id"], None)
            self._blocks.drop_sst(ent["id"])
            dropped += 1
        self._retired = keep
        self._update_space_amp()
        return dropped

    def _update_space_amp(self) -> None:
        """storage_space_amp gauge: (manifest-live + retired-on-disk)
        bytes over manifest-live bytes — 1.0 when GC is caught up, the
        honest measure of vacuum lag under pinned readers."""
        logical = sum(i.get("size", 0) for i in self._l0 + self._l1)
        dead = sum(ent.get("size", 0) for ent in self._retired)
        if logical > 0:
            _METRICS.storage_space_amp.set(
                round((logical + dead) / logical, 4))

    # -- dedicated-compaction plane (reserve → execute → apply) -----------
    def level_snapshot(self) -> dict:
        """Topology the CompactionManager's pickers read: per-level SST
        infos + the ids already frozen under an in-flight task."""
        reserved: set = set()
        for key in self._reservations:
            reserved |= set(key)
        return {
            "version_id": self._version_id,
            "committed_epoch": self._committed_epoch,
            "l0": [dict(i) for i in self._l0],
            "l1": [dict(i) for i in self._l1],
            "reserved": sorted(reserved),
        }

    def reserve_task(self, input_ids: List[int],
                     id_block: int = 16) -> dict:
        """Freeze a task's inputs and burn it a durable output-id
        block. Serving commits proceed concurrently — new L0 runs are
        simply not in the frozen input set. The id block commits to the
        manifest NOW so a compactor crash after uploading outputs can
        never race a later allocation onto the same ids."""
        inset = frozenset(input_ids)
        current = {i["id"] for i in self._l0 + self._l1}
        missing = sorted(inset - current)
        if missing:
            raise ValueError(
                f"compaction inputs not in current version: {missing}")
        for key in self._reservations:
            busy = sorted(inset & key)
            if busy:
                raise ValueError(
                    f"compaction inputs already reserved: {busy}")
        cap = max(1, id_block)
        base = self._next_sst_id
        self._next_sst_id += cap
        self._commit_version()
        self._reservations[inset] = (base, cap)
        return {"read_version": self._version_id,
                "safe_epoch": self._committed_epoch,
                "output_base": base, "output_cap": cap}

    def apply_version_delta(self, input_ids: List[int],
                            outputs: List[dict]) -> dict:
        """Compare-and-commit: swap EXACTLY the reserved inputs for the
        task's outputs. Raises ValueError (conflict) if any input is no
        longer in the current version — e.g. an inline compact ran in
        between — leaving levels untouched; the manager aborts and
        requeues. Inputs retire under the new version; vacuum frees
        them once no pin predates the swap."""
        inset = frozenset(input_ids)
        olds = [i for i in self._l0 + self._l1 if i["id"] in inset]
        if len(olds) != len(inset):
            have = {i["id"] for i in olds}
            self._reservations.pop(inset, None)
            raise ValueError(
                f"version delta conflict: inputs "
                f"{sorted(inset - have)} no longer current")
        keep = [i for i in self._l1 if i["id"] not in inset]
        merged = sorted(keep + [dict(i) for i in outputs],
                        key=lambda i: _user_prefix(i["smallest"]))
        for a, b in zip(merged, merged[1:]):
            if _user_prefix(a["largest"]) >= _user_prefix(b["smallest"]):
                self._reservations.pop(inset, None)
                raise ValueError(
                    f"version delta conflict: outputs overlap L1 run "
                    f"{b['id']} — task inputs were not range-complete")
        self._l0 = [i for i in self._l0 if i["id"] not in inset]
        self._l1 = merged
        self._commit_version()
        self._reservations.pop(inset, None)
        self._retire(olds, self._version_id)
        _METRICS.compaction_bytes_read.inc(
            sum(i.get("size", 0) for i in olds), arm="dedicated")
        _METRICS.compaction_bytes_written.inc(
            sum(i.get("size", 0) for i in outputs), arm="dedicated")
        self.maybe_vacuum()
        self._update_space_amp()
        return {"version_id": self._version_id}

    def abort_task(self, input_ids: List[int],
                   output_ids: List[int]) -> None:
        """Release a failed/expired task: unfreeze its inputs and
        delete any outputs it managed to upload (their ids stay
        burned — never reused)."""
        self._reservations.pop(frozenset(input_ids), None)
        for sid in output_ids:
            try:
                self.obj.delete(f"data/{sid}.sst")
            except OSError:
                pass
            self._handles.pop(sid, None)
            self._blocks.drop_sst(sid)

    # -- SST access -------------------------------------------------------
    def _sst(self, info: dict) -> LazySst:
        s = self._handles.get(info["id"])
        if s is None:
            s = LazySst(self.obj, f"data/{info['id']}.sst", info,
                        cache=self._blocks)
            self._handles[info["id"]] = s
            while len(self._handles) > self._handles_max:
                self._handles.popitem(last=False)
        else:
            self._handles.move_to_end(info["id"])
        return s

    def _upload_sst(self, entry: dict) -> Sst:
        """Read handle over a built-but-uncommitted SST: the bytes are
        still in memory, so no object-store round trip."""
        s = entry.get("handle")
        if s is None:
            s = entry["handle"] = Sst(entry["data"], entry["sst"])
        return s

    def _sst_once(self, info: dict) -> Sst:
        """Whole-bytes read for one-shot sequential scans (compaction
        consumes every block exactly once — caching would only evict
        the hot read path)."""
        return Sst(self.obj.read(f"data/{info['id']}.sst"), info)

    # -- read path --------------------------------------------------------
    def get(self, table_id: int, key: bytes, epoch: int) -> Value:
        # 1) unsealed epochs, newest first
        for e in sorted(self._mem, reverse=True):
            if e > epoch:
                continue
            kv = self._mem[e].get(table_id)
            if kv is not None and key in kv:
                return kv[key]
        # 2) imms, newest first
        for e, tables in reversed(self._imms):
            if e > epoch:
                continue
            kv = tables.get(table_id)
            if kv is not None and key in kv:
                return kv[key]
        # 3) built-but-uncommitted checkpoint SSTs (async upload in
        # flight — newer than anything committed), newest first
        for u in reversed(self._uploading):
            if u["sst"]["min_epoch"] > epoch:
                continue
            hit = self._upload_sst(u).get(table_id, key, epoch)
            if hit is not None:
                _found, tomb, row = hit
                return None if tomb else decode_row(row)
        # 4) staged (two-phase, newest layer) → L0 newest → oldest,
        # then L1 (bloom-pruned point lookups)
        for s in reversed(self._staged):
            info = s["sst"]
            if info["min_epoch"] > epoch:
                continue
            hit = self._sst(info).get(table_id, key, epoch)
            if hit is not None:
                _found, tomb, row = hit
                return None if tomb else decode_row(row)
        for info in reversed(self._l0):
            if info["min_epoch"] > epoch:
                continue
            hit = self._sst(info).get(table_id, key, epoch)
            if hit is not None:
                _found, tomb, row = hit
                return None if tomb else decode_row(row)
        lo = self._l1_candidate(table_id, key)
        if lo is not None:
            hit = self._sst(self._l1[lo]).get(table_id, key, epoch)
            if hit is not None:
                _found, tomb, row = hit
                return None if tomb else decode_row(row)
        return None

    def _l1_candidate(self, table_id: int, key: bytes) -> Optional[int]:
        """Run that could hold (table, key) — compare USER-key prefixes;
        the inverted-epoch suffix would mis-order full-key compares."""
        if not self._l1:
            return None
        target = full_key(table_id, key, 0)[:-8]
        lo, hi, ans = 0, len(self._l1) - 1, None
        while lo <= hi:
            mid = (lo + hi) // 2
            if _user_prefix(self._l1[mid]["smallest"]) <= target:
                ans = mid
                lo = mid + 1
            else:
                hi = mid - 1
        if ans is None:
            return None
        # key beyond this run's largest user key ⇒ in no run (disjoint)
        if _user_prefix(self._l1[ans]["largest"]) < target:
            return None
        return ans

    def iter(self, table_id: int, epoch: int,
             start: Optional[bytes] = None, end: Optional[bytes] = None,
             reverse: bool = False) -> Iterator[Tuple[bytes, tuple]]:
        """Snapshot range scan: newest version ≤ epoch per key, no
        tombstones — a k-way merge across all layers. `reverse=True`
        scans keys DESCENDING (backward iterator; the merge key flips
        the user key but keeps newest-version-first within a key).

        The scan PINS the version at its first next() and unpins when
        exhausted or closed: compactions committing mid-scan retire the
        replaced objects but the vacuum cannot free them until this
        reader finishes — an iterator opened before a compaction reads
        its snapshot to completion, however many compactions land."""
        def gen():
            pin = self.pin_version()
            try:
                yield from self._iter_impl(table_id, epoch, start, end,
                                           reverse)
            finally:
                self.unpin_version(pin)
        return gen()

    def _iter_impl(self, table_id: int, epoch: int,
                   start: Optional[bytes], end: Optional[bytes],
                   reverse: bool) -> Iterator[Tuple[bytes, tuple]]:
        start = start or b""
        sources = []
        rank = 0

        def mem_source(e: int, kv: Dict[bytes, Value], r: int):
            inv = (~e) & EPOCH_MASK
            for k in sorted(kv, reverse=reverse):
                if k < start or (end is not None and k >= end):
                    continue
                yield (k, inv, r, kv[k])

        for e in sorted(self._mem, reverse=True):
            if e <= epoch:
                kv = self._mem[e].get(table_id)
                if kv:
                    sources.append(mem_source(e, kv, rank))
                    rank += 1
        for e, tables in reversed(self._imms):
            if e <= epoch:
                kv = tables.get(table_id)
                if kv:
                    sources.append(mem_source(e, kv, rank))
                    rank += 1

        def sst_source(sst, r: int):
            sfk = full_key(table_id, start, EPOCH_MASK)
            for fk, tomb, row in sst.iter_from(sfk):
                t, uk, e = split_full_key(fk)
                if t != table_id:
                    break
                if end is not None and uk >= end:
                    break
                if e > epoch:
                    continue
                yield (uk, (~e) & EPOCH_MASK, r,
                       None if tomb else decode_row(row))

        def sst_source_rev(sst, r: int):
            # descending keys; within one user key iter_rev yields
            # versions oldest-first (fk order), so buffer the tiny
            # same-key run and re-emit newest-first
            import struct as _s
            ufk = _s.pack(">I", table_id + 1) if end is None else \
                full_key(table_id, end, EPOCH_MASK)
            run: List[tuple] = []
            run_uk: Optional[bytes] = None
            for fk, tomb, row in sst.iter_rev(ufk):
                t, uk, e = split_full_key(fk)
                if t != table_id or uk < start:
                    break
                if end is not None and uk >= end:
                    continue
                if e > epoch:
                    continue
                item = (uk, (~e) & EPOCH_MASK, r,
                        None if tomb else decode_row(row))
                if uk != run_uk:
                    yield from reversed(run)
                    run, run_uk = [], uk
                run.append(item)
            yield from reversed(run)

        mk = sst_source_rev if reverse else sst_source
        for u in reversed(self._uploading):
            sources.append(mk(self._upload_sst(u), rank))
            rank += 1
        for s in reversed(self._staged):
            sources.append(mk(self._sst(s["sst"]), rank))
            rank += 1
        for info in reversed(self._l0):
            sources.append(mk(self._sst(info), rank))
            rank += 1
        for info in self._l1:
            sources.append(mk(self._sst(info), rank))
            rank += 1

        if reverse:
            # descending user keys; within a key newest version first
            # (EPOCH_MASK - inv descends with reverse=True ⇢ inv
            # ascends), lowest rank breaking ties
            merged = heapq.merge(
                *sources, reverse=True,
                key=lambda t: (t[0], EPOCH_MASK - t[1], -t[2]))
        else:
            merged = heapq.merge(
                *sources, key=lambda t: (t[0], t[1], t[2]))
        last_key: Optional[bytes] = None
        for uk, _inv, _r, value in merged:
            if uk == last_key:
                continue
            last_key = uk
            if value is not None:
                yield uk, value

    # -- compaction -------------------------------------------------------
    def compact(self) -> None:
        """Leveled compaction (level picker): merge L0 with ONLY the
        L1 runs whose user-key range overlaps L0's — untouched runs
        carry over unread (manager/compaction picker analog; the r3
        build rewrote the whole L1 every trigger, O(total LSM) write
        amplification per compaction instead of O(overlap)).

        Within the compacted range every level participates, so the
        old full-merge GC rules hold unchanged there: versions
        shadowed below the committed epoch drop, and a tombstone that
        is the newest surviving version drops with its key. Replaced
        objects retire into the pin-gated vacuum (an in-flight scan
        that pinned an older version keeps them readable).
        """
        # key range of the L0 files being absorbed (user-key compare:
        # the inverted-epoch suffix would mis-order full keys)
        if self._l0:
            lo = min(_user_prefix(i["smallest"]) for i in self._l0)
            hi = max(_user_prefix(i["largest"]) for i in self._l0)
            overlap, keep_lo, keep_hi = [], [], []
            for info in self._l1:
                if _user_prefix(info["largest"]) < lo:
                    keep_lo.append(info)
                elif _user_prefix(info["smallest"]) > hi:
                    keep_hi.append(info)
                else:
                    overlap.append(info)
        else:
            # manual full compaction (ctl / tests): absorb everything
            overlap, keep_lo, keep_hi = list(self._l1), [], []
        olds = list(self._l0) + overlap
        if not olds:
            self._commit_version()
            return
        safe = self._committed_epoch

        def source(info: dict, r: int):
            for fk, tomb, row in self._sst_once(info).iter_from(b""):
                yield (fk, r, tomb, row)

        merged = heapq.merge(
            *[source(info, r)
              for r, info in enumerate(reversed(list(self._l0)))] +
            [source(info, len(self._l0) + r)
             for r, info in enumerate(overlap)],
            key=lambda t: (t[0], t[1]))

        new_infos: List[dict] = []
        builder: Optional[SstBuilder] = None
        last_tu: Optional[bytes] = None
        kept_le_safe = False

        def out(fk: bytes, tomb: bool, row: bytes) -> None:
            nonlocal builder
            # cut SSTs ONLY at user-key boundaries: all versions of one
            # key must live in one run or _l1_candidate's disjoint-run
            # binary search would find the wrong (stale) run
            if (builder is not None
                    and builder._off + builder.block.size()
                    >= L1_TARGET_SST_BYTES
                    and builder.largest is not None
                    and builder.largest[:-8] != fk[:-8]):
                data, info = builder.finish()
                self.obj.upload(f"data/{info['id']}.sst", data)
                _METRICS.sst_upload_count.inc(source="compact")
                _METRICS.sst_upload_bytes.inc(len(data),
                                              source="compact")
                new_infos.append(info)
                builder = None
            if builder is None:
                builder = SstBuilder(self._next_sst_id)
                self._next_sst_id += 1
            builder.add(fk, tomb, row)

        seen_fk: Optional[bytes] = None
        for fk, _r, tomb, row in merged:
            if fk == seen_fk:
                continue               # same key+epoch: newer layer wins
            seen_fk = fk
            tu = fk[:-8]
            _t, _u, e = split_full_key(fk)
            if tu != last_tu:
                last_tu = tu
                kept_le_safe = False
            if e > safe:
                out(fk, tomb, row)
                continue
            if kept_le_safe:
                continue               # older shadowed version: drop
            kept_le_safe = True
            if tomb:
                continue               # newest ≤ safe is a delete: gone
            out(fk, tomb, row)
        if builder is not None:
            data, info = builder.finish()
            self.obj.upload(f"data/{info['id']}.sst", data)
            _METRICS.sst_upload_count.inc(source="compact")
            _METRICS.sst_upload_bytes.inc(len(data), source="compact")
            new_infos.append(info)
        self._l0 = []
        # splice: untouched runs below + rewritten range + above stays
        # key-disjoint and sorted (the picker chose by range)
        self._l1 = keep_lo + new_infos + keep_hi
        self._commit_version()
        # pin-exact GC (vacuum.rs analog): retire the replaced objects
        # under the new version; the vacuum frees each only once no
        # pinned reader (in-flight scan) predates the swap
        self._retire(olds, self._version_id)
        _METRICS.compaction_bytes_read.inc(
            sum(i.get("size", 0) for i in olds), arm="inline")
        _METRICS.compaction_bytes_written.inc(
            sum(i.get("size", 0) for i in new_infos), arm="inline")
        self.maybe_vacuum()
        self._update_space_amp()

    # -- test/debug helpers ----------------------------------------------
    def table_size(self, table_id: int, epoch: int) -> int:
        return sum(1 for _ in self.iter(table_id, epoch))

    @property
    def levels(self) -> Tuple[int, int]:
        return len(self._l0), len(self._l1)
