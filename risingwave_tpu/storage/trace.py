"""Storage trace: record state-store operations, replay + verify.

Reference parity: src/storage/hummock_trace/ (risingwave_hummock_trace)
— a recording layer over the state-store API plus a replay tool that
re-executes the trace against a fresh store and verifies every read
returns byte-identical results. Used the same way: capture a failing
workload's storage interaction once, then replay it deterministically
(no stream, no timing) to bisect storage bugs.

Records are JSONL-able dicts; values are host row tuples (bytes hex-
tagged so the encoding is lossless and diffable).
"""

from __future__ import annotations

import json
from typing import Iterator, List, Optional, Tuple

from risingwave_tpu.state.store import StateStore, Value


def _enc_val(v):
    if isinstance(v, bytes):
        return {"__b": v.hex()}
    if isinstance(v, tuple):
        return {"__t": [_enc_val(x) for x in v]}
    return v


def _dec_val(v):
    if isinstance(v, dict):
        if "__b" in v:
            return bytes.fromhex(v["__b"])
        if "__t" in v:
            return tuple(_dec_val(x) for x in v["__t"])
    return v


class TracingStateStore(StateStore):
    """Record every store op + read result (hummock_trace recorder)."""

    def __init__(self, inner: StateStore):
        self.inner = inner
        self.records: List[dict] = []

    # -- write path -------------------------------------------------------
    def ingest_batch(self, table_id, batch, epoch) -> int:
        batch = list(batch)
        self.records.append({
            "op": "ingest", "table": table_id, "epoch": epoch,
            "batch": [[k.hex(), _enc_val(v)] for k, v in batch]})
        return self.inner.ingest_batch(table_id, batch, epoch)

    def seal_epoch(self, epoch, is_checkpoint=True) -> None:
        self.records.append({"op": "seal", "epoch": epoch,
                             "ckpt": bool(is_checkpoint)})
        self.inner.seal_epoch(epoch, is_checkpoint)

    def sync(self, epoch) -> dict:
        self.records.append({"op": "sync", "epoch": epoch})
        return self.inner.sync(epoch)

    def committed_epoch(self) -> int:
        return self.inner.committed_epoch()

    # -- read path (results recorded for replay verification) -------------
    def get(self, table_id, key, epoch) -> Value:
        v = self.inner.get(table_id, key, epoch)
        self.records.append({"op": "get", "table": table_id,
                             "key": key.hex(), "epoch": epoch,
                             "result": _enc_val(v)})
        return v

    def iter(self, table_id, epoch, start=None, end=None,
             reverse: bool = False) -> Iterator[Tuple[bytes, tuple]]:
        out = list(self.inner.iter(table_id, epoch, start, end,
                                   reverse=reverse))
        self.records.append({
            "op": "iter", "table": table_id, "epoch": epoch,
            "start": None if start is None else start.hex(),
            "end": None if end is None else end.hex(),
            "reverse": reverse,
            "result": [[k.hex(), _enc_val(v)] for k, v in out]})
        return iter(out)

    # -- persistence ------------------------------------------------------
    def dump(self, path: str) -> int:
        with open(path, "w") as f:
            for r in self.records:
                f.write(json.dumps(r) + "\n")
        return len(self.records)


def load_trace(path: str) -> List[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def replay_trace(records, store: StateStore) -> List[dict]:
    """Re-execute a trace against a FRESH store; every recorded read
    must return identical results. Returns the mismatches (empty =
    the storage layer is deterministic for this workload) — the
    hummock_trace replay verifier."""
    mismatches: List[dict] = []
    for i, r in enumerate(records):
        op = r["op"]
        if op == "ingest":
            store.ingest_batch(
                r["table"],
                [(bytes.fromhex(k), _dec_val(v))
                 for k, v in r["batch"]], r["epoch"])
        elif op == "seal":
            store.seal_epoch(r["epoch"], r["ckpt"])
        elif op == "sync":
            store.sync(r["epoch"])
        elif op == "get":
            got = store.get(r["table"], bytes.fromhex(r["key"]),
                            r["epoch"])
            want = _dec_val(r["result"])
            if got != want:
                mismatches.append({"at": i, "op": "get",
                                   "got": got, "want": want})
        elif op == "iter":
            got = list(store.iter(
                r["table"], r["epoch"],
                None if r["start"] is None
                else bytes.fromhex(r["start"]),
                None if r["end"] is None
                else bytes.fromhex(r["end"]),
                reverse=r.get("reverse", False)))
            want = [(bytes.fromhex(k), _dec_val(v))
                    for k, v in r["result"]]
            if got != want:
                mismatches.append({"at": i, "op": "iter",
                                   "got_n": len(got),
                                   "want_n": len(want)})
    return mismatches
