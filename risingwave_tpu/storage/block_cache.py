"""Block cache: byte-budget LRU of SST block bytes.

Reference parity: src/storage/src/hummock/sstable_store.rs's
block_cache — reads touch BLOCKS, not whole SSTs, so a point get on a
cold 64MB SST ships one ~4KB block (an S3 byte-range GET through
ObjectStore.read_range) and hot blocks stay resident under an explicit
byte budget. Replaces the whole-decoded-SST LRU the r3 verdict called
out ("no block-granular cache").
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Tuple

from risingwave_tpu.utils.metrics import STORAGE as _METRICS


class BlockCache:
    """(sst_id, block_idx) → block bytes, evicted by byte budget."""

    def __init__(self, capacity_bytes: int = 32 << 20):
        self.capacity = capacity_bytes
        self._blocks: "OrderedDict[Tuple[int, int], bytes]" = \
            OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def get_or_load(self, key: Tuple[int, int],
                    loader: Callable[[], bytes]) -> bytes:
        b = self._blocks.get(key)
        if b is not None:
            self.hits += 1
            _METRICS.block_cache_hits.inc()
            self._blocks.move_to_end(key)
            return b
        self.misses += 1
        _METRICS.block_cache_misses.inc()
        b = loader()
        self._blocks[key] = b
        self._bytes += len(b)
        while self._bytes > self.capacity and self._blocks:
            _k, old = self._blocks.popitem(last=False)
            self._bytes -= len(old)
        return b

    def drop_sst(self, sst_id: int) -> None:
        """Vacuum hook: a deleted SST's blocks must not be served."""
        for k in [k for k in self._blocks if k[0] == sst_id]:
            self._bytes -= len(self._blocks.pop(k))

    def nbytes(self) -> int:
        return self._bytes
