"""CheckpointUploader: the asynchronous, ordered checkpoint-commit
pipeline between the barrier loop and the state store.

Reference parity: src/storage/src/hummock/event_handler/uploader.rs:567
— compute nodes build and upload checkpoint SSTs in a background
uploader; meta commits the epoch once the uploads land. Hazelcast Jet
(PAPERS.md) attributes its tail latencies to the same decoupling:
snapshotting never rides the processing path.

The barrier loop's ``collect_next`` only SEALS an epoch and submits it
here. The pipeline then, per epoch:

  1. BUILDS the epoch's SSTs (``store.build_ssts``) — strictly in
     epoch order, because the shared-buffer drain is cumulative (a
     younger epoch's build would swallow an older epoch's imms). The
     build mutates store state, so it stays on the event loop, just
     off the barrier's critical path.
  2. UPLOADS the built SSTs (``store.upload_payload``) through a
     bounded-concurrency queue, each object-store PUT offloaded via
     ``asyncio.to_thread`` so the event loop never blocks on I/O, with
     exponential-backoff retries for transient failures.
  3. COMMITS the epoch (``store.commit_ssts``) strictly in order once
     its uploads durably landed — ``committed_epoch`` NEVER skips past
     an unfinished older epoch, so the manifest only ever references
     objects that exist.

The sealed-but-uncommitted window is bounded (``max_uploading``):
``submit`` back-pressures the barrier loop instead of letting staging
grow without bound. A failed upload (out of retries) poisons the
pipeline: younger epochs never commit past it, ``failed`` wakes the
barrier loop immediately, and the original error surfaces from the
next ``submit``/``drain``/``raise_if_failed``.

Stores without the build/commit split (MemoryStateStore, the cluster
coordinator's epoch shim) take the inline ``sync()`` fallback — same
ordering and callbacks, no overlap.
"""

from __future__ import annotations

import asyncio
import time
from collections import OrderedDict, deque
from typing import Callable, Deque, Optional

from risingwave_tpu.utils.metrics import (
    STORAGE as _STORAGE, STREAMING as _STREAMING,
)


class CheckpointUploader:
    """Ordered async build→upload→commit pipeline for one store."""

    def __init__(self, store,
                 max_uploading: int = 4,
                 upload_concurrency: int = 2,
                 upload_retries: int = 2,
                 retry_backoff_s: float = 0.05,
                 monotonic: Callable[[], float] = time.monotonic,
                 on_commit: Optional[Callable[[int, float], None]] = None):
        self.store = store
        self._split = (hasattr(store, "build_ssts")
                       and hasattr(store, "commit_ssts"))
        self.max_uploading = max(1, max_uploading)
        self.upload_retries = max(0, upload_retries)
        self.retry_backoff_s = retry_backoff_s
        self.monotonic = monotonic
        self.on_commit = on_commit
        # epoch → task, insertion (= epoch) order; the back-pressure
        # wait rides the OLDEST entry because commits are ordered
        self._tasks: "OrderedDict[int, asyncio.Task]" = OrderedDict()
        # build/commit chains: each submitted epoch awaits its
        # predecessor's future before building / committing
        self._built_chain: Optional[asyncio.Future] = None
        self._commit_chain: Optional[asyncio.Future] = None
        self._concurrency = max(1, upload_concurrency)
        self._sem = asyncio.Semaphore(self._concurrency)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self.committed_epoch = store.committed_epoch()
        # ordered commit history — bounded like EpochProfiler.profiles
        # (a long-lived server just loses the oldest entries)
        self.commit_log: Deque[int] = deque(maxlen=1 << 16)
        self.failed = asyncio.Event()        # set on terminal failure
        self._failure: Optional[BaseException] = None
        # exactly-once sinks (meta/sink_coordinator.py): the owner of
        # this uploader attaches its SinkCoordinator here. Deferred
        # sink payloads stage in the epoch's async tail BEFORE the
        # durable commit (the floor never advances past unstaged
        # rows), and manifests commit strictly AFTER it (a manifest
        # never outruns the floor) — the two crash-window invariants
        # of connectors/sink.py live in this ordering
        self.sinks = None

    # -- introspection ----------------------------------------------------
    @property
    def depth(self) -> int:
        """Epochs sealed but not yet durably committed (the uploading
        window the barrier loop reports alongside in_flight)."""
        return len(self._tasks)

    def raise_if_failed(self) -> None:
        if self._failure is not None:
            raise self._failure

    def _set_depth(self) -> None:
        _STREAMING.uploader_queue_depth.set(len(self._tasks))

    def bind_loop(self) -> None:
        """Re-bind the loop-bound primitives (Semaphore/Event) to the
        CURRENT running loop. asyncio primitives latch onto the loop
        they are first awaited on; a BarrierLoop driven across
        separate asyncio.run() calls (each a fresh loop) worked before
        this pipeline existed and must keep working — recreating the
        idle primitives restores that. Only legal with no epochs in
        flight (they would hold futures of the dead loop)."""
        loop = asyncio.get_running_loop()
        if self._loop is loop:
            return
        assert not self._tasks, \
            "checkpoint uploader moved event loops with epochs in flight"
        self._loop = loop
        self._sem = asyncio.Semaphore(self._concurrency)
        was_failed = self.failed.is_set()
        self.failed = asyncio.Event()
        if was_failed:
            self.failed.set()
        self._built_chain = None
        self._commit_chain = None

    # -- the pipeline -----------------------------------------------------
    async def submit(self, epoch: int) -> bool:
        """Hand a sealed epoch to the pipeline. Returns as soon as the
        flush task is queued (True), blocking only when the uploading
        window is full (back-pressure) or on the inline fallback;
        False when the epoch needs no flush (caller drops per-epoch
        bookkeeping it registered ahead of the call)."""
        self.raise_if_failed()
        self.bind_loop()
        if epoch <= self.committed_epoch:
            # the recovery-initial barrier's prev IS the recovered
            # committed epoch — nothing new can be staged at or below
            # it (writes are rejected below the sealed epoch)
            return False
        if not self._split:
            t0 = self.monotonic()
            if self.sinks is not None:
                self.sinks.stage_upto_sync(epoch)
            self.store.sync(epoch)
            self._note_commit(epoch, self.monotonic() - t0)
            if self.sinks is not None:
                self.sinks.commit_upto(epoch)
            return True
        while len(self._tasks) >= self.max_uploading:
            await asyncio.wait({next(iter(self._tasks.values()))})
            self.raise_if_failed()
        loop = asyncio.get_running_loop()
        prev_built, prev_committed = self._built_chain, self._commit_chain
        built = loop.create_future()
        committed = loop.create_future()
        self._built_chain, self._commit_chain = built, committed
        self._tasks[epoch] = asyncio.ensure_future(self._run_epoch(
            epoch, prev_built, built, prev_committed, committed))
        self._set_depth()
        return True

    async def drain(self) -> None:
        """Await every in-flight epoch's durable commit (checkpoint()/
        shutdown barrier semantics); raises the pipeline's failure."""
        while self._tasks:
            await asyncio.wait(set(self._tasks.values()))
        self.raise_if_failed()

    async def _run_epoch(self, epoch: int,
                         prev_built: Optional[asyncio.Future],
                         built: asyncio.Future,
                         prev_committed: Optional[asyncio.Future],
                         committed: asyncio.Future) -> None:
        t0 = self.monotonic()
        try:
            if prev_built is not None:
                await prev_built
            if self._failure is not None:
                # an older epoch died mid-build: draining imms past it
                # could orphan its data — abort before touching state
                raise self._failure
            try:
                payloads = self.store.build_ssts(epoch)
            finally:
                if not built.done():
                    built.set_result(None)
            for p in payloads:
                await self._upload(p)
            if self.sinks is not None:
                # sink staging is part of the epoch's durability set:
                # it must land before the commit below advances the
                # floor, and it rides the same async tail the SST
                # uploads do (upload_s, never barrier_wait)
                await self.sinks.stage_upto(epoch)
            if prev_committed is not None:
                await prev_committed
            if self._failure is not None:
                raise self._failure      # NEVER commit past a failure
            self.store.commit_ssts(epoch, payloads)
            self._note_commit(epoch, self.monotonic() - t0)
            if self.sinks is not None:
                await asyncio.to_thread(self.sinks.commit_upto, epoch)
        except asyncio.CancelledError:
            raise
        except BaseException as e:  # noqa: BLE001 — recorded, not lost
            if self._failure is None:
                self._failure = e
                self.failed.set()
        finally:
            # complete the chains even on failure/cancellation so
            # younger epochs wake up (they re-check _failure and abort
            # instead of committing)
            if not built.done():
                built.set_result(None)
            if not committed.done():
                committed.set_result(None)
            self._tasks.pop(epoch, None)
            self._set_depth()

    async def _upload(self, payload: dict) -> None:
        """One payload's durable upload: thread-offloaded PUT under the
        concurrency bound, retried with exponential backoff before the
        failure poisons the pipeline (fails the barrier)."""
        delay = self.retry_backoff_s
        for attempt in range(self.upload_retries + 1):
            async with self._sem:
                try:
                    await asyncio.to_thread(self.store.upload_payload,
                                            payload)
                    return
                except asyncio.CancelledError:
                    raise
                except BaseException:
                    if attempt >= self.upload_retries:
                        raise
                    _STORAGE.sst_upload_retries.inc()
            await asyncio.sleep(delay)
            delay *= 2

    def _note_commit(self, epoch: int, upload_s: float) -> None:
        assert epoch > self.committed_epoch, \
            (epoch, self.committed_epoch)    # ordered, never skips
        self.committed_epoch = epoch
        self.commit_log.append(epoch)
        _STREAMING.barrier_upload.observe(upload_s)
        if self.on_commit is not None:
            self.on_commit(epoch, upload_s)
