"""SST: immutable sorted-run file format.

Reference parity: src/storage/src/hummock/sstable/{builder.rs:91,
block.rs, xor_filter.rs} and the FullKey encoding of
hummock_sdk/src/key.rs:48-79 — same *semantics*, smaller format:

  full key  = table_id(4B BE) ++ user_key ++ (~epoch)(8B BE)
              → byte order == (table, key asc, epoch DESC): the newest
              version of a key is the first one an iterator meets.
  block     = restart-interval prefix-compressed entries
              [shared][unshared][vlen][key suffix][value]; value byte 0
              is the tombstone flag, the rest is value_codec row bytes.
  filter    = split-block Bloom (10 bits/key, k=7) over
              table_id ++ user_key — point-get pruning, same role as
              the reference's xor filter.
  footer    = block index (first key + offset + len per block),
              smallest/largest key, epoch range, magic.

Builders take entries pre-sorted (the LSM merge guarantees it);
everything is write-once (object-store friendly).
"""

from __future__ import annotations

import struct
import zlib
from typing import Iterator, List, Optional, Tuple

import numpy as np

from risingwave_tpu import native as _native
from risingwave_tpu.storage.value_codec import (
    read_uvarint, write_uvarint,
)

MAGIC = b"RWT1"
BLOCK_TARGET = 64 * 1024
RESTART_INTERVAL = 16
BLOOM_BITS_PER_KEY = 10
BLOOM_K = 7

EPOCH_MASK = (1 << 64) - 1


def _esc_user(user_key: bytes) -> bytes:
    """Order-preserving PREFIX-FREE encoding of arbitrary byte keys:
    0x00 → 0x00 0xFF, terminated by 0x00 0x00. Without this, a user key
    that is a byte-prefix of another would compare differently once the
    inverted-epoch suffix is appended, breaking full-key ordering (and
    with it the merge iterators and the L1 disjoint-run search)."""
    return user_key.replace(b"\x00", b"\x00\xff") + b"\x00\x00"


def _unesc_user(enc: bytes) -> bytes:
    assert enc.endswith(b"\x00\x00"), enc
    return enc[:-2].replace(b"\x00\xff", b"\x00")


def full_key(table_id: int, user_key: bytes, epoch: int) -> bytes:
    return (struct.pack(">I", table_id) + _esc_user(user_key)
            + struct.pack(">Q", (~epoch) & EPOCH_MASK))


def split_full_key(fk: bytes) -> Tuple[int, bytes, int]:
    table_id = struct.unpack_from(">I", fk, 0)[0]
    epoch = (~struct.unpack_from(">Q", fk, len(fk) - 8)[0]) & EPOCH_MASK
    return table_id, _unesc_user(fk[4:-8]), epoch


def _bloom_hashes(data: bytes) -> Tuple[int, int]:
    h1 = zlib.crc32(data) & 0xFFFFFFFF
    h2 = zlib.crc32(data, 0x9E3779B9) & 0xFFFFFFFF
    return h1, h2 | 1


class _BloomBuilder:
    def __init__(self) -> None:
        self.items: List[bytes] = []

    def add(self, data: bytes) -> None:
        self.items.append(data)

    def finish(self) -> bytes:
        n = max(1, len(self.items))
        nbits = max(64, n * BLOOM_BITS_PER_KEY)
        nbits = (nbits + 7) // 8 * 8
        nat = _native.lib()
        if nat is not None and self.items:
            import ctypes
            blob = b"".join(self.items)
            lens = (ctypes.c_int32 * len(self.items))(
                *[len(i) for i in self.items])
            bits = ctypes.create_string_buffer(nbits // 8)
            nat.rw_bloom_build(blob, lens, len(self.items), BLOOM_K,
                               bits, nbits)
            return bits.raw
        bits = np.zeros(nbits, dtype=bool)
        for item in self.items:
            h1, h2 = _bloom_hashes(item)
            for i in range(BLOOM_K):
                bits[(h1 + i * h2) % nbits] = True
        return np.packbits(bits).tobytes()


def bloom_may_contain(filter_bytes: bytes, data: bytes) -> bool:
    if not filter_bytes:
        return True
    nbits = len(filter_bytes) * 8
    nat = _native.lib()
    if nat is not None:
        return bool(nat.rw_bloom_may_contain(data, len(data),
                                             filter_bytes, nbits,
                                             BLOOM_K))
    h1, h2 = _bloom_hashes(data)
    for i in range(BLOOM_K):
        bit = (h1 + i * h2) % nbits
        if not (filter_bytes[bit >> 3] >> (7 - (bit & 7))) & 1:
            return False
    return True


class _BlockBuilder:
    """Buffers entries; encoding happens at finish() (native or py)."""

    def __init__(self) -> None:
        self.keys: List[bytes] = []
        self.values: List[bytes] = []
        self._size = 0
        self.count = 0
        self.first_key = b""

    def add(self, key: bytes, value: bytes) -> None:
        if self.count == 0:
            self.first_key = key
        self.keys.append(key)
        self.values.append(value)
        # conservative size estimate (uncompressed + varint headroom)
        self._size += len(key) + len(value) + 6
        self.count += 1

    def size(self) -> int:
        return self._size

    def finish(self) -> bytes:
        nat = _native.lib()
        if nat is not None and self.count:
            import ctypes
            kblob = b"".join(self.keys)
            vblob = b"".join(self.values)
            klens = (ctypes.c_int32 * self.count)(
                *[len(k) for k in self.keys])
            vlens = (ctypes.c_int32 * self.count)(
                *[len(v) for v in self.values])
            cap = self._size + 30 * self.count
            out = ctypes.create_string_buffer(cap)
            n = nat.rw_block_encode(kblob, klens, vblob, vlens,
                                    self.count, RESTART_INTERVAL, out,
                                    cap)
            if n >= 0:
                return out.raw[:n]
        buf = bytearray()
        last_key = b""
        for i, (key, value) in enumerate(zip(self.keys, self.values)):
            if i % RESTART_INTERVAL == 0:
                shared = 0
            else:
                shared = 0
                m = min(len(key), len(last_key))
                while shared < m and key[shared] == last_key[shared]:
                    shared += 1
            write_uvarint(buf, shared)
            write_uvarint(buf, len(key) - shared)
            write_uvarint(buf, len(value))
            buf.extend(key[shared:])
            buf.extend(value)
            last_key = key
        return bytes(buf)


def _iter_block_py(data: bytes) -> Iterator[Tuple[bytes, bytes]]:
    pos = 0
    key = b""
    n = len(data)
    while pos < n:
        shared, pos = read_uvarint(data, pos)
        unshared, pos = read_uvarint(data, pos)
        vlen, pos = read_uvarint(data, pos)
        key = key[:shared] + data[pos:pos + unshared]
        pos += unshared
        value = data[pos:pos + vlen]
        pos += vlen
        yield key, value


def iter_block(data: bytes) -> Iterator[Tuple[bytes, bytes]]:
    nat = _native.lib()
    if nat is None or not data:
        yield from _iter_block_py(data)
        return
    import ctypes
    max_entries = len(data)           # ≥ true count (≥1 byte/entry)
    # modest caps: prefix compression rarely expands 4x on real keys;
    # the -1 overflow return falls back to the Python decoder
    keys_cap = vals_cap = len(data) * 4 + 65536
    keys_out = ctypes.create_string_buffer(keys_cap)
    vals_out = ctypes.create_string_buffer(vals_cap)
    klens = (ctypes.c_int32 * max_entries)()
    vlens = (ctypes.c_int32 * max_entries)()
    n = nat.rw_block_decode(data, len(data), keys_out, keys_cap, klens,
                            vals_out, vals_cap, vlens, max_entries)
    if n < 0:                          # overflow/malformed → fallback
        yield from _iter_block_py(data)
        return
    kused = sum(klens[i] for i in range(n))
    vused = sum(vlens[i] for i in range(n))
    kraw = ctypes.string_at(keys_out, kused)   # copy USED bytes only
    vraw = ctypes.string_at(vals_out, vused)
    kp = vp = 0
    for i in range(n):
        kl, vl = klens[i], vlens[i]
        yield kraw[kp:kp + kl], vraw[vp:vp + vl]
        kp += kl
        vp += vl


def build_sst(sst_id: int,
              entries: Iterator[Tuple[bytes, bool, bytes]]
              ) -> Tuple[bytes, dict]:
    """Pre-sorted (full_key, tombstone, row_bytes) entries → one SST's
    (bytes, info). The pure-CPU half of a checkpoint flush, shared by
    the inline ``sync`` path and the async CheckpointUploader's
    off-critical-path build (storage/uploader.py)."""
    b = SstBuilder(sst_id)
    for fk, tomb, row in entries:
        b.add(fk, tomb, row)
    return b.finish()


class SstBuilder:
    """Builds one SST from pre-sorted (full_key, tombstone, row_bytes)."""

    def __init__(self, sst_id: int) -> None:
        self.sst_id = sst_id
        self.blocks: List[bytes] = []
        self.index: List[Tuple[bytes, int, int]] = []  # first_key, off, len
        self.block = _BlockBuilder()
        self.bloom = _BloomBuilder()
        self.smallest: Optional[bytes] = None
        self.largest: Optional[bytes] = None
        self.count = 0
        self.tombstones = 0
        self.min_epoch = EPOCH_MASK
        self.max_epoch = 0
        self._off = 0
        self._last_user = None

    def add(self, fk: bytes, tombstone: bool, row: bytes) -> None:
        assert self.largest is None or fk > self.largest, "unsorted add"
        value = (b"\x01" if tombstone else b"\x00") + row
        self.block.add(fk, value)
        if self.smallest is None:
            self.smallest = fk
        self.largest = fk
        table_user = fk[:-8]
        if table_user != self._last_user:
            self.bloom.add(table_user)
            self._last_user = table_user
        _t, _u, epoch = split_full_key(fk)
        self.min_epoch = min(self.min_epoch, epoch)
        self.max_epoch = max(self.max_epoch, epoch)
        self.count += 1
        if tombstone:
            self.tombstones += 1
        if self.block.size() >= BLOCK_TARGET:
            self._flush_block()

    def _flush_block(self) -> None:
        if self.block.count == 0:
            return
        data = self.block.finish()
        self.index.append((self.block.first_key, self._off, len(data)))
        self.blocks.append(data)
        self._off += len(data)
        self.block = _BlockBuilder()

    def finish(self) -> Tuple[bytes, dict]:
        self._flush_block()
        out = bytearray()
        for b in self.blocks:
            out.extend(b)
        bloom = self.bloom.finish() if self.count else b""
        meta = bytearray()
        write_uvarint(meta, len(self.index))
        for first, off, ln in self.index:
            write_uvarint(meta, len(first))
            meta.extend(first)
            write_uvarint(meta, off)
            write_uvarint(meta, ln)
        write_uvarint(meta, len(bloom))
        meta.extend(bloom)
        meta_off = len(out)
        out.extend(meta)
        out.extend(struct.pack(">Q", meta_off))
        out.extend(MAGIC)
        info = {
            "id": self.sst_id,
            "smallest": (self.smallest or b"").hex(),
            "largest": (self.largest or b"").hex(),
            "count": self.count,
            # tombstone density feeds the reclaim picker; older
            # manifests lack the field — readers .get(, 0)
            "tombstones": self.tombstones,
            "min_epoch": self.min_epoch if self.count else 0,
            "max_epoch": self.max_epoch,
            "size": len(out),
        }
        return bytes(out), info


def _parse_meta(buf: bytes, pos: int
                ) -> Tuple[List[Tuple[bytes, int, int]], bytes]:
    """Meta section → (block index [(first_key, off, len)], bloom).
    Block offsets are ABSOLUTE file positions, so the meta slice of a
    ranged read parses identically to the whole buffer."""
    n, pos = read_uvarint(buf, pos)
    index: List[Tuple[bytes, int, int]] = []
    for _ in range(n):
        kl, pos = read_uvarint(buf, pos)
        first = buf[pos:pos + kl]
        pos += kl
        off, pos = read_uvarint(buf, pos)
        ln, pos = read_uvarint(buf, pos)
        index.append((first, off, ln))
    bl, pos = read_uvarint(buf, pos)
    return index, buf[pos:pos + bl]


class _SstOps:
    """Shared read algorithms over a block index; subclasses provide
    `_block_bytes(i)` (whole-buffer or ranged/cached access)."""

    index: List[Tuple[bytes, int, int]]
    bloom: bytes

    def _block_bytes(self, i: int) -> bytes:      # pragma: no cover
        raise NotImplementedError

    def may_contain(self, table_id: int, user_key: bytes) -> bool:
        # bloom keys are the ESCAPED table+user prefix (what add() hashed)
        return bloom_may_contain(
            self.bloom, struct.pack(">I", table_id) + _esc_user(user_key))

    def _block_range(self, start_fk: bytes) -> int:
        """Index of the first block that could contain start_fk."""
        lo, hi = 0, len(self.index) - 1
        ans = 0
        while lo <= hi:
            mid = (lo + hi) // 2
            if self.index[mid][0] <= start_fk:
                ans = mid
                lo = mid + 1
            else:
                hi = mid - 1
        return ans

    def iter_from(self, start_fk: bytes, lazy: bool = False
                  ) -> Iterator[Tuple[bytes, bool, bytes]]:
        """(full_key, tombstone, row_bytes) in order, from start_fk.

        lazy=True decodes entry-by-entry in Python — right for point
        gets that stop after one hit; the default native whole-block
        decode wins for scans that consume most of the block."""
        if not self.index:
            return
        decode = _iter_block_py if lazy else iter_block
        bi = self._block_range(start_fk)
        for i in range(bi, len(self.index)):
            for fk, value in decode(self._block_bytes(i)):
                if fk < start_fk:
                    continue
                yield fk, value[0] == 1, value[1:]

    def iter_rev(self, upper_fk: Optional[bytes] = None
                 ) -> Iterator[Tuple[bytes, bool, bytes]]:
        """(full_key, tombstone, row_bytes) in DESCENDING key order,
        from the largest key ≤ upper_fk (backward iterator — the r3
        verdict's missing direction). Blocks decode forward then
        reverse: prefix compression only restores front-to-back."""
        if not self.index:
            return
        bi = len(self.index) - 1 if upper_fk is None \
            else self._block_range(upper_fk)
        for i in range(bi, -1, -1):
            entries = list(iter_block(self._block_bytes(i)))
            for fk, value in reversed(entries):
                if upper_fk is not None and fk > upper_fk:
                    continue
                yield fk, value[0] == 1, value[1:]

    def get(self, table_id: int, user_key: bytes, epoch: int
            ) -> Optional[Tuple[bool, bool, bytes]]:
        """(found, tombstone, row_bytes) for newest version ≤ epoch."""
        if not self.may_contain(table_id, user_key):
            return None
        start = full_key(table_id, user_key, epoch)   # epoch desc order
        prefix = start[:-8]
        for fk, tomb, row in self.iter_from(start, lazy=True):
            if fk[:-8] != prefix:
                return None
            return (True, tomb, row)
        return None


class Sst(_SstOps):
    """Read handle over one SST's full bytes."""

    def __init__(self, data: bytes, info: Optional[dict] = None) -> None:
        assert data[-4:] == MAGIC, "bad SST magic"
        meta_off = struct.unpack_from(">Q", data, len(data) - 12)[0]
        self.data = data
        self.info = info or {}
        self.index, self.bloom = _parse_meta(data, meta_off)

    def _block_bytes(self, i: int) -> bytes:
        _first, off, ln = self.index[i]
        return self.data[off:off + ln]


class LazySst(_SstOps):
    """Ranged-read handle: footer + meta load once; blocks fetch on
    demand through a shared BlockCache (sstable_store.rs block_cache
    analog) — a point get on a cold SST ships ONE block, not the file."""

    def __init__(self, obj, path: str, info: Optional[dict] = None,
                 cache=None) -> None:
        self.obj = obj
        self.path = path
        self.info = info or {}
        self.cache = cache
        size = obj.size(path)
        foot = obj.read_range(path, size - 12, 12)
        assert foot[-4:] == MAGIC, "bad SST magic"
        meta_off = struct.unpack(">Q", foot[:8])[0]
        meta = obj.read_range(path, meta_off, size - 12 - meta_off)
        self.index, self.bloom = _parse_meta(meta, 0)
        # ranged reads parse the meta SLICE: offsets are absolute, so
        # a block fetch below seeks the file directly

    def _block_bytes(self, i: int) -> bytes:
        _first, off, ln = self.index[i]
        if self.cache is None:
            return self.obj.read_range(self.path, off, ln)
        sst_id = int(self.info.get("id", -1))
        return self.cache.get_or_load(
            (sst_id, i),
            lambda: self.obj.read_range(self.path, off, ln))
