"""Object store abstraction.

Reference parity: src/object_store/src/object/mod.rs:81-121 — the
`ObjectStore` trait (upload/read/delete/list) with S3/OpenDAL/mem
backends. Here: an in-memory backend for tests and a local-FS backend
(atomic temp+rename writes) standing in for cloud object storage; the
interface is what matters — hummock-lite only ever uploads immutable
whole objects and reads them back, exactly the reference's access
pattern (SSTs are write-once).
"""

from __future__ import annotations

import os
import tempfile
from typing import Dict, List, Protocol

from risingwave_tpu.utils.failpoint import fail_point


class ObjectStore(Protocol):
    def upload(self, path: str, data: bytes) -> None: ...

    def read(self, path: str) -> bytes: ...

    def delete(self, path: str) -> None: ...

    def list(self, prefix: str) -> List[str]: ...

    def exists(self, path: str) -> bool: ...


class MemObjectStore:
    """In-memory object store (object/mem.rs analog)."""

    def __init__(self) -> None:
        self._objects: Dict[str, bytes] = {}

    def upload(self, path: str, data: bytes) -> None:
        fail_point("object_store.upload")
        self._objects[path] = bytes(data)

    def read(self, path: str) -> bytes:
        fail_point("object_store.read")
        return self._objects[path]

    def read_range(self, path: str, off: int, length: int) -> bytes:
        """Ranged read (S3 byte-range GET analog) — the block cache's
        way to touch one block without shipping the whole SST."""
        fail_point("object_store.read")
        return self._objects[path][off:off + length]

    def size(self, path: str) -> int:
        return len(self._objects[path])

    def delete(self, path: str) -> None:
        self._objects.pop(path, None)

    def list(self, prefix: str) -> List[str]:
        return sorted(p for p in self._objects if p.startswith(prefix))

    def exists(self, path: str) -> bool:
        return path in self._objects


class LocalFsObjectStore:
    """Filesystem-backed store (OpenDAL-fs analog); atomic uploads."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _abs(self, path: str) -> str:
        p = os.path.normpath(os.path.join(self.root, path))
        if not (p == self.root or p.startswith(self.root + os.sep)):
            raise ValueError(f"path escapes object-store root: {path}")
        return p

    def upload(self, path: str, data: bytes) -> None:
        fail_point("object_store.upload")
        dst = self._abs(path)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(dst))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, dst)          # atomic publish
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def read(self, path: str) -> bytes:
        fail_point("object_store.read")
        with open(self._abs(path), "rb") as f:
            return f.read()

    def read_range(self, path: str, off: int, length: int) -> bytes:
        fail_point("object_store.read")
        with open(self._abs(path), "rb") as f:
            f.seek(off)
            return f.read(length)

    def size(self, path: str) -> int:
        return os.path.getsize(self._abs(path))

    def delete(self, path: str) -> None:
        try:
            os.unlink(self._abs(path))
        except FileNotFoundError:
            pass

    def list(self, prefix: str) -> List[str]:
        out = []
        root = os.path.abspath(self.root)
        for dirpath, _dirs, files in os.walk(root):
            for fn in files:
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                rel = rel.replace(os.sep, "/")
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)

    def exists(self, path: str) -> bool:
        return os.path.exists(self._abs(path))
