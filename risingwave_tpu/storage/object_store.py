"""Object store abstraction.

Reference parity: src/object_store/src/object/mod.rs:81-121 — the
`ObjectStore` trait (upload/read/delete/list) with S3/OpenDAL/mem
backends. Here: an in-memory backend for tests and a local-FS backend
(atomic temp+rename writes) standing in for cloud object storage; the
interface is what matters — hummock-lite only ever uploads immutable
whole objects and reads them back, exactly the reference's access
pattern (SSTs are write-once).
"""

from __future__ import annotations

import contextlib
import os
import tempfile
import time
from typing import Dict, List, Optional, Protocol

from risingwave_tpu.utils.failpoint import fail_point
from risingwave_tpu.utils.metrics import STORAGE as _METRICS

_suppress_ops = 0


@contextlib.contextmanager
def unmetered():
    """Suppress op metering for the block (tooling copies — the ctl
    snapshot clone — must not count as serving traffic)."""
    global _suppress_ops
    _suppress_ops += 1
    try:
        yield
    finally:
        _suppress_ops -= 1


def _record_op(op: str, t0: float) -> None:
    """Op count + latency per object-store verb (the object_store_
    operation metric family every backend feeds)."""
    if _suppress_ops:
        return
    _METRICS.object_store_ops.inc(op=op)
    _METRICS.object_store_latency.observe(
        time.perf_counter() - t0, op=op)


class ObjectStore(Protocol):
    def upload(self, path: str, data: bytes) -> None: ...

    def read(self, path: str) -> bytes: ...

    def delete(self, path: str) -> None: ...

    def list(self, prefix: str) -> List[str]: ...

    def exists(self, path: str) -> bool: ...


class MemObjectStore:
    """In-memory object store (object/mem.rs analog)."""

    def __init__(self) -> None:
        self._objects: Dict[str, bytes] = {}

    def upload(self, path: str, data: bytes) -> None:
        fail_point("object_store.upload")
        t0 = time.perf_counter()
        self._objects[path] = bytes(data)
        _record_op("upload", t0)

    def read(self, path: str) -> bytes:
        fail_point("object_store.read")
        t0 = time.perf_counter()
        data = self._objects[path]
        _record_op("read", t0)
        return data

    def read_range(self, path: str, off: int, length: int) -> bytes:
        """Ranged read (S3 byte-range GET analog) — the block cache's
        way to touch one block without shipping the whole SST."""
        fail_point("object_store.read")
        t0 = time.perf_counter()
        data = self._objects[path][off:off + length]
        _record_op("read_range", t0)
        return data

    def size(self, path: str) -> int:
        return len(self._objects[path])

    def delete(self, path: str) -> None:
        self._objects.pop(path, None)

    def list(self, prefix: str) -> List[str]:
        return sorted(p for p in self._objects if p.startswith(prefix))

    def exists(self, path: str) -> bool:
        return path in self._objects


class LocalFsObjectStore:
    """Filesystem-backed store (OpenDAL-fs analog); atomic uploads."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _abs(self, path: str) -> str:
        p = os.path.normpath(os.path.join(self.root, path))
        if not (p == self.root or p.startswith(self.root + os.sep)):
            raise ValueError(f"path escapes object-store root: {path}")
        return p

    def upload(self, path: str, data: bytes) -> None:
        fail_point("object_store.upload")
        t0 = time.perf_counter()
        dst = self._abs(path)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(dst))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, dst)          # atomic publish
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        _record_op("upload", t0)

    def read(self, path: str) -> bytes:
        fail_point("object_store.read")
        t0 = time.perf_counter()
        with open(self._abs(path), "rb") as f:
            data = f.read()
        _record_op("read", t0)
        return data

    def read_range(self, path: str, off: int, length: int) -> bytes:
        fail_point("object_store.read")
        t0 = time.perf_counter()
        with open(self._abs(path), "rb") as f:
            f.seek(off)
            data = f.read(length)
        _record_op("read_range", t0)
        return data

    def size(self, path: str) -> int:
        return os.path.getsize(self._abs(path))

    def delete(self, path: str) -> None:
        try:
            os.unlink(self._abs(path))
        except FileNotFoundError:
            pass

    def list(self, prefix: str) -> List[str]:
        out = []
        root = os.path.abspath(self.root)
        for dirpath, _dirs, files in os.walk(root):
            for fn in files:
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                rel = rel.replace(os.sep, "/")
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)

    def exists(self, path: str) -> bool:
        return os.path.exists(self._abs(path))


class DelayedObjectStore:
    """Latency-injecting wrapper over any ObjectStore: sleeps
    ``delay_s`` in ``upload`` for paths under ``prefix`` (SST data by
    default), delegating everything else untouched. Stands in for real
    object-store round trips when exercising the async checkpoint
    pipeline — the sleep blocks the CALLING thread, so an upload
    offloaded via ``asyncio.to_thread`` keeps the event loop live
    while an inline upload visibly stalls it."""

    def __init__(self, inner: ObjectStore, delay_s: float = 0.05,
                 prefix: str = "data/") -> None:
        self.inner = inner
        self.delay_s = delay_s
        self.prefix = prefix

    def upload(self, path: str, data: bytes) -> None:
        if path.startswith(self.prefix):
            time.sleep(self.delay_s)
        self.inner.upload(path, data)

    def __getattr__(self, name):
        return getattr(self.inner, name)


class RetryingObjectStore:
    """Transient-fault absorption for any ObjectStore: ``upload`` /
    ``read`` / ``read_range`` retry with jittered exponential backoff
    before the error surfaces (the graduated-response ladder's bottom
    rung — a flaky PUT/GET never reaches the recovery supervisor).

    Transient means OSError/IOError that is NOT a missing object:
    ``FileNotFoundError`` (and path-escape ``ValueError``) surface
    immediately — a 404 retried is a correctness bug hidden, not a
    fault absorbed. Jitter draws from a PRNG seeded per PROCESS by
    default (pid): N workers hitting one flaky endpoint must draw
    DIFFERENT jitter or the anti-stampede spread is a no-op; pass an
    explicit seed for fully reproducible timing. Each retry increments
    ``object_store_retry_total{op=...}``.
    """

    def __init__(self, inner: ObjectStore, retries: int = 3,
                 backoff_s: float = 0.02, backoff_cap_s: float = 1.0,
                 seed: Optional[int] = None) -> None:
        import random
        self.inner = inner
        self.retries = max(0, retries)
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self._rng = random.Random(os.getpid() if seed is None
                                  else seed)

    def _retry(self, op: str, fn, *args):
        delay = self.backoff_s
        for attempt in range(self.retries + 1):
            try:
                return fn(*args)
            except FileNotFoundError:
                raise                      # missing ≠ transient
            except (OSError, IOError):
                if attempt >= self.retries:
                    raise
                _METRICS.object_store_retries.inc(op=op)
                # full jitter: uniform in (0.5, 1.5)× the backoff —
                # concurrent retriers (N upload threads against one
                # flaky endpoint) must not stampede in lockstep
                time.sleep(delay * (0.5 + self._rng.random()))
                delay = min(delay * 2, self.backoff_cap_s)

    def upload(self, path: str, data: bytes) -> None:
        return self._retry("upload", self.inner.upload, path, data)

    def read(self, path: str) -> bytes:
        return self._retry("read", self.inner.read, path)

    def read_range(self, path: str, off: int, length: int) -> bytes:
        return self._retry("read_range", self.inner.read_range,
                           path, off, length)

    def __getattr__(self, name):
        return getattr(self.inner, name)


class S3ObjectStore:
    """S3-API backend (object/s3.rs analog): whole-object PUT/GET/
    DELETE/HEAD, byte-range GET for the block cache, ListObjectsV2 —
    over plain stdlib HTTP against any S3-compatible endpoint
    (AWS, MinIO, ceph-rgw). AWS SigV4 request signing is implemented
    here with hmac/hashlib (no SDK dependency); passing no credentials
    sends unsigned requests (anonymous/dev-mode endpoints).

    Path-style addressing (endpoint/bucket/key) — the form every
    S3-compatible store accepts.
    """

    def __init__(self, endpoint: str, bucket: str, prefix: str = "",
                 access_key: str = None, secret_key: str = None,
                 region: str = "us-east-1") -> None:
        from urllib.parse import urlparse
        u = urlparse(endpoint)
        self._secure = u.scheme == "https"
        self._host = u.netloc
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region

    # -- SigV4 (AWS Signature Version 4) ------------------------------
    def _sign(self, method: str, canonical_uri: str, query: str,
              headers: dict, payload_hash: str) -> dict:
        import datetime
        import hashlib
        import hmac
        if self.access_key is None:
            return headers
        t = datetime.datetime.now(datetime.timezone.utc)
        amz_date = t.strftime("%Y%m%dT%H%M%SZ")
        datestamp = t.strftime("%Y%m%d")
        headers = dict(headers)
        headers["x-amz-date"] = amz_date
        headers["x-amz-content-sha256"] = payload_hash
        signed = sorted(k.lower() for k in headers) + ["host"]
        signed = sorted(set(signed))
        hdrmap = {k.lower(): str(v).strip()
                  for k, v in headers.items()}
        hdrmap["host"] = self._host
        canonical_headers = "".join(
            f"{k}:{hdrmap[k]}\n" for k in signed)
        signed_headers = ";".join(signed)
        creq = "\n".join([method, canonical_uri, query,
                          canonical_headers, signed_headers,
                          payload_hash])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        sts = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(creq.encode()).hexdigest()])

        def _hmac(key, msg):
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = _hmac(("AWS4" + self.secret_key).encode(), datestamp)
        k = _hmac(k, self.region)
        k = _hmac(k, "s3")
        k = _hmac(k, "aws4_request")
        sig = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={sig}")
        return headers

    def _key(self, path: str) -> str:
        return f"{self.prefix}/{path}" if self.prefix else path

    def _request(self, method: str, path: str, query: str = "",
                 body: bytes = b"", headers: dict = None):
        import hashlib
        import http.client
        from urllib.parse import quote
        if query:
            # SigV4 canonicalizes query params SORTED; sending them in
            # the same order keeps signature and request identical
            query = "&".join(sorted(query.split("&")))
        uri = "/" + quote(f"{self.bucket}/{self._key(path)}"
                          if path else self.bucket)
        payload_hash = hashlib.sha256(body).hexdigest()
        hdrs = dict(headers or {})
        hdrs = self._sign(method, uri, query, hdrs, payload_hash)
        conn = (http.client.HTTPSConnection if self._secure
                else http.client.HTTPConnection)(self._host, timeout=30)
        try:
            url = uri + ("?" + query if query else "")
            conn.request(method, url, body=body, headers=hdrs)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, data, dict(resp.getheaders())
        finally:
            conn.close()

    # -- ObjectStore protocol -----------------------------------------
    def upload(self, path: str, data: bytes) -> None:
        fail_point("object_store.upload")
        t0 = time.perf_counter()
        status, body, _h = self._request("PUT", path, body=data)
        if status not in (200, 201, 204):
            raise IOError(f"S3 PUT {path}: {status} {body[:200]!r}")
        _record_op("upload", t0)

    def read(self, path: str) -> bytes:
        fail_point("object_store.read")
        t0 = time.perf_counter()
        status, data, _h = self._request("GET", path)
        if status == 404:
            raise FileNotFoundError(path)
        if status != 200:
            raise IOError(f"S3 GET {path}: {status}")
        _record_op("read", t0)
        return data

    def read_range(self, path: str, off: int, length: int) -> bytes:
        fail_point("object_store.read")
        t0 = time.perf_counter()
        status, data, _h = self._request(
            "GET", path,
            headers={"Range": f"bytes={off}-{off + length - 1}"})
        if status in (200, 206):
            # a 200 means the endpoint ignored Range — slice locally
            _record_op("read_range", t0)
            return data[off:off + length] if status == 200 else data
        if status == 404:
            raise FileNotFoundError(path)
        raise IOError(f"S3 ranged GET {path}: {status}")

    def size(self, path: str) -> int:
        status, _d, h = self._request("HEAD", path)
        if status != 200:
            raise FileNotFoundError(path)
        return int(h.get("Content-Length", "0"))

    def delete(self, path: str) -> None:
        status, _d, _h = self._request("DELETE", path)
        if status not in (200, 204, 404):
            raise IOError(f"S3 DELETE {path}: {status}")

    def exists(self, path: str) -> bool:
        status, _d, _h = self._request("HEAD", path)
        return status == 200

    def list(self, prefix: str) -> List[str]:
        import xml.etree.ElementTree as ET
        from urllib.parse import quote
        full = self._key(prefix)
        keys: List[str] = []
        token = None
        while True:
            query = f"list-type=2&prefix={quote(full, safe='')}"
            if token:
                query += ("&continuation-token="
                          + quote(token, safe=""))
            status, data, _h = self._request("GET", "", query=query)
            if status != 200:
                raise IOError(f"S3 LIST {prefix}: {status}")
            root = ET.fromstring(data)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[:root.tag.index("}") + 1]
            keys += [e.text for e in root.iter(f"{ns}Key")]
            # a page holds ≤1000 keys; follow the continuation chain
            # or vacuum/recovery would see a truncated namespace
            trunc = next(root.iter(f"{ns}IsTruncated"), None)
            if trunc is None or trunc.text != "true":
                break
            tok = next(root.iter(f"{ns}NextContinuationToken"), None)
            if tok is None or not tok.text:
                break
            token = tok.text
        strip = (self.prefix + "/") if self.prefix else ""
        return sorted(k[len(strip):] if strip and
                      k.startswith(strip) else k for k in keys)
