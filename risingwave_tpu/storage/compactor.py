"""Dedicated compactor: off-path compaction merge execution.

Reference parity: src/storage/src/hummock/compactor/compactor_runner.rs
— the compactor node receives a task naming a FROZEN input SST set and
a reserved output-id block, merges against the object store, uploads
the outputs, and reports back; the version change happens elsewhere
(meta's compare-and-commit version delta — here
``HummockLite.apply_version_delta``). Because ``execute_task`` never
touches the owning store's in-memory state, it can run on a background
thread (``InProcessCompactor``, the single-process session's arm) or
in a dedicated subprocess (``role="compactor"`` in cluster/worker.py)
while serving commits keep landing new L0 runs concurrently — the
arxiv 1904.03800 concurrent-state stance: the merge reads an immutable
snapshot, reconciliation is a single atomic swap.

Merge semantics mirror ``HummockLite.compact`` exactly (the inline arm
is the oracle): newest layer wins per (key, epoch); versions shadowed
below the task's safe epoch drop; a tombstone that is the newest
surviving version ≤ safe drops ONLY on bottom-level merges (``bottom``
flag) — a non-bottom merge must keep it or data in lower levels would
resurrect.
"""

from __future__ import annotations

import heapq
from typing import List, Optional

from risingwave_tpu.storage.object_store import ObjectStore
from risingwave_tpu.storage.sst import Sst, SstBuilder, split_full_key
from risingwave_tpu.utils.failpoint import fail_point
from risingwave_tpu.utils.metrics import STORAGE as _METRICS

# default output cut size — re-declared (not imported from hummock) so
# this module has no import cycle with the store it serves
TARGET_SST_BYTES = 4 * 1024 * 1024


def execute_task(obj: ObjectStore, task: dict) -> dict:
    """Run one compaction task against the object store and return
    ``{"outputs": [sst infos], "bytes_read": n, "bytes_written": n}``.

    The task dict carries ``inputs_l0`` (in L0 order, newest LAST, as
    the level stores them), ``inputs_l1`` (overlapping runs in L1
    order), ``safe_epoch``, ``bottom``, and the reserved id block
    ``output_base``/``output_cap`` from ``reserve_task``. Outputs cut
    at user-key boundaries at ``target_bytes`` — all versions of one
    key stay in one run (the L1 disjoint-run binary search depends on
    it). Exhausting the id block raises (the manager aborts and
    requeues with a bigger grant) rather than minting unreserved ids.
    """
    fail_point("compactor.execute")
    inputs_l0: List[dict] = list(task.get("inputs_l0") or [])
    inputs_l1: List[dict] = list(task.get("inputs_l1") or [])
    safe = int(task.get("safe_epoch", 0))
    bottom = bool(task.get("bottom", True))
    base = int(task["output_base"])
    cap = int(task.get("output_cap", 16))
    target = int(task.get("target_bytes", TARGET_SST_BYTES))

    def source(info: dict, r: int):
        # one-shot sequential scan: whole-bytes read, no cache churn
        sst = Sst(obj.read(f"data/{info['id']}.sst"), info)
        for fk, tomb, row in sst.iter_from(b""):
            yield (fk, r, tomb, row)

    # rank order mirrors HummockLite.compact: L0 newest first (newest
    # is LAST in the level list), then the overlapping L1 runs
    ranked = [source(info, r)
              for r, info in enumerate(reversed(inputs_l0))]
    ranked += [source(info, len(inputs_l0) + r)
               for r, info in enumerate(inputs_l1)]
    merged = heapq.merge(*ranked, key=lambda t: (t[0], t[1]))

    outputs: List[dict] = []
    next_id = base
    builder: Optional[SstBuilder] = None
    bytes_written = 0

    def flush() -> None:
        nonlocal builder, bytes_written
        if builder is None:
            return
        data, info = builder.finish()
        obj.upload(f"data/{info['id']}.sst", data)
        _METRICS.sst_upload_count.inc(source="compact")
        _METRICS.sst_upload_bytes.inc(len(data), source="compact")
        bytes_written += len(data)
        outputs.append(info)
        builder = None

    def out(fk: bytes, tomb: bool, row: bytes) -> None:
        nonlocal builder, next_id
        # cut ONLY at user-key boundaries (see docstring)
        if (builder is not None
                and builder._off + builder.block.size() >= target
                and builder.largest is not None
                and builder.largest[:-8] != fk[:-8]):
            flush()
        if builder is None:
            if next_id >= base + cap:
                raise RuntimeError(
                    f"compaction output overflow: reserved id block "
                    f"[{base}, {base + cap}) exhausted")
            builder = SstBuilder(next_id)
            next_id += 1
        builder.add(fk, tomb, row)

    seen_fk: Optional[bytes] = None
    last_tu: Optional[bytes] = None
    kept_le_safe = False
    for fk, _r, tomb, row in merged:
        if fk == seen_fk:
            continue               # same key+epoch: newer layer wins
        seen_fk = fk
        tu = fk[:-8]
        _t, _u, e = split_full_key(fk)
        if tu != last_tu:
            last_tu = tu
            kept_le_safe = False
        if e > safe:
            out(fk, tomb, row)
            continue
        if kept_le_safe:
            continue               # older shadowed version: drop
        kept_le_safe = True
        if tomb and bottom:
            continue               # newest ≤ safe is a delete: gone
        # non-bottom merges KEEP a ≤-safe tombstone: levels below the
        # destination may still hold the key it deletes
        out(fk, tomb, row)
    flush()
    bytes_read = sum(i.get("size", 0) for i in inputs_l0 + inputs_l1)
    return {"outputs": outputs, "bytes_read": bytes_read,
            "bytes_written": bytes_written}


class InProcessCompactor:
    """The single-process session's dedicated arm: merges run on ONE
    background thread so the barrier/commit path never carries a
    ``compact()`` frame. Speaks the same reserve → execute → apply
    protocol as the cluster compactor role, minus the subprocess:
    ``submit`` returns a Future the CompactionManager polls at its
    next tick and resolves into ``apply_version_delta``."""

    def __init__(self, obj: ObjectStore):
        import concurrent.futures
        self.obj = obj
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="compactor")

    def submit(self, task: dict):
        return self._pool.submit(execute_task, self.obj, task)

    def close(self) -> None:
        self._pool.shutdown(wait=True)
