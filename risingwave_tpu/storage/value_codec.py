"""Compact row value encoding for SSTs.

Reference parity: the *role* of src/common/src/util/value_encoding/ —
a schema-light byte encoding of physical rows for storage values. The
encoding is tag-per-value (rows are small; SST blocks amortize), with
zigzag varints for ints: physical rows in this framework are host
tuples of int / float / str / bool / None (DECIMAL is its scaled int64,
timestamps are µs ints — see state/state_table.py).
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as np

_INT64_MIN = -(1 << 63)
_INT64_MAX = (1 << 63) - 1

_T_NULL = 0
_T_INT = 1       # zigzag varint
_T_FLOAT = 2     # 8-byte little-endian double
_T_STR = 3       # varint len + utf8
_T_TRUE = 4
_T_FALSE = 5
_T_BYTES = 6


def write_uvarint(out: bytearray, v: int) -> None:
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)


def read_uvarint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    v = 0
    while True:
        b = buf[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if b < 0x80:
            return v, pos
        shift += 7


def _zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63) if v >= 0 else ((-v) << 1) - 1


def _unzigzag(v: int) -> int:
    return (v >> 1) if (v & 1) == 0 else -((v + 1) >> 1)


def encode_row(row: Tuple) -> bytes:
    out = bytearray()
    write_uvarint(out, len(row))
    for v in row:
        if v is None:
            out.append(_T_NULL)
        elif isinstance(v, (bool, np.bool_)):
            out.append(_T_TRUE if v else _T_FALSE)
        elif isinstance(v, int) or hasattr(v, "__index__"):
            iv = int(v)
            if not (_INT64_MIN <= iv <= _INT64_MAX):
                raise TypeError(f"int out of int64 range: {iv}")
            out.append(_T_INT)
            write_uvarint(out, _zigzag(iv))
        elif isinstance(v, float) or (hasattr(v, "dtype")
                                      and v.dtype.kind == "f"):
            out.append(_T_FLOAT)
            out.extend(struct.pack("<d", float(v)))
        elif isinstance(v, str):
            out.append(_T_STR)
            b = v.encode("utf-8")
            write_uvarint(out, len(b))
            out.extend(b)
        elif isinstance(v, (bytes, bytearray)):
            out.append(_T_BYTES)
            write_uvarint(out, len(v))
            out.extend(v)
        else:
            raise TypeError(f"unencodable value {v!r} ({type(v)})")
    return bytes(out)


def decode_row(buf: bytes) -> Tuple:
    n, pos = read_uvarint(buf, 0)
    out: List[Optional[object]] = []
    for _ in range(n):
        tag = buf[pos]
        pos += 1
        if tag == _T_NULL:
            out.append(None)
        elif tag == _T_TRUE:
            out.append(True)
        elif tag == _T_FALSE:
            out.append(False)
        elif tag == _T_INT:
            z, pos = read_uvarint(buf, pos)
            out.append(_unzigzag(z))
        elif tag == _T_FLOAT:
            out.append(struct.unpack_from("<d", buf, pos)[0])
            pos += 8
        elif tag == _T_STR:
            ln, pos = read_uvarint(buf, pos)
            out.append(buf[pos:pos + ln].decode("utf-8"))
            pos += ln
        elif tag == _T_BYTES:
            ln, pos = read_uvarint(buf, pos)
            out.append(bytes(buf[pos:pos + ln]))
            pos += ln
        else:
            raise ValueError(f"bad value tag {tag}")
    return tuple(out)
