"""StateTier: memory-governed cold tier shared by stateful executors.

Reference parity: src/stream/src/executor/managed_state/join/mod.rs
:379-420 (JoinHashMap as an LRU over the StateTable), cache/
managed_lru.rs (epoch-sequenced LRU eviction) and memory_management/
memory_manager.rs:33-70 (the watermark memory manager driving those
LRUs). TPU re-design: the join-only cold-keys mechanism generalizes to
ONE manager every stateful executor can register with — the device
holds the hot working set, the state table holds everything, and a
touch of an evicted key reloads it.

Contract per participant (an executor-owned cache of keyed state):

- ``touch(part, keys, seq)`` on the ingest path records per-key
  last-touched sequence (the executor's barrier counter — the
  managed_lru epoch). The tier's map holds exactly the RESIDENT keys.
- ``sweep(part, seq)`` runs at the executor's own CHECKPOINT barrier,
  after its flush/commit — never mid-epoch, so eviction can never race
  an in-flight epoch's probes or un-flushed device state (the
  epoch-sequencing argument: all state observed by the tier is the
  just-committed barrier snapshot). It picks the OLDEST keys past the
  participant's cap — or past the pressure watermark when the
  MemoryContext (utils/memory.py) has crossed its soft limit — and
  hands them to the participant's ``evict(keys)`` callback, which moves
  them out of device slots + host caches (they stay durable in the
  state table; a later touch reloads).
- ``forget(part, keys)`` drops keys that left the state entirely
  (watermark expiry, retraction to zero). Stale entries self-heal:
  an evicted key the participant no longer holds is a no-op evict.

The tier never touches executor state itself — eviction/reload
mechanics stay with the owners (kernel rebuild paths, arena
compaction); this module owns WHICH keys and WHEN.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Tuple

from risingwave_tpu.utils.metrics import STREAMING as _METRICS


class TierParticipant:
    """One registered executor-side cache (name, cap, evict hook)."""

    __slots__ = ("name", "cap", "evict", "nbytes", "keys",
                 "evicted_total", "reload_total")

    def __init__(self, name: str, evict: Callable[[List], int],
                 cap: Optional[int],
                 nbytes: Optional[Callable[[], int]]):
        # `evict(keys)` must return the number of KEYS actually
        # evicted (units contract: every counter here is in keys)
        self.name = name
        self.evict = evict
        self.cap = cap
        self.nbytes = nbytes
        # key → last-touched sequence. Python dicts preserve insertion
        # order; a re-touch deletes + reinserts, so iteration order IS
        # oldest-first — an O(1)-per-touch LRU without a linked list.
        self.keys: Dict[object, int] = {}
        self.evicted_total = 0
        self.reload_total = 0


class StateTier:
    """Central registry + eviction policy (the managed-LRU watermark)."""

    # keep ~this fraction of the cap after a cap-driven sweep (room to
    # absorb arrivals before the next barrier)
    EVICT_TARGET_RATIO = 0.75
    # under memory pressure, evict each participant down to this
    # fraction of its current residency at its next sweep
    PRESSURE_KEEP_RATIO = 0.5

    def __init__(self, memory=None):
        # memory context injected for tests; default is the process
        # global (resolved lazily — no import cycle at module load)
        self._memory = memory
        self._parts: Dict[str, TierParticipant] = {}

    def _mem(self):
        if self._memory is None:
            from risingwave_tpu.utils import memory as _mem
            self._memory = _mem.GLOBAL
        return self._memory

    # -- registration -----------------------------------------------------
    def register(self, name: str, evict: Callable[[List], int],
                 cap: Optional[int] = None,
                 nbytes: Optional[Callable[[], int]] = None
                 ) -> TierParticipant:
        part = TierParticipant(name, evict, cap, nbytes)
        self._parts[name] = part
        return part

    def unregister(self, part: TierParticipant) -> None:
        self._parts.pop(part.name, None)
        _METRICS.state_tier_resident.remove(executor=part.name)
        _METRICS.state_tier_bytes.remove(executor=part.name)

    # -- hot path ---------------------------------------------------------
    @staticmethod
    def touch(part: TierParticipant, keys: Iterable, seq: int,
              insert: bool = True) -> None:
        """Refresh recency for `keys`. ``insert=False`` refreshes only
        keys already tracked (probe touches of the OTHER join side must
        not mint phantom residents)."""
        d = part.keys
        for k in keys:
            if k in d:
                del d[k]
            elif not insert:
                continue
            d[k] = seq

    @staticmethod
    def forget(part: TierParticipant, keys: Iterable) -> None:
        d = part.keys
        for k in keys:
            d.pop(k, None)

    @staticmethod
    def note_reload(part: TierParticipant, n: int) -> None:
        part.reload_total += n
        _METRICS.state_tier_reloads.inc(n, executor=part.name)

    # -- the barrier sweep ------------------------------------------------
    def _pressure(self) -> bool:
        mem = self._mem()
        if mem.soft_limit is None:
            return False
        return mem.last_total > mem.soft_limit

    def sweep(self, part: TierParticipant, seq: int) -> int:
        """Evict this participant's oldest keys past its cap (or past
        the pressure watermark). Runs ONLY at the owner's checkpoint
        barrier — see the module docstring's epoch-sequencing argument.
        Returns keys evicted."""
        del seq                       # recency clock; policy is size-based
        resident = len(part.keys)
        target = None
        if part.cap is not None and resident > part.cap:
            target = int(part.cap * self.EVICT_TARGET_RATIO)
        if self._pressure() and resident > 0:
            ptarget = int(resident * self.PRESSURE_KEEP_RATIO)
            target = ptarget if target is None else min(target, ptarget)
        if target is None:
            self._refresh_gauges(part)
            return 0
        n_evict = resident - target
        victims = []
        for k in part.keys:           # oldest-first iteration order
            if len(victims) >= n_evict:
                break
            victims.append(k)
        n = 0
        if victims:
            # the callback returns keys ACTUALLY evicted (stale/
            # phantom entries are no-ops there) — count those, not the
            # request, or rw_state_tier overreports
            n = int(part.evict(victims))
            for k in victims:
                del part.keys[k]
            if n:
                part.evicted_total += n
                _METRICS.state_tier_evicted.inc(n, executor=part.name)
        self._refresh_gauges(part)
        return n

    def _refresh_gauges(self, part: TierParticipant) -> None:
        _METRICS.state_tier_resident.set(len(part.keys),
                                         executor=part.name)
        if part.nbytes is not None:
            _METRICS.state_tier_bytes.set(int(part.nbytes()),
                                          executor=part.name)

    # -- introspection (rw_state_tier) ------------------------------------
    def stats_rows(self) -> List[Tuple]:
        """(executor, cap, resident_keys, evicted_total, reload_total,
        accounted_bytes) per participant — the rw_state_tier payload."""
        out = []
        for p in self._parts.values():
            out.append((p.name, -1 if p.cap is None else int(p.cap),
                        len(p.keys), p.evicted_total, p.reload_total,
                        0 if p.nbytes is None else int(p.nbytes())))
        return out


# the process-global tier (managed-LRU registry analog)
GLOBAL = StateTier()
