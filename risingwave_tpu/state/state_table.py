"""StateTable: schema-aware, vnode-partitioned view over the state store.

Reference parity: src/stream/src/common/table/state_table.rs:76 —
write API insert/delete/update (:746,760,773) buffered in a MemTable;
``commit(new_epoch)`` (:901) flushes the buffer at the sealed epoch;
read API get_row (:587) and iterators (:1092); per-table vnode ownership
bitmap + update_vnode_bitmap on scaling (:650).

TPU re-design: this is the *host-side durability seam*. Device-resident
operator state (HBM hash tables) flushes dirty entries through this API at
every barrier; recovery reads it back to rebuild device state. Keys are
2-byte-vnode-prefixed memcomparable bytes; values are host row tuples.
"""

from __future__ import annotations

import decimal
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.epoch import EpochPair
from risingwave_tpu.common.hash import (
    VNODE_COUNT, hash_strings_host, vnodes_of_host,
)
from risingwave_tpu.common.types import DataType, Schema, decimal_to_scaled
from risingwave_tpu.state.keycodec import (
    decode_memcomparable, encode_memcomparable, encode_vnode_prefix,
)
from risingwave_tpu.state.mem_table import KeyOp, MemTable
from risingwave_tpu.state.store import StateStore


class StateTable:
    """One logical table of operator state, partitioned by vnode."""

    def __init__(self, table_id: int, schema: Schema,
                 pk_indices: Sequence[int], store: StateStore,
                 dist_key_indices: Optional[Sequence[int]] = None,
                 vnodes: Optional[np.ndarray] = None,
                 sanity_check: bool = True):
        self.table_id = table_id
        self.schema = schema
        self.pk_indices = list(pk_indices)
        self.pk_types = [schema[i].data_type for i in self.pk_indices]
        # dist keys must be a subset of the pk so vnode is derivable from pk
        self.dist_key_indices = (list(dist_key_indices)
                                 if dist_key_indices is not None else [])
        for i in self.dist_key_indices:
            assert i in self.pk_indices, \
                "dist key must be part of the state-table pk"
        self.store = store
        self.mem_table = MemTable(sanity_check=sanity_check)
        # ownership bitmap: which vnodes this instance owns (scaling swaps it)
        self.vnodes = (np.ones(VNODE_COUNT, dtype=bool)
                       if vnodes is None else np.asarray(vnodes, dtype=bool))
        self.epoch: Optional[EpochPair] = None

    # -- epoch lifecycle ------------------------------------------------
    def init_epoch(self, epoch: EpochPair) -> None:
        """Set the epoch at which buffered writes will land (recovery/boot)."""
        self.epoch = epoch

    def commit(self, new_epoch: EpochPair) -> int:
        """Flush buffered ops at the sealed (current) epoch; advance.

        Returns the number of flushed entries. state_table.rs:901 analog —
        the caller (actor on barrier) invokes this for every state table,
        then the barrier manager syncs the store.
        """
        assert self.epoch is not None, "init_epoch first"
        assert new_epoch.prev == self.epoch.curr, (new_epoch, self.epoch)
        n = self.store.ingest_batch(self.table_id, self.mem_table.drain(),
                                    self.epoch.curr.value)
        self.epoch = new_epoch
        return n

    # -- key helpers ----------------------------------------------------
    def _vnode_of_pk(self, pk_values: Sequence) -> int:
        if not self.dist_key_indices:
            return 0  # singleton distribution (VirtualNode::ZERO analog)
        lanes: List[np.ndarray] = []
        for i in self.dist_key_indices:
            dt = self.schema[i].data_type
            v = pk_values[self.pk_indices.index(i)]
            lanes.append(_key_lane(v, dt))
        return int(vnodes_of_host(lanes)[0])

    def _encode_pk(self, pk_values: Sequence) -> bytes:
        vnode = self._vnode_of_pk(pk_values)
        return (encode_vnode_prefix(vnode) +
                encode_memcomparable(pk_values, self.pk_types))

    def pk_of(self, row: Sequence) -> tuple:
        return tuple(row[i] for i in self.pk_indices)

    # -- write API -------------------------------------------------------
    def insert(self, row: Sequence) -> None:
        row = tuple(row)
        self.mem_table.insert(self._encode_pk(self.pk_of(row)), row)

    def delete(self, row: Sequence) -> None:
        row = tuple(row)
        self.mem_table.delete(self._encode_pk(self.pk_of(row)), row)

    def update(self, old_row: Sequence, new_row: Sequence) -> None:
        old_row, new_row = tuple(old_row), tuple(new_row)
        ok, nk = self._encode_pk(self.pk_of(old_row)), \
            self._encode_pk(self.pk_of(new_row))
        if ok == nk:
            self.mem_table.update(ok, old_row, new_row)
        else:  # pk changed: delete + insert (reference requires same pk; we allow)
            self.mem_table.delete(ok, old_row)
            self.mem_table.insert(nk, new_row)

    def write_chunk(self, chunk: StreamChunk) -> None:
        """Apply a visible-row StreamChunk (barrier-flush entry point)."""
        for op, row in chunk.to_records():
            if op in (Op.INSERT, Op.UPDATE_INSERT):
                self.insert(row)
            else:
                self.delete(row)

    # -- read API --------------------------------------------------------
    def _read_epoch(self) -> int:
        assert self.epoch is not None, "init_epoch first"
        return self.epoch.prev.value

    def get_row(self, pk_values: Sequence) -> Optional[tuple]:
        key = self._encode_pk(tuple(pk_values))
        present, value = self.mem_table.get(key)
        if present:
            return value
        return self.store.get(self.table_id, key, self._read_epoch())

    def iter_rows(self, vnode: Optional[int] = None
                  ) -> Iterator[Tuple[tuple, tuple]]:
        """Yield (pk, row) in memcomparable pk order, memtable merged.

        v0 correctness-first: materializes the committed range then overlays
        buffered ops (the in-memory fake is small; hummock-lite gets a real
        merge iterator).
        """
        if vnode is None:
            start, end = None, None
        else:
            start = encode_vnode_prefix(vnode)
            end = encode_vnode_prefix(vnode + 1) if vnode + 1 < VNODE_COUNT \
                else None
        merged = {k: v for k, v in self.store.iter(
            self.table_id, self._read_epoch(), start, end)}
        for key, (op, _old, new) in self.mem_table.iter_ops():
            if start is not None and key < start:
                continue
            if end is not None and key >= end:
                continue
            if op == KeyOp.DELETE:
                merged.pop(key, None)
            else:
                merged[key] = new
        for key in sorted(merged):
            pk = decode_memcomparable(key[2:], self.pk_types)
            yield pk, merged[key]

    def owned_vnodes(self) -> List[int]:
        return np.flatnonzero(self.vnodes).tolist()

    # -- scaling ---------------------------------------------------------
    def update_vnode_bitmap(self, new_vnodes: np.ndarray) -> np.ndarray:
        """Swap partition ownership at a barrier (state_table.rs:650)."""
        assert not self.mem_table.is_dirty(), \
            "vnode bitmap swap with dirty memtable"
        prev = self.vnodes
        self.vnodes = np.asarray(new_vnodes, dtype=bool)
        return prev


def _key_lane(v, dt: DataType) -> np.ndarray:
    """One scalar → length-1 lane array matching device hashing rules."""
    if dt.is_device:
        if dt == DataType.DECIMAL:
            # scale ANY logical value (int/float/Decimal) exactly like
            # column ingest, so host vnode == device vnode of the column
            v = decimal_to_scaled(v)
        return np.asarray([v], dtype=dt.np_dtype)
    return hash_strings_host(np.asarray([v], dtype=object), 1)
