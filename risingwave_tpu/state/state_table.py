"""StateTable: schema-aware, vnode-partitioned view over the state store.

Reference parity: src/stream/src/common/table/state_table.rs:76 —
write API insert/delete/update (:746,760,773) buffered in a MemTable;
``commit(new_epoch)`` (:901) flushes the buffer at the sealed epoch;
read API get_row (:587) and iterators (:1092); per-table vnode ownership
bitmap + update_vnode_bitmap on scaling (:650).

TPU re-design: this is the *host-side durability seam*. Device-resident
operator state (HBM hash tables) flushes dirty entries through this API at
every barrier; recovery reads it back to rebuild device state. Keys are
2-byte-vnode-prefixed memcomparable bytes; values are host row tuples.

Rows are PHYSICAL tuples: DECIMAL is its scaled int64, timestamps are µs
ints, NULL is None — the exact representation device kernels flush and
recovery re-uploads (no host conversion on the hot path). Present rows to
users via ``to_logical_row``.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.epoch import EpochPair
from risingwave_tpu.common.hash import (
    VNODE_COUNT, hash_strings_host, vnodes_of_host,
)
from risingwave_tpu.common.types import DataType, Schema, scaled_to_decimal
from risingwave_tpu.state.keycodec import (
    decode_memcomparable, encode_memcomparable, encode_vnode_prefix,
)
from risingwave_tpu.state.mem_table import KeyOp, MemTable
from risingwave_tpu.state.store import StateStore
from risingwave_tpu.state import topology as _topology

# barrier-domain mode (meta/domains.py flips this on when a
# BarrierPlane exists in the process; workers flip it on the first
# domain-protocol inject): commit() then accepts the MONOTONE epoch
# re-anchor a domain merge produces. Off (the default and the
# single-loop oracle arm), strict prev == curr continuity is enforced
# so a missed barrier fails at the fault. Sticky per process.
MONOTONE_REANCHOR = False


def allow_monotone_reanchor(on: bool = True) -> None:
    global MONOTONE_REANCHOR
    MONOTONE_REANCHOR = bool(on)


class StateTable:
    """One logical table of operator state, partitioned by vnode."""

    def __init__(self, table_id: int, schema: Schema,
                 pk_indices: Sequence[int], store: StateStore,
                 dist_key_indices: Optional[Sequence[int]] = None,
                 vnodes: Optional[np.ndarray] = None,
                 sanity_check: bool = True):
        self.table_id = table_id
        self.schema = schema
        self.pk_indices = list(pk_indices)
        self.pk_types = [schema[i].data_type for i in self.pk_indices]
        # dist keys must be a subset of the pk so vnode is derivable from pk
        self.dist_key_indices = (list(dist_key_indices)
                                 if dist_key_indices is not None else [])
        for i in self.dist_key_indices:
            assert i in self.pk_indices, \
                "dist key must be part of the state-table pk"
        self.store = store
        self.mem_table = MemTable(sanity_check=sanity_check)
        # staged all-insert chunk batches (write_chunk(defer=True) —
        # the materialize/join emit hot path): encoded keys + physical
        # rows held OUTSIDE the memtable until flush, skipping the
        # per-row op-merge dict entirely. Invariant: staged batches
        # exist only while the memtable is CLEAN — any interleaved
        # read or non-insert write spills them into the memtable
        # first, restoring the exact merge semantics.
        self._staged_keys: List[List[bytes]] = []
        self._staged_vals: List[List[tuple]] = []
        # ownership bitmap: which vnodes this instance owns (scaling swaps it)
        self.vnodes = (np.ones(VNODE_COUNT, dtype=bool)
                       if vnodes is None else np.asarray(vnodes, dtype=bool))
        self.epoch: Optional[EpochPair] = None
        # schema-constant physical row size (None when host-typed
        # fields size per value) — lets the topology books take their
        # bulk-update fast path on the staged all-insert flush shape
        self._fixed_row_nbytes = _topology.fixed_row_nbytes(schema)

    # -- epoch lifecycle ------------------------------------------------
    def init_epoch(self, epoch: EpochPair) -> None:
        """Set the epoch at which buffered writes will land (recovery/boot)."""
        self.epoch = epoch

    def flush(self) -> Tuple[List[bytes], List, int]:
        """Drain the buffered ops as staged imms — (keys, values,
        write_epoch) — WITHOUT writing through to the store.
        Extraction point only: ``commit`` below is its one caller
        today (the async checkpoint pipeline decouples at the STORE
        level — HummockLite.build_ssts drains imms, not state tables);
        callers that need to route a flush elsewhere (worker shipping,
        tests) take the staged batch from here."""
        assert self.epoch is not None, "init_epoch first"
        if self._staged_keys:
            if self.mem_table.is_dirty():
                # defensive: the staged-while-clean invariant should
                # make this unreachable — merge order-exactly anyway
                self._spill_staged()
            else:
                kbs, vbs = self._staged_keys, self._staged_vals
                self._staged_keys, self._staged_vals = [], []
                if len(kbs) == 1:
                    return kbs[0], vbs[0], self.epoch.curr.value
                return ([k for b in kbs for k in b],
                        [v for b in vbs for v in b],
                        self.epoch.curr.value)
        keys, vals = self.mem_table.drain_bulk()
        return keys, vals, self.epoch.curr.value

    def commit(self, new_epoch: EpochPair) -> int:
        """Flush buffered ops at the sealed (current) epoch; advance.

        Returns the number of flushed entries. state_table.rs:901 analog —
        the caller (actor on barrier) invokes this for every state table,
        then the barrier manager seals the epoch and hands the flush to
        the checkpoint uploader.
        """
        assert self.epoch is not None, "init_epoch first"
        if MONOTONE_REANCHOR:
            # barrier-domain mode (meta/domains.py): ``>`` happens at
            # a domain MERGE/re-anchor — the absorbed chain continues
            # under the merged loop, whose prev is the larger
            # frontier; the buffered writes still flush at the OLD
            # curr, which stays under the cross-domain seal fence
            # until the merged round ends it, so monotone re-anchoring
            # is safe
            assert new_epoch.prev.value >= self.epoch.curr.value, \
                (new_epoch, self.epoch)
        else:
            # strict continuity (the single-loop/off arm): a prev
            # mismatch means a missed barrier — fail at the fault,
            # not at a later opaque sealed-write rejection
            assert new_epoch.prev == self.epoch.curr, \
                (new_epoch, self.epoch)
        keys, vals, epoch = self.flush()
        n = self.store.ingest_keyed(self.table_id, keys, vals, epoch)
        # per-(table, vnode) topology upkeep rides the SAME flush the
        # store ingests — incremental at the write-through point, so
        # reads (rw_state_topology, rescale costing) never scan state
        _topology.TOPOLOGY.record(self.table_id, keys, vals,
                                  self._fixed_row_nbytes)
        self.epoch = new_epoch
        return n

    # -- key helpers ----------------------------------------------------
    def _vnode_of_pk(self, pk_values: Sequence) -> int:
        if not self.dist_key_indices:
            return 0  # singleton distribution (VirtualNode::ZERO analog)
        lanes: List[np.ndarray] = []
        for i in self.dist_key_indices:
            dt = self.schema[i].data_type
            v = pk_values[self.pk_indices.index(i)]
            lanes.append(_key_lane(v, dt))
        return int(vnodes_of_host(lanes)[0])

    def _encode_pk(self, pk_values: Sequence) -> bytes:
        vnode = self._vnode_of_pk(pk_values)
        return (encode_vnode_prefix(vnode) +
                encode_memcomparable(pk_values, self.pk_types))

    def pk_of(self, row: Sequence) -> tuple:
        return tuple(row[i] for i in self.pk_indices)

    # -- staged-batch spill (write_chunk(defer=True) fast path) ----------
    def is_dirty(self) -> bool:
        return bool(self._staged_keys) or self.mem_table.is_dirty()

    def _spill_staged(self) -> None:
        """Replay staged all-insert batches into the memtable (in
        arrival order) so interleaved reads/non-insert writes see the
        exact per-key merge semantics the fast path skipped."""
        if not self._staged_keys:
            return
        kbs, vbs = self._staged_keys, self._staged_vals
        self._staged_keys, self._staged_vals = [], []
        mt = self.mem_table
        for keys, rows in zip(kbs, vbs):
            if not mt.insert_batch(keys, rows):
                for key, row in zip(keys, rows):
                    mt.insert(key, row)

    # -- write API -------------------------------------------------------
    def insert(self, row: Sequence) -> None:
        self._spill_staged()
        row = tuple(row)
        self.mem_table.insert(self._encode_pk(self.pk_of(row)), row)

    def delete(self, row: Sequence) -> None:
        self._spill_staged()
        row = tuple(row)
        self.mem_table.delete(self._encode_pk(self.pk_of(row)), row)

    def update(self, old_row: Sequence, new_row: Sequence) -> None:
        self._spill_staged()
        old_row, new_row = tuple(old_row), tuple(new_row)
        ok, nk = self._encode_pk(self.pk_of(old_row)), \
            self._encode_pk(self.pk_of(new_row))
        if ok == nk:
            self.mem_table.update(ok, old_row, new_row)
        else:  # pk changed: delete + insert (reference requires same pk; we allow)
            self.mem_table.delete(ok, old_row)
            self.mem_table.insert(nk, new_row)

    def delete_below_prefix(self, watermark) -> int:
        """Watermark state cleaning (state_table.rs:894 update_watermark):
        delete every row whose FIRST pk column is strictly below the
        watermark. Cost is O(deleted) + an ordered seek per owned vnode
        (rows below a watermark on the pk prefix form a contiguous range
        in memcomparable order). Returns rows deleted."""
        first_pk_type = self.pk_types[0]
        end_suffix = encode_memcomparable([watermark], [first_pk_type])
        deleted = 0
        for vnode in self.owned_vnodes():
            start = encode_vnode_prefix(vnode)
            end = start + end_suffix
            for _pk, row in self._iter_range(start, end):
                self.delete(row)
                deleted += 1
        return deleted

    # -- bulk row API (barrier-flush hot path for device operators) -----
    def insert_rows(self, rows: Sequence[Sequence]) -> None:
        """Batch insert: pk encoding + vnode hashing vectorized over all
        rows (one numpy pass per pk column instead of per-row hashing —
        the r3 profile spent half of q8 in per-row ``_encode_pk``)."""
        self._spill_staged()
        mt = self.mem_table
        keys = self._encode_pk_rows(rows)
        rows_t = [tuple(r) for r in rows]
        if mt.insert_batch(keys, rows_t):
            return
        for key, row in zip(keys, rows_t):
            mt.insert(key, row)

    def delete_rows(self, rows: Sequence[Sequence]) -> None:
        self._spill_staged()
        mt = self.mem_table
        for key, row in zip(self._encode_pk_rows(rows), rows):
            mt.delete(key, tuple(row))

    def update_rows(self, old_rows: Sequence[Sequence],
                    new_rows: Sequence[Sequence]) -> None:
        self._spill_staged()
        mt = self.mem_table
        ok_keys = self._encode_pk_rows(old_rows)
        nk_keys = self._encode_pk_rows(new_rows)
        for ok, nk, old, new in zip(ok_keys, nk_keys, old_rows, new_rows):
            old, new = tuple(old), tuple(new)
            if ok == nk:
                mt.update(ok, old, new)
            else:
                mt.delete(ok, old)
                mt.insert(nk, new)

    def _encode_pk_rows(self, rows: Sequence[Sequence]) -> List[bytes]:
        """Vectorized vnode-prefixed pk keys from row tuples."""
        n = len(rows)
        if n == 0:
            return []
        pk_cols: List[Tuple[np.ndarray, DataType]] = []
        bulk_ok = True
        for i in self.pk_indices:
            dt = self.schema[i].data_type
            col = [r[i] for r in rows]
            if dt not in self._BULK_OK or any(v is None for v in col):
                bulk_ok = False
                break
            pk_cols.append((np.asarray(col, dtype=dt.np_dtype), dt))
        if not bulk_ok:          # rare: varchar/NULL pks → per-row codec
            return [self._encode_pk(self.pk_of(r)) for r in rows]
        if not self.dist_key_indices:
            vnodes = np.zeros(n, dtype=np.int64)
        else:
            # dist keys are a pk subset (asserted in __init__) and the
            # bulk path excludes NULLs/varchar — reuse the arrays the pk
            # pass just built instead of re-extracting per row
            lanes = [pk_cols[self.pk_indices.index(i)][0]
                     for i in self.dist_key_indices]
            vnodes = vnodes_of_host(lanes).astype(np.int64)
        return self._pack_keys(vnodes, pk_cols)

    def write_chunk(self, chunk: StreamChunk,
                    defer: bool = False) -> None:
        """Apply a visible-row StreamChunk — the barrier-flush hot path.

        Fully vectorized up to the memtable: physical row extraction, vnode
        hashing and pk encoding are whole-column numpy passes; only the
        final dict ops are per-row.

        ``defer=True`` (ISSUE 12): all-insert chunks against a clean
        memtable STAGE as (keys, rows) batches and flow to the store as
        one bulk ingest at flush — no per-row memtable dict ops at all.
        Only callers that trust upstream key discipline (the NO_CHECK
        materialize contract, the join's append-fast state writes) pass
        it: the fast path skips the memtable's double-insert sanity
        check, and duplicate pks within one epoch resolve last-wins at
        the store instead of raising. Any interleaved read, delete, or
        row-API write spills the stage first, so mixed epochs keep the
        exact merge semantics.
        """
        idx, rows, ops = chunk.to_physical_records()
        if not rows:
            return
        keys = self._encode_pks_bulk(chunk, idx)
        is_ins = (ops == int(Op.INSERT)) | (ops == int(Op.UPDATE_INSERT))
        if defer and not self.mem_table.is_dirty() and is_ins.all():
            self._staged_keys.append(keys)
            self._staged_vals.append(rows)
            return
        self._spill_staged()
        mt = self.mem_table
        if is_ins.all() and mt.insert_batch(keys, rows):
            return
        for key, row, ins in zip(keys, rows, is_ins.tolist()):
            if ins:
                mt.insert(key, row)
            else:
                mt.delete(key, row)

    # fixed-width device pk types eligible for the bulk encoder
    _BULK_OK = frozenset({
        DataType.INT16, DataType.INT32, DataType.INT64, DataType.SERIAL,
        DataType.DECIMAL, DataType.DATE, DataType.TIME, DataType.TIMESTAMP,
        DataType.TIMESTAMPTZ, DataType.FLOAT32, DataType.FLOAT64,
        DataType.BOOLEAN,
    })

    def _encode_pks_bulk(self, chunk: StreamChunk,
                         idx: np.ndarray) -> List[bytes]:
        """Vectorized vnode-prefixed memcomparable keys for visible rows."""
        n = len(idx)
        # vnodes (vectorized, same math as device dispatch)
        if not self.dist_key_indices:
            vnodes = np.zeros(n, dtype=np.int64)
        else:
            lanes = []
            for i in self.dist_key_indices:
                c = chunk.columns[i]
                vals = np.asarray(c.values)[idx]
                if c.data_type.is_device:
                    if c.validity is not None:
                        # NULL dist-key values hash as the zero lane (same
                        # rule as _key_lane(None)) regardless of buffer fill
                        vals = np.where(np.asarray(c.validity)[idx], vals,
                                        np.zeros((), dtype=vals.dtype))
                    lanes.append(vals)
                else:
                    lanes.append(hash_strings_host(vals, n))
            vnodes = vnodes_of_host(lanes).astype(np.int64)

        pk_cols = [chunk.columns[i] for i in self.pk_indices]
        bulk_ok = all(
            c.data_type in self._BULK_OK and
            (c.validity is None or bool(np.asarray(c.validity)[idx].all()))
            for c in pk_cols)
        if not bulk_ok:  # rare path: varchar/null pks — per-row codec
            out = []
            host_pk = [(np.asarray(c.values)[idx],
                        None if c.validity is None
                        else np.asarray(c.validity)[idx]) for c in pk_cols]
            for j in range(n):
                pk = tuple(
                    None if (val is not None and not val[j])
                    else (vals[j].item() if hasattr(vals[j], "item")
                          else vals[j])
                    for vals, val in host_pk)
                out.append(encode_vnode_prefix(int(vnodes[j]))
                           + encode_memcomparable(pk, self.pk_types))
            return out

        typed = [(np.asarray(c.values)[idx], c.data_type)
                 for c in pk_cols]
        return self._pack_keys(vnodes, typed)

    @staticmethod
    def _pack_keys(vnodes: np.ndarray,
                   cols: Sequence[Tuple[np.ndarray, DataType]]
                   ) -> List[bytes]:
        """Non-null fixed-width pk columns → memcomparable key matrix.

        Layout: [2B vnode][per col: 0x01 + payload]."""
        n = len(vnodes)
        widths = [2] + [1 + (1 if dt == DataType.BOOLEAN else 8)
                        for _v, dt in cols]
        total = sum(widths)
        m = np.empty((n, total), dtype=np.uint8)
        m[:, 0] = (vnodes >> 8).astype(np.uint8)
        m[:, 1] = (vnodes & 0xFF).astype(np.uint8)
        off = 2
        for vals, dt in cols:
            m[:, off] = 1  # non-null tag
            off += 1
            if dt == DataType.BOOLEAN:
                m[:, off] = vals.astype(np.uint8)
                off += 1
                continue
            if dt in (DataType.FLOAT32, DataType.FLOAT64):
                with np.errstate(over="ignore"):
                    f = vals.astype(np.float64)
                    f = np.where(f == 0, 0.0, f)  # -0.0 → 0.0
                    bits = f.view(np.uint64)
                    neg = (bits >> np.uint64(63)) == 1
                    bits = np.where(neg, ~bits,
                                    bits | np.uint64(1 << 63))
            else:
                with np.errstate(over="ignore"):
                    bits = vals.astype(np.int64).view(np.uint64) \
                        + np.uint64(1 << 63)
            be = bits.astype(">u8").view(np.uint8).reshape(n, 8)
            m[:, off:off + 8] = be
            off += 8
        flat = m.tobytes()
        return [flat[i * total:(i + 1) * total] for i in range(n)]

    # -- read API --------------------------------------------------------
    def _read_epoch(self) -> int:
        assert self.epoch is not None, "init_epoch first"
        return self.epoch.prev.value

    def get_row(self, pk_values: Sequence) -> Optional[tuple]:
        self._spill_staged()
        key = self._encode_pk(tuple(pk_values))
        present, value = self.mem_table.get(key)
        if present:
            return value
        return self.store.get(self.table_id, key, self._read_epoch())

    def iter_rows(self, vnode: Optional[int] = None,
                  reverse: bool = False
                  ) -> Iterator[Tuple[tuple, tuple]]:
        """Yield (pk, row) in memcomparable pk order (descending with
        `reverse=True` — the backward iterator), memtable merged.

        v0 correctness-first: materializes the committed range then overlays
        buffered ops (the in-memory fake is small; hummock-lite gets a real
        merge iterator).
        """
        if vnode is None:
            start, end = None, None
        else:
            start = encode_vnode_prefix(vnode)
            end = encode_vnode_prefix(vnode + 1) if vnode + 1 < VNODE_COUNT \
                else None
        yield from self._iter_range(start, end, reverse=reverse)

    def iter_prefix(self, prefix_values: Sequence
                    ) -> Iterator[Tuple[tuple, tuple]]:
        """(pk, row) for every pk starting with the given leading pk
        values (state_table.rs:1092 prefix iterators). The prefix must
        cover the dist keys so the vnode is derivable."""
        k = len(prefix_values)
        for i in self.dist_key_indices:
            assert self.pk_indices.index(i) < k, \
                "prefix must include all dist keys"
        vnode = self._vnode_of_pk(
            list(prefix_values) + [None] * (len(self.pk_indices) - k))
        start = (encode_vnode_prefix(vnode) +
                 encode_memcomparable(prefix_values, self.pk_types[:k]))
        yield from self._iter_range(start, _next_prefix(start))

    def _iter_range_raw(self, start: Optional[bytes],
                        end: Optional[bytes], reverse: bool = False
                        ) -> Iterator[Tuple[bytes, tuple]]:
        self._spill_staged()
        merged = {k: v for k, v in self.store.iter(
            self.table_id, self._read_epoch(), start, end)}
        for key, (op, _old, new) in self.mem_table.iter_ops():
            if start is not None and key < start:
                continue
            if end is not None and key >= end:
                continue
            if op == KeyOp.DELETE:
                merged.pop(key, None)
            else:
                merged[key] = new
        for key in sorted(merged, reverse=reverse):
            yield key, merged[key]

    def _iter_range(self, start: Optional[bytes], end: Optional[bytes],
                    reverse: bool = False
                    ) -> Iterator[Tuple[tuple, tuple]]:
        for key, row in self._iter_range_raw(start, end, reverse):
            yield decode_memcomparable(key[2:], self.pk_types), row

    def iter_encoded_range(self, start: Optional[bytes] = None,
                           end: Optional[bytes] = None
                           ) -> Iterator[Tuple[bytes, tuple]]:
        """(full encoded key incl. vnode prefix, row) in byte order —
        the backfill scan order (vnode-major, then memcomparable pk)."""
        yield from self._iter_range_raw(start, end)

    def owned_vnodes(self) -> List[int]:
        return np.flatnonzero(self.vnodes).tolist()

    # -- scaling ---------------------------------------------------------
    def update_vnode_bitmap(self, new_vnodes: np.ndarray) -> np.ndarray:
        """Swap partition ownership at a barrier (state_table.rs:650)."""
        assert not self.is_dirty(), \
            "vnode bitmap swap with dirty memtable"
        prev = self.vnodes
        self.vnodes = np.asarray(new_vnodes, dtype=bool)
        return prev


def _next_prefix(b: bytes) -> Optional[bytes]:
    """Smallest byte string greater than every string prefixed by b."""
    arr = bytearray(b)
    while arr:
        if arr[-1] != 0xFF:
            arr[-1] += 1
            return bytes(arr)
        arr.pop()
    return None


def _key_lane(v, dt: DataType) -> np.ndarray:
    """One physical scalar → length-1 lane array (device hashing rules).

    NULL hashes as the zero lane — consistent with the bulk encoder's
    treatment of invalid slots, so a NULL dist-key row is addressable."""
    if dt.is_device:
        return np.asarray([0 if v is None else v], dtype=dt.np_dtype)
    return hash_strings_host(np.asarray([v], dtype=object), 1)


def to_logical_row(row: Sequence, schema: Schema) -> tuple:
    """Physical state-table row → logical values (DECIMAL → Decimal)."""
    out = []
    for v, f in zip(row, schema):
        if v is not None and f.data_type == DataType.DECIMAL:
            v = scaled_to_decimal(v)
        out.append(v)
    return tuple(out)
