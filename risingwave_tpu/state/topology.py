"""Per-(table, vnode) state topology, maintained at flush (ISSUE 16).

The incremental-rescale planner (ROADMAP item 3) needs to know which
vnodes' state would move and how big they are BEFORE committing to a
handoff — and the serving-cost ledger (stream/costs.py) needs state
bytes attributed to the MV that owns them. Both reads come from here.

Maintenance invariant: the per-key size map is updated incrementally at
``StateTable.commit`` — the one write-through point every operator's
flush funnels into — and NEVER by scanning the store. Per-vnode
breakdowns (hot-vnode imbalance, ``ctl memory``) derive from the map
at EXPLICIT read time only; the per-MV byte rollup — which runs at
every checkpoint (``costs.publish_state_bytes``) — reads the O(#tables)
delta totals and never walks the map. The hot path pays only the map
upkeep:

- the append-fast case (uniform fixed-width keys, fixed-width rows, no
  deletes — the materialize/join staged-batch shape) is one C-speed
  ``dict.update`` plus delta arithmetic, mirroring the store's own
  ``ingest_keyed`` fast form;
- mixed batches (deletes, varchar rows) fall back to a per-entry loop.

Two independently-maintained books cross-check each other: the
authoritative per-key map vs. delta-arithmetic per-table totals. The
tier-1 gate (``gate_violations``) recounts the map and fails on drift —
Σ per-table topology bytes must equal the accounted resident bytes.
"""

from __future__ import annotations

import threading
from itertools import repeat
from typing import Dict, Iterable, List, Optional, Tuple

# one knob for the whole attribution subsystem (SET stream_costs):
# costs rollup, hot-key sketches and topology upkeep flip together
ENABLED = True


def set_enabled(on: bool) -> None:
    global ENABLED
    ENABLED = bool(on)


# value-size model (EstimateSize analog): fixed-width physical scalars
# are 8B + 1B tag; host-typed values charge their length. The model is
# stable across insert/overwrite of the same schema, which is what
# makes the append-fast delta arithmetic exact.
_FIXED_NBYTES = 9


def row_nbytes(row: tuple) -> int:
    """Estimated bytes of one physical row tuple."""
    n = 0
    for v in row:
        if isinstance(v, (str, bytes)):
            n += len(v) + 1
        else:
            n += _FIXED_NBYTES
    return n


def fixed_row_nbytes(schema) -> Optional[int]:
    """Schema-constant row size, or None when any field is host-typed
    (varchar/bytea rows are sized per value)."""
    for f in schema:
        if not f.data_type.is_device:
            return None
    return _FIXED_NBYTES * len(schema)


class StateTopology:
    """Process-global per-(table, vnode) row/byte accounting."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # table_id -> key -> accounted bytes (key + value estimate):
        # the authoritative book, maintained incrementally at flush
        self._sizes: Dict[int, Dict[bytes, int]] = {}
        # table_id -> [rows, bytes]: delta-arithmetic totals kept NEXT
        # TO the map — the gate recounts the map against these
        self._totals: Dict[int, List[int]] = {}
        # table_id -> owning MV/fragment (frontend binds at deploy)
        self._mv_of: Dict[int, str] = {}
        # table_id -> append-fast unit, or -1 once any mixed-shape
        # flush touched the table. The fast path's bulk overwrite is
        # only delta-exact when every resident entry carries the same
        # unit it is about to write (old == new → replacement is a
        # totals no-op); this book proves that precondition in O(1)
        self._unit: Dict[int, int] = {}
        # worker -> drained remote rows (coordinator merge; a state
        # table lives in exactly one process, so rows union cleanly)
        self._remote: Dict[str, List[tuple]] = {}
        # armed by the tier-1 conftest gate: checkpoint_verify() then
        # recounts at every checkpoint instead of only at teardown
        self._verify_each_checkpoint = False
        self._violations: List[tuple] = []

    # -- maintenance (StateTable.commit hot path) -----------------------
    def record(self, table_id: int, keys: List[bytes], vals: List,
               fixed_nbytes: Optional[int] = None) -> None:
        if not ENABLED or not keys:
            return
        with self._lock:
            if table_id not in self._mv_of:
                # lazy ownership bind: commit runs inside the owning
                # MV's pull (the costs ContextVar the monitor pushes),
                # so the first attributed flush names the table's MV —
                # no table-registry plumbing needed
                from risingwave_tpu.stream.costs import current_mv
                mv = current_mv()
                if mv:
                    self._mv_of[table_id] = mv
            m = self._sizes.setdefault(table_id, {})
            tot = self._totals.setdefault(table_id, [0, 0])
            if fixed_nbytes is not None:
                try:
                    vals.index(None)       # C-speed delete probe
                except ValueError:
                    unit = len(keys[0]) + fixed_nbytes
                    u = self._unit.get(table_id)
                    # uniform-key check is one C-speed pass (NULL pk
                    # slots take the short null-tag encoding). The
                    # unit check guards overwrites: the bulk merge
                    # replaces existing entries blind, which is only
                    # a totals no-op when they already hold `unit` —
                    # i.e. every prior flush was fast-path at the
                    # same unit (a schema-width change, e.g. column
                    # pruning re-planning the same table id, must
                    # take the per-entry loop below)
                    if (u == unit or (u is None and not m)) and \
                            sum(map(len, keys)) == \
                            len(keys[0]) * len(keys):
                        # append-fast form: uniform keys + constant
                        # row size → one bulk dict merge, exact deltas
                        self._unit[table_id] = unit
                        before = len(m)
                        m.update(zip(keys, repeat(unit)))
                        fresh = len(m) - before
                        tot[0] += fresh
                        tot[1] += fresh * unit
                        return
            self._unit[table_id] = -1      # mixed shapes from here on
            for key, val in zip(keys, vals):
                old = m.pop(key, None)
                if old is not None:
                    tot[0] -= 1
                    tot[1] -= old
                if val is None:            # delete
                    continue
                nb = len(key) + (fixed_nbytes if fixed_nbytes
                                 is not None else row_nbytes(val))
                m[key] = nb
                tot[0] += 1
                tot[1] += nb

    # -- ownership ------------------------------------------------------
    def bind(self, table_id: int, mv: str) -> None:
        with self._lock:
            self._mv_of[table_id] = mv

    def unbind_mv(self, mv: str) -> None:
        """Drop a dropped MV's tables from the books (series lifecycle:
        no `{mv=...}` topology rows may outlive the MV)."""
        with self._lock:
            dead = [t for t, m in self._mv_of.items() if m == mv]
            for t in dead:
                self._mv_of.pop(t, None)
                self._sizes.pop(t, None)
                self._totals.pop(t, None)
                self._unit.pop(t, None)
            self._remote = {
                w: [r for r in rows if r[1] != mv]
                for w, rows in self._remote.items()}

    def mv_of(self, table_id: int) -> str:
        with self._lock:
            return self._mv_of.get(table_id, "")

    # -- read side (system tables / ctl — off the hot path) -------------
    @staticmethod
    def _vnode_of(key: bytes) -> int:
        return (key[0] << 8) | key[1] if len(key) >= 2 else 0

    def _local_rows(self) -> List[tuple]:
        with self._lock:
            items = [(t, dict(m)) for t, m in self._sizes.items()]
            mv_of = dict(self._mv_of)
        rows: List[tuple] = []
        for t, m in items:
            per_vnode: Dict[int, List[int]] = {}
            for key, nb in m.items():
                c = per_vnode.setdefault(self._vnode_of(key), [0, 0])
                c[0] += 1
                c[1] += nb
            mv = mv_of.get(t, "")
            for vn, (nrows, nbytes) in per_vnode.items():
                rows.append((t, mv, vn, nrows, nbytes))
        return rows

    def rows(self) -> List[tuple]:
        """rw_state_topology payload: (table_id, mv, vnode, rows,
        bytes) — local tables plus drained worker rows."""
        rows = self._local_rows()
        with self._lock:
            for remote in self._remote.values():
                rows.extend(remote)
        return sorted(rows)

    def table_stats(self) -> List[tuple]:
        """(table_id, mv, rows, bytes, vnodes, imbalance): per-table
        rollup with the hot-vnode max/mean ratio — the rescale
        planner's move-cost input."""
        agg: Dict[int, list] = {}
        for t, mv, _vn, nrows, nbytes in self.rows():
            a = agg.setdefault(t, [mv, 0, 0, []])
            a[1] += nrows
            a[2] += nbytes
            a[3].append(nbytes)
        out = []
        for t, (mv, nrows, nbytes, per_vn) in sorted(agg.items()):
            mean = nbytes / len(per_vn) if per_vn else 0.0
            imb = (max(per_vn) / mean) if mean > 0 else 1.0
            out.append((t, mv, nrows, nbytes, len(per_vn),
                        round(imb, 3)))
        return out

    def top_vnodes(self, table_id: int, n: int = 8) -> List[tuple]:
        """(vnode, rows, bytes) for the table's n biggest vnodes —
        the `ctl memory` breakdown."""
        per = [(vn, nrows, nbytes) for t, _mv, vn, nrows, nbytes
               in self.rows() if t == table_id]
        return sorted(per, key=lambda r: -r[2])[:n]

    def bytes_by_mv(self) -> Dict[str, int]:
        """Per-MV resident-byte rollup from the delta-arithmetic
        totals — O(#tables), NOT a key scan: this runs at every
        checkpoint (costs.publish_state_bytes) and must never walk
        the per-key map (the map holds one entry per state row)."""
        out: Dict[str, int] = {}
        with self._lock:
            for t, (_nrows, nbytes) in self._totals.items():
                mv = self._mv_of.get(t, "")
                out[mv] = out.get(mv, 0) + nbytes
            for remote in self._remote.values():
                for _t, mv, _vn, _nrows, nbytes in remote:
                    out[mv] = out.get(mv, 0) + nbytes
        return out

    def imbalance_by_mv(self) -> Dict[str, float]:
        """Worst per-table hot-vnode ratio per MV (the bench
        marginal_cost block's aggregate skew signal)."""
        out: Dict[str, float] = {}
        for _t, mv, _nrows, _nbytes, _vns, imb in self.table_stats():
            out[mv] = max(out.get(mv, 1.0), imb)
        return out

    # -- conservation gate ----------------------------------------------
    def arm_checkpoint_verify(self, on: bool = True) -> None:
        self._verify_each_checkpoint = bool(on)

    def checkpoint_verify(self) -> None:
        """Checkpoint-time recount (meta/barrier.py piggyback): armed
        by the tier-1 gate fixture, a no-op in production."""
        if not self._verify_each_checkpoint:
            return
        with self._lock:
            self._violations.extend(self._recount_locked())

    def _recount_locked(self) -> List[tuple]:
        out = []
        for t, m in self._sizes.items():
            rows_inc, bytes_inc = self._totals.get(t, [0, 0])
            rows_true, bytes_true = len(m), sum(m.values())
            if rows_inc != rows_true or bytes_inc != bytes_true:
                out.append((t, rows_inc, rows_true,
                            bytes_inc, bytes_true))
        return out

    def gate_violations(self) -> List[tuple]:
        """(table_id, rows_incremental, rows_recount,
        bytes_incremental, bytes_recount) wherever the two books
        disagree — Σ per-table topology bytes must equal the accounted
        resident bytes (the map recount) exactly."""
        with self._lock:
            return self._violations + self._recount_locked()

    # -- cross-process merge (cluster `signals` drain) -------------------
    def drain_rows(self) -> List[tuple]:
        """Snapshot this process's local rows for the coordinator (a
        snapshot, not a drain — upkeep continues here)."""
        return self._local_rows()

    def ingest(self, rows: Iterable[tuple], worker: str = "") -> int:
        rows = [tuple(r) for r in rows]
        with self._lock:
            self._remote[worker] = rows
        return len(rows)

    def clear(self) -> None:
        with self._lock:
            self._sizes.clear()
            self._totals.clear()
            self._mv_of.clear()
            self._unit.clear()
            self._remote.clear()
            self._violations.clear()
            self._verify_each_checkpoint = False


TOPOLOGY = StateTopology()
