"""State layer: epoch-MVCC state store + relational StateTable.

Reference parity: src/storage/src/{store.rs,memory.rs,mem_table.rs} and
src/stream/src/common/table/state_table.rs. This is the checkpoint interface
the north star keeps: TPU-resident operator state (device hash tables) must
flush per-barrier deltas through a StateTable-shaped API, and every executor
test runs against the in-memory fake.
"""

from risingwave_tpu.state.keycodec import (
    decode_memcomparable,
    encode_memcomparable,
    encode_vnode_prefix,
)
from risingwave_tpu.state.store import MemoryStateStore, StateStore
from risingwave_tpu.state.mem_table import KeyOp, MemTable, MemTableError
from risingwave_tpu.state.state_table import StateTable

__all__ = [
    "encode_memcomparable",
    "decode_memcomparable",
    "encode_vnode_prefix",
    "StateStore",
    "MemoryStateStore",
    "MemTable",
    "MemTableError",
    "KeyOp",
    "StateTable",
]
