"""Memcomparable primary-key encoding.

Reference parity: src/common/src/util/ordered/ and the memcomparable crate —
encoded bytes compare (as unsigned byte strings) in the same order as the
SQL values they encode. Re-designed minimal: we encode host python values
(the state store is host-side; device state flushes through it at barriers).

Values are PHYSICAL: DECIMAL is its scaled-int64 payload, timestamps are µs
ints — the same representation device kernels and state-table rows use, so
the vectorized bulk encoder (state_table._encode_pks_bulk) and this scalar
codec produce identical bytes. Logical→physical normalization happens once,
at chunk ingest (chunk._make_column / types.decimal_to_scaled).

Layout per value:
  0x00                      NULL (nulls sort first, matching our iter tests)
  0x01 <payload>            non-null value

Payloads:
  bool        1 byte 0/1
  int         8 bytes big-endian with sign bit flipped (order-preserving)
  float       IEEE-754 bits; >=0: flip sign bit, <0: invert all bits
  str/bytes   utf-8/raw with 0x00 escaped as 0x00 0xFF, terminated 0x00 0x00
  Decimal     scaled int64 (exact fixed point), same as int
"""

from __future__ import annotations

import struct
from typing import Iterable, List, Optional, Sequence, Tuple

from risingwave_tpu.common.types import DataType

_NULL = b"\x00"
_NONNULL = b"\x01"
_STR_TERM = b"\x00\x00"


def _encode_int(v: int) -> bytes:
    return struct.pack(">Q", (v + (1 << 63)) & ((1 << 64) - 1))


def _decode_int(b: bytes) -> int:
    return struct.unpack(">Q", b)[0] - (1 << 63)


def _encode_float(v: float) -> bytes:
    if v == 0.0:
        v = 0.0  # normalize -0.0: one SQL value, one key (matches hash.py)
    bits = struct.unpack(">Q", struct.pack(">d", v))[0]
    if bits & (1 << 63):
        bits = ~bits & ((1 << 64) - 1)   # negative: invert all
    else:
        bits |= 1 << 63                  # positive: flip sign bit
    return struct.pack(">Q", bits)


def _decode_float(b: bytes) -> float:
    bits = struct.unpack(">Q", b)[0]
    if bits & (1 << 63):
        bits &= ~(1 << 63) & ((1 << 64) - 1)
    else:
        bits = ~bits & ((1 << 64) - 1)
    return struct.unpack(">d", struct.pack(">Q", bits))[0]


def _encode_bytes(v: bytes) -> bytes:
    return v.replace(b"\x00", b"\x00\xff") + _STR_TERM


def _scan_bytes(buf: bytes, pos: int) -> Tuple[bytes, int]:
    out = bytearray()
    while True:
        i = buf.index(b"\x00", pos)
        out += buf[pos:i]
        nxt = buf[i + 1]
        if nxt == 0xFF:
            out += b"\x00"
            pos = i + 2
        elif nxt == 0x00:
            return bytes(out), i + 2
        else:
            raise ValueError("malformed escaped byte string")


def encode_value(v, dt: DataType) -> bytes:
    if v is None:
        return _NULL
    if dt == DataType.BOOLEAN:
        return _NONNULL + (b"\x01" if v else b"\x00")
    if dt in (DataType.FLOAT32, DataType.FLOAT64):
        return _NONNULL + _encode_float(float(v))
    if dt == DataType.DECIMAL:
        # physical scaled-int64 payload (already scaled at chunk ingest)
        return _NONNULL + _encode_int(int(v))
    if dt == DataType.VARCHAR:
        return _NONNULL + _encode_bytes(str(v).encode("utf-8"))
    if dt == DataType.BYTEA:
        return _NONNULL + _encode_bytes(bytes(v))
    # all remaining device types are integral (ints, dates, timestamps)
    return _NONNULL + _encode_int(int(v))


def decode_value(buf: bytes, pos: int, dt: DataType):
    tag = buf[pos]
    pos += 1
    if tag == 0x00:
        return None, pos
    if dt == DataType.BOOLEAN:
        return buf[pos] == 1, pos + 1
    if dt in (DataType.FLOAT32, DataType.FLOAT64):
        return _decode_float(buf[pos:pos + 8]), pos + 8
    if dt == DataType.DECIMAL:
        return _decode_int(buf[pos:pos + 8]), pos + 8
    if dt == DataType.VARCHAR:
        raw, pos = _scan_bytes(buf, pos)
        return raw.decode("utf-8"), pos
    if dt == DataType.BYTEA:
        return _scan_bytes(buf, pos)
    return _decode_int(buf[pos:pos + 8]), pos + 8


def encode_memcomparable(values: Sequence, types: Sequence[DataType]) -> bytes:
    """Encode a pk tuple → order-preserving bytes."""
    return b"".join(encode_value(v, t) for v, t in zip(values, types))


def decode_memcomparable(buf: bytes, types: Sequence[DataType]) -> tuple:
    out: List = []
    pos = 0
    for t in types:
        v, pos = decode_value(buf, pos, t)
        out.append(v)
    return tuple(out)


def encode_vnode_prefix(vnode: int) -> bytes:
    """2-byte big-endian vnode prefix (state_table.rs pk layout)."""
    return struct.pack(">H", vnode)
