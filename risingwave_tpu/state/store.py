"""State store: epoch-MVCC KV with table namespaces.

Reference parity: src/storage/src/store.rs:72 (StateStoreRead: get/iter),
:198 (LocalStateStore: ingest at epoch, seal), and memory.rs
(MemoryStateStore — the BTreeMap fake every executor test runs on).

Re-design notes: keys are vnode-prefixed memcomparable bytes; values are
host row tuples (serialization to bytes happens at the hummock-lite SST
boundary, not here). MVCC: per key we keep (epoch, value|None) versions,
newest first; a read at epoch e sees the newest version with epoch <= e.
Tombstones are value=None.
"""

from __future__ import annotations

import bisect
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

Value = Optional[tuple]            # None = tombstone
Versions = List[Tuple[int, Value]]  # newest-first [(epoch, value)]


class StateStore:
    """Interface both MemoryStateStore and hummock-lite implement."""

    def ingest_batch(self, table_id: int,
                     batch: Iterable[Tuple[bytes, Value]],
                     epoch: int) -> int:
        raise NotImplementedError

    def ingest_keyed(self, table_id: int, keys: List[bytes],
                     values: List[Value], epoch: int) -> int:
        """Bulk ingest of parallel key/value lists (keys unique —
        memtable-drained). Backends may take a C-speed merge path;
        the default delegates to ingest_batch."""
        return self.ingest_batch(table_id, zip(keys, values), epoch)

    def get(self, table_id: int, key: bytes, epoch: int) -> Value:
        raise NotImplementedError

    def iter(self, table_id: int, epoch: int,
             start: Optional[bytes] = None, end: Optional[bytes] = None
             ) -> Iterator[Tuple[bytes, tuple]]:
        raise NotImplementedError

    def seal_epoch(self, epoch: int, is_checkpoint: bool) -> None:
        """Global order point: no further writes at <= epoch."""

    def sync(self, epoch: int) -> dict:
        """Await all data at <= epoch durable; returns uploadinfo."""
        return {}

    def committed_epoch(self) -> int:
        """Latest durably committed (checkpoint) epoch — the recovery
        point the initial barrier's `prev` is set to after a restart."""
        return 0


class _Table:
    """One table's ordered MVCC map: sorted key index + version lists.

    The key index is LAZILY sorted: puts append (O(1)) and set a dirty
    flag; the first ordered read re-sorts. Timsort on a sorted prefix +
    appended tail is near O(n) — while ``bisect.insort`` per new key is
    O(n) EACH, which made streaming ingest quadratic in table size (the
    r3 join benches spent most of their p99 barrier here)."""

    __slots__ = ("keys", "versions", "_dirty")

    def __init__(self) -> None:
        self.keys: List[bytes] = []          # sorted iff not _dirty
        self.versions: Dict[bytes, Versions] = {}
        self._dirty = False

    def sorted_keys(self) -> List[bytes]:
        if self._dirty:
            self.keys.sort()
            self._dirty = False
        return self.keys

    def put_batch(self, batch: Iterable[Tuple[bytes, Value]],
                  epoch: int) -> int:
        """Barrier-flush hot loop (one call per written key per
        epoch; a method call per key costs ~1/3 of q8 throughput).
        Inlines put()'s new-key insert and newest-at-head update —
        the in-order cases every barrier flush hits — and falls back
        to put() only for out-of-order epoch ingest. Keep the two in
        lockstep with put() below."""
        versions = self.versions
        keys = self.keys
        n = 0
        for key, value in batch:
            vs = versions.get(key)
            if vs is None:
                versions[key] = [(epoch, value)]
                keys.append(key)
                self._dirty = True
            else:
                if type(vs) is tuple:       # bulk-ingest single-version
                    vs = versions[key] = [vs]       # form: normalize
                e0 = vs[0][0]
                if e0 == epoch:
                    vs[0] = (epoch, value)
                elif e0 < epoch:
                    vs.insert(0, (epoch, value))
                else:
                    self.put(key, epoch, value)
            n += 1
        return n

    def put(self, key: bytes, epoch: int, value: Value) -> None:
        vs = self.versions.get(key)
        if vs is None:
            self.versions[key] = [(epoch, value)]
            self.keys.append(key)
            self._dirty = True
            return
        if type(vs) is tuple:
            vs = self.versions[key] = [vs]
        # keep newest-first order even for out-of-order epoch ingest;
        # same-epoch overwrite replaces (linear scan: version lists are short)
        for i, (e, _v) in enumerate(vs):
            if e == epoch:
                vs[i] = (epoch, value)
                return
            if e < epoch:
                vs.insert(i, (epoch, value))
                return
        vs.append((epoch, value))

    def read(self, key: bytes, epoch: int) -> Value:
        vs = self.versions.get(key)
        if not vs:
            return None
        if type(vs) is tuple:           # single-version fast form
            return vs[1] if vs[0] <= epoch else None
        for e, v in vs:
            if e <= epoch:
                return v
        return None


class MemoryStateStore(StateStore):
    """In-memory MVCC store (memory.rs analog) — the test/checkpoint fake."""

    def __init__(self) -> None:
        self._tables: Dict[int, _Table] = {}
        self._sealed_epoch = 0
        self._committed_epoch = 0

    def _table(self, table_id: int) -> _Table:
        t = self._tables.get(table_id)
        if t is None:
            t = self._tables[table_id] = _Table()
        return t

    # -- write path ----------------------------------------------------
    def ingest_batch(self, table_id: int,
                     batch: Iterable[Tuple[bytes, Value]],
                     epoch: int) -> int:
        if epoch <= self._sealed_epoch:
            raise ValueError(
                f"write at epoch {epoch} <= sealed {self._sealed_epoch}")
        return self._table(table_id).put_batch(batch, epoch)

    def ingest_keyed(self, table_id: int, keys: List[bytes],
                     values: List[Value], epoch: int) -> int:
        if epoch <= self._sealed_epoch:
            raise ValueError(
                f"write at epoch {epoch} <= sealed {self._sealed_epoch}")
        t = self._table(table_id)
        versions = t.versions
        if versions.keys().isdisjoint(keys):
            # all-fresh bulk path (append-only streams): one C-speed
            # dict merge of BARE (epoch, value) versions — the
            # single-version tuple fast form (_Table normalizes it to
            # a list on the first subsequent mutation), built by
            # zip(repeat, …) with no python-level per-row work at all
            # (the [(epoch, v)] list-per-row was the top q1 host_emit
            # cost in the r10 profile)
            from itertools import repeat
            before = len(versions)
            versions.update(zip(keys, zip(repeat(epoch), values)))
            if len(versions) - before == len(keys):
                t.keys.extend(keys)
            else:
                # intra-batch duplicate pks (a blind NO_CHECK upstream
                # re-inserting one key in an epoch): versions resolved
                # last-wins above, but the key INDEX must stay unique
                # or scans would yield the row twice forever
                t.keys.extend(dict.fromkeys(keys))
            t._dirty = True
            return len(keys)
        return t.put_batch(zip(keys, values), epoch)

    def seal_epoch(self, epoch: int, is_checkpoint: bool = True) -> None:
        assert epoch >= self._sealed_epoch, (epoch, self._sealed_epoch)
        self._sealed_epoch = epoch

    def sync(self, epoch: int) -> dict:
        self._committed_epoch = max(self._committed_epoch, epoch)
        return {}

    def committed_epoch(self) -> int:
        return self._committed_epoch

    # -- read path -----------------------------------------------------
    def get(self, table_id: int, key: bytes, epoch: int) -> Value:
        return self._table(table_id).read(key, epoch)

    def iter(self, table_id: int, epoch: int,
             start: Optional[bytes] = None, end: Optional[bytes] = None,
             reverse: bool = False) -> Iterator[Tuple[bytes, tuple]]:
        t = self._table(table_id)
        keys = t.sorted_keys()
        lo = bisect.bisect_left(keys, start) if start is not None else 0
        hi = bisect.bisect_left(keys, end) if end is not None else len(keys)
        rng = range(hi - 1, lo - 1, -1) if reverse else range(lo, hi)
        for i in rng:
            key = keys[i]
            v = t.read(key, epoch)
            if v is not None:
                yield key, v

    # -- test/debug helpers --------------------------------------------
    def table_size(self, table_id: int, epoch: int) -> int:
        return sum(1 for _ in self.iter(table_id, epoch))
