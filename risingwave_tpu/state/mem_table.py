"""MemTable: per-executor buffer of uncommitted key ops.

Reference parity: src/storage/src/mem_table.rs:44,53 — buffered
KeyOp{Insert,Delete,Update} with inconsistent-operation detection, merged
into the state store at barrier commit.
"""

from __future__ import annotations

import enum
from typing import Dict, Iterator, Optional, Tuple


class MemTableError(Exception):
    pass


class KeyOp(enum.Enum):
    INSERT = "insert"
    DELETE = "delete"
    UPDATE = "update"


class MemTable:
    """key → (op, old_value, new_value); op merge rules match mem_table.rs."""

    def __init__(self, sanity_check: bool = True):
        self._ops: Dict[bytes, Tuple[KeyOp, Optional[tuple],
                                     Optional[tuple]]] = {}
        self.sanity_check = sanity_check

    def __len__(self) -> int:
        return len(self._ops)

    def is_dirty(self) -> bool:
        return bool(self._ops)

    def insert_batch(self, keys, values) -> bool:
        """All-insert bulk path: ONE C-speed dict merge when every key
        is fresh (the append-only hot case — a method call per row cost
        ~1/3 of q8 host throughput). Returns False when any key is
        already buffered or duplicated in the batch: the caller must
        then run the per-row merge rules instead."""
        new = dict(zip(keys, values))
        if len(new) != len(keys) or not self._ops.keys().isdisjoint(new):
            return False
        ins = KeyOp.INSERT
        # listcomp + C-level zip beats a genexpr-fed update by ~25%
        # at 100K rows/epoch (the r10 host_emit profile)
        self._ops.update(zip(new.keys(),
                             [(ins, None, v) for v in new.values()]))
        return True

    def drain_bulk(self):
        """(keys, values) lists for ingest_keyed; clears. Same content
        as drain(), shaped for the store's bulk ingest."""
        ops, self._ops = self._ops, {}
        keys = list(ops.keys())
        delete = KeyOp.DELETE
        vals = [None if op is delete else new
                for (op, _old, new) in ops.values()]
        return keys, vals

    def insert(self, key: bytes, value: tuple) -> None:
        cur = self._ops.get(key)
        if cur is None:
            self._ops[key] = (KeyOp.INSERT, None, value)
            return
        op, old, _new = cur
        if op == KeyOp.INSERT:
            if self.sanity_check:
                raise MemTableError(f"double insert on key {key!r}")
            self._ops[key] = (KeyOp.INSERT, None, value)
        elif op == KeyOp.DELETE:
            self._ops[key] = (KeyOp.UPDATE, old, value)
        else:  # UPDATE = delete-then-insert already happened
            if self.sanity_check:
                raise MemTableError(f"insert after update on key {key!r}")
            self._ops[key] = (KeyOp.UPDATE, old, value)

    def delete(self, key: bytes, old_value: tuple) -> None:
        cur = self._ops.get(key)
        if cur is None:
            self._ops[key] = (KeyOp.DELETE, old_value, None)
            return
        op, old, _new = cur
        if op == KeyOp.INSERT:
            del self._ops[key]          # insert+delete annihilate
        elif op == KeyOp.DELETE:
            if self.sanity_check:
                raise MemTableError(f"double delete on key {key!r}")
        else:  # UPDATE
            self._ops[key] = (KeyOp.DELETE, old, None)

    def update(self, key: bytes, old_value: tuple, new_value: tuple) -> None:
        cur = self._ops.get(key)
        if cur is None:
            self._ops[key] = (KeyOp.UPDATE, old_value, new_value)
            return
        op, old, new = cur
        if op == KeyOp.INSERT:
            if self.sanity_check and new != old_value:
                raise MemTableError(
                    f"update old {old_value!r} != buffered insert {new!r}")
            self._ops[key] = (KeyOp.INSERT, None, new_value)
        elif op == KeyOp.DELETE:
            if self.sanity_check:
                raise MemTableError(f"update after delete on key {key!r}")
            self._ops[key] = (KeyOp.UPDATE, old, new_value)
        else:
            self._ops[key] = (KeyOp.UPDATE, old, new_value)

    def get(self, key: bytes):
        """(present, value) — present=False means 'no buffered op'."""
        cur = self._ops.get(key)
        if cur is None:
            return False, None
        op, _old, new = cur
        return True, (new if op != KeyOp.DELETE else None)

    def drain(self) -> Iterator[Tuple[bytes, Optional[tuple]]]:
        """(key, value|None-tombstone) pairs for ingest_batch; clears."""
        ops, self._ops = self._ops, {}
        for key, (op, _old, new) in ops.items():
            yield key, (None if op == KeyOp.DELETE else new)

    def iter_ops(self):
        return iter(sorted(self._ops.items()))
