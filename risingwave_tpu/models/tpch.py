"""Hand-built TPC-H streaming pipelines: q3 (3-way join → agg → topn).

Reference parity: e2e_test/streaming/tpch/q3 semantics —

    SELECT l_orderkey, SUM(l_extendedprice * (1 - l_discount)) AS
           revenue, o_orderdate, o_shippriority
    FROM customer, orders, lineitem
    WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
      AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
      AND l_shipdate > DATE '1995-03-15'
    GROUP BY l_orderkey, o_orderdate, o_shippriority
    ORDER BY revenue DESC, o_orderdate LIMIT 10

The plan chains two HashJoinExecutors (nested barrier alignment over
three sources), DECIMAL revenue arithmetic (exact scaled-int64), the
device hash-agg, and the streaming TopN window. Hand-assembled here
because the SQL planner currently supports one join per MV; the
executor layer itself has no such limit — which is exactly what this
model demonstrates.
"""

from __future__ import annotations

import datetime
from typing import Dict, Optional

from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.connectors.tpch import TpchConfig, TpchSplitReader
from risingwave_tpu.expr.expr import InputRef, lit
from risingwave_tpu.meta.barrier import BarrierLoop
from risingwave_tpu.models.nexmark import (
    SPLIT_STATE_SCHEMA, Pipeline, drive_to_completion,
)
from risingwave_tpu.ops.hash_agg import AggKind
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
from risingwave_tpu.stream.exchange import channel_for_test
from risingwave_tpu.stream.executors.hash_agg import (
    AggCall, HashAggExecutor, agg_state_schema,
)
from risingwave_tpu.stream.executors.hash_join import HashJoinExecutor
from risingwave_tpu.stream.executors.materialize import MaterializeExecutor
from risingwave_tpu.stream.executors.row_id_gen import RowIdGenExecutor
from risingwave_tpu.stream.executors.simple import (
    FilterExecutor, ProjectExecutor,
)
from risingwave_tpu.stream.executors.source import SourceExecutor
from risingwave_tpu.stream.executors.top_n import GroupTopNExecutor

EPOCH_DAY = datetime.date(1970, 1, 1)
CUTOFF = (datetime.date(1995, 3, 15) - EPOCH_DAY).days


def _src(local, store, aid, cfg, tid, rate_limit, min_chunks):
    reader = TpchSplitReader(cfg)
    tx, rx = channel_for_test(edge=f"barrier:tpch-{cfg.table}-{aid}")
    st = StateTable(tid, SPLIT_STATE_SCHEMA, [0], store)
    local.register_sender(aid, tx)
    return SourceExecutor(reader, rx, st, actor_id=aid,
                          rate_limit_chunks_per_barrier=rate_limit,
                          min_chunks_per_barrier=min_chunks), reader


def build_q3(store, customers: int = 300, orders: int = 3000,
             rate_limit: Optional[int] = 8,
             min_chunks: Optional[int] = None,
             top_limit: int = 10,
             fusion: bool = False) -> Pipeline:
    local = LocalBarrierManager()
    mk = lambda t, rows=None: TpchConfig(table=t, customers=customers,
                                         orders=orders, row_count=rows)
    cust, cust_r = _src(local, store, 1, mk("customer"), 1,
                        rate_limit, min_chunks)
    ordr, ordr_r = _src(local, store, 2, mk("orders"), 2,
                        rate_limit, min_chunks)
    line, line_r = _src(local, store, 3, mk("lineitem"), 3,
                        rate_limit, min_chunks)

    # capacity presize from KNOWN tpch cardinalities (see
    # common/chunk.presize_cap — growth doublings compile mid-run)
    from risingwave_tpu.common.chunk import presize_cap, presize_flush_cap
    from risingwave_tpu.connectors.tpch import LINES_PER_ORDER

    n_line = orders * LINES_PER_ORDER
    j_opts = {"key_capacity": presize_cap(n_line),
              "row_capacity": presize_cap(n_line),
              "probe_capacity": 1 << 16}

    cs = cust.schema
    c_f = RowIdGenExecutor(FilterExecutor(
        cust, InputRef(cs.index_of("c_mktsegment"), DataType.VARCHAR)
        == lit("BUILDING")))
    os_ = ordr.schema
    o_f = RowIdGenExecutor(FilterExecutor(
        ordr, InputRef(os_.index_of("o_orderdate"), DataType.DATE)
        < lit(CUTOFF, DataType.DATE)))
    ls = line.schema
    l_f = RowIdGenExecutor(FilterExecutor(
        line, InputRef(ls.index_of("l_shipdate"), DataType.DATE)
        > lit(CUTOFF, DataType.DATE)))

    # join 1: customer ⋈ orders on custkey
    n_c = len(c_f.schema)
    j1_lt = StateTable(4, c_f.schema, [n_c - 1], store)
    j1_rt = StateTable(5, o_f.schema, [len(o_f.schema) - 1], store)
    j1 = HashJoinExecutor(
        c_f, o_f,
        left_keys=[c_f.schema.index_of("c_custkey")],
        right_keys=[o_f.schema.index_of("o_custkey")],
        left_table=j1_lt, right_table=j1_rt, shard_opts=j_opts)

    # join 2: (customer ⋈ orders) ⋈ lineitem on orderkey
    j1_pk = list(j1.pk_indices)
    j2_lt = StateTable(6, j1.schema, j1_pk, store)
    j2_rt = StateTable(7, l_f.schema, [len(l_f.schema) - 1], store)
    j2 = HashJoinExecutor(
        j1, l_f,
        left_keys=[j1.schema.index_of("o_orderkey")],
        right_keys=[l_f.schema.index_of("l_orderkey")],
        left_table=j2_lt, right_table=j2_rt, shard_opts=j_opts)

    js = j2.schema
    revenue = (InputRef(js.index_of("l_extendedprice"), DataType.DECIMAL)
               * (lit(1, DataType.DECIMAL)
                  - InputRef(js.index_of("l_discount"),
                             DataType.DECIMAL)))
    proj = ProjectExecutor(
        j2,
        exprs=[InputRef(js.index_of("l_orderkey"), DataType.INT64),
               InputRef(js.index_of("o_orderdate"), DataType.DATE),
               InputRef(js.index_of("o_shippriority"), DataType.INT32),
               revenue],
        names=["l_orderkey", "o_orderdate", "o_shippriority", "revenue"])

    calls = [AggCall(AggKind.SUM, 3)]
    agg_sch, agg_pk = agg_state_schema(proj.schema, [0, 1, 2], calls)
    agg = HashAggExecutor(
        proj, [0, 1, 2], calls,
        StateTable(8, agg_sch, agg_pk, store,
                   dist_key_indices=[0]),
        append_only=True,
        output_names=["l_orderkey", "o_orderdate", "o_shippriority",
                      "revenue"],
        kernel_capacity=presize_cap(orders, 1 << 18),
        flush_capacity=presize_flush_cap(orders))

    topn_state = StateTable(9, agg.schema, [0, 1, 2], store)
    topn = GroupTopNExecutor(
        agg, order_by=[(3, True), (1, False)], offset=0,
        limit=top_limit, state=topn_state, pk_indices=[0, 1, 2])

    mv = StateTable(10, topn.schema, [0, 1, 2], store)
    mat = MaterializeExecutor(topn, mv, mv_name="tpch-q3")
    from risingwave_tpu.models.nexmark import _register_freshness
    _register_freshness(mat, "tpch-q3")
    if fusion:
        # same fusion rule the SQL sessions apply (SET stream_fusion)
        from risingwave_tpu.frontend.opt import rewrite_stream_plan
        mat, _report = rewrite_stream_plan(mat, "none", record=False,
                                           fusion=True)
    local.set_expected_actors([11])
    from risingwave_tpu.stream.monitor import install_monitoring
    consumer = install_monitoring(mat, fragment="tpch-q3", actor_id=11)
    actor = Actor(11, consumer, dispatchers=[], barrier_manager=local,
                  fragment="tpch-q3")
    return Pipeline(actor, BarrierLoop(local, store), mv,
                    {1: cust_r, 2: ordr_r, 3: line_r})
