"""Hand-built Nexmark pipelines: q1 (stateless), q7-core (hash agg on
device), q8 (windowed join on device).

Reference parity: e2e_test/streaming/nexmark/q1|q7|q8 semantics; plan
shapes mirror what the reference's fragmenter produces for these queries
(src/frontend/src/stream_fragmenter/mod.rs) — hand-assembled here until
the SQL frontend lands. Used by BOTH tests/test_e2e_q*.py and bench.py:
the benchmarked pipeline is exactly the tested pipeline.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from risingwave_tpu.common.types import DataType, Field, Interval, Schema
from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkSplitReader
from risingwave_tpu.expr.expr import InputRef, lit, tumble_start
from risingwave_tpu.meta.barrier import BarrierLoop
from risingwave_tpu.ops.hash_agg import AggKind
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
from risingwave_tpu.stream.exchange import channel_for_test
from risingwave_tpu.stream.executor import Executor
from risingwave_tpu.stream.executors.hash_agg import (
    AggCall, HashAggExecutor, agg_state_schema,
)
from risingwave_tpu.stream.executors.hash_join import HashJoinExecutor
from risingwave_tpu.stream.executors.materialize import MaterializeExecutor
from risingwave_tpu.stream.executors.row_id_gen import RowIdGenExecutor
from risingwave_tpu.stream.executors.simple import ProjectExecutor
from risingwave_tpu.stream.executors.source import SourceExecutor

SPLIT_STATE_SCHEMA = Schema([Field("split_id", DataType.VARCHAR),
                             Field("offset", DataType.INT64)])
DEFAULT_WINDOW = Interval(usecs=10_000_000)   # 10 seconds


@dataclass
class Pipeline:
    """A runnable hand-built plan: one actor + its barrier loop."""

    actor: Actor
    loop: BarrierLoop
    mv_table: StateTable
    readers: Dict[int, NexmarkSplitReader]

    @property
    def reader(self) -> NexmarkSplitReader:
        assert len(self.readers) == 1
        return next(iter(self.readers.values()))


def _source(local: LocalBarrierManager, store, actor_id: int,
            cfg: NexmarkConfig, table_id: int,
            rate_limit: Optional[int],
            min_chunks: Optional[int] = None) -> SourceExecutor:
    reader = NexmarkSplitReader(cfg)
    tx, rx = channel_for_test(
        edge=f"barrier:nexmark-{cfg.table_type}-{actor_id}")
    split_state = StateTable(table_id, SPLIT_STATE_SCHEMA, [0], store)
    local.register_sender(actor_id, tx)
    return SourceExecutor(reader, rx, split_state, actor_id=actor_id,
                          rate_limit_chunks_per_barrier=rate_limit,
                          min_chunks_per_barrier=min_chunks)


def _register_freshness(mat: MaterializeExecutor, fragment: str) -> None:
    """Freshness lineage (stream/freshness.py) for a hand-built
    pipeline: name the MV after its fragment and bind the chain's
    source executors' ingest frontiers — the benched pipeline reports
    per-MV lag exactly like a SQL-deployed one."""
    from risingwave_tpu.stream.executor import executor_children
    from risingwave_tpu.stream.freshness import FRESHNESS
    mat.mv_name = fragment

    def _source_keys(ex) -> list:
        keys = [ex.freshness_key] if isinstance(ex, SourceExecutor) \
            else []
        for _a, _i, child in executor_children(ex):
            keys += _source_keys(child)
        return keys

    FRESHNESS.register_mv(fragment, _source_keys(mat))


def _finish(local: LocalBarrierManager, store, mat: MaterializeExecutor,
            mv_table: StateTable, actor_id: int,
            readers: Dict[int, NexmarkSplitReader],
            fragment: str = "nexmark",
            fusion: bool = False) -> Pipeline:
    from risingwave_tpu.stream.monitor import install_monitoring
    _register_freshness(mat, fragment)
    if fusion:
        # fragment fusion (frontend/opt/fusion.py): same rule the SQL
        # sessions apply under SET stream_fusion — the benched
        # pipeline stays exactly the tested pipeline
        from risingwave_tpu.frontend.opt import rewrite_stream_plan
        mat, _report = rewrite_stream_plan(mat, "none", record=False,
                                           fusion=True)
    local.set_expected_actors([actor_id])
    consumer = install_monitoring(mat, fragment=fragment,
                                  actor_id=actor_id)
    actor = Actor(actor_id, consumer, dispatchers=[],
                  barrier_manager=local, fragment=fragment)
    return Pipeline(actor, BarrierLoop(local, store), mv_table, readers)


def build_q1(store, cfg: NexmarkConfig,
             rate_limit: Optional[int] = 3,
             min_chunks: Optional[int] = None,
             fusion: bool = False) -> Pipeline:
    """q1: SELECT auction, bidder, 0.908*price, date_time FROM bid."""
    local = LocalBarrierManager()
    source = _source(local, store, 1, cfg, 1, rate_limit, min_chunks)
    row_id = RowIdGenExecutor(source)
    s = row_id.schema
    project = ProjectExecutor(
        row_id,
        exprs=[InputRef(s.index_of("auction"), DataType.INT64),
               InputRef(s.index_of("bidder"), DataType.INT64),
               lit("0.908", DataType.DECIMAL)
               * InputRef(s.index_of("price"), DataType.INT64),
               InputRef(s.index_of("date_time"), DataType.TIMESTAMP),
               InputRef(s.index_of("_row_id"), DataType.SERIAL)],
        names=["auction", "bidder", "price", "date_time", "_row_id"])
    mv_table = StateTable(2, project.schema, [4], store)  # pk = _row_id
    mat = MaterializeExecutor(project, mv_table)
    return _finish(local, store, mat, mv_table, 1,
                   {1: source.reader}, fragment="nexmark-q1",
                   fusion=fusion)


def build_q7(store, cfg: NexmarkConfig,
             rate_limit: Optional[int] = 4,
             window: Interval = DEFAULT_WINDOW,
             min_chunks: Optional[int] = None,
             watermark_delay: Optional[Interval] = None,
             mesh=None, shard_capacity: int = 1 << 14,
             coalesce_rows: Optional[int] = None,
             tier_cap: Optional[int] = None,
             fusion: bool = False) -> Pipeline:
    """q7-core: MAX(price), COUNT(*) per tumbling window (device agg).

    With ``watermark_delay``, a WatermarkFilter generates event-time
    watermarks on date_time; the projection derives a window_start
    watermark through tumble_start, and the agg retires closed windows
    (bounded state — the honest steady-state configuration).

    With ``mesh``, the aggregation runs vnode-sharded across the mesh
    (parallel/agg.ShardedAggKernel): the reference's hash dispatch to N
    parallel actors (dispatch.rs:582) becomes one SPMD all_to_all."""
    local = LocalBarrierManager()
    source = _source(local, store, 1, cfg, 1, rate_limit, min_chunks)
    s = source.schema
    upstream: "SourceExecutor | WatermarkFilterExecutor" = source
    derivations = None
    if watermark_delay is not None:
        from risingwave_tpu.stream.executors.watermark_filter import (
            WATERMARK_STATE_SCHEMA, WatermarkFilterExecutor,
        )
        wm_state = StateTable(10, WATERMARK_STATE_SCHEMA, [0], store)
        upstream = WatermarkFilterExecutor(
            source, s.index_of("date_time"), watermark_delay, wm_state)
        w = window.exact_usecs()
        derivations = {s.index_of("date_time"): (0, lambda v: v - v % w)}
    project = ProjectExecutor(
        upstream,
        exprs=[tumble_start(
            InputRef(s.index_of("date_time"), DataType.TIMESTAMP), window),
            InputRef(s.index_of("price"), DataType.INT64)],
        names=["window_start", "price"],
        watermark_derivations=derivations)
    calls = [AggCall(AggKind.MAX, 1), AggCall(AggKind.COUNT)]
    agg_schema, agg_pk = agg_state_schema(project.schema, [0], calls)
    agg_state = StateTable(2, agg_schema, agg_pk, store,
                           dist_key_indices=[0])
    kernel = None
    if mesh is not None:
        from risingwave_tpu.parallel.agg import ShardedAggKernel
        from risingwave_tpu.stream.executors.keys import LANES_PER_KEY
        kernel = ShardedAggKernel(
            mesh, key_width=LANES_PER_KEY * 1,
            specs=[c.spec(project.schema) for c in calls],
            capacity=shard_capacity)
    agg_in: Executor = project
    if coalesce_rows:
        # barrier-bounded chunk coalescing in front of the keyed
        # executor (stream/coalesce.py) — the SQL planner inserts this
        # automatically; the hand-built pipeline takes it as a knob so
        # the oracle test can compare on vs off
        from risingwave_tpu.stream.coalesce import CoalesceExecutor
        agg_in = CoalesceExecutor(project, coalesce_rows)
    # tier_cap: resident-group cap for the state-tiering oracle tests
    # and bench parity runs (state/tier.py; single-chip only)
    agg = HashAggExecutor(agg_in, [0], calls, agg_state,
                          append_only=True,
                          output_names=["max_price", "bid_count"],
                          kernel=kernel,
                          tier_cap=tier_cap if mesh is None else None)
    mv_table = StateTable(3, agg.schema, [0], store)  # pk = window_start
    mat = MaterializeExecutor(agg, mv_table)
    return _finish(local, store, mat, mv_table, 1,
                   {1: source.reader}, fragment="nexmark-q7",
                   fusion=fusion)


def build_q8(store, cfg_p: NexmarkConfig, cfg_a: NexmarkConfig,
             rate_limit: Optional[int] = 4,
             window: Interval = DEFAULT_WINDOW,
             min_chunks: Optional[int] = None, mesh=None,
             fusion: bool = False) -> Pipeline:
    """q8: persons who created an auction in the same tumbling window.

    two sources → projects → auction-side hash-agg dedup → inner
    HashJoin (device matcher) → project → materialize.

    With ``mesh``, the join runs on the vnode-sharded SPMD matcher
    (parallel/join.ShardedJoinKernel): both sides' state routes to key
    owners over one all_to_all — the reference's hash dispatch to N
    parallel join actors (dispatch.rs:582)."""
    local = LocalBarrierManager()
    persons = _source(local, store, 1, cfg_p, 1, rate_limit, min_chunks)
    ps = persons.schema
    p_proj = ProjectExecutor(
        persons,
        exprs=[InputRef(ps.index_of("id"), DataType.INT64),
               InputRef(ps.index_of("name"), DataType.VARCHAR),
               tumble_start(InputRef(ps.index_of("date_time"),
                                     DataType.TIMESTAMP), window)],
        names=["id", "name", "starttime"])
    auctions = _source(local, store, 2, cfg_a, 2, rate_limit, min_chunks)
    asch = auctions.schema
    a_proj = ProjectExecutor(
        auctions,
        exprs=[InputRef(asch.index_of("seller"), DataType.INT64),
               tumble_start(InputRef(asch.index_of("date_time"),
                                     DataType.TIMESTAMP), window)],
        names=["seller", "starttime"])
    calls = [AggCall(AggKind.COUNT)]
    agg_sch, agg_pk = agg_state_schema(a_proj.schema, [0, 1], calls)
    # capacity presize from the KNOWN nexmark cardinalities (see
    # common/chunk.presize_cap — growth doublings compile mid-run)
    from risingwave_tpu.common.chunk import presize_cap, presize_flush_cap
    n_p = max(cfg_p.event_num // 50, 1)
    n_a = max(cfg_a.event_num * 3 // 50, 1)
    a_dedup = HashAggExecutor(
        a_proj, [0, 1], calls,
        StateTable(3, agg_sch, agg_pk, store, dist_key_indices=[0]),
        append_only=True, output_names=["seller", "starttime", "_cnt"],
        kernel_capacity=presize_cap(n_a, 1 << 18),
        flush_capacity=presize_flush_cap(n_a))
    a_dedup_proj = ProjectExecutor(
        a_dedup,
        exprs=[InputRef(0, DataType.INT64),
               InputRef(1, DataType.TIMESTAMP)],
        names=["seller", "starttime"])
    lt = StateTable(4, p_proj.schema, [0, 2], store, dist_key_indices=[0])
    rt = StateTable(5, a_dedup_proj.schema, [0, 1], store,
                    dist_key_indices=[0])
    join_opts = None if mesh is not None else {
        "key_capacity": presize_cap(max(n_p, n_a)),
        "row_capacity": presize_cap(max(n_p, n_a)),
        "probe_capacity": 1 << 16,
    }
    join = HashJoinExecutor(p_proj, a_dedup_proj,
                            left_keys=[0, 2], right_keys=[0, 1],
                            left_table=lt, right_table=rt, mesh=mesh,
                            shard_opts=join_opts)
    out = ProjectExecutor(
        join,
        exprs=[InputRef(0, DataType.INT64),
               InputRef(1, DataType.VARCHAR),
               InputRef(2, DataType.TIMESTAMP)],
        names=["id", "name", "starttime"])
    mv = StateTable(6, out.schema, [0, 2], store)
    mat = MaterializeExecutor(out, mv)
    return _finish(local, store, mat, mv, 7,
                   {1: persons.reader, 2: auctions.reader},
                   fragment="nexmark-q8", fusion=fusion)


def drive_to_completion(pipeline: Pipeline,
                        targets: Dict[int, int],
                        max_epochs: int = 500,
                        in_flight: int = 2):
    """Async driver: barrier-tick until every reader hits its target
    offset, one final checkpoint, then a Stop barrier.

    Barriers are PIPELINED up to `in_flight` (the reference's
    in_flight_barrier_nums): epoch N+1's data processing overlaps
    epoch N's barrier flush — on a tunneled device the flush's
    device→host fetch (~0.1-1s) hides under the next epoch's compute
    instead of serializing the stream. NOTE: recorded barrier latency
    is inject→commit and therefore includes queueing behind earlier
    in-flight barriers (the reference's in-flight semantics) — compare
    latencies only across runs with the same window.

    Returns (timed_elapsed_s, timed_rows) measured AFTER a warmup epoch
    (jit compiles land outside the timed window)."""
    import time

    from risingwave_tpu.stream.message import StopMutation

    in_flight_w = max(1, in_flight)

    async def run():
        task = pipeline.actor.spawn()
        loop = pipeline.loop
        readers = pipeline.readers
        await loop.inject_and_collect()      # warmup epoch
        warm_rows = sum(r.offset for r in readers.values())
        warm_epochs = len(loop.stats.latencies_s)
        t0 = time.perf_counter()

        def done() -> bool:
            return all(readers[a].offset >= t
                       for a, t in targets.items())

        injected = 0
        while not done():
            if injected >= max_epochs:
                raise RuntimeError(
                    f"sources stalled: "
                    f"{ {a: readers[a].offset for a in targets} } "
                    f"vs {targets}")
            while loop.in_flight_count < in_flight_w \
                    and injected < max_epochs:
                await loop.inject()
                injected += 1
            await loop.collect_next()
        while loop.in_flight_count:
            await loop.collect_next()
        elapsed = time.perf_counter() - t0
        timed_rows = sum(r.offset for r in readers.values()) - warm_rows
        await loop.inject_and_collect(
            mutation=StopMutation(frozenset(readers.keys())))
        await task
        if pipeline.actor.failure is not None:
            raise pipeline.actor.failure
        loop.stats.latencies_s = loop.stats.latencies_s[warm_epochs:]
        loop.profiler.drop_first(warm_epochs)
        return elapsed, timed_rows

    return run()


def build_q5(store, cfg: NexmarkConfig,
             rate_limit: Optional[int] = 8,
             min_chunks: Optional[int] = None,
             slide: Interval = Interval(usecs=2_000_000),
             size: Interval = Interval(usecs=10_000_000),
             top_per_window: int = 1,
             tier_cap: Optional[int] = None,
             fusion: bool = False) -> Pipeline:
    """q5 (hot items): auctions with the most bids per sliding window.

    source → hop-window expansion → per-(window, auction) device count
    agg → per-window group top-n → materialize (e2e_test/streaming/
    nexmark/q5 semantics; ties kept deterministically by auction id).
    """
    from risingwave_tpu.stream.executors.hop_window import (
        HopWindowExecutor,
    )
    from risingwave_tpu.stream.executors.top_n import GroupTopNExecutor

    local = LocalBarrierManager()
    source = _source(local, store, 1, cfg, 1, rate_limit, min_chunks)
    s = source.schema
    hop = HopWindowExecutor(source, s.index_of("date_time"), slide, size)
    hs = hop.schema
    proj = ProjectExecutor(
        hop,
        exprs=[InputRef(hs.index_of("window_start"), DataType.TIMESTAMP),
               InputRef(hs.index_of("auction"), DataType.INT64)],
        names=["window_start", "auction"])
    calls = [AggCall(AggKind.COUNT)]
    agg_sch, agg_pk = agg_state_schema(proj.schema, [0, 1], calls)
    # tier_cap governs BOTH stateful stages (state/tier.py): resident
    # agg groups and resident TopN group caches
    agg = HashAggExecutor(
        proj, [0, 1], calls,
        StateTable(2, agg_sch, agg_pk, store, dist_key_indices=[0]),
        append_only=True,
        output_names=["window_start", "auction", "bid_count"],
        tier_cap=tier_cap)
    topn_state = StateTable(3, agg.schema, [0, 1], store)
    topn = GroupTopNExecutor(
        agg, order_by=[(2, True), (1, False)], offset=0,
        limit=top_per_window, state=topn_state,
        group_indices=[0], pk_indices=[0, 1], tier_cap=tier_cap)
    mv = StateTable(4, topn.schema, [0, 1], store)
    mat = MaterializeExecutor(topn, mv)
    return _finish(local, store, mat, mv, 1, {1: source.reader},
                   fragment="nexmark-q5", fusion=fusion)
