"""Hand-built streaming query plans (the "model zoo" of this framework).

Until the SQL frontend's planner/fragmenter lands, these builders are the
canonical executable plans for the headline Nexmark queries — shared by
the e2e tests and bench.py so the benchmarked pipeline is exactly the
tested pipeline.
"""

from risingwave_tpu.models.nexmark import (  # noqa: F401
    Pipeline, build_q1, build_q7, build_q8,
)
