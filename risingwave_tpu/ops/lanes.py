"""32-bit lane codecs: how 64-bit logical values live on the TPU.

TPU v5e has no native int64; XLA's x64-rewrite emulates it, and emulated
64-bit *scatter* is catastrophically slow (measured ~1000x vs int32 on the
chip this project benches on). The device side of every stateful kernel
therefore speaks int32/float32 exclusively; 64-bit logical values are
(de)composed on the host with vectorized numpy. Three codecs:

- **key lanes** (`split_i64`): bijective (hi, lo) int32 pair. Equality of
  pairs == equality of values; that's all a hash key needs.
- **sum limbs** (`sum_limbs`): signed base-2^17 positional decomposition
  into 4 int32 limbs. Limb scatter-adds of a ≤2^13-row chunk stay within
  int32 (17+13 < 31); a per-chunk carry pass renormalizes so limbs never
  overflow across chunks. Exact for |Σ| < 2^63 — money aggregation keeps
  reference semantics (sum of scaled-int64 DECIMAL is exact).
- **order lanes** (`order_lanes_*`): order-preserving (hi, lo) int32 pair —
  lexicographic (hi, lo) comparison == value comparison — so MIN/MAX run
  as two int32 scatter-min/max passes. Works for ints and floats (floats
  use the standard total-order bit trick).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

LIMB_BITS = 17
N_LIMBS = 4
# chunk row-count bound that keeps limb scatter-adds inside int32
MAX_CHUNK_ROWS = 1 << (31 - LIMB_BITS - 1)       # 8192

_BIAS32 = np.int64(1) << np.int64(31)
_MASK32 = np.int64(0xFFFFFFFF)


# -- bijective key lanes ----------------------------------------------------

def split_i64(v: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """int64[N] → (hi, lo) int32[N], bijective."""
    v = v.astype(np.int64, copy=False)
    hi = (v >> np.int64(32)).astype(np.int32)
    lo = (v & _MASK32).astype(np.uint32).view(np.int32)
    return hi, lo


def merge_i64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    return (hi.astype(np.int64) << np.int64(32)) | \
        lo.view(np.uint32).astype(np.int64)


# -- exact integer sums: signed base-2^17 limbs -----------------------------

def sum_limbs(v: np.ndarray) -> Tuple[np.ndarray, ...]:
    """int64[N] → N_LIMBS int32 limb arrays; v = Σ limb_i << (17*i).

    Limbs 0..2 ∈ [0, 2^17); limb 3 carries the sign (arithmetic shift)."""
    v = v.astype(np.int64, copy=False)
    out = []
    for i in range(N_LIMBS - 1):
        out.append(((v >> np.int64(LIMB_BITS * i))
                    & np.int64((1 << LIMB_BITS) - 1)).astype(np.int32))
    out.append((v >> np.int64(LIMB_BITS * (N_LIMBS - 1))).astype(np.int32))
    return tuple(out)


def merge_limbs(*limbs: np.ndarray) -> np.ndarray:
    """Inverse of sum_limbs for arbitrary (possibly unnormalized) limbs."""
    acc = np.zeros(limbs[0].shape, dtype=np.int64)
    for i, l in enumerate(limbs):
        acc += l.astype(np.int64) << np.int64(LIMB_BITS * i)
    return acc


# -- payload lanes: bit-preserving i64 image for stored row columns ---------
# The join's device-resident payload store (ops/hash_join.py) keeps one
# (hi, lo, valid) int32 lane triple per device-typed column, indexed by
# row ref. Unlike key lanes (to_i64 normalizes -0.0 so it GROUPS with
# 0.0), payload values must round-trip bit-exactly — the device-emit
# path has to be indistinguishable from a host arena gather.


def payload_i64(v, xp=np):
    """Column values → int64, bit-preserving (xp-generic: the fused
    join prelude traces this exact implementation under jit)."""
    dt = np.dtype(v.dtype)
    if dt == np.float64:
        return v.view(xp.int64) if xp is np else _jax_bitcast_i64(v)
    if dt == np.float32:
        w = v.astype(xp.float64)
        return w.view(xp.int64) if xp is np else _jax_bitcast_i64(w)
    return v.astype(xp.int64)


def _jax_bitcast_i64(a):
    import jax
    return jax.lax.bitcast_convert_type(a, np.int64)


def payload_lanes(pairs, xp=np):
    """[(values, validity | None)] → int32[N, 3p] payload lanes —
    (hi, lo, valid) per column, NULL values zeroed. THE one encode
    serving the host paths (_JoinSide payload_rows / payload_from_
    arena, xp=numpy) and the traced join prelude (xp=jnp) — the
    device scatter and the emit decode both depend on this exact
    layout, so there is exactly one copy of it."""
    out = []
    for vals, ok in pairs:
        n = vals.shape[0]
        okm = xp.ones(n, dtype=bool) if ok is None else ok
        v64 = xp.where(okm, payload_i64(vals, xp), xp.int64(0))
        hi, lo = split_i64(v64)
        out.append(hi)
        out.append(lo)
        out.append(okm.astype(xp.int32))
    if not out:
        return xp.zeros((0, 0), dtype=xp.int32)
    return xp.stack(out, axis=1)


def decode_payload_i64(v64: np.ndarray, dtype: np.dtype) -> np.ndarray:
    """Host inverse of payload_i64 (numpy only; runs on the fetched
    packed probe matrix)."""
    dtype = np.dtype(dtype)
    if dtype == np.float64:
        return v64.view(np.float64)
    if dtype == np.float32:
        return v64.view(np.float64).astype(np.float32)
    if dtype == np.bool_:
        return v64 != 0
    return v64.astype(dtype)


# -- order-preserving lanes for MIN/MAX -------------------------------------

def _order_u64_from_i64(v: np.ndarray) -> np.ndarray:
    """int64 → uint64 where unsigned order == signed order."""
    return (v.astype(np.int64) ^ (np.int64(1) << np.int64(63))) \
        .view(np.uint64)


def _order_u64_from_f64(v: np.ndarray) -> np.ndarray:
    """float64 → uint64 total order (IEEE bit trick; -0.0 == 0.0).

    xp-generic (get_xp): the fused-stage prelude traces this exact
    implementation under jit — one drifting twin would silently break
    fused-vs-unfused bit identity for float MIN/MAX."""
    from risingwave_tpu.common.chunk import get_xp
    xp = get_xp(v)
    v = xp.where(v == 0, xp.zeros((), dtype=v.dtype), v)
    bits = v.astype(xp.float64).view(xp.uint64)
    neg = (bits >> np.uint64(63)) == 1
    return xp.where(neg, ~bits, bits | (np.uint64(1) << np.uint64(63)))


def _lanes_from_u64(m: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """uint64 order key → (hi, lo) int32 with lexicographic int32 order."""
    hi = ((m >> np.uint64(32)).astype(np.int64) - _BIAS32).astype(np.int32)
    lo = ((m & np.uint64(0xFFFFFFFF)).astype(np.int64)
          - _BIAS32).astype(np.int32)
    return hi, lo


def _u64_from_lanes(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    h = (hi.astype(np.int64) + _BIAS32).astype(np.uint64)
    l = (lo.astype(np.int64) + _BIAS32).astype(np.uint64)
    return (h << np.uint64(32)) | l


def order_lanes(v: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """value array (any device dtype) → order-preserving (hi, lo) int32."""
    if np.issubdtype(v.dtype, np.floating):
        return _lanes_from_u64(_order_u64_from_f64(v))
    if v.dtype == np.bool_:
        v = v.astype(np.int64)
    return _lanes_from_u64(_order_u64_from_i64(v))


def inv_order_lanes(hi: np.ndarray, lo: np.ndarray,
                    dtype: np.dtype) -> np.ndarray:
    m = _u64_from_lanes(hi, lo)
    if np.issubdtype(dtype, np.floating):
        neg = (m >> np.uint64(63)) == 0
        bits = np.where(neg, ~m, m & ~(np.uint64(1) << np.uint64(63)))
        return bits.view(np.float64).astype(dtype)
    v = (m.view(np.int64) ^ (np.int64(1) << np.int64(63)))
    if dtype == np.bool_:
        return v != 0
    return v.astype(dtype)
