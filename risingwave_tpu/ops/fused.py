"""Composable traced stages: the kernel half of fragment fusion.

TiLT thesis (arxiv 2301.12030) applied to the plan IR: instead of
interpreting a fragment's executor chain one vectorized host pass per
operator per chunk, compile the whole source→filter→project→keyed-input
run into ONE traced dataflow step. The expression layer is already
backend-polymorphic (``get_xp`` — common/chunk.py), so the SAME
``Expression.eval`` / ``FilterExecutor`` math that runs interpretively
on numpy traces under ``jax.jit`` bit-identically; this module supplies
the static plumbing around it:

- ``traceable_reason``: the eligibility walker. An expression tree is
  fusable iff every node stays in the device domain end to end — host
  comparisons (varchar), host scalar functions, and DECIMAL casts whose
  numpy path carries overflow *detection* (raising is untraceable) all
  refuse with a reason string the rewrite layer surfaces in EXPLAIN.
- ``FusedStages``: a filter/project run in composed normal form — all
  predicates and output expressions substituted back onto the ONE input
  schema (subst_expr, the projection-composition machinery of the
  plan-rewrite engine) — plus the raw-chunk codec for the agg-prelude
  path and per-logical-stage row attribution.
- ``build_chain_step``: the standalone traced step (chunk in → chunk
  out), used by FusedStagesExecutor for runs feeding joins/materialize.
- ``build_agg_prelude``: the same chain fused INTO ``hash_agg.py``'s
  jitted apply — raw int64 chunk matrix → (key lanes, signs, vis,
  per-call input lanes), inlined ahead of the accumulator updates so a
  whole fragment step is one dispatch with donated state buffers.

Pair semantics are preserved exactly: filter degradation (U-/U+ halves
diverging under the predicate) reuses ``FilterExecutor.apply_predicate``
— the one implementation — and the project noop-update drop runs as a
branchless shifted-compare (identical result to the numpy early-out
version: no U-/U+ pairs ⇒ no drops). Batched raw matrices place one
always-invisible SEPARATOR row between chunks so the shifted compares
never marry rows across chunk boundaries.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu.common.chunk import (
    Column, Op, StreamChunk, ops_to_signs,
)
from risingwave_tpu.common.types import DataType, Field, Schema

# FuncCalls whose registered implementations are xp-generic (no numpy
# object arrays, no python loops) — verified by tests/test_fusion.py
# against the interpretive path on random data.
TRACEABLE_FUNCS = frozenset({"tumble_start", "tumble_end",
                             "extract_epoch"})


# -- eligibility walker ----------------------------------------------------


def traceable_reason(e, schema: Schema) -> Optional[str]:
    """None if `e` traces bit-identically under jit against `schema`;
    otherwise a human-readable refusal (EXPLAIN shows it)."""
    from risingwave_tpu.expr.expr import (
        BinaryOp, Case, Cast, FuncCall, InputRef, Literal, UnaryOp,
    )
    if isinstance(e, InputRef):
        if not e.return_type.is_device:
            return f"host-typed column ref ${e.index}:{e.return_type.value}"
        return None
    if isinstance(e, Literal):
        # host-typed literals (varchar format strings, intervals) are
        # CONSTANT — they evaluate host-side even inside a trace (the
        # chunk capacity is static), so they are fine as FuncCall args;
        # standalone host literals in value position are not.
        return None
    if isinstance(e, BinaryOp):
        if not e._common.is_device:
            return (f"operator {e.op!r} over host type "
                    f"{e._common.value}")
        for side in (e.left, e.right):
            # implicit float→DECIMAL promotion carries overflow/
            # non-finite DETECTION on the numpy path (raising is
            # untraceable); int→DECIMAL wraps identically wherever the
            # interpretive path doesn't raise, which is the bit-identity
            # contract — see ARCHITECTURE.md "Fragment fusion"
            if (e._common == DataType.DECIMAL
                    and side.return_type in (DataType.FLOAT32,
                                             DataType.FLOAT64)):
                return "float->DECIMAL promotion (overflow detection)"
            r = traceable_reason(side, schema)
            if r:
                return r
        return None
    if isinstance(e, UnaryOp):
        return traceable_reason(e.child, schema)
    if isinstance(e, Cast):
        if not e.return_type.is_device:
            return f"cast to host type {e.return_type.value}"
        src = e.child.return_type
        if not src.is_device:
            return f"cast from host type {src.value}"
        if e.return_type == DataType.DECIMAL and src != DataType.DECIMAL:
            return "cast to DECIMAL (overflow detection is host-only)"
        return traceable_reason(e.child, schema)
    if isinstance(e, Case):
        if not e.return_type.is_device:
            return f"CASE over host type {e.return_type.value}"
        for c, v in e.whens:
            r = traceable_reason(c, schema) or traceable_reason(v, schema)
            if r:
                return r
        return traceable_reason(e.else_, schema)
    if isinstance(e, FuncCall):
        if e.name not in TRACEABLE_FUNCS:
            return f"function {e.name}() has no traceable kernel"
        from risingwave_tpu.expr.expr import Literal as _Lit
        for a in e.args:
            if isinstance(a, _Lit):
                continue            # constant args evaluate host-side
            r = traceable_reason(a, schema)
            if r:
                return r
        return None
    return f"unknown expression node {type(e).__name__}"


# -- traced twins of the host key/lane codecs ------------------------------
# Identical bit semantics to ops/lanes.py + executors/keys.py, running
# on xp (numpy OR traced jnp). The integer paths of lanes.py are already
# xp-generic and are called directly; only the float normalizations
# needed get_xp (see lanes._order_u64_from_f64 / keys.to_i64).


def key_lanes_traced(cols: Sequence[Tuple[object, Optional[object]]],
                     xp) -> object:
    """Device-typed key columns → int32[N, 3k] lanes, the exact
    KeyCodec.build_arrays image (hi, lo, valid per column)."""
    from risingwave_tpu.ops import lanes as _lanes
    from risingwave_tpu.stream.executors.keys import to_i64
    out = []
    for vals, ok in cols:
        v64 = to_i64(vals)
        if ok is not None:
            v64 = xp.where(ok, v64, xp.int64(0))
        hi, lo = _lanes.split_i64(v64)
        out.append(hi)
        out.append(lo)
        out.append(xp.ones(v64.shape[0], dtype=xp.int32)
                   if ok is None else ok.astype(xp.int32))
    return xp.stack(out, axis=1)


# -- raw-chunk codec (the ONE upload of the fused agg path) ----------------
# Layout (int64 columns): [ops, vis] then (value, valid) per referenced
# input column. f64 travels bitcast; f32 widens exactly through f64.
# One matrix = one host→device transfer per dispatch, mirroring
# pack_chunk's rationale for the unfused path.

RAW_META_COLS = 2


def raw_width(n_ref_cols: int) -> int:
    return RAW_META_COLS + 2 * n_ref_cols


def encode_raw_chunk(chunk: StreamChunk,
                     ref_cols: Sequence[int]) -> np.ndarray:
    """Host side: one int64[N, W] matrix for the referenced columns."""
    n = chunk.capacity
    m = np.zeros((n, raw_width(len(ref_cols))), dtype=np.int64)
    m[:, 0] = np.asarray(chunk.ops)
    m[:, 1] = np.asarray(chunk.visibility)
    for k, i in enumerate(ref_cols):
        c = chunk.columns[i]
        vals = np.asarray(c.values)
        if vals.dtype == np.float64:
            v = vals.view(np.int64)
        elif vals.dtype == np.float32:
            v = vals.astype(np.float64).view(np.int64)
        else:
            v = vals.astype(np.int64)
        m[:, RAW_META_COLS + 2 * k] = v
        m[:, RAW_META_COLS + 2 * k + 1] = (
            1 if c.validity is None
            else np.asarray(c.validity).astype(np.int64))
    return m


def decode_raw_cols(raw, in_schema: Schema,
                    ref_cols: Sequence[int], xp
                    ) -> Tuple[List[Column], object, object]:
    """Traced inverse of encode_raw_chunk → (columns in FULL input
    arity, vis bool, ops int8-domain). Unreferenced slots get dummy
    zero columns (never evaluated — eligibility guarantees it)."""
    cap = raw.shape[0]
    ops = raw[:, 0].astype(xp.int8)
    vis = raw[:, 1].astype(bool)
    cols: List[Column] = []
    by_pos = {i: k for k, i in enumerate(ref_cols)}
    for i, f in enumerate(in_schema):
        k = by_pos.get(i)
        if k is None:
            cols.append(Column(f.data_type, xp.zeros(cap, dtype=xp.int32)))
            continue
        v64 = raw[:, RAW_META_COLS + 2 * k]
        okl = raw[:, RAW_META_COLS + 2 * k + 1].astype(bool)
        dt = np.dtype(f.data_type.np_dtype)
        if dt == np.float64:
            vals = v64.view(xp.float64) if xp is np else \
                _bitcast(v64, xp.float64)
        elif dt == np.float32:
            vals = (v64.view(np.float64) if xp is np
                    else _bitcast(v64, xp.float64)).astype(dt)
        else:
            vals = v64.astype(dt)
        cols.append(Column(f.data_type, vals, okl))
    return cols, vis, ops


def _bitcast(a, dtype):
    import jax
    return jax.lax.bitcast_convert_type(a, dtype)


# -- composed stage normal form --------------------------------------------


@dataclass(frozen=True)
class FusedStage:
    """One logical executor inside a fused block (metrics identity +
    the pieces EXPLAIN and the fragmenter serialize)."""

    kind: str    # "filter" | "project" | "row_id_gen"
    #            # | "watermark_filter" | "hop_window"
    identity: str                  # e.g. "FilterExecutor"
    # filter: the ORIGINAL predicate (own column space); project: the
    # original exprs/names. Serialized by the fragmenter.
    exprs: tuple = ()
    names: tuple = ()
    watermark_derivations: dict = field(default_factory=dict)
    # watermark_filter: event-time column (own input space) + delay;
    # row_id_gen / watermark_filter runtime state (the id counter's
    # shard base, the watermark StateTable) is carried by `runtime` —
    # a HOST-ONLY handle, never serialized (the fragmenter re-derives
    # it from table ids); hop_window: time_col + slide/size (pure
    # parameters, no runtime)
    time_col: int = -1
    delay_usecs: int = 0
    slide_usecs: int = 0
    size_usecs: int = 0
    runtime: object = None

    @property
    def units(self) -> int:
        return self.size_usecs // self.slide_usecs


class FusedStages:
    """A maximal fusable filter/project run in composed normal form.

    ``stages`` is the run in dataflow order (closest-to-upstream
    first). Construction composes everything onto ``in_schema``:
    ``preds`` (each substituted back to input space, applied as one
    conjunction + one pair-degradation pass) and ``out_exprs`` /
    ``out_schema`` (the final projection; None means the run is
    filter-only and the output schema is the input schema).

    The composition is visible-semantics-exact w.r.t. the sequential
    executors: predicate conjunction commutes, degradation of a pair
    whose halves diverge under ANY predicate equals sequential
    degradation, and the noop-update drop after the FINAL projection
    drops exactly the pairs the per-stage drops would have (equal
    inputs stay equal through every later projection). Invisible rows'
    op bytes may differ — they are unobservable by contract (the spine
    suppresses/compacts them end to end).
    """

    def __init__(self, in_schema: Schema, stages: Sequence[FusedStage]):
        from risingwave_tpu.frontend.opt.rules import subst_expr
        from risingwave_tpu.expr.expr import InputRef
        self.in_schema = in_schema
        self.stages = list(stages)
        if not self.stages:
            raise ValueError("FusedStages needs at least one stage")
        # synthetic RUNTIME columns appended past the real input: each
        # row_id_gen stage contributes its per-chunk id column (host
        # arithmetic: base + arange) and each watermark_filter its
        # scalar threshold, broadcast per row. The trace sees them as
        # ordinary device inputs; `augment` builds them per chunk.
        self.syn_specs: List[tuple] = []     # ("row_id"|"wm", stage_i)
        syn_fields: List[Field] = []
        n_in = len(in_schema)
        self.row_id_stages: List[tuple] = []   # (stage_i, ext col)
        self.wm_stages: List[tuple] = []       # (stage_i, ext col)
        # hop_window absorption (ISSUE 12): a head-of-run hop stage
        # replicates every row `units`× IN-TRACE and synthesizes
        # window_start/window_end columns from the time column — the
        # composition space for everything downstream is the hop
        # OUTPUT space (in_schema + the two window columns), while the
        # raw upload keeps the PRE-hop arity (the expansion never
        # crosses the host boundary).
        self.hop: Optional[FusedStage] = None
        base_fields = list(in_schema.fields)
        if self.stages[0].kind == "hop_window":
            self.hop = self.stages[0]
            base_fields = base_fields + [
                Field("window_start", DataType.TIMESTAMP),
                Field("window_end", DataType.TIMESTAMP)]
        if any(st.kind == "hop_window" for st in self.stages[1:]):
            # non-head hops never compose (the window columns would
            # not exist in the downstream stages' spaces) — the rule
            # pre-refuses these runs; fail loud on direct misuse
            raise ValueError("hop_window stage must head the run")
        base_schema = Schema(base_fields) if self.hop is not None \
            else in_schema
        self._base_schema = base_schema
        # compose onto the (post-hop) input space
        cur: Optional[list] = None          # None = identity projection
        preds: List[object] = []
        pred_stage: List[int] = []          # stage index per pred
        names = [f.name for f in base_schema]
        for si, st in enumerate(self.stages):
            if st.kind == "hop_window":
                continue                     # space change handled above
            if st.kind == "filter":
                (p,) = st.exprs
                preds.append(p if cur is None else subst_expr(p, cur))
                pred_stage.append(si)
            elif st.kind == "project":
                cur = [e if cur is None else subst_expr(e, cur)
                       for e in st.exprs]
                names = list(st.names)
            elif st.kind == "row_id_gen":
                syn = n_in + len(syn_fields)
                syn_fields.append(Field("_row_id", DataType.SERIAL))
                self.syn_specs.append(("row_id", si))
                self.row_id_stages.append((si, syn))
                if cur is None:
                    cur = [InputRef(i, f.data_type)
                           for i, f in enumerate(in_schema)]
                cur = cur + [InputRef(syn, DataType.SERIAL)]
                names = names + ["_row_id"]
            elif st.kind == "watermark_filter":
                # gated to the HEAD of the run (fusable_reason): the
                # late mask then reads the raw event-time column and
                # the synthetic threshold directly
                dt_t = in_schema[st.time_col].data_type
                syn = n_in + len(syn_fields)
                syn_fields.append(Field(f"_wm_thr{si}", dt_t))
                self.syn_specs.append(("wm", si))
                self.wm_stages.append((si, syn))
            else:
                raise ValueError(f"unknown stage kind {st.kind!r}")
        self.ext_schema = Schema(list(in_schema.fields)
                                 + syn_fields) if syn_fields \
            else in_schema
        # the space the composed preds/exprs bind against: the RAW
        # trace-input space plus synthetics — or, with an absorbed
        # hop, the hop OUTPUT space (window columns are synthesized
        # in-trace from the time column, never uploaded)
        self.body_schema = base_schema if self.hop is not None \
            else self.ext_schema
        self.preds = preds
        self._pred_stage = pred_stage
        self.out_exprs = cur
        if cur is None:
            self.out_schema = base_schema
        else:
            self.out_schema = Schema([
                Field(n, e.return_type) for n, e in zip(names, cur)])
        # referenced input columns (trace inputs); everything else
        # stays host-side. A filter-only run (out_exprs None) passes
        # EVERY column through, so all device columns are referenced —
        # omitting them would hand dummy zero columns to the consumer.
        refs: set = set()
        from risingwave_tpu.frontend.opt.checker import expr_refs
        for p in self.preds:
            refs |= expr_refs(p)
        for e in (self.out_exprs or []):
            refs |= expr_refs(e)
        for si, syn in self.wm_stages:
            refs.add(self.stages[si].time_col)
            refs.add(syn)
        for _si, syn in self.row_id_stages:
            refs.add(syn)
        # host passthrough outputs: bare InputRefs to host-typed input
        # columns ride AROUND the trace (positional vis/ops are shared)
        self.host_out: Dict[int, int] = {}
        if self.out_exprs is None:
            for i, f in enumerate(base_schema):
                if i >= n_in:
                    continue      # hop window cols: synthesized in-trace
                if f.data_type.is_device:
                    refs.add(i)
                else:
                    self.host_out[i] = i
        else:
            for j, e in enumerate(self.out_exprs):
                if isinstance(e, InputRef) and not e.return_type.is_device:
                    self.host_out[j] = e.index
        if self.hop is not None:
            # window-column refs resolve to the time column they are
            # synthesized from; the raw matrix never carries them
            refs = {self.hop.time_col if i >= n_in else i
                    for i in refs}
            refs.add(self.hop.time_col)
        self.ref_cols: List[int] = sorted(
            i for i in refs if self.ext_schema[i].data_type.is_device)
        # per-stage row attribution drained by the monitor at barriers
        self.stage_rows = np.zeros(len(self.stages), dtype=np.int64)
        self.stage_chunks = np.zeros(len(self.stages), dtype=np.int64)

    # -- eligibility -------------------------------------------------------
    def fusable_reason(self) -> Optional[str]:
        """None iff the composed run traces; else the first refusal."""
        if self.hop is not None:
            if self.wm_stages or self.row_id_stages:
                # both machineries claim the head/synthetic-column
                # slots; the planner never emits these shapes anyway
                return ("hop_window cannot share a run with absorbed "
                        "runtime stages")
            for st in self.stages[1:]:
                if st.kind == "hop_window":
                    return "more than one hop_window stage in the run"
            dt_t = self.in_schema[self.hop.time_col].data_type
            if not dt_t.is_device or \
                    np.dtype(dt_t.np_dtype).kind not in "iu":
                return ("hop_window over non-integer time column "
                        f"{dt_t.value}")
        if len(self.wm_stages) > 1:
            return "more than one watermark_filter stage in the run"
        for si, _syn in self.wm_stages:
            if si != 0:
                return ("watermark_filter stage not at the head of "
                        "the run (its late mask must see raw rows)")
            st = self.stages[si]
            dt_t = self.in_schema[st.time_col].data_type
            if not dt_t.is_device or \
                    np.dtype(dt_t.np_dtype).kind not in "iu":
                # float time columns would make the no-watermark-yet
                # sentinel (I64_MIN broadcast) observable (-inf rows);
                # integer event times are the planner's only shape
                return ("watermark_filter over non-integer time "
                        f"column {dt_t.value}")
        for p in self.preds:
            r = traceable_reason(p, self.body_schema)
            if r:
                return r
        for j, e in enumerate(self.out_exprs or []):
            if j in self.host_out:
                continue            # host passthrough, never traced
            r = traceable_reason(e, self.body_schema)
            if r:
                return r
        return None

    # -- synthetic runtime columns (host side, per chunk) ------------------
    def augment(self, chunk):
        """Chunk over in_schema → chunk over ext_schema: append each
        row_id_gen stage's id column (base + arange — RowIdGenExecutor
        assigns ids to EVERY slot, visible or padding) and each
        watermark_filter's threshold column (the watermark EMITTED
        before this chunk; dtype-min sentinel = no watermark yet,
        which lates nothing since ts < dtype_min is unsatisfiable
        in-dtype). Advances
        the absorbed executors' runtime state exactly as their own
        chunk loops would have — the id counter bumps by capacity, the
        watermark advances to max(event_time) - delay."""
        if not self.syn_specs:
            return chunk
        cap = chunk.capacity
        cols = list(chunk.columns)
        for kind, si in self.syn_specs:
            rt = self.stages[si].runtime
            if kind == "row_id":
                cols.append(Column(
                    DataType.SERIAL,
                    rt._next + np.arange(cap, dtype=np.int64)))
                rt._next += cap
            else:
                thr = rt.current          # the PRE-chunk watermark:
                # the mask must not see this chunk's own max (see
                # WatermarkFilterExecutor._apply)
                dt = self.ext_schema[len(cols)].data_type
                info = np.iinfo(np.dtype(dt.np_dtype))
                # sentinel/clamp in the TIME COLUMN's OWN dtype:
                # np.full would silently WRAP an out-of-range int64
                # (int64-min → 0 on an int32 column), turning
                # "no watermark yet" into "drop every negative ts".
                # dtype-min is exact either way: ts < dtype_min is
                # unsatisfiable for in-dtype ts, same as no filter
                # (and a true threshold below dtype_min lates nothing
                # a narrower column could hold).
                val = info.min if thr is None \
                    else min(max(int(thr), info.min), info.max)
                cols.append(Column(dt, np.full(
                    cap, val, dtype=np.dtype(dt.np_dtype))))
                st = self.stages[si]
                c = chunk.columns[st.time_col]
                ts = np.asarray(c.values).astype(np.int64)
                ok = np.asarray(chunk.visibility) if c.validity is None \
                    else (np.asarray(chunk.visibility)
                          & np.asarray(c.validity))
                if ok.any():
                    mx = int(ts[ok].max()) - st.delay_usecs
                    if rt.current is None or mx > rt.current:
                        rt.current = mx
        return StreamChunk(self.ext_schema, cols, chunk.visibility,
                           chunk.ops)

    def on_barrier(self, barrier, first: bool = False) -> List:
        """Absorbed-runtime barrier work (the hosting executor calls
        this where the sequential executors' own barrier handling
        would have run). Returns watermark messages to emit AFTER the
        barrier (IN-schema column space — callers derive through the
        projection stages). First barrier: restore the persisted
        watermark; later barriers: persist + commit; row-id counters
        rebase to the epoch floor either way."""
        from risingwave_tpu.stream.message import Watermark
        out: List = []
        for si, _syn in self.row_id_stages:
            self.stages[si].runtime._rebase(barrier.epoch.curr.value)
        for si, _syn in self.wm_stages:
            st = self.stages[si]
            rt = st.runtime
            if first:
                if rt.state is not None:
                    rt.state.init_epoch(barrier.epoch)
                    row = rt.state.get_row((0,))
                    if row is not None:
                        rt.current = int(row[1])
                # the restored watermark re-announces itself, exactly
                # like the sequential executor's first-barrier yield
                if rt.current is not None:
                    out.append(Watermark(st.time_col,
                                         DataType.TIMESTAMP,
                                         rt.current))
            else:
                rt._persist()
                if rt.state is not None:
                    rt.state.commit(barrier.epoch)
        return out

    def post_chunk_watermarks(self) -> List:
        """Watermark messages due after a data chunk (IN-schema space;
        WatermarkFilterExecutor emits its current watermark after
        every chunk it forwards)."""
        from risingwave_tpu.stream.message import Watermark
        return [Watermark(self.stages[si].time_col, DataType.TIMESTAMP,
                          self.stages[si].runtime.current)
                for si, _syn in self.wm_stages
                if self.stages[si].runtime.current is not None]

    def wm_time_cols(self) -> List[int]:
        """IN-schema columns owned by absorbed watermark_filter stages
        (upstream watermarks on them are superseded, like the
        sequential executor's own-column drop)."""
        return [self.stages[si].time_col for si, _syn in self.wm_stages]

    def describe(self) -> str:
        return "→".join(s.identity for s in self.stages)

    def trace_key(self) -> str:
        """STRUCTURAL identity of the traced program this run builds:
        two FusedStages with equal keys trace byte-equivalent preludes
        (runtime state — row-id counters, watermark tables — feeds the
        host-built synthetic columns, never the trace). Keying jit
        caches by this instead of object identity lets fresh sessions
        and both join sides reuse compiled programs — warmup compiles
        stop riding every run's p99 tail."""
        import json as _json

        from risingwave_tpu.stream.plan_ir import expr_to_ir
        parts = []
        for st in self.stages:
            d = {"kind": st.kind}
            if st.kind == "filter":
                d["pred"] = expr_to_ir(st.exprs[0])
            elif st.kind == "project":
                d["exprs"] = [expr_to_ir(e) for e in st.exprs]
            elif st.kind == "watermark_filter":
                d["time_col"] = st.time_col
            elif st.kind == "hop_window":
                d["time_col"] = st.time_col
                d["slide"] = st.slide_usecs
                d["size"] = st.size_usecs
            parts.append(d)
        schema = [f.data_type.value for f in self.in_schema]
        return _json.dumps([schema, parts], sort_keys=True,
                           default=str)

    def input_positions(self, cols) -> Optional[List[int]]:
        """Map OUTPUT column positions back through the composed
        projection to RAW input positions, or None when any is not a
        pure input ref (a computed key cannot be hash-dispatched in
        raw space; synthetic runtime columns — absorbed row ids,
        watermark thresholds — do not exist pre-run either). The
        parallelism>1 fused cut (fragmenter) hashes raw rows on the
        mapped columns: value equality with the post-stage keys makes
        the partition consistent."""
        from risingwave_tpu.expr.expr import InputRef
        n_in = len(self.in_schema)
        out: List[int] = []
        for c in cols:
            if self.out_exprs is None:
                if not (0 <= c < n_in):
                    return None
                out.append(int(c))
                continue
            e = self.out_exprs[c]
            if isinstance(e, InputRef) and e.index < n_in:
                out.append(int(e.index))
            else:
                return None
        return out

    # -- watermark path (host, per message) --------------------------------
    def derive_watermarks(self, msg) -> List:
        """Watermark(s) in OUTPUT column space, composing each stage's
        semantics in order (filters pass through, projects derive or
        drop — ProjectExecutor's exact rules)."""
        from risingwave_tpu.stream.message import Watermark
        outs = [msg]
        for st in self.stages:
            if st.kind == "hop_window":
                # HopWindowExecutor's exact rule: a bound on ts is a
                # bound on the LAST covering window's start; every
                # other watermark is consumed (the expansion breaks
                # per-column monotonicity guarantees)
                nxt = []
                ws_idx = len(self.in_schema)
                for m in outs:
                    if m.col_idx == st.time_col:
                        b = (int(m.value) // st.slide_usecs) \
                            * st.slide_usecs
                        nxt.append(Watermark(
                            ws_idx, DataType.TIMESTAMP,
                            b - (st.units - 1) * st.slide_usecs))
                outs = nxt
                continue
            if st.kind != "project":
                continue
            nxt: List = []
            for m in outs:
                d = st.watermark_derivations.get(m.col_idx)
                for one in (d if isinstance(d, list)
                            else [] if d is None else [d]):
                    if isinstance(one, tuple):
                        oi, fn = one
                        nxt.append(Watermark(oi, m.data_type,
                                             fn(m.value)))
                    else:
                        nxt.append(m.with_idx(one))
            outs = nxt
        return outs

    def note_stage_rows(self, counts: np.ndarray, chunks: int) -> None:
        self.stage_rows += counts.astype(np.int64)
        self.stage_chunks += chunks

    def drain_stage_metrics(self) -> List[Tuple[str, int, int]]:
        # same-kind stages in one run (e.g. filter→filter after an MV
        # over a filtered view) get a position suffix so their metric
        # series stay distinct instead of summing into one label
        idents = [st.identity for st in self.stages]
        dup = {n for n in idents if idents.count(n) > 1}
        out = [(f"{st.identity}[{i}]" if st.identity in dup
                else st.identity,
                int(self.stage_rows[i]), int(self.stage_chunks[i]))
               for i, st in enumerate(self.stages)]
        self.stage_rows[:] = 0
        self.stage_chunks[:] = 0
        return out

    # -- host half of the noop-pair drop ----------------------------------
    def host_noop_eq(self, chunk) -> Optional[np.ndarray]:
        """Adjacent-row equality over the HOST passthrough columns
        (ProjectExecutor._drop_noop_updates' exact semantics, numpy).
        Host columns bypass the trace, but a U-/U+ pair whose only
        change is a varchar must NOT be dropped — this mask is ANDed
        into the traced drop. None when there are no host columns."""
        if not self.host_out or self.out_exprs is None:
            return None
        same = np.ones(chunk.capacity, dtype=bool)
        for _j, src in self.host_out.items():
            c = chunk.columns[src]
            v = np.asarray(c.values)
            eq = np.asarray(v == np.roll(v, -1), dtype=bool)
            if c.validity is not None:
                ok = np.asarray(c.validity)
                ok_n = np.roll(ok, -1)
                eq = (eq & ok & ok_n) | (~ok & ~ok_n)
            same &= eq
        return same

    # -- the traced chain body --------------------------------------------
    def chain_body(self, cols: List[Column], vis, ops, xp,
                   host_same=None
                   ) -> Tuple[List[Column], object, object, object]:
        """Composed filter+project over (possibly traced) arrays.

        Returns (out device columns, vis, ops, per-stage visible-row
        counts int64[n_stages]). Host passthrough outputs come back as
        None placeholders — the caller reattaches them positionally,
        and passes ``host_same`` (host_noop_eq) so the noop-pair drop
        sees their equality too. The agg-prelude path passes None: the
        agg consumes only device columns, whose in-pair equality makes
        drop-vs-keep output-invisible there (net-zero group delta with
        unchanged accumulators either way).
        """
        from risingwave_tpu.stream.executors.simple import (
            FilterExecutor,
        )
        # per-stage rows: each filter's post-predicate count; projects
        # report the count AT THEIR POSITION in dataflow order (not the
        # final count — a filter after a project must not retroactively
        # shrink the project's attribution)
        n_stages = len(self.stages)
        stage_rows = [None] * n_stages
        if self.hop is not None:
            cols, vis, ops, host_same = self._expand_hop(
                cols, vis, ops, xp, host_same)
        chunk = StreamChunk(self.body_schema, cols, vis, ops)
        if self.hop is not None:
            stage_rows[0] = xp.sum(vis.astype(xp.int64))
        for si, syn in self.wm_stages:
            # head-of-run late mask (WatermarkFilterExecutor._apply):
            # rows with a valid event time BELOW the pre-chunk
            # watermark (the synthetic threshold column) go invisible
            st = self.stages[si]
            c_ts = chunk.columns[st.time_col]
            ts = c_ts.values
            thr = chunk.columns[syn].values
            okm = chunk.visibility if c_ts.validity is None \
                else chunk.visibility & c_ts.validity
            late = okm & (ts < thr)
            chunk = StreamChunk(self.ext_schema, chunk.columns,
                                chunk.visibility & ~late, chunk.ops)
            stage_rows[si] = xp.sum(chunk.visibility.astype(xp.int64))
        for p, si in zip(self.preds, self._pred_stage):
            chunk = FilterExecutor.apply_predicate(chunk, p)
            stage_rows[si] = xp.sum(chunk.visibility.astype(xp.int64))
        out_cols: List[Optional[Column]] = []
        if self.out_exprs is None:
            # filter-only run: every INPUT column passes through —
            # device columns from the (possibly traced) chunk, host
            # columns as None placeholders the caller reattaches
            # positionally. Synthetic runtime columns never leave;
            # hop window columns (part of the base space) do.
            out_cols = [None if j in self.host_out else c
                        for j, c in
                        enumerate(chunk.columns[:len(self._base_schema)])]
        else:
            for j, e in enumerate(self.out_exprs):
                out_cols.append(None if j in self.host_out
                                else e.eval(chunk))
        vis2, ops2 = chunk.visibility, chunk.ops
        # branchless noop-update-pair drop over the FINAL projection
        # (identity when no U-/U+ pairs — ProjectExecutor parity)
        if self.out_exprs is not None:
            vis2 = _drop_noop_pairs_xp(
                [c for c in out_cols if c is not None], vis2, ops2, xp,
                host_same=host_same)
        final_n = xp.sum(vis2.astype(xp.int64))
        cur = xp.sum(vis.astype(xp.int64))   # input visible count
        for si in range(n_stages):
            if stage_rows[si] is None:       # project: rows at its slot
                stage_rows[si] = cur
            else:                            # filter: its own count
                cur = stage_rows[si]
        # the LAST stage's emission includes the composed noop-pair
        # drop (the sequential chain's final project would drop there)
        stage_rows[-1] = final_n
        return out_cols, vis2, ops2, xp.stack(stage_rows)

    def _expand_hop(self, cols: List[Column], vis, ops, xp,
                    host_same=None):
        """In-trace hop expansion (HopWindowExecutor's exact math):
        `units` copy-major replicas of every column — copy i carries
        window_start = floor(ts/slide)*slide - i*slide — with NULL-
        timestamp rows masked invisible up front. Copy-major order
        preserves U-/U+ adjacency inside every copy, and copy
        boundaries end on the batch codec's invisible separator row,
        so the shifted pair compares never marry rows across copies.
        ``host_same`` (host passthrough adjacent-equality) tiles the
        same way — its wrap element lands exactly on the copy
        boundary's (last, first) pair, which the original wrap already
        computed."""
        st = self.hop
        units = st.units
        slide = st.slide_usecs
        c_ts = cols[st.time_col]
        ts = c_ts.values.astype(xp.int64)
        okm = vis if c_ts.validity is None else vis & c_ts.validity
        base = (ts // slide) * slide
        ws = xp.concatenate([base - i * slide for i in range(units)])
        out_cols = [Column(c.data_type, xp.tile(c.values, units),
                           None if c.validity is None
                           else xp.tile(c.validity, units))
                    for c in cols]
        out_cols.append(Column(DataType.TIMESTAMP, ws, None))
        out_cols.append(Column(DataType.TIMESTAMP, ws + st.size_usecs,
                               None))
        return (out_cols, xp.tile(okm, units), xp.tile(ops, units),
                None if host_same is None
                else xp.tile(host_same, units))


def _drop_noop_pairs_xp(cols: Sequence[Column], vis, ops, xp,
                        host_same=None):
    """Traced twin of ProjectExecutor._drop_noop_updates: clear both
    halves of adjacent (U-, U+) pairs whose projected values (and
    validities) are identical. ``host_same`` carries the host
    passthrough columns' adjacent equality (they bypass the trace)."""
    ud = xp.int8(int(Op.UPDATE_DELETE))
    ui = xp.int8(int(Op.UPDATE_INSERT))
    is_pair = (vis & xp.roll(vis, -1)
               & (ops == ud) & (xp.roll(ops, -1) == ui))
    # roll wraps the last row onto the first: a well-formed chunk never
    # ends with a dangling U-, and batched matrices carry an invisible
    # separator row per chunk, so the wrap term is always masked
    same = xp.ones(vis.shape[0], dtype=bool) if host_same is None \
        else host_same.astype(bool)
    for c in cols:
        v = c.values
        eq = v == xp.roll(v, -1)
        if c.validity is not None:
            ok = c.validity
            ok_n = xp.roll(ok, -1)
            eq = (eq & ok & ok_n) | (~ok & ~ok_n)
        same = same & eq
    drop = is_pair & same
    return vis & ~drop & ~xp.roll(drop, 1)


# -- standalone traced step (chunk → chunk) --------------------------------


def build_chain_step(fs: FusedStages):
    """jit-compiled (device cols, valids, vis, ops) → (out cols+valids,
    vis, ops, stage_rows). Host columns bypass; per-capacity compile
    cache like every other per-shape program."""
    import jax
    import jax.numpy as jnp

    in_schema = fs.ext_schema     # synthetic runtime columns (row ids,
    ref = list(fs.ref_cols)       # watermark thresholds) enter as
                                  # ordinary device inputs

    def step(vals, valids, vis, ops, host_same):
        cap = vis.shape[0]
        cols: List[Column] = []
        k = 0
        for i, f in enumerate(in_schema):
            if i in fs._ref_set:
                cols.append(Column(f.data_type, vals[k], valids[k]))
                k += 1
            else:
                cols.append(Column(f.data_type,
                                   jnp.zeros(cap, dtype=jnp.int32)))
        out_cols, vis2, ops2, stage_rows = fs.chain_body(
            cols, vis, ops, jnp, host_same=host_same)
        flat_vals = tuple(c.values for c in out_cols if c is not None)
        flat_ok = tuple((jnp.ones(cap, dtype=bool)
                         if c.validity is None else c.validity)
                        for c in out_cols if c is not None)
        return flat_vals, flat_ok, vis2, ops2, stage_rows

    fs._ref_set = set(ref)
    from risingwave_tpu.utils import jaxtools
    return jaxtools.instrumented_jit(step, "fused.chain_step")


# -- the agg prelude (inlined into hash_agg.build_apply) -------------------


def build_agg_prelude(fs: FusedStages, group_indices: Sequence[int],
                      agg_calls, specs):
    """Traced fn: raw int64 matrix → (key_lanes i32[N,3g], signs i32,
    vis bool, per-call (in_lanes, valid)) — the contract
    ops/hash_agg.build_apply's core consumes. Everything between the
    raw upload and the accumulator scatter happens inside the ONE
    jitted step (filter, project, key/lane encode)."""
    import jax.numpy as jnp

    in_schema = fs.ext_schema
    ref = list(fs.ref_cols)
    group = list(group_indices)

    def prelude(raw):
        cols, vis, ops = decode_raw_cols(raw, in_schema, ref, jnp)
        out_cols, vis2, ops2, stage_rows = fs.chain_body(
            cols, vis, ops, jnp)
        signs = ops_to_signs(ops2)
        gcols = []
        for i in group:
            c = out_cols[i]
            gcols.append((c.values, c.validity))
        key_lanes = key_lanes_traced(gcols, jnp)
        call_inputs = []
        for call, spec in zip(agg_calls, specs):
            if call.input_idx is None:          # count(*)
                call_inputs.append(((), None))
                continue
            c = out_cols[call.input_idx]
            ok = (jnp.ones(vis2.shape[0], dtype=bool)
                  if c.validity is None else c.validity)
            # THE per-kind encoding — AggSpec.encode_input, same as
            # the executor's interpretive _inputs path; the lane
            # codecs it calls are xp-generic, so one implementation
            # serves both (no drifting twin)
            call_inputs.append((spec.encode_input(c.values), ok))
        return key_lanes, signs, vis2, tuple(call_inputs), stage_rows

    return prelude


# -- the join input prelude (inlined into hash_join's epoch jits) ----------


def payload_lanes_traced(cols: Sequence[Column], xp) -> object:
    """Device-typed payload columns → int32[N, 3p] lanes: the ONE
    encode in ops/lanes.py (bit-preserving payload_i64 — NOT the key
    normalization, which would fold -0.0 into 0.0 on the emit path),
    here traced under jit (xp=jnp) — same bytes as the host paths."""
    from risingwave_tpu.ops.lanes import payload_lanes
    return payload_lanes([(c.values, c.validity) for c in cols], xp)


def build_join_prelude(fs: FusedStages, key_indices: Sequence[int],
                       pay_indices: Sequence[int]):
    """Traced fn: raw int64 matrix → the [key_lanes | payload_lanes]
    int32 upload matrix ops/hash_join's epoch apply/probe consume —
    the join twin of build_agg_prelude. The absorbed run's value
    computation (projection exprs, key/lane encode, payload encode)
    happens INSIDE the epoch dispatches; visibility decisions (filter
    predicates, the watermark late mask, pair degradation) ride in the
    host-built aux flags, which the executor derives from the SAME
    composed chain run on numpy — bit-identical by the fusion
    contract, so the device never needs to re-decide them."""
    import jax.numpy as jnp

    assert fs.hop is None, \
        "hop expansion changes cardinality — join preludes refuse it"
    schema = fs.ext_schema
    ref = list(fs.ref_cols)
    keys = list(key_indices)
    pays = list(pay_indices)
    need = set(keys) | set(pays)

    def prelude(raw):
        cols, vis, ops = decode_raw_cols(raw, schema, ref, jnp)
        chunk = StreamChunk(schema, cols, vis, ops)
        if fs.out_exprs is None:
            out_cols = list(chunk.columns[:len(fs.in_schema)])
        else:
            # only the columns the lanes read get evaluated — the rest
            # are dead in this trace (XLA would DCE them anyway; not
            # emitting them keeps the jaxpr small)
            out_cols = [e.eval(chunk) if j in need else None
                        for j, e in enumerate(fs.out_exprs)]
        key_lanes = key_lanes_traced(
            [(out_cols[i].values, out_cols[i].validity)
             for i in keys], jnp)
        if not pays:
            return key_lanes
        pay_lanes = payload_lanes_traced([out_cols[i] for i in pays],
                                         jnp)
        return jnp.concatenate([key_lanes, pay_lanes], axis=1)

    return prelude
