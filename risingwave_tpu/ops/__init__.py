"""Device-resident stateful kernels: the TPU execution core.

This package is the reason the project exists (BASELINE north star): the
per-row Rust loops of the reference's stateful operators
(src/stream/src/executor/hash_agg.rs:329, hash_join.rs:990) become
whole-chunk XLA kernels over HBM-resident open-addressing hash tables.

    hash_table   functional open-addressing table: probe/insert as jitted
                 whole-batch kernels (the shared primitive)
    hash_agg     grouped aggregation state machine (count/sum/min/max with
                 retraction semantics)
    hash_join    two-sided equi-join state (row arena + per-key chains)
"""

from risingwave_tpu.ops.hash_table import DeviceHashTable, TableState

__all__ = ["DeviceHashTable", "TableState"]
