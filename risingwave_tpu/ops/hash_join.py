"""Device-resident join-side state (the q8 kernel's matcher).

Reference parity: JoinHashMap (src/stream/src/executor/managed_state/
join/mod.rs:228) — join key → multiset of rows — and the probe loop of
hash_join.rs:990 (``eq_join_oneside``). TPU re-design: the reference
walks a CPU hashbrown map row by row; here MATCHING runs on device as
whole-batch kernels, while row payloads stay in host arenas (varchar can
never live in HBM anyway — the device's job is the equality/match
structure, the host's job is materialization):

    table  DeviceHashTable     join-key lanes → key slot
    head   int32[cap]          key slot → first row ref (-1 end)
    next   int32[row_cap]      row ref → next row ref in its key chain
    live   bool[row_cap]       tombstones (deletes unlink lazily)

- ``insert``: whole-batch: one key probe-insert, then one chain-link
  kernel. Rows of one batch that share a key are chained to each other
  with one stable sort + shifted compares — no per-row host loop.
- ``delete``: tombstone (live=False). Chains keep the node until a
  rebuild; probes skip dead rows.
- ``probe``: two passes — a degree-count walk, a host sync for the output
  size, then an emit walk writing (probe_row, matched_ref) pairs at
  cumsum offsets. ``lax.while_loop`` runs exactly max-chain-length
  iterations (dynamic trip count, static shapes).

All lanes int32 (ops/lanes.py rationale).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.ops import hash_table as ht


class ChainState(NamedTuple):
    """Functional chain arrays (the non-key half of a join side)."""

    head: jnp.ndarray    # int32[cap]
    next: jnp.ndarray    # int32[row_cap]
    live: jnp.ndarray    # bool[row_cap]


def link_rows(chains: ChainState, slots: jnp.ndarray,
              row_refs: jnp.ndarray, vis: jnp.ndarray,
              cap: int) -> ChainState:
    """Front-insert a batch of rows into their key chains.

    `slots` comes from the key table's probe_insert for the same batch;
    rows of the batch that share a slot are linked to each other via a
    stable sort so the whole batch needs one scatter per array."""
    row_cap = int(chains.next.shape[0])
    skey = jnp.where(vis & (slots >= 0), slots, cap)
    order = jnp.argsort(skey, stable=True)
    s = skey[order]
    r = row_refs[order]
    valid = s < cap
    first = jnp.concatenate([jnp.ones(1, bool), s[1:] != s[:-1]])
    last = jnp.concatenate([s[1:] != s[:-1], jnp.ones(1, bool)])
    succ = jnp.roll(r, -1)                      # r[i+1] (garbage at end)
    old_head = chains.head[jnp.minimum(s, cap - 1)]
    nxt_val = jnp.where(last, old_head, succ)
    nxt = chains.next.at[jnp.where(valid, r, row_cap)].set(
        nxt_val, mode="drop")
    head = chains.head.at[jnp.where(valid & first, s, cap)].set(
        r, mode="drop")
    live = chains.live.at[jnp.where(valid, r, row_cap)].set(
        True, mode="drop")
    return ChainState(head, nxt, live)


def tombstone_rows(chains: ChainState, row_refs: jnp.ndarray,
                   vis: jnp.ndarray) -> ChainState:
    """Tombstone deletes; the chain node is skipped by probes."""
    row_cap = int(chains.next.shape[0])
    live = chains.live.at[jnp.where(vis, row_refs, row_cap)].set(
        False, mode="drop")
    return chains._replace(live=live)


def _chain_walk(table: ht.TableState, chains: ChainState,
                key_lanes, vis, body_extra, carry0):
    """Shared chain-walk loop: calls body_extra(cur, is_match, carry)."""
    slots = ht.lookup(table, key_lanes, vis)
    cur0 = jnp.where(slots >= 0,
                     chains.head[jnp.maximum(slots, 0)], jnp.int32(-1))

    def cond(c):
        cur = c[0]
        return jnp.any(cur >= 0)

    def body(c):
        cur, carry = c
        safe = jnp.maximum(cur, 0)
        is_match = (cur >= 0) & chains.live[safe]
        carry = body_extra(cur, is_match, carry)
        cur = jnp.where(cur >= 0, chains.next[safe], jnp.int32(-1))
        return cur, carry

    _cur, carry = jax.lax.while_loop(cond, body, (cur0, carry0))
    return carry


def probe_degrees(table: ht.TableState, chains: ChainState,
                  key_lanes: jnp.ndarray, vis: jnp.ndarray) -> jnp.ndarray:
    """Matches per probe row (live rows in the key's chain)."""
    n = key_lanes.shape[0]

    def acc(cur, is_match, deg):
        return deg + is_match.astype(jnp.int32)

    return _chain_walk(table, chains, key_lanes, vis, acc,
                       jnp.zeros(n, dtype=jnp.int32))


def probe_emit(table: ht.TableState, chains: ChainState,
               key_lanes: jnp.ndarray, vis: jnp.ndarray,
               offsets: jnp.ndarray, out_cap: int
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Write (probe_row_idx, matched_ref) pairs at cumsum offsets.

    out_cap is static (host computed next_pow2(total degrees))."""
    n = key_lanes.shape[0]
    row_ids = jnp.arange(n, dtype=jnp.int32)
    out_probe = jnp.full(out_cap, -1, dtype=jnp.int32)
    out_ref = jnp.full(out_cap, -1, dtype=jnp.int32)

    def emit(cur, is_match, carry):
        wp, op, orf = carry
        dest = jnp.where(is_match, wp, out_cap)
        op = op.at[dest].set(row_ids, mode="drop")
        orf = orf.at[dest].set(cur, mode="drop")
        return wp + is_match.astype(jnp.int32), op, orf

    _wp, out_probe, out_ref = _chain_walk(
        table, chains, key_lanes, vis, emit,
        (offsets.astype(jnp.int32), out_probe, out_ref))
    return out_probe, out_ref


_link_jit = jax.jit(link_rows, donate_argnums=(0,), static_argnums=(4,))
_tombstone_jit = jax.jit(tombstone_rows, donate_argnums=(0,))
_degrees_jit = jax.jit(probe_degrees)
_emit_jit = jax.jit(probe_emit, static_argnums=(5,))


def _remap_head(head: jnp.ndarray, old_to_new: jnp.ndarray,
                new_cap: int) -> jnp.ndarray:
    safe = jnp.where(old_to_new >= 0, old_to_new, new_cap)
    return jnp.full(new_cap, -1, dtype=jnp.int32).at[safe].set(
        head, mode="drop")


_remap_head_jit = jax.jit(_remap_head, static_argnums=(2,))


class JoinSideKernel:
    """Host wrapper: key table + chain arrays + arena growth.

    The key table is a DeviceHashTable (growth, load factor, sync-free
    occupancy bound all live there); on rehash its on_grow hook remaps
    `head` from old slots to new. The executor assigns row refs (host
    pk→ref map); tombstoned refs are NOT recycled — a dead ref stays
    linked in its chain, so reuse would splice one node into two chains
    and create cycles. Dead refs are reclaimed wholesale by `rebuild`
    (recovery / future compaction)."""

    def __init__(self, key_width: int,
                 key_capacity: int = ht.MIN_CAPACITY,
                 row_capacity: int = ht.MIN_CAPACITY):
        self.key_width = key_width
        self.table = ht.DeviceHashTable(key_width, key_capacity)
        self.table.on_grow(self._on_table_grow)
        self.chains = ChainState(
            head=jnp.full(self.table.capacity, -1, dtype=jnp.int32),
            next=jnp.full(row_capacity, -1, dtype=jnp.int32),
            live=jnp.zeros(row_capacity, dtype=bool))

    @property
    def row_capacity(self) -> int:
        return int(self.chains.next.shape[0])

    # -- growth ----------------------------------------------------------
    def _on_table_grow(self, old_to_new: jnp.ndarray,
                       old_capacity: int) -> None:
        self.chains = self.chains._replace(
            head=_remap_head_jit(self.chains.head, old_to_new,
                                 self.table.capacity))

    def reserve_rows(self, max_ref: int) -> None:
        row_cap = self.row_capacity
        if max_ref < row_cap:
            return
        new_cap = row_cap
        while new_cap <= max_ref:
            new_cap *= 2
        pad = new_cap - row_cap
        self.chains = self.chains._replace(
            next=jnp.concatenate(
                [self.chains.next, jnp.full(pad, -1, dtype=jnp.int32)]),
            live=jnp.concatenate(
                [self.chains.live, jnp.zeros(pad, dtype=bool)]))

    # -- ops --------------------------------------------------------------
    def insert(self, key_lanes: jnp.ndarray, row_refs: np.ndarray,
               vis: jnp.ndarray) -> None:
        if len(row_refs):
            self.reserve_rows(int(np.max(row_refs)))
        slots = self.table.probe_insert(key_lanes, vis)
        self.chains = _link_jit(self.chains, slots,
                                jnp.asarray(row_refs), vis,
                                self.table.capacity)

    def delete(self, row_refs: np.ndarray, vis: jnp.ndarray) -> None:
        self.chains = _tombstone_jit(self.chains, jnp.asarray(row_refs),
                                     vis)

    def probe(self, key_lanes: jnp.ndarray, vis: jnp.ndarray
              ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(degrees, probe_idx[pairs], refs[pairs]) — one host sync."""
        deg = np.asarray(_degrees_jit(self.table.state, self.chains,
                                      key_lanes, vis))
        total = int(deg.sum())
        if total == 0:
            z = np.zeros(0, dtype=np.int32)
            return deg, z, z
        offsets = np.cumsum(deg) - deg
        from risingwave_tpu.common.chunk import next_pow2
        # floor at 1024: collapses the 1..512 pow2 buckets into one jit
        # entry — small probes dominate tests and warmup, and each
        # distinct out_cap is a fresh XLA compile.
        out_cap = max(1024, next_pow2(total))
        op, orf = _emit_jit(self.table.state, self.chains, key_lanes, vis,
                            jnp.asarray(offsets.astype(np.int32)), out_cap)
        op = np.asarray(op)[:total]
        orf = np.asarray(orf)[:total]
        return deg, op, orf

    # -- recovery ---------------------------------------------------------
    def rebuild(self, key_lanes: np.ndarray, row_refs: np.ndarray) -> None:
        """Reload all live rows (recovery): one batched insert."""
        n = len(row_refs)
        key_cap = max(self.table.capacity,
                      ht.MIN_CAPACITY if n == 0 else
                      1 << int(np.ceil(np.log2(max(n / ht.MAX_LOAD, 1)))))
        row_cap = max(self.row_capacity,
                      1 << int(np.ceil(np.log2(max(n + 1, 2)))))
        self.table = ht.DeviceHashTable(self.key_width, key_cap)
        self.table.on_grow(self._on_table_grow)
        self.chains = ChainState(
            head=jnp.full(self.table.capacity, -1, dtype=jnp.int32),
            next=jnp.full(row_cap, -1, dtype=jnp.int32),
            live=jnp.zeros(row_cap, dtype=bool))
        if n == 0:
            return
        self.insert(jnp.asarray(key_lanes), row_refs,
                    jnp.ones(n, dtype=bool))
