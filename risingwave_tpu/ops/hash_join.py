"""Device-resident join-side state (the q8 kernel's matcher).

Reference parity: JoinHashMap (src/stream/src/executor/managed_state/
join/mod.rs:228) — join key → multiset of rows — and the probe loop of
hash_join.rs:990 (``eq_join_oneside``). TPU re-design: the reference
walks a CPU hashbrown map row by row; here MATCHING runs on device as
whole-batch kernels, while row payloads stay in host arenas (varchar can
never live in HBM anyway — the device's job is the equality/match
structure, the host's job is materialization):

    table    DeviceHashTable   join-key lanes → key slot
    head     int32[cap]        key slot → first row ref (-1 end)
    next     int32[row_cap]    row ref → next row ref in its key chain
    ins_seq  int32[row_cap]    message sequence that inserted the row
    del_seq  int32[row_cap]    message sequence that deleted it (MAX=∞)

SEQUENCE-VERSIONED state (the load-bearing TPU design choice): every
message carries a monotone sequence number, and a probe at sequence s
sees exactly the rows with ``ins_seq < s <= del_seq`` — i.e. the state
as of message s, regardless of when the probe's RESULT is read. That
makes probes pure functions of (end-of-epoch state, s), so the host can
dispatch every chunk's probe asynchronously, fetch ALL results in one
DMA round at the barrier, and safely RE-dispatch any probe whose pair
buffer overflowed — on a tunneled device where every blocking read
costs 70ms+, this is the difference between per-chunk and per-epoch
synchronization. (The reference's hashbrown map reads are synchronous
CPU lookups and need none of this.)

- ``insert``: whole-batch: one key probe-insert, then one chain-link
  kernel. Rows of one batch that share a key are chained to each other
  with one stable sort + shifted compares — no per-row host loop.
- ``delete``: sets del_seq. Chains keep the node until a rebuild;
  probes at later sequences skip it.
- ``probe``: ONE fused kernel — degree-count walk, device cumsum, emit
  walk writing (probe_row, matched_ref) pairs at the cumsum offsets,
  all returned as one packed matrix with a header. ``lax.while_loop``
  runs exactly max-chain-length iterations (dynamic trip count, static
  shapes).

All lanes int32 (ops/lanes.py rationale).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.ops import hash_table as ht
from risingwave_tpu.utils import jaxtools
from risingwave_tpu.utils.ledger import LEDGER


I32_MAX = (1 << 31) - 1


class ChainState(NamedTuple):
    """Functional chain arrays (the non-key half of a join side)."""

    head: jnp.ndarray     # int32[cap]
    next: jnp.ndarray     # int32[row_cap]
    ins_seq: jnp.ndarray  # int32[row_cap] (I32_MAX = never inserted)
    del_seq: jnp.ndarray  # int32[row_cap] (I32_MAX = live)


def link_rows(chains: ChainState, slots: jnp.ndarray,
              row_refs: jnp.ndarray, vis: jnp.ndarray,
              cap: int, seq: jnp.ndarray = None) -> ChainState:
    """Front-insert a batch of rows into their key chains.

    `slots` comes from the key table's probe_insert for the same batch;
    rows of the batch that share a slot are linked to each other via a
    stable sort so the whole batch needs one scatter per array.

    Within one batch, rows sharing a key keep BATCH ORDER in the chain
    via the stable sort; `seq` may be a per-row vector (epoch batching:
    each row carries its message sequence) or a scalar."""
    row_cap = int(chains.next.shape[0])
    skey = jnp.where(vis & (slots >= 0), slots, cap)
    order = jnp.argsort(skey, stable=True)
    s = skey[order]
    r = row_refs[order]
    valid = s < cap
    first = jnp.concatenate([jnp.ones(1, bool), s[1:] != s[:-1]])
    last = jnp.concatenate([s[1:] != s[:-1], jnp.ones(1, bool)])
    succ = jnp.roll(r, -1)                      # r[i+1] (garbage at end)
    old_head = chains.head[jnp.minimum(s, cap - 1)]
    nxt_val = jnp.where(last, old_head, succ)
    nxt = chains.next.at[jnp.where(valid, r, row_cap)].set(
        nxt_val, mode="drop")
    head = chains.head.at[jnp.where(valid & first, s, cap)].set(
        r, mode="drop")
    if seq is None:
        sv = jnp.int32(0)
    elif jnp.ndim(seq) == 0:
        sv = seq
    else:
        sv = seq[order]                         # per-row seq follows r
    ins = chains.ins_seq.at[jnp.where(valid, r, row_cap)].set(
        sv, mode="drop")
    return ChainState(head, nxt, ins, chains.del_seq)


def tombstone_rows(chains: ChainState, row_refs: jnp.ndarray,
                   vis: jnp.ndarray,
                   seq: jnp.ndarray = None) -> ChainState:
    """Tombstone deletes; probes at sequences > seq skip the node."""
    row_cap = int(chains.next.shape[0])
    del_ = chains.del_seq.at[jnp.where(vis, row_refs, row_cap)].set(
        jnp.int32(0) if seq is None else seq, mode="drop")
    return chains._replace(del_seq=del_)


def probe_pairs(table: ht.TableState, chains: ChainState,
                key_lanes: jnp.ndarray, vis: jnp.ndarray,
                seq: jnp.ndarray, out_cap: int,
                with_degrees: bool = True) -> jnp.ndarray:
    """Fused degrees + cumsum + emit: ONE kernel, ONE packed d2h array.

    Returns int32[1 + n + out_cap, 2]: row 0 header [total_pairs, 0];
    rows 1..1+n degrees (col 0); remaining rows (probe_row_idx, ref)
    pairs at device-computed cumsum offsets. Through a tunneled device
    the separate degrees fetch + host cumsum + emit fetch cost three
    round-trips per chunk; this costs one (the host retries with a
    doubled out_cap if the header says the pair buffer overflowed).

    `seq` may be a per-row vector (epoch batching: every row probes at
    its own message sequence). `with_degrees=False` drops the n degree
    rows from the output — inner joins never read them, and on a
    ~20MB/s tunnel the d2h bytes are the barrier's dominant cost.
    """
    n = key_lanes.shape[0]
    slots = ht.lookup(table, key_lanes, vis)
    cur0 = jnp.where(slots >= 0,
                     chains.head[jnp.maximum(slots, 0)], jnp.int32(-1))

    def cond(c):
        return jnp.any(c[0] >= 0)

    def visible(safe):
        return (chains.ins_seq[safe] < seq) & (chains.del_seq[safe] >= seq)

    def body1(c):
        cur, deg = c
        safe = jnp.maximum(cur, 0)
        m = (cur >= 0) & visible(safe)
        return (jnp.where(cur >= 0, chains.next[safe], jnp.int32(-1)),
                deg + m.astype(jnp.int32))

    _cur, deg = jax.lax.while_loop(
        cond, body1, (cur0, jnp.zeros(n, dtype=jnp.int32)))
    offsets = jnp.cumsum(deg, dtype=jnp.int32) - deg
    total = jnp.sum(deg, dtype=jnp.int32)
    row_ids = jnp.arange(n, dtype=jnp.int32)

    def body2(c):
        cur, wp, op, orf = c
        safe = jnp.maximum(cur, 0)
        m = (cur >= 0) & visible(safe)
        dest = jnp.where(m, wp, out_cap)
        op = op.at[dest].set(row_ids, mode="drop")
        orf = orf.at[dest].set(cur, mode="drop")
        return (jnp.where(cur >= 0, chains.next[safe], jnp.int32(-1)),
                wp + m.astype(jnp.int32), op, orf)

    _cur, _wp, out_probe, out_ref = jax.lax.while_loop(
        cond, body2,
        (cur0, offsets, jnp.full(out_cap, -1, dtype=jnp.int32),
         jnp.full(out_cap, -1, dtype=jnp.int32)))
    pairs = jnp.stack([out_probe, out_ref], axis=1)
    header = jnp.zeros((1, 2), dtype=jnp.int32).at[0, 0].set(total)
    if not with_degrees:
        return jnp.concatenate([header, pairs], axis=0)
    degs = jnp.stack([deg, jnp.zeros(n, dtype=jnp.int32)], axis=1)
    return jnp.concatenate([header, degs, pairs], axis=0)


_link_jit = jaxtools.instrumented_jit(
    link_rows, "hash_join.link", donate_argnums=(0,),
    static_argnums=(4,))
_tombstone_jit = jaxtools.instrumented_jit(
    tombstone_rows, "hash_join.tombstone", donate_argnums=(0,))
_probe_pairs_jit = jaxtools.instrumented_jit(
    probe_pairs, "hash_join.probe", static_argnums=(5, 6))


# -- epoch batching --------------------------------------------------------
# One packed aux matrix rides along with the upload matrix (key lanes
# concatenated with payload lanes) and feeds BOTH the apply and the
# probe of a whole epoch: through the tunnel, per-barrier transfer
# count (not compute) bounds throughput, so the executor concatenates
# every chunk of the epoch and ships each side as exactly two uploads
# + one apply dispatch + one probe dispatch.
AUX_INS_REF, AUX_DEL_REF, AUX_FLAGS, AUX_SEQ = 0, 1, 2, 3
FLAG_PROBE, FLAG_INS, FLAG_DEL = 1, 2, 4
# probe row's op sign is negative (DELETE / UPDATE_DELETE) — the
# device-side degree scatter needs it (see epoch_probe)
FLAG_NEG = 8


def epoch_apply(table: ht.TableState, chains: ChainState,
                pay: jnp.ndarray, up: jnp.ndarray, aux: jnp.ndarray,
                key_width: int):
    """Apply a whole epoch's inserts + tombstones in one dispatch.

    ``up`` is [key_lanes | payload_lanes] int32[n, key_width + P]: the
    payload lanes of inserted rows scatter into the device payload
    store in the SAME dispatch that links their chains. Rows carry
    their message sequence in aux[:, AUX_SEQ]; sequence visibility
    makes application order irrelevant (probes reconstruct any
    interleaving exactly), so one batched apply per side per epoch is
    semantically identical to per-chunk applies."""
    key_lanes = up[:, :key_width]
    flags = aux[:, AUX_FLAGS]
    ins_mask = (flags & FLAG_INS) != 0
    del_mask = (flags & FLAG_DEL) != 0
    seq = aux[:, AUX_SEQ]
    table2, slots, ins = ht.probe_insert(table, key_lanes, ins_mask)
    chains2 = link_rows(chains, slots, aux[:, AUX_INS_REF], ins_mask,
                        table2.capacity, seq)
    chains2 = tombstone_rows(chains2, aux[:, AUX_DEL_REF], del_mask, seq)
    if pay.shape[1]:
        row_cap = pay.shape[0]
        dest = jnp.where(ins_mask, aux[:, AUX_INS_REF],
                         jnp.int32(row_cap))
        pay = pay.at[dest].set(up[:, key_width:], mode="drop")
    return table2, chains2, pay, ins


_epoch_apply_jit = jaxtools.instrumented_jit(
    epoch_apply, "hash_join.epoch_apply", donate_argnums=(0, 1, 2),
    static_argnums=(5,))


def epoch_probe(table: ht.TableState, chains: ChainState,
                pay: jnp.ndarray, deg_self: jnp.ndarray,
                deg_sink: jnp.ndarray, up: jnp.ndarray,
                aux: jnp.ndarray, key_width: int, out_cap: int,
                with_degrees: bool):
    """Probe a whole epoch's rows (each at its own sequence) in one
    dispatch against post-apply state — exact by sequence visibility.

    Fused degrees + cumsum + emit + payload gather + degree
    maintenance: ONE kernel, ONE packed d2h matrix of width
    W = 2 + P + (1 if with_degrees). Layout:

      row 0                      header [total_pairs, 0, ...]
      rows 1..1+n (deg only)     per-probe-row match degrees (col 0)
      out_cap pair rows          [probe_row, ref, pay lanes..., old]

    ``pay`` is THIS side's payload store: the emit walk gathers each
    matched ref's lanes ON DEVICE, so the host materializes matched
    rows from the one packed fetch instead of arena-gathering
    column-by-column per chunk. With ``with_degrees``:

    - ``old`` is deg_self[ref] BEFORE this epoch's updates — the host
      replays per-chunk degree transitions from it without keeping a
      host degrees array;
    - deg_self gets one scatter-add of every pair's probe-row sign
      (FLAG_NEG), i.e. the stored side's degree transitions;
    - deg_sink (the PROBING side's degree array) gets one scatter-add
      of each inserted row's probe-time match count at its ref — the
      initial degree of rows stored this epoch. Adds commute, so the
      two sides' probes may run in either order; fresh refs start at
      zero by the bump-allocation invariant.

    deg arrays are NOT donated: an overflow redispatch re-runs this
    exact computation from the original arrays, and the host installs
    the outputs only after a successful collect."""
    key_lanes = up[:, :key_width]
    flags = aux[:, AUX_FLAGS]
    vis = (flags & FLAG_PROBE) != 0
    seq = aux[:, AUX_SEQ]
    n = key_lanes.shape[0]
    P = pay.shape[1]
    row_cap = chains.next.shape[0]
    slots = ht.lookup(table, key_lanes, vis)
    cur0 = jnp.where(slots >= 0,
                     chains.head[jnp.maximum(slots, 0)], jnp.int32(-1))

    def cond(c):
        return jnp.any(c[0] >= 0)

    def visible(safe):
        return (chains.ins_seq[safe] < seq) & (chains.del_seq[safe] >= seq)

    def body1(c):
        cur, deg = c
        safe = jnp.maximum(cur, 0)
        m = (cur >= 0) & visible(safe)
        return (jnp.where(cur >= 0, chains.next[safe], jnp.int32(-1)),
                deg + m.astype(jnp.int32))

    _cur, deg = jax.lax.while_loop(
        cond, body1, (cur0, jnp.zeros(n, dtype=jnp.int32)))
    offsets = jnp.cumsum(deg, dtype=jnp.int32) - deg
    total = jnp.sum(deg, dtype=jnp.int32)
    row_ids = jnp.arange(n, dtype=jnp.int32)

    def body2(c):
        cur, wp, op, orf, opay, oold = c
        safe = jnp.maximum(cur, 0)
        m = (cur >= 0) & visible(safe)
        dest = jnp.where(m, wp, out_cap)
        op = op.at[dest].set(row_ids, mode="drop")
        orf = orf.at[dest].set(cur, mode="drop")
        if P:
            opay = opay.at[dest].set(pay[safe], mode="drop")
        if with_degrees:
            oold = oold.at[dest].set(deg_self[safe], mode="drop")
        return (jnp.where(cur >= 0, chains.next[safe], jnp.int32(-1)),
                wp + m.astype(jnp.int32), op, orf, opay, oold)

    init2 = (cur0, offsets,
             jnp.full(out_cap, -1, dtype=jnp.int32),
             jnp.full(out_cap, -1, dtype=jnp.int32),
             jnp.zeros((out_cap, P), dtype=jnp.int32),
             jnp.zeros(out_cap, dtype=jnp.int32))
    (_cur, _wp, out_probe, out_ref, out_pay,
     out_old) = jax.lax.while_loop(cond, body2, init2)
    parts = [out_probe[:, None], out_ref[:, None]]
    if P:
        parts.append(out_pay)
    if with_degrees:
        parts.append(out_old[:, None])
    pairs = jnp.concatenate(parts, axis=1)
    W = pairs.shape[1]
    header = jnp.zeros((1, W), dtype=jnp.int32).at[0, 0].set(total)
    if with_degrees:
        # stored-side transitions: one scatter-add of pair signs
        pair_mask = out_ref >= 0
        sgn_row = jnp.where((flags & FLAG_NEG) != 0,
                            jnp.int32(-1), jnp.int32(1))
        pair_sgn = jnp.where(
            pair_mask, sgn_row[jnp.maximum(out_probe, 0)], 0)
        deg_self = deg_self.at[
            jnp.where(pair_mask, out_ref, row_cap)].add(
                pair_sgn, mode="drop")
        # probing-side initial degrees: probe-time count at each
        # inserted row's ref (add, not set — commutes with the other
        # probe's transition adds; fresh slots are zero)
        ins_mask = (flags & FLAG_INS) != 0
        sink_cap = deg_sink.shape[0]
        deg_sink = deg_sink.at[
            jnp.where(ins_mask, aux[:, AUX_INS_REF], sink_cap)].add(
                jnp.where(ins_mask, deg, 0), mode="drop")
        degs = jnp.zeros((n, W), dtype=jnp.int32).at[:, 0].set(deg)
        mat = jnp.concatenate([header, degs, pairs], axis=0)
        return mat, deg_self, deg_sink
    # degree-free (inner) probes return only the matrix: passing the
    # untouched deg arrays through would force XLA output copies
    return jnp.concatenate([header, pairs], axis=0)


_epoch_probe_jit = jaxtools.instrumented_jit(
    epoch_probe, "hash_join.epoch_probe", static_argnums=(7, 8, 9))


def _masked_scatter(arr: jnp.ndarray, refs: jnp.ndarray,
                    vis: jnp.ndarray, vals: jnp.ndarray) -> jnp.ndarray:
    """Masked write-by-ref into a donated device array (payload rows
    AND degree values share this one scatter; masked rows drop on the
    out-of-range sentinel)."""
    cap = arr.shape[0]
    dest = jnp.where(vis, refs, jnp.int32(cap))
    return arr.at[dest].set(vals, mode="drop")


_masked_scatter_jit = jaxtools.instrumented_jit(
    _masked_scatter, "hash_join.masked_scatter", donate_argnums=(0,))


def _pad_scatter_args(refs: np.ndarray, vals: np.ndarray):
    """Host staging for _masked_scatter: pad refs/vals to the next
    pow-2 row count (stable jit shapes) with a validity mask."""
    from risingwave_tpu.common.chunk import next_pow2
    n = len(refs)
    cap = next_pow2(max(n, 1))
    r = np.zeros(cap, dtype=np.int32)
    r[:n] = refs
    m = np.zeros(cap, dtype=bool)
    m[:n] = True
    v = np.zeros((cap,) + np.shape(vals)[1:], dtype=np.int32)
    v[:n] = vals
    return jnp.asarray(r), jnp.asarray(m), jnp.asarray(v)


def make_prelude_epoch_jits(prelude, label: str):
    """Jitted epoch apply/probe with a fused input run inlined: the
    upload is the RAW int64 chunk matrix and ``prelude`` (ops/fused.py
    build_join_prelude) computes the [key_lanes | payload_lanes]
    matrix INSIDE the dispatch — projection exprs, key normalization
    and payload bit-encode all trace into the same program that
    scatters state (donated, exactly like the direct-upload twins)."""
    def ap(table, chains, pay, raw, aux, key_width):
        return epoch_apply(table, chains, pay, prelude(raw), aux,
                           key_width)

    def pr(table, chains, pay, deg_self, deg_sink, raw, aux,
           key_width, out_cap, with_degrees):
        return epoch_probe(table, chains, pay, deg_self, deg_sink,
                           prelude(raw), aux, key_width, out_cap,
                           with_degrees)

    return (jaxtools.instrumented_jit(
                ap, f"hash_join.epoch_apply[{label}]",
                donate_argnums=(0, 1, 2), static_argnums=(5,)),
            jaxtools.instrumented_jit(
                pr, f"hash_join.epoch_probe[{label}]",
                static_argnums=(7, 8, 9)))


def apply_and_probe(my_table: ht.TableState, my_chains: ChainState,
                    other_table: ht.TableState, other_chains: ChainState,
                    key_lanes: jnp.ndarray, probe_vis: jnp.ndarray,
                    ins_refs: jnp.ndarray, ins_mask: jnp.ndarray,
                    del_refs: jnp.ndarray, del_mask: jnp.ndarray,
                    seq: jnp.ndarray, out_cap: int):
    """The whole per-chunk device step as ONE dispatch.

    Through the tunnel each pjit call costs ~2ms of host time on big
    pytrees, so the hot path's probe(other) + probe_insert(mine) +
    link + tombstone — four calls — bounded chunk throughput at
    ~500K rows/s before any compute. Fused: one call, one d2h array
    (the packed probe matrix), my-side state updated in place
    (donated). Probe semantics are unchanged — the probe reads the
    OTHER side at `seq` while the insert/delete lands on MY side at
    `seq`, and sequence visibility keeps the two independent."""
    mat = probe_pairs(other_table, other_chains, key_lanes, probe_vis,
                      seq, out_cap)
    my_table2, slots, ins = ht.probe_insert(my_table, key_lanes,
                                            ins_mask)
    chains = link_rows(my_chains, slots, ins_refs, ins_mask,
                       my_table2.capacity, seq)
    chains = tombstone_rows(chains, del_refs, del_mask, seq)
    return my_table2, chains, ins, mat


_apply_and_probe_jit = jaxtools.instrumented_jit(
    apply_and_probe, "hash_join.apply_and_probe",
    donate_argnums=(0, 1), static_argnums=(11,))


def _remap_head(head: jnp.ndarray, old_to_new: jnp.ndarray,
                new_cap: int) -> jnp.ndarray:
    safe = jnp.where(old_to_new >= 0, old_to_new, new_cap)
    return jnp.full(new_cap, -1, dtype=jnp.int32).at[safe].set(
        head, mode="drop")


_remap_head_jit = jaxtools.instrumented_jit(
    _remap_head, "hash_join.remap_head", static_argnums=(2,))


def _rebase_jit(chains: ChainState) -> ChainState:
    mx = jnp.int32(I32_MAX)
    return chains._replace(
        ins_seq=jnp.where(chains.ins_seq == mx, mx, jnp.int32(0)),
        del_seq=jnp.where(chains.del_seq == mx, mx, jnp.int32(0)))


_rebase_jit = jaxtools.instrumented_jit(_rebase_jit, "hash_join.rebase")


class PendingProbe:
    """An in-flight probe: dispatched, DMA started, not yet read.

    Sequence versioning makes collect() safe at any later point — the
    kernel may have applied more messages, and a re-dispatch after a
    pair-buffer overflow still returns the probe-time result.
    `redispatch(cap)` re-runs the probe against the kernel's CURRENT
    state at a larger pair capacity; `bump(cap)` records the grown
    capacity on the owning kernel."""

    def __init__(self, mat, n: int, cap: int, redispatch,
                 with_degrees: bool = True, bump=None):
        self.mat = mat
        self.n = n
        self.cap = cap
        self.redispatch = redispatch
        self.with_degrees = with_degrees
        self.bump = bump

    def collect(self) -> Tuple[Optional[np.ndarray], np.ndarray,
                               np.ndarray]:
        """(degrees | None, probe_idx[pairs], refs[pairs]). Pairs are
        sorted by probe row index (device cumsum offsets)."""
        n = self.n
        with LEDGER.kernel_scope("hash_join"):
            while True:
                mat = jaxtools.fetch1(self.mat)
                total = int(mat[0, 0])
                if total <= self.cap:
                    break
                from risingwave_tpu.common.chunk import next_pow2
                self.cap = max(self.cap * 2, next_pow2(total))
                if self.bump is not None:
                    self.bump(self.cap)
                self.mat = self.redispatch(self.cap)
                jaxtools.start_fetch(self.mat)
        if self.with_degrees:
            deg = np.ascontiguousarray(mat[1:1 + n, 0])
            pairs = mat[1 + n:1 + n + total]
        else:
            deg = None
            pairs = mat[1:1 + total]
        return (deg, np.ascontiguousarray(pairs[:, 0]),
                np.ascontiguousarray(pairs[:, 1]))


class PendingEpochProbe:
    """An in-flight epoch probe over the payload-widened matrix.

    Like PendingProbe, but parses the packed layout of `epoch_probe`
    (pair rows carry the probed side's payload lanes and, with
    degrees, the pre-epoch degree per ref) and installs the updated
    degree arrays into their owning kernels only once the collect
    succeeds — an overflow redispatch recomputes them from the
    original arrays, so a retry never double-counts a transition."""

    def __init__(self, mat, n: int, cap: int, redispatch,
                 pay_width: int, with_degrees: bool, install, bump):
        self.mat = mat
        self.n = n
        self.cap = cap
        self.redispatch = redispatch
        self.pay_width = pay_width
        self.with_degrees = with_degrees
        self.install = install        # (deg_self, deg_sink) -> None
        self.bump = bump
        self._degs = None             # latest (deg_self, deg_sink)

    def set_degs(self, deg_self, deg_sink) -> None:
        self._degs = (deg_self, deg_sink)

    def collect(self):
        """(degrees | None, probe_idx, refs, pay_rows | None,
        old_deg | None); pairs sorted by probe row index."""
        n = self.n
        with LEDGER.kernel_scope("hash_join"):
            while True:
                mat = jaxtools.fetch1(self.mat)
                total = int(mat[0, 0])
                if total <= self.cap:
                    break
                from risingwave_tpu.common.chunk import next_pow2
                self.cap = max(self.cap * 2, next_pow2(total))
                if self.bump is not None:
                    self.bump(self.cap)
                self.mat = self.redispatch(self.cap)
                jaxtools.start_fetch(self.mat)
        if self.with_degrees and self._degs is not None:
            self.install(*self._degs)
        if self.with_degrees:
            deg = np.ascontiguousarray(mat[1:1 + n, 0])
            pairs = mat[1 + n:1 + n + total]
        else:
            deg = None
            pairs = mat[1:1 + total]
        P = self.pay_width
        pay = np.ascontiguousarray(pairs[:, 2:2 + P]) if P else None
        old = np.ascontiguousarray(pairs[:, 2 + P]) \
            if self.with_degrees else None
        return (deg, np.ascontiguousarray(pairs[:, 0]),
                np.ascontiguousarray(pairs[:, 1]), pay, old)


class JoinSideKernel:
    """Host wrapper: key table + chain arrays + arena growth.

    The key table is a DeviceHashTable (growth, load factor, sync-free
    occupancy bound all live there); on rehash its on_grow hook remaps
    `head` from old slots to new. The executor assigns row refs (host
    pk→ref map); tombstoned refs are NOT recycled — a dead ref stays
    linked in its chain, so reuse would splice one node into two chains
    and create cycles. Dead refs are reclaimed wholesale by `rebuild`
    (recovery / future compaction)."""

    # pre-sized like GroupedAggKernel.DEFAULT_CAPACITY: the growth
    # ladder costs a rehash + retrace per doubling, and the sync-free
    # occupancy bound drains (70ms-1s blocked read on a tunneled chip)
    # whenever an epoch's rows outrun the key table
    DEFAULT_CAPACITY = 1 << 16

    def __init__(self, key_width: int,
                 key_capacity: int = DEFAULT_CAPACITY,
                 row_capacity: int = DEFAULT_CAPACITY,
                 probe_capacity: int = 1 << 14,
                 payload_width: int = 0):
        self.key_width = key_width
        # payload lanes per stored row (3 int32 lanes per device-typed
        # column — ops/lanes.py payload_i64): written at insert time in
        # the same dispatch that links chains, gathered ON DEVICE by
        # the probe's emit walk so matched rows materialize from the
        # one packed fetch instead of a host arena gather per column
        self.payload_width = payload_width
        self.table = ht.DeviceHashTable(key_width, key_capacity)
        self.table.on_grow(self._on_table_grow)
        # pair-output buffer rows for the fused probe; doubles on
        # overflow (kept generous: each size is a fresh XLA compile)
        self._probe_cap = probe_capacity
        self.chains = ChainState(
            head=jnp.full(self.table.capacity, -1, dtype=jnp.int32),
            next=jnp.full(row_capacity, -1, dtype=jnp.int32),
            ins_seq=jnp.full(row_capacity, I32_MAX, dtype=jnp.int32),
            del_seq=jnp.full(row_capacity, I32_MAX, dtype=jnp.int32))
        self.pay = jnp.zeros((row_capacity, payload_width),
                             dtype=jnp.int32)
        # device-resident per-ref match degrees (outer/semi/anti
        # bookkeeping): maintained inside the epoch probe dispatches;
        # unallocated refs are 0 by the bump-allocation invariant
        self.deg = jnp.zeros(row_capacity, dtype=jnp.int32)
        # fused-input epoch jits, keyed by prelude label: this kernel
        # may serve two preludes (its OWN side's on apply, the PROBING
        # side's on probe)
        self._prelude_jits: dict = {}

    def _epoch_jits(self, prelude, key: str):
        jits = self._prelude_jits.get(key)
        if jits is None:
            jits = make_prelude_epoch_jits(prelude, key)
            self._prelude_jits[key] = jits
        return jits

    @property
    def row_capacity(self) -> int:
        return int(self.chains.next.shape[0])

    @property
    def device_payload_bytes(self) -> int:
        """HBM bytes held by the payload lane store + degree array
        (the residency metric's device half)."""
        return int(self.pay.size + self.deg.size) * 4

    # -- growth ----------------------------------------------------------
    def _on_table_grow(self, old_to_new: jnp.ndarray,
                       old_capacity: int) -> None:
        self.chains = self.chains._replace(
            head=_remap_head_jit(self.chains.head, old_to_new,
                                 self.table.capacity))

    def reserve_rows(self, max_ref: int) -> None:
        row_cap = self.row_capacity
        if max_ref < row_cap:
            return
        new_cap = row_cap
        while new_cap <= max_ref:
            # 4x, not 2x: every growth step retraces/recompiles the
            # apply+probe programs at the new row shape (~0.1s trace on
            # host, far worse through the tunnel); chains are 3 int32
            # arrays, so the overshoot is cheap HBM
            new_cap *= 4
        pad = new_cap - row_cap
        self.chains = self.chains._replace(
            next=jnp.concatenate(
                [self.chains.next, jnp.full(pad, -1, dtype=jnp.int32)]),
            ins_seq=jnp.concatenate(
                [self.chains.ins_seq,
                 jnp.full(pad, I32_MAX, dtype=jnp.int32)]),
            del_seq=jnp.concatenate(
                [self.chains.del_seq,
                 jnp.full(pad, I32_MAX, dtype=jnp.int32)]))
        self.pay = jnp.concatenate(
            [self.pay, jnp.zeros((pad, self.payload_width),
                                 dtype=jnp.int32)])
        self.deg = jnp.concatenate(
            [self.deg, jnp.zeros(pad, dtype=jnp.int32)])

    # -- ops --------------------------------------------------------------
    # seq=0 defaults keep kernel-level tests/recovery simple: probes at
    # seq 0 use I32_MAX and see everything inserted at seq 0.
    def insert(self, key_lanes: jnp.ndarray, row_refs: np.ndarray,
               vis: jnp.ndarray, seq: int = 0) -> None:
        if len(row_refs):
            self.reserve_rows(int(np.max(row_refs)))
        slots = self.table.probe_insert(key_lanes, vis)
        self.chains = _link_jit(self.chains, slots,
                                jnp.asarray(row_refs), vis,
                                self.table.capacity, jnp.int32(seq))

    def delete(self, row_refs: np.ndarray, vis: jnp.ndarray,
               seq: int = 0, key_lanes=None) -> None:
        # key_lanes: routing info for the SHARDED kernel's API twin
        # (parallel/join.py); a single chip tombstones by ref directly
        self.chains = _tombstone_jit(self.chains, jnp.asarray(row_refs),
                                     vis, jnp.int32(seq))

    def apply_and_probe(self, other: "JoinSideKernel",
                        key_lanes: jnp.ndarray, probe_vis: np.ndarray,
                        ins_refs: np.ndarray, ins_mask: np.ndarray,
                        del_refs: np.ndarray, del_mask: np.ndarray,
                        seq: int) -> "PendingProbe":
        """One fused dispatch: probe `other` at `seq` + apply this
        side's inserts/deletes at `seq`. Returns the pending probe
        (DMA started; collect at the barrier sweep)."""
        n = int(key_lanes.shape[0])
        if ins_mask.any():     # ins_refs is the full chunk-width array
            self.reserve_rows(int(ins_refs.max()))
        self.table.reserve(n)
        s = jnp.int32(seq)
        out_cap = other._probe_cap
        lanes_d = jnp.asarray(key_lanes)
        vis_d = jnp.asarray(probe_vis)
        self.table.state, self.chains, ins, mat = _apply_and_probe_jit(
            self.table.state, self.chains,
            other.table.state, other.chains,
            lanes_d, vis_d,
            jnp.asarray(ins_refs), jnp.asarray(ins_mask),
            jnp.asarray(del_refs), jnp.asarray(del_mask),
            s, out_cap)
        self.table._counters.push(ins, n)
        jaxtools.start_fetch(mat)

        def redispatch(cap):
            return _probe_pairs_jit(other.table.state, other.chains,
                                    lanes_d, vis_d, s, cap, True)

        def bump(cap):
            other._probe_cap = max(other._probe_cap, cap)

        return PendingProbe(mat, n, out_cap, redispatch, bump=bump)

    def probe_submit(self, key_lanes: jnp.ndarray, vis: jnp.ndarray,
                     seq: Optional[int] = None) -> "PendingProbe":
        """Dispatch the fused probe and kick its DMA; no blocking.
        The result is a pure function of (state, seq): collect() may
        run after later applies and may re-dispatch on overflow."""
        s = jnp.int32(I32_MAX if seq is None else seq)
        lanes_d = jnp.asarray(key_lanes)
        vis_d = jnp.asarray(vis)
        with LEDGER.phase("device_compute", kernel="hash_join"):
            mat = _probe_pairs_jit(self.table.state, self.chains,
                                   lanes_d, vis_d, s, self._probe_cap,
                                   True)
        jaxtools.start_fetch(mat)

        def redispatch(cap):
            return _probe_pairs_jit(self.table.state, self.chains,
                                    lanes_d, vis_d, s, cap, True)

        def bump(cap):
            self._probe_cap = max(self._probe_cap, cap)

        return PendingProbe(mat, int(lanes_d.shape[0]),
                            self._probe_cap, redispatch, bump=bump)

    # -- epoch batching ---------------------------------------------------
    def stage_epoch(self, up: np.ndarray, aux: np.ndarray, total: int,
                    max_ins_ref: int, owners=None) -> tuple:
        """Host→device staging of one side's epoch matrices (the
        sharded kernel's twin additionally pads to the mesh width,
        derives the skew-exact routing bucket from ``owners`` and
        row-shards the upload; a single chip just device_puts and has
        no routing bucket)."""
        del max_ins_ref, owners
        from risingwave_tpu.utils.ledger import note_backlog
        note_backlog("hash_join", total)
        return (jaxtools.upload(up, kernel="hash_join"),
                jaxtools.upload(aux, kernel="hash_join"), None)

    def apply_epoch(self, up_dev, aux_dev, n_rows: int,
                    max_ins_ref: int, prelude=None,
                    prelude_key: str = "", bucket=None) -> None:
        """Apply a whole epoch's concatenated inserts/tombstones (and
        their payload lanes) in one dispatch. ``up_dev`` is the
        [key_lanes | payload_lanes] upload matrix — or, with a fused
        input ``prelude``, the raw int64 chunk matrix the prelude
        turns into that layout in-trace. aux layout AUX_*. The up/aux
        device arrays are shared with probe_epoch — upload once."""
        if max_ins_ref >= 0:
            self.reserve_rows(max_ins_ref)
        self.table.reserve(n_rows)
        jit = _epoch_apply_jit if prelude is None else \
            self._epoch_jits(prelude, prelude_key)[0]
        self.table.state, self.chains, self.pay, ins = jit(
            self.table.state, self.chains, self.pay, up_dev, aux_dev,
            self.key_width)
        self.table._counters.push(ins, n_rows)

    def probe_epoch(self, up_dev, aux_dev, with_degrees: bool,
                    sink: "JoinSideKernel" = None, prelude=None,
                    prelude_key: str = "",
                    bucket=None) -> "PendingEpochProbe":
        """Probe a whole epoch's rows against THIS side, each row at
        its aux sequence; call after both sides' apply_epoch. With
        degrees, ``sink`` is the PROBING side's kernel: this side's
        degree transitions and the sink's inserted-row initial degrees
        both update on device in this dispatch (installed at collect —
        see PendingEpochProbe). ``prelude`` is the PROBING side's
        fused-input prelude (the uploaded rows are that side's raw
        matrix)."""
        out_cap = self._probe_cap
        sink = sink if sink is not None else self
        probe_jit = _epoch_probe_jit if prelude is None else \
            self._epoch_jits(prelude, prelude_key)[1]
        # capture the degree arrays at ENTRY: an overflow redispatch
        # must recompute from the same pre-probe state (the truncated
        # first dispatch's adds are discarded wholesale)
        deg0_self, deg0_sink = self.deg, sink.deg

        def dispatch(cap):
            out = probe_jit(
                self.table.state, self.chains, self.pay, deg0_self,
                deg0_sink, up_dev, aux_dev, self.key_width, cap,
                with_degrees)
            return out if with_degrees else (out, None, None)

        def install(d_self, d_sink):
            self.deg = d_self
            sink.deg = d_sink

        def bump(cap):
            self._probe_cap = max(self._probe_cap, cap)

        with LEDGER.phase("device_compute", kernel="hash_join"):
            mat, d_self, d_sink = dispatch(out_cap)
        jaxtools.start_fetch(mat)

        def redispatch(cap):
            m, ds, dk = dispatch(cap)
            pending.set_degs(ds, dk)
            return m

        pending = PendingEpochProbe(
            mat, int(up_dev.shape[0]), out_cap, redispatch,
            pay_width=self.payload_width, with_degrees=with_degrees,
            install=install, bump=bump)
        if with_degrees:
            pending.set_degs(d_self, d_sink)
        return pending

    # -- degrees (device-resident; recovery/reload writes) ---------------
    def write_degrees(self, refs: np.ndarray, vals: np.ndarray) -> None:
        """Scatter exact degree values (recovery / cold-tier reload:
        the degree of a stored row is a pure function of both sides'
        state, recomputed by one batch probe of the other side)."""
        if len(refs) == 0:
            return
        self.deg = _masked_scatter_jit(
            self.deg, *_pad_scatter_args(refs, vals))

    def read_degrees(self, refs: np.ndarray) -> np.ndarray:
        """Degree values by ref (host fetch; compaction-only path)."""
        return np.asarray(self.deg)[refs].astype(np.int64)

    def probe(self, key_lanes: jnp.ndarray, vis: jnp.ndarray,
              seq: Optional[int] = None
              ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Synchronous submit+collect (tests, recovery)."""
        return self.probe_submit(key_lanes, vis, seq).collect()

    def rebase_seq(self) -> None:
        """Reset every finite ins/del sequence to 0 (a safe point with
        no probes in flight) so the int32 message counter can restart
        instead of wrapping."""
        self.chains = _rebase_jit(self.chains)

    # -- recovery ---------------------------------------------------------
    def rebuild(self, key_lanes: np.ndarray, row_refs: np.ndarray,
                payload: Optional[np.ndarray] = None) -> None:
        """Reload all live rows (recovery): one batched insert.
        ``payload`` (int32[n, payload_width]) rebuilds the device
        payload lanes exactly where the chains rebuild; degrees reset
        to zero and are recomputed by the caller's batch probe."""
        n = len(row_refs)
        key_cap = max(self.table.capacity,
                      ht.MIN_CAPACITY if n == 0 else
                      1 << int(np.ceil(np.log2(max(n / ht.MAX_LOAD, 1)))))
        row_cap = max(self.row_capacity,
                      1 << int(np.ceil(np.log2(max(n + 1, 2)))))
        self.table = ht.DeviceHashTable(self.key_width, key_cap)
        self.table.on_grow(self._on_table_grow)
        self.chains = ChainState(
            head=jnp.full(self.table.capacity, -1, dtype=jnp.int32),
            next=jnp.full(row_cap, -1, dtype=jnp.int32),
            ins_seq=jnp.full(row_cap, I32_MAX, dtype=jnp.int32),
            del_seq=jnp.full(row_cap, I32_MAX, dtype=jnp.int32))
        self.pay = jnp.zeros((row_cap, self.payload_width),
                             dtype=jnp.int32)
        self.deg = jnp.zeros(row_cap, dtype=jnp.int32)
        if n == 0:
            return
        self.insert(jnp.asarray(key_lanes), row_refs,
                    jnp.ones(n, dtype=bool), seq=0)
        if payload is not None and self.payload_width:
            self.pay = _masked_scatter_jit(
                self.pay, *_pad_scatter_args(row_refs, payload))
