"""HBM-resident open-addressing hash table with whole-batch jitted kernels.

Reference parity: the *role* of src/common/src/hash/key.rs (HashKey) plus
the in-memory halves of JoinHashMap (src/stream/src/executor/managed_state/
join/mod.rs:228) and the hash_agg group map (hash_agg.rs:67). The design is
NOT a port: the reference probes a CPU hashbrown map row by row; here the
whole chunk probes in parallel as one XLA computation.

Design (TPU-first):

- State is a pair of device arrays: ``keys: int64[cap, K]`` and
  ``occ: bool[cap]``. Capacity is a power of two; the jit cache is keyed by
  (cap, K, N) so growth or a new chunk bucket compiles once and is cached.
- ``probe_insert`` finds-or-inserts a whole batch in one call. Collisions
  *within* the batch (several rows landing on one empty slot) are resolved
  with a claim round: an int32 scatter-min elects one winner per slot, the
  winner writes its key, and every loser re-checks for a key match before
  advancing — so duplicate keys in one batch converge on one slot.
- Linear probing, stride 1: probe chains stay contiguous in HBM which is
  exactly what the vector units want; the host wrapper keeps load factor
  under ``MAX_LOAD`` so chains stay short.
- Deletion is logical (the aggregation layer zeroes its per-group counts);
  slots are reclaimed on growth rehash. Tombstone-free probing keeps the
  kernel branchless.
- All functions are pure: they take and return ``TableState``. The host
  wrapper ``DeviceHashTable`` owns growth scheduling with a *sync-free*
  occupancy upper bound (exact count is only synced at barriers, mirroring
  the "no host round-trip inside the hot loop" rule).

Keys are **int32 lanes** — the TPU has no native int64, and emulated
64-bit scatters are ~1000x slower (see ops/lanes.py). Callers map key
columns to lanes: 64-bit values split bijectively into (hi, lo) int32
pairs (lanes.split_i64); narrower ints cast; varchar keys hash on the host
(common/hash.py:hash_strings_host) and feed the hash lane — equality on the
lane is then *hash* equality, which is the same contract the reference's
``HashKey`` serialization provides for its Key8..Key256 fast paths.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.hash import hash_columns
from risingwave_tpu.utils import jaxtools

MAX_LOAD = 0.70          # grow when occupancy upper bound crosses this
MIN_CAPACITY = 1 << 10


class TableState(NamedTuple):
    """Functional hash-table state (all device arrays)."""

    keys: jnp.ndarray    # int32[cap, K]
    occ: jnp.ndarray     # bool[cap]

    @property
    def capacity(self) -> int:
        return int(self.keys.shape[0])

    @property
    def key_width(self) -> int:
        return int(self.keys.shape[1])


def make_state(capacity: int, key_width: int) -> TableState:
    assert capacity & (capacity - 1) == 0, "capacity must be a power of two"
    return TableState(
        keys=jnp.zeros((capacity, key_width), dtype=jnp.int32),
        occ=jnp.zeros((capacity,), dtype=bool),
    )


def hash_key_lanes(batch_keys: jnp.ndarray) -> jnp.ndarray:
    """uint32[N] hash of int32[N, K] key lanes (shared with dispatch)."""
    cols = [batch_keys[:, i] for i in range(batch_keys.shape[1])]
    return hash_columns(cols)


def _match_at(keys: jnp.ndarray, occ: jnp.ndarray, slot: jnp.ndarray,
              batch_keys: jnp.ndarray) -> jnp.ndarray:
    return occ[slot] & jnp.all(keys[slot] == batch_keys, axis=1)


def probe_insert(state: TableState, batch_keys: jnp.ndarray,
                 valid: jnp.ndarray
                 ) -> Tuple[TableState, jnp.ndarray, jnp.ndarray]:
    """Find-or-insert every valid row of the batch.

    Returns (new_state, slots int32[N], n_inserted int32). Rows with
    ``valid=False`` get slot -1 and do not touch the table. The caller must
    guarantee a free slot exists for every valid row (load-factor contract
    enforced by DeviceHashTable) — under that contract the loop terminates
    before ``cap`` steps.
    """
    assert batch_keys.dtype == jnp.int32, \
        "keys must be int32 lanes (lanes.split_i64 for 64-bit values)"
    cap = state.capacity
    mask = jnp.int32(cap - 1)
    n = batch_keys.shape[0]
    row_ids = jnp.arange(n, dtype=jnp.int32)
    slot0 = (hash_key_lanes(batch_keys).astype(jnp.int32)) & mask

    def cond(carry):
        _slot, done, _keys, _occ, steps, _ins = carry
        return (~jnp.all(done)) & (steps < cap)

    def body(carry):
        slot, done, keys, occ, steps, ins = carry
        # 1) key already present (from the table or an earlier iteration)?
        done = done | _match_at(keys, occ, slot, batch_keys)
        # 2) claim round for empty slots: scatter-min elects one winner.
        want = ~done & ~occ[slot]
        claim_idx = jnp.where(want, slot, cap)  # cap = out-of-bounds, dropped
        claim = jnp.full((cap,), n, dtype=jnp.int32) \
            .at[claim_idx].min(row_ids, mode="drop")
        won = want & (claim[slot] == row_ids)
        scat = jnp.where(won, slot, cap)
        keys = keys.at[scat].set(batch_keys, mode="drop")
        occ = occ.at[scat].set(True, mode="drop")
        ins = ins + jnp.sum(won, dtype=jnp.int32)
        # 3) re-check: winners match their own write; a loser whose key was
        #    just written by its winner matches too (no duplicate chains).
        done = done | _match_at(keys, occ, slot, batch_keys)
        slot = jnp.where(done, slot, (slot + 1) & mask)
        return slot, done, keys, occ, steps + 1, ins

    init = (slot0, ~valid, state.keys, state.occ, jnp.int32(0), jnp.int32(0))
    slot, done, keys, occ, _steps, ins = jax.lax.while_loop(cond, body, init)
    slots = jnp.where(valid, slot, jnp.int32(-1))
    return TableState(keys, occ), slots, ins


def lookup(state: TableState, batch_keys: jnp.ndarray,
           valid: jnp.ndarray) -> jnp.ndarray:
    """Slots of existing keys; -1 for absent/invalid rows. Read-only."""
    assert batch_keys.dtype == jnp.int32, \
        "keys must be int32 lanes (lanes.split_i64 for 64-bit values)"
    cap = state.capacity
    mask = jnp.int32(cap - 1)
    slot0 = (hash_key_lanes(batch_keys).astype(jnp.int32)) & mask
    found0 = jnp.zeros(batch_keys.shape[0], dtype=bool)

    def cond(carry):
        _slot, done, _found, steps = carry
        return (~jnp.all(done)) & (steps < cap)

    def body(carry):
        slot, done, found, steps = carry
        m = _match_at(state.keys, state.occ, slot, batch_keys)
        empty = ~state.occ[slot]
        found = found | (~done & m)
        done = done | m | empty          # empty slot ⇒ key absent
        slot = jnp.where(done, slot, (slot + 1) & mask)
        return slot, done, found, steps + 1

    init = (slot0, ~valid, found0, jnp.int32(0))
    slot, _done, found, _steps = jax.lax.while_loop(cond, body, init)
    return jnp.where(valid & found, slot, jnp.int32(-1))


_probe_insert_jit = jaxtools.instrumented_jit(
    probe_insert, "hash_table.probe_insert", donate_argnums=(0,))
_lookup_jit = jaxtools.instrumented_jit(lookup, "hash_table.lookup")


class DeviceHashTable:
    """Host wrapper: owns growth scheduling and the sync-free load bound.

    ``probe_insert`` never syncs; occupancy is tracked as an upper bound
    (each batch can insert at most its row count). ``sync_count()`` — called
    at barriers, where a device round-trip is already happening — collapses
    the bound to the true count.
    """

    def __init__(self, key_width: int, capacity: int = MIN_CAPACITY):
        self.state = make_state(max(capacity, MIN_CAPACITY), key_width)
        self._counters = jaxtools.PendingCounters()

    @property
    def capacity(self) -> int:
        return self.state.capacity

    def _count_upper_bound(self) -> int:
        return self._counters.bound()

    def probe_insert(self, batch_keys: jnp.ndarray,
                     valid: jnp.ndarray) -> jnp.ndarray:
        n = int(batch_keys.shape[0])
        self.reserve(n)
        self.state, slots, ins = _probe_insert_jit(
            self.state, batch_keys, valid)
        self._counters.push(ins, n)
        return slots

    def lookup(self, batch_keys: jnp.ndarray,
               valid: jnp.ndarray) -> jnp.ndarray:
        return _lookup_jit(self.state, batch_keys, valid)

    def reserve(self, n: int) -> bool:
        """Grow (rehash) until `n` more insertions respect MAX_LOAD.

        Returns True if a rehash happened (slots from before are invalid —
        callers that cache slots must subscribe via on_grow).
        """
        grew = False
        self._counters.drain_ready()
        while self._count_upper_bound() + n > MAX_LOAD * self.capacity:
            if self._counters.pending_rows():
                self.sync_count()      # bound too loose? sync before paying
                if self._count_upper_bound() + n <= MAX_LOAD * self.capacity:
                    break              # for a rehash we may not need
            self._grow()
            grew = True
        return grew

    def _grow(self) -> None:
        old = self.state
        new = make_state(old.capacity * 2, old.key_width)
        # Rehash: one batched probe_insert of every occupied slot.
        occ = old.occ
        new, slots, ins = _probe_insert_jit(new, old.keys, occ)
        self.state = new
        for hook in getattr(self, "_on_grow", []):
            hook(slots, old.capacity)

    def on_grow(self, hook) -> None:
        """Register `hook(old_to_new_slots, old_capacity)` called on rehash."""
        if not hasattr(self, "_on_grow"):
            self._on_grow = []
        self._on_grow.append(hook)

    def sync_count(self) -> int:
        """Collapse the occupancy bound to the exact device count (syncs;
        the DMAs were started at dispatch, so the wait is short)."""
        return self._counters.drain_all()
