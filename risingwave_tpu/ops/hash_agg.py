"""Device-resident grouped aggregation state (the q7 kernel).

Reference parity: src/stream/src/executor/hash_agg.rs:67 (executor state),
:329 (``apply_chunk``), :445 (``flush_data``); value-state accumulators
src/stream/src/executor/aggregation/agg_group.rs. Re-designed TPU-first:
the reference updates one `AggGroup` at a time through a hashbrown map —
here the entire chunk is one XLA step: batch probe-insert into the HBM
table, then scatter-add / scatter-max the per-row contributions into
accumulator arrays. Python cost per chunk is O(1).

State layout (all device arrays, slot-indexed, functional updates):

    keys        int64[cap, K]   group-key lanes        (hash_table)
    occ         bool[cap]                              (hash_table)
    group_rows  int64[cap]      net row count (Σ signs) — group liveness
    accs        flat per-call   COUNT: cnt  |  SUM: acc, nn  |  MIN/MAX:
                                ext, nn   (nn = non-null input count)
    dirty       bool[cap]       touched since last barrier flush
    emitted_*   snapshot of (group_rows, *accs) as of the last flush — the
                exact physical row persisted in the value StateTable, so
                the barrier flush derives Insert/Update/Delete and the old
                row for the state-table write with zero host-side maps.

Retraction rules (Op sign semantics, stream_chunk.rs):
  COUNT/SUM are sign-linear — scatter-add of ``sign * x``.
  MIN/MAX are not invertible: supported on device for *append-only* input
  (scatter-max/min); with retractions the executor layers the reference's
  materialized-input strategy (aggregation/minput.rs) on top — deletes
  force a recompute of affected groups at flush.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import next_pow2
from risingwave_tpu.ops import hash_table as ht


class AggKind(enum.Enum):
    COUNT = "count"        # count(col) or count(*) when input is None
    SUM = "sum"
    MIN = "min"
    MAX = "max"


@dataclass(frozen=True)
class AggSpec:
    """One aggregate call, physical view (numpy dtypes)."""

    kind: AggKind
    in_dtype: Optional[np.dtype] = None   # None ⇒ count(*)

    @property
    def out_dtype(self) -> np.dtype:
        if self.kind == AggKind.COUNT:
            return np.dtype(np.int64)
        assert self.in_dtype is not None
        if self.kind == AggKind.SUM:
            if np.issubdtype(self.in_dtype, np.floating):
                return np.dtype(np.float64)
            return np.dtype(np.int64)     # ints + scaled DECIMAL
        return np.dtype(self.in_dtype)    # MIN/MAX

    @property
    def n_accs(self) -> int:
        return 1 if self.kind == AggKind.COUNT else 2


def _extreme(dtype: np.dtype, kind: AggKind):
    """Identity element for scatter-max/min in `dtype`."""
    if np.issubdtype(dtype, np.floating):
        return -np.inf if kind == AggKind.MAX else np.inf
    info = np.iinfo(dtype)
    return info.min if kind == AggKind.MAX else info.max


def acc_dtypes(specs: Sequence[AggSpec]) -> List[np.dtype]:
    """Flat accumulator dtypes (the physical value-state row layout
    after group keys and group_rows)."""
    out: List[np.dtype] = []
    for s in specs:
        if s.kind == AggKind.COUNT:
            out.append(np.dtype(np.int64))
        else:
            out.extend([s.out_dtype, np.dtype(np.int64)])
    return out


def acc_fills(specs: Sequence[AggSpec]) -> List:
    fills: List = []
    for s in specs:
        if s.kind == AggKind.COUNT:
            fills.append(0)
        elif s.kind == AggKind.SUM:
            fills.extend([0, 0])
        else:
            fills.extend([_extreme(s.in_dtype, s.kind), 0])
    return fills


def split_outputs(specs: Sequence[AggSpec], accs: Sequence
                  ) -> Tuple[List, List]:
    """Flat acc columns → per-call (out_value, is_null) — works on both
    device arrays (jit-traced) and host numpy slices."""
    xp = jnp if isinstance(accs[0], (jax.Array, jax.core.Tracer)) else np
    outs, nulls = [], []
    j = 0
    for s in specs:
        if s.kind == AggKind.COUNT:
            outs.append(accs[j])
            nulls.append(xp.zeros(accs[j].shape[0], dtype=bool))
            j += 1
        else:
            outs.append(accs[j])
            nulls.append(accs[j + 1] == 0)
            j += 2
    return outs, nulls


class AggState(NamedTuple):
    """Functional device state for one grouped-agg operator."""

    table: ht.TableState
    group_rows: jnp.ndarray            # int64[cap]
    dirty: jnp.ndarray                 # bool[cap]
    accs: Tuple[jnp.ndarray, ...]      # flat accumulators (acc_dtypes)
    emitted_valid: jnp.ndarray         # bool[cap] — group was live at flush
    emitted_rows: jnp.ndarray          # int64[cap] — snapshot group_rows
    emitted_accs: Tuple[jnp.ndarray, ...]   # snapshot accs


def make_agg_state(capacity: int, key_width: int,
                   specs: Sequence[AggSpec]) -> AggState:
    dts, fills = acc_dtypes(specs), acc_fills(specs)
    accs = tuple(jnp.full(capacity, f, dtype=dt)
                 for dt, f in zip(dts, fills))
    return AggState(
        table=ht.make_state(capacity, key_width),
        group_rows=jnp.zeros(capacity, dtype=jnp.int64),
        dirty=jnp.zeros(capacity, dtype=bool),
        accs=accs,
        emitted_valid=jnp.zeros(capacity, dtype=bool),
        emitted_rows=jnp.zeros(capacity, dtype=jnp.int64),
        emitted_accs=tuple(jnp.full(capacity, f, dtype=dt)
                           for dt, f in zip(dts, fills)),
    )


def build_apply(specs: Sequence[AggSpec]):
    """Compile the per-chunk step for a fixed agg plan.

    step(state, key_lanes[N,K], signs[N] int32, vis[N] bool,
         inputs: tuple per non-count(*) call of (values[N], valid[N]))
    → (state, n_inserted). jit-cached per (cap, N).
    """
    specs = tuple(specs)

    def step(state: AggState, key_lanes, signs, vis, inputs):
        cap = state.table.capacity
        table, slots, ins = ht.probe_insert(state.table, key_lanes, vis)
        scat = jnp.where(vis, slots, cap)   # invisible rows dropped
        s64 = signs.astype(jnp.int64)
        group_rows = state.group_rows.at[scat].add(s64, mode="drop")
        dirty = state.dirty.at[scat].set(True, mode="drop")
        accs = list(state.accs)
        j = 0       # flat acc cursor
        k = 0       # inputs cursor
        for spec in specs:
            if spec.kind == AggKind.COUNT and spec.in_dtype is None:
                accs[j] = accs[j].at[scat].add(s64, mode="drop")
                j += 1
                continue
            vals, val_ok = inputs[k]
            k += 1
            live = vis & val_ok
            live_scat = jnp.where(live, slots, cap)
            if spec.kind == AggKind.COUNT:
                accs[j] = accs[j].at[live_scat].add(s64, mode="drop")
                j += 1
            elif spec.kind == AggKind.SUM:
                contrib = vals.astype(accs[j].dtype) * \
                    s64.astype(accs[j].dtype)
                accs[j] = accs[j].at[live_scat].add(contrib, mode="drop")
                accs[j + 1] = accs[j + 1].at[live_scat].add(s64, mode="drop")
                j += 2
            else:   # MIN/MAX — device path covers inserts (sign > 0)
                ins_scat = jnp.where(live & (s64 > 0), slots, cap)
                v = vals.astype(accs[j].dtype)
                if spec.kind == AggKind.MAX:
                    accs[j] = accs[j].at[ins_scat].max(v, mode="drop")
                else:
                    accs[j] = accs[j].at[ins_scat].min(v, mode="drop")
                accs[j + 1] = accs[j + 1].at[live_scat].add(s64, mode="drop")
                j += 2
        return AggState(table, group_rows, dirty, tuple(accs),
                        state.emitted_valid, state.emitted_rows,
                        state.emitted_accs), ins

    return jax.jit(step, donate_argnums=(0,))


def build_flush(specs: Sequence[AggSpec]):
    """Compile the barrier-flush gather/advance pair.

    gather(state, idx[P]) → host-bound bundle for (padded) dirty slots.
    advance(state, idx[P], live[P]) → emitted := current, dirty cleared.
    """

    @jax.jit
    def gather(state: AggState, idx):
        safe = jnp.minimum(idx, state.table.capacity - 1)
        return (
            state.table.keys[safe],
            state.group_rows[safe],
            tuple(a[safe] for a in state.accs),
            state.emitted_valid[safe],
            state.emitted_rows[safe],
            tuple(a[safe] for a in state.emitted_accs),
        )

    @jax.jit
    def advance(state: AggState, idx, live):
        cap = state.table.capacity
        safe = jnp.minimum(idx, cap - 1)
        scat = jnp.where(live, idx, cap)
        ev = state.emitted_valid.at[scat].set(
            state.group_rows[safe] > 0, mode="drop")
        er = state.emitted_rows.at[scat].set(
            state.group_rows[safe], mode="drop")
        ea = tuple(e.at[scat].set(a[safe], mode="drop")
                   for e, a in zip(state.emitted_accs, state.accs))
        return AggState(state.table, state.group_rows,
                        jnp.zeros_like(state.dirty), state.accs,
                        ev, er, ea)

    return gather, advance


def build_patch(specs: Sequence[AggSpec]):
    """Compile the host→device acc patch (retractable MIN/MAX recompute
    writes corrected extremes back before the snapshot advances)."""

    @jax.jit
    def patch(state: AggState, idx, new_accs):
        cap = state.table.capacity
        accs = tuple(a.at[jnp.minimum(idx, cap)].set(v, mode="drop")
                     for a, v in zip(state.accs, new_accs))
        return state._replace(accs=accs)

    return patch


def remap_slots(arr: jnp.ndarray, old_to_new: jnp.ndarray,
                new_cap: int, fill) -> jnp.ndarray:
    """Re-scatter a slot-indexed array after a table rehash.

    `old_to_new[i]` is the new slot of old slot i (-1 for unoccupied)."""
    if arr.dtype == jnp.bool_:
        init = jnp.full(new_cap, bool(fill), dtype=arr.dtype)
    else:
        init = jnp.full(new_cap, fill, dtype=arr.dtype)
    safe = jnp.where(old_to_new >= 0, old_to_new, new_cap)
    return init.at[safe].set(arr, mode="drop")


_remap_jit = jax.jit(remap_slots, static_argnums=(2, 3))


@dataclass
class FlushResult:
    """Host view of the dirty groups at a barrier (pre-advance)."""

    n: int
    keys: np.ndarray                 # int64[n, K]
    group_rows: np.ndarray           # int64[n] — current
    accs: List[np.ndarray]           # flat acc columns, current
    was_emitted: np.ndarray          # bool[n]
    prev_rows: np.ndarray            # int64[n] — at last flush
    prev_accs: List[np.ndarray]      # flat acc columns at last flush

    @staticmethod
    def empty(specs: Sequence[AggSpec], key_width: int) -> "FlushResult":
        dts = acc_dtypes(specs)
        return FlushResult(
            0, np.zeros((0, key_width), dtype=np.int64),
            np.zeros(0, dtype=np.int64),
            [np.zeros(0, dtype=dt) for dt in dts],
            np.zeros(0, dtype=bool),
            np.zeros(0, dtype=np.int64),
            [np.zeros(0, dtype=dt) for dt in dts])


class GroupedAggKernel:
    """Host wrapper: growth scheduling, flush bookkeeping, jit caches.

    The executor drives it: ``apply`` per chunk (no device syncs),
    ``flush`` per barrier (one gather round-trip), ``rebuild`` on recovery.
    """

    def __init__(self, key_width: int, specs: Sequence[AggSpec],
                 capacity: int = ht.MIN_CAPACITY):
        capacity = max(next_pow2(capacity), ht.MIN_CAPACITY)
        self.specs = tuple(specs)
        self.key_width = key_width
        self.state = make_agg_state(capacity, key_width, self.specs)
        self._apply = build_apply(self.specs)
        self._gather, self._advance = build_flush(self.specs)
        self._patch = build_patch(self.specs)
        self._count_exact = 0
        self._pending_rows = 0
        self._pending_counters: List[jnp.ndarray] = []
        # idx of the in-progress flush (set by flush, used by patch/advance)
        self._flush_idx: Optional[np.ndarray] = None

    @property
    def capacity(self) -> int:
        return self.state.table.capacity

    # -- hot path -------------------------------------------------------
    def apply(self, key_lanes: jnp.ndarray, signs: jnp.ndarray,
              vis: jnp.ndarray, inputs: Tuple) -> None:
        n = int(key_lanes.shape[0])
        self._reserve(n)
        self.state, ins = self._apply(self.state, key_lanes, signs, vis,
                                      inputs)
        self._pending_counters.append(ins)
        self._pending_rows += n

    # -- growth ---------------------------------------------------------
    def _reserve(self, n: int) -> None:
        while (self._count_exact + self._pending_rows + n
               > ht.MAX_LOAD * self.capacity):
            if self._pending_counters:
                self._sync_count()   # bound may be loose — sync first
                continue
            self._grow()

    def _sync_count(self) -> None:
        for c in self._pending_counters:
            self._count_exact += int(c)
        self._pending_counters = []
        self._pending_rows = 0

    def _grow(self) -> None:
        """Rehash into a doubled table, reclaiming dead groups.

        A slot is live iff its group has rows OR a flush hasn't retired it
        yet (dirty / still-emitted) — tumbling-window churn leaves fully
        retracted groups behind, and carrying them forever would grow the
        table without bound."""
        old = self.state
        new_cap = old.table.capacity * 2
        new_table = ht.make_state(new_cap, self.key_width)
        live = old.table.occ & ((old.group_rows != 0) | old.dirty
                                | old.emitted_valid)
        new_table, old_to_new, n_live = ht.probe_insert(
            new_table, old.table.keys, live)
        fills = acc_fills(self.specs)
        self.state = AggState(
            table=new_table,
            group_rows=_remap_jit(old.group_rows, old_to_new, new_cap, 0),
            dirty=_remap_jit(old.dirty, old_to_new, new_cap, 0),
            accs=tuple(_remap_jit(a, old_to_new, new_cap, f)
                       for a, f in zip(old.accs, fills)),
            emitted_valid=_remap_jit(old.emitted_valid, old_to_new,
                                     new_cap, 0),
            emitted_rows=_remap_jit(old.emitted_rows, old_to_new,
                                    new_cap, 0),
            emitted_accs=tuple(_remap_jit(a, old_to_new, new_cap, f)
                               for a, f in zip(old.emitted_accs, fills)),
        )
        # occupancy accounting restarts from the live population
        self._count_exact = int(n_live)
        assert not self._pending_counters, "grow with unsynced counters"

    # -- barrier flush ---------------------------------------------------
    def flush(self) -> FlushResult:
        """Gather dirty groups to host. Call ``advance`` after consuming
        (optionally ``patch``-ing corrected accs in between)."""
        self._sync_count()
        dirty = np.asarray(self.state.dirty)
        idx = np.flatnonzero(dirty).astype(np.int32)
        p = len(idx)
        self._flush_idx = idx
        if p == 0:
            return FlushResult.empty(self.specs, self.key_width)
        pad = next_pow2(p)
        idx_padded = np.full(pad, self.capacity, dtype=np.int32)
        idx_padded[:p] = idx
        bundle = self._gather(self.state, jnp.asarray(idx_padded))
        keys, rows, accs, was, prows, paccs = jax.device_get(bundle)
        return FlushResult(
            n=p, keys=keys[:p], group_rows=rows[:p],
            accs=[a[:p] for a in accs], was_emitted=was[:p],
            prev_rows=prows[:p], prev_accs=[a[:p] for a in paccs])

    def patch_accs(self, accs: List[np.ndarray]) -> None:
        """Overwrite the flushed groups' accumulators (minput recompute)."""
        idx = self._flush_idx
        assert idx is not None and len(idx) > 0
        pad = next_pow2(len(idx))
        idx_padded = np.full(pad, self.capacity, dtype=np.int32)
        idx_padded[:len(idx)] = idx
        padded = tuple(
            np.concatenate([a, np.zeros(pad - len(idx), dtype=a.dtype)])
        for a in accs)
        self.state = self._patch(self.state, jnp.asarray(idx_padded),
                                 padded)

    def advance(self) -> None:
        """Snapshot emitted := current for flushed groups; clear dirty."""
        idx = self._flush_idx
        assert idx is not None, "flush() first"
        self._flush_idx = None
        if len(idx) == 0:
            return
        pad = next_pow2(len(idx))
        idx_padded = np.full(pad, self.capacity, dtype=np.int32)
        idx_padded[:len(idx)] = idx
        live = np.zeros(pad, dtype=bool)
        live[:len(idx)] = True
        self.state = self._advance(self.state, jnp.asarray(idx_padded),
                                   jnp.asarray(live))

    # -- recovery ---------------------------------------------------------
    def rebuild(self, keys: np.ndarray, group_rows: np.ndarray,
                acc_cols: Sequence[np.ndarray]) -> None:
        """Reload from committed value-state rows (boot/recovery).

        Restored groups are marked emitted — their outputs were committed
        downstream before the recovery epoch.
        """
        n = len(group_rows)
        cap = max(self.capacity, next_pow2(int(n / ht.MAX_LOAD) + 1))
        self.state = make_agg_state(cap, self.key_width, self.specs)
        self._count_exact = n
        self._pending_rows = 0
        self._pending_counters = []
        if n == 0:
            return
        table, slots, _ = ht.probe_insert(
            self.state.table, jnp.asarray(keys), jnp.ones(n, dtype=bool))
        accs = tuple(a.at[slots].set(jnp.asarray(col))
                     for a, col in zip(self.state.accs, acc_cols))
        rows_dev = self.state.group_rows.at[slots].set(
            jnp.asarray(group_rows))
        self.state = AggState(
            table=table, group_rows=rows_dev, dirty=self.state.dirty,
            accs=accs,
            emitted_valid=self.state.emitted_valid.at[slots].set(True),
            # distinct buffers: the apply step donates the state, and a
            # buffer may be donated at most once per call
            emitted_rows=jnp.copy(rows_dev),
            emitted_accs=tuple(jnp.copy(a) for a in accs),
        )
