"""Device-resident grouped aggregation state (the q7 kernel).

Reference parity: src/stream/src/executor/hash_agg.rs:67 (executor state),
:329 (``apply_chunk``), :445 (``flush_data``); value-state accumulators
src/stream/src/executor/aggregation/agg_group.rs. Re-designed TPU-first:
the reference updates one `AggGroup` at a time through a hashbrown map —
here the entire chunk is one XLA step: batch probe-insert into the HBM
table, then scatter the per-row contributions into accumulator arrays.
Python cost per chunk is O(1).

Everything on device is **int32/float32** (see ops/lanes.py — emulated
64-bit scatter on TPU is ~1000x slower than native int32):

    keys        int32[cap, K]   group-key lanes        (hash_table)
    occ         bool[cap]                              (hash_table)
    group_rows  int32[cap]      net row count (Σ signs) — group liveness
                                (int32 bound: 2^31 rows PER GROUP; the
                                 flush guards against wraparound)
    accs        per call:       COUNT → [cnt i32]
                                SUM(int) → [4 limb i32] + nn   (exact)
                                SUM(float) → [hi f32, lo f32] + nn
                                  (paired-f32: per-value residual kept in
                                   lo, but cross-chunk accumulation is
                                   f32 — large/cancellation-heavy float
                                   sums lose precision vs the reference's
                                   f64 accumulator. DECIMAL/int money
                                   sums use the exact limb path; an exact
                                   float superaccumulator is backlogged.)
                                MIN/MAX → [hi i32, lo i32] + nn
    dirty       bool[cap]       touched since last barrier flush
    emitted_*   device snapshot of (group_rows, accs) at last flush — the
                flush derives Insert/Update/Delete and the old state row
                with zero host-side group maps.

Retraction rules (Op sign semantics, stream_chunk.rs):
  COUNT/SUM are sign-linear — limb scatter-adds of ``sign * x``.
  MIN/MAX are not invertible: supported on device for *append-only* input
  (two-pass lexicographic scatter-max on order lanes); with retractions
  the executor layers the reference's materialized-input strategy
  (aggregation/minput.rs) on top.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from functools import partial
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import next_pow2
from risingwave_tpu.ops import hash_table as ht
from risingwave_tpu.ops import lanes
from risingwave_tpu.utils import jaxtools, spans
from risingwave_tpu.utils.ledger import LEDGER

I32_MIN = -(1 << 31)
I32_MAX = (1 << 31) - 1


class AggKind(enum.Enum):
    COUNT = "count"        # count(col) or count(*) when input is None
    SUM = "sum"
    MIN = "min"
    MAX = "max"
    # HyperLogLog cardinality sketch (append-only; see HLL_* below)
    APPROX_COUNT_DISTINCT = "approx_count_distinct"
    # HOST-ONLY aggs (string/list outputs can never live in HBM): the
    # device keeps one dummy lane for dirty-tracking arity; outputs
    # recompute from the minput value multiset at flush
    # (expr/src/aggregate string_agg.rs / array_agg.rs parity)
    STRING_AGG = "string_agg"
    ARRAY_AGG = "array_agg"


HOST_AGG_KINDS = (AggKind.STRING_AGG, AggKind.ARRAY_AGG)


# -- HyperLogLog (approx_count_distinct) ----------------------------------
# Reference parity: src/expr/src/aggregate/approx_count_distinct/mod.rs
# :35-42 — the reference keeps 2^16 buckets; this build keeps a DENSE
# 2^16-register sketch per group (standard error 1.04/sqrt(2^16) ≈
# 0.4%) maintained host-side on the executor's host-agg path (one
# uint8 register array per group, vectorized scatter-max per chunk)
# and persisted as one BYTEA row per group. The device kernel carries
# only the dummy lane (grouping/dirtiness); a register file this wide
# does not fit the per-call scalar-accumulator layout. 2^16 registers
# matches the reference's bucket count (theirs are u64 counters —
# 512KB/group; one byte per register keeps ours at 64KB).
HLL_B = 16              # index bits
HLL_M = 1 << HLL_B      # registers (65536)
HLL_RHO_MAX = 65 - HLL_B
HLL_ALPHA = 0.7213 / (1 + 1.079 / HLL_M)   # bias constant, m >= 128


def _clz64(x: np.ndarray) -> np.ndarray:
    """Vectorized count-leading-zeros over uint64 (0 → 64)."""
    x = x.astype(np.uint64)
    n = np.full(x.shape, 64, dtype=np.int64)
    cur = x
    for s in (32, 16, 8, 4, 2, 1):
        big = cur >= (np.uint64(1) << np.uint64(s))
        n = np.where(big, n - s, n)
        cur = np.where(big, cur >> np.uint64(s), cur)
    return n - 1 * (x > 0)          # exact clz: 64-bitlen, 64 for 0


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — uniform 64-bit hash of the i64 image."""
    with np.errstate(over="ignore"):
        z = x.astype(np.uint64) + np.uint64(0x9E3779B97F4A7C15)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


def hll_lanes(v64: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """i64 value image → (register index, rho) int32 input lanes."""
    h = _mix64(v64)
    reg = (h >> np.uint64(64 - HLL_B)).astype(np.int32)
    w = (h << np.uint64(HLL_B)).astype(np.uint64)
    rho = np.where(w == 0, HLL_RHO_MAX,
                   _clz64(w) + 1).astype(np.int32)
    return reg, np.minimum(rho, HLL_RHO_MAX).astype(np.int32)


def hll_estimate_dense(mat: np.ndarray) -> np.ndarray:
    """Estimates for stacked register files: (G, HLL_M) uint8 → int64
    per group, with linear-counting small-range correction."""
    mat = np.atleast_2d(mat)
    m = float(HLL_M)
    inv = np.power(2.0, -mat.astype(np.float64)).sum(axis=1)
    zeros = (mat == 0).sum(axis=1)
    e = HLL_ALPHA * m * m / inv
    small = (e <= 2.5 * m) & (zeros > 0)
    with np.errstate(divide="ignore"):
        lin = m * np.log(np.where(zeros > 0, m / np.maximum(zeros, 1),
                                  1.0))
    return np.where(small, lin, e).round().astype(np.int64)


@dataclass(frozen=True)
class AggSpec:
    """One aggregate call, physical view (numpy dtypes)."""

    kind: AggKind
    in_dtype: Optional[np.dtype] = None   # None ⇒ count(*)

    @property
    def out_dtype(self) -> np.dtype:
        if self.kind in HOST_AGG_KINDS:
            return np.dtype(object)
        if self.kind in (AggKind.COUNT,
                         AggKind.APPROX_COUNT_DISTINCT):
            return np.dtype(np.int64)
        assert self.in_dtype is not None
        if self.kind == AggKind.SUM:
            if np.issubdtype(self.in_dtype, np.floating):
                return np.dtype(np.float64)
            return np.dtype(np.int64)     # ints + scaled DECIMAL
        return np.dtype(self.in_dtype)    # MIN/MAX

    @property
    def is_float_sum(self) -> bool:
        return (self.kind == AggKind.SUM and self.in_dtype is not None
                and np.issubdtype(self.in_dtype, np.floating))

    # device-array layout of this call's accumulators: [(dtype, fill)]
    def dev_layout(self) -> List[Tuple[np.dtype, object]]:
        i32 = np.dtype(np.int32)
        f32 = np.dtype(np.float32)
        if self.kind == AggKind.COUNT:
            return [(i32, 0)]
        if self.kind in HOST_AGG_KINDS:
            return [(i32, 0)]             # dummy lane (arity only)
        if self.kind == AggKind.APPROX_COUNT_DISTINCT:
            return [(i32, 0)]   # dummy lane: the dense sketch is host
        if self.kind == AggKind.SUM:
            if self.is_float_sum:
                return [(f32, 0.0), (f32, 0.0), (i32, 0)]
            return [(i32, 0)] * lanes.N_LIMBS + [(i32, 0)]
        fill = I32_MIN if self.kind == AggKind.MAX else I32_MAX
        return [(i32, fill), (i32, fill), (i32, 0)]

    # -- host codecs -----------------------------------------------------
    def encode_input(self, vals: np.ndarray) -> Tuple[np.ndarray, ...]:
        """Host value column → device input lanes (numpy, vectorized)."""
        if self.kind == AggKind.COUNT or self.kind in HOST_AGG_KINDS:
            return ()
        if self.kind == AggKind.APPROX_COUNT_DISTINCT:
            return ()           # sketch updates are host-side
        if self.kind == AggKind.SUM:
            if self.is_float_sum:
                hi = vals.astype(np.float32)
                lo = (vals.astype(np.float64)
                      - hi.astype(np.float64)).astype(np.float32)
                return (hi, lo)
            return lanes.sum_limbs(vals)
        return lanes.order_lanes(vals)

    def decode_acc(self, cols: Sequence[np.ndarray]
                   ) -> Tuple[np.ndarray, np.ndarray]:
        """Gathered device acc columns → (value hostarray, is_null)."""
        if self.kind == AggKind.COUNT:
            cnt = cols[0].astype(np.int64)
            assert (cnt >= 0).all(), \
                "COUNT wrapped int32 — a group exceeded 2^31 rows"
            return cnt, np.zeros(cnt.shape, dtype=bool)
        if self.kind in HOST_AGG_KINDS:
            # placeholder: the executor overwrites these from the
            # minput multiset at flush (host path)
            n = len(cols[0])
            return (np.full(n, None, dtype=object),
                    np.ones(n, dtype=bool))
        if self.kind == AggKind.APPROX_COUNT_DISTINCT:
            # placeholder: the executor overwrites from the host
            # sketch registry at flush
            n = len(cols[0])
            return np.zeros(n, dtype=np.int64), np.ones(n, dtype=bool)
        nn = cols[-1]
        assert (nn >= 0).all(), \
            "non-null count wrapped int32 — a group exceeded 2^31 rows"
        null = nn == 0
        if self.kind == AggKind.SUM:
            if self.is_float_sum:
                v = cols[0].astype(np.float64) + cols[1].astype(np.float64)
            else:
                v = lanes.merge_limbs(*cols[:-1])
            return v, null
        v = lanes.inv_order_lanes(cols[0], cols[1], self.out_dtype)
        return v, null

    # -- host (state-row) accumulator layout ------------------------------
    def host_acc_dtypes(self) -> List[np.dtype]:
        """Columns this call persists in the value-state row."""
        i64 = np.dtype(np.int64)
        if self.kind == AggKind.COUNT:
            return [i64]
        if self.kind in HOST_AGG_KINDS:
            # nothing to persist: outputs recompute from the minput
            # multiset; one placeholder keeps the row arity stable
            return [i64]
        if self.kind == AggKind.APPROX_COUNT_DISTINCT:
            # nothing to persist here: the sketch lives in its own
            # BYTEA aux table; one placeholder keeps row arity stable
            return [i64]
        return [self.out_dtype, i64]

    def host_acc_cols(self, vals: np.ndarray, nulls: np.ndarray,
                      nn: Optional[np.ndarray],
                      raw_cols: Optional[List[np.ndarray]]
                      ) -> List[list]:
        """Decoded flush columns (+ raw device accs) → per-column
        python lists for state rows, NULLs as None."""
        if self.kind == AggKind.COUNT:
            return [vals.tolist()]
        if self.kind in HOST_AGG_KINDS:
            return [[0] * len(vals)]
        if self.kind == AggKind.APPROX_COUNT_DISTINCT:
            return [[0] * len(vals)]
        value_col = [None if bad else v
                     for v, bad in zip(vals.tolist(), nulls.tolist())]
        return [value_col, nn.tolist()]

    def host_to_dev(self, host_cols: Sequence[np.ndarray]
                    ) -> Tuple[np.ndarray, ...]:
        """Recovered host acc columns → device-layout columns."""
        if self.kind == AggKind.COUNT:
            return (host_cols[0].astype(np.int32),)
        if self.kind in HOST_AGG_KINDS:
            return (host_cols[0].astype(np.int32),)   # dummy lane
        if self.kind == AggKind.APPROX_COUNT_DISTINCT:
            return (host_cols[0].astype(np.int32),)   # dummy lane
        return self.encode_acc(host_cols[0], host_cols[1])

    def encode_acc(self, value: np.ndarray, nn: Optional[np.ndarray]
                   ) -> Tuple[np.ndarray, ...]:
        """(decoded value, nn) → device acc columns (recovery path).

        NULL slots (nn == 0) re-encode as the identity fill."""
        if self.kind == AggKind.COUNT:
            return (value.astype(np.int32),)
        assert nn is not None
        nn32 = nn.astype(np.int32)
        if self.kind == AggKind.SUM:
            if self.is_float_sum:
                hi = value.astype(np.float32)
                lo = (value.astype(np.float64)
                      - hi.astype(np.float64)).astype(np.float32)
                return (hi, lo, nn32)
            return lanes.sum_limbs(value.astype(np.int64)) + (nn32,)
        hi, lo = lanes.order_lanes(
            np.asarray(value, dtype=self.out_dtype))
        fill = I32_MIN if self.kind == AggKind.MAX else I32_MAX
        dead = nn32 == 0
        hi = np.where(dead, np.int32(fill), hi).astype(np.int32)
        lo = np.where(dead, np.int32(fill), lo).astype(np.int32)
        return (hi, lo, nn32)


def encode_host_accs(specs: Sequence[AggSpec],
                     acc_cols: Sequence[np.ndarray]) -> List[np.ndarray]:
    """HOST state-row acc columns (host_acc_dtypes layout) →
    device-layout columns, for recovery rebuilds (shared by the
    single-chip and sharded kernels)."""
    out: List[np.ndarray] = []
    j = 0
    for s in specs:
        k = len(s.host_acc_dtypes())
        out.extend(s.host_to_dev(acc_cols[j:j + k]))
        j += k
    return out


def acc_dtypes(specs: Sequence[AggSpec]) -> List[np.dtype]:
    """HOST (state-row) accumulator columns, per call."""
    out: List[np.dtype] = []
    for s in specs:
        out.extend(s.host_acc_dtypes())
    return out


def dev_layout(specs: Sequence[AggSpec]) -> List[Tuple[np.dtype, object]]:
    out: List[Tuple[np.dtype, object]] = []
    for s in specs:
        out.extend(s.dev_layout())
    return out


def n_input_lanes(spec: AggSpec) -> int:
    """Device input lanes per row for this call (encode_input arity)."""
    if spec.kind == AggKind.COUNT or spec.kind in HOST_AGG_KINDS:
        return 0
    if spec.kind == AggKind.SUM:
        return 2 if spec.is_float_sum else lanes.N_LIMBS
    return 2              # MIN/MAX order lanes; HLL (register, rho)


def _call_slices(specs: Sequence[AggSpec]) -> List[slice]:
    """Flat device-acc array index range per call."""
    out, j = [], 0
    for s in specs:
        n = len(s.dev_layout())
        out.append(slice(j, j + n))
        j += n
    return out


def decode_outputs(specs: Sequence[AggSpec],
                   dev_cols: Sequence[np.ndarray]
                   ) -> Tuple[List[np.ndarray], List[np.ndarray]]:
    """Gathered device acc columns → per-call (value, is_null) host cols."""
    outs, nulls = [], []
    for s, sl in zip(specs, _call_slices(specs)):
        v, nu = s.decode_acc(dev_cols[sl])
        outs.append(v)
        nulls.append(nu)
    return outs, nulls


class AggState(NamedTuple):
    """Functional device state for one grouped-agg operator."""

    table: ht.TableState
    group_rows: jnp.ndarray            # int32[cap]
    dirty: jnp.ndarray                 # bool[cap]
    accs: Tuple[jnp.ndarray, ...]      # flat device accumulators
    emitted_valid: jnp.ndarray         # bool[cap] — live at last flush
    emitted_rows: jnp.ndarray          # int32[cap]
    emitted_accs: Tuple[jnp.ndarray, ...]


def make_agg_state(capacity: int, key_width: int,
                   specs: Sequence[AggSpec]) -> AggState:
    lay = dev_layout(specs)
    accs = tuple(jnp.full(capacity, f, dtype=dt) for dt, f in lay)
    return AggState(
        table=ht.make_state(capacity, key_width),
        group_rows=jnp.zeros(capacity, dtype=jnp.int32),
        dirty=jnp.zeros(capacity, dtype=bool),
        accs=accs,
        emitted_valid=jnp.zeros(capacity, dtype=bool),
        emitted_rows=jnp.zeros(capacity, dtype=jnp.int32),
        emitted_accs=tuple(jnp.full(capacity, f, dtype=dt)
                           for dt, f in lay),
    )


def _update_call(spec: AggSpec, accs: List[jnp.ndarray], sl: slice,
                 in_lanes, valid_ok, slots, vis, sign, cap) -> None:
    """Trace one call's accumulator updates in place (list mutation)."""
    live = vis & valid_ok
    scat = jnp.where(live, slots, cap)
    if spec.kind in HOST_AGG_KINDS:
        return                              # host path owns the value
    if spec.kind == AggKind.COUNT:
        accs[sl.start] = accs[sl.start].at[scat].add(sign, mode="drop")
        return
    if spec.kind == AggKind.APPROX_COUNT_DISTINCT:
        return          # dense sketch is host-side (see HLL_B above)
    nn_i = sl.stop - 1
    accs[nn_i] = accs[nn_i].at[scat].add(sign, mode="drop")
    if spec.kind == AggKind.SUM:
        if spec.is_float_sum:
            sf = sign.astype(jnp.float32)
            for k in range(2):
                accs[sl.start + k] = accs[sl.start + k].at[scat].add(
                    in_lanes[k] * sf, mode="drop")
        else:
            # limb scatter-adds overflow int32 past MAX_CHUNK_ROWS rows;
            # batched applies slice the batch and carry-normalize per
            # slice (static unroll — still ONE dispatched program)
            n = int(scat.shape[0])
            for lo in range(0, n, lanes.MAX_CHUNK_ROWS):
                hi = min(lo + lanes.MAX_CHUNK_ROWS, n)
                s_ = slice(lo, hi)
                for k in range(lanes.N_LIMBS):
                    accs[sl.start + k] = accs[sl.start + k] \
                        .at[scat[s_]].add(in_lanes[k][s_] * sign[s_],
                                          mode="drop")
                for k in range(lanes.N_LIMBS - 1):
                    carry = accs[sl.start + k] >> lanes.LIMB_BITS
                    accs[sl.start + k] = accs[sl.start + k] - \
                        (carry << lanes.LIMB_BITS)
                    accs[sl.start + k + 1] = accs[sl.start + k + 1] + carry
        return
    # MIN/MAX (append-only device path: sign > 0 rows only): lexicographic
    # (hi, lo) two-pass — pass 1 settles hi; pass 2 rebases lo wherever hi
    # moved (a stale lo from a smaller hi must not win) and maxes in the
    # lo of rows whose hi ties the new hi.
    is_max = spec.kind == AggKind.MAX
    ident = jnp.int32(I32_MIN if is_max else I32_MAX)
    ins = live & (sign > 0)
    iscat = jnp.where(ins, slots, cap)
    hi_i, lo_i = sl.start, sl.start + 1
    v_hi, v_lo = in_lanes
    old_hi = accs[hi_i]
    if is_max:
        new_hi = old_hi.at[iscat].max(v_hi, mode="drop")
    else:
        new_hi = old_hi.at[iscat].min(v_hi, mode="drop")
    lo_base = jnp.where(old_hi == new_hi, accs[lo_i], ident)
    lo_contrib = jnp.where(v_hi == new_hi[jnp.where(ins, slots, 0)],
                           v_lo, ident)
    lscat = jnp.where(ins, slots, cap)
    if is_max:
        new_lo = lo_base.at[lscat].max(lo_contrib, mode="drop")
    else:
        new_lo = lo_base.at[lscat].min(lo_contrib, mode="drop")
    accs[hi_i], accs[lo_i] = new_hi, new_lo


def _has_valid_col(spec: AggSpec) -> bool:
    """count(*) is the only call with no input → no non-null mask.
    count(col) has zero value lanes but still needs its valid column."""
    return spec.in_dtype is not None or spec.kind != AggKind.COUNT


def packed_layout(key_width: int, specs: Sequence[AggSpec]
                  ) -> List[Tuple[List[int], Optional[int]]]:
    """Per-call (value-lane columns, valid column | None) of the packed
    per-chunk input matrix — the ONE place the column cursor lives;
    pack_chunk, build_apply and packed_width all consume it.

    Layout: key lanes | signs | vis | per call with input: lanes + valid.
    Everything is int32 (f32 lanes travel bitcast) so the whole chunk is
    ONE host→device transfer — through a tunneled device, per-array
    transfer latency dominates, so fewer transfers beats nicer dtypes.
    """
    out: List[Tuple[List[int], Optional[int]]] = []
    c = key_width + 2
    for s in specs:
        if _has_valid_col(s):
            nl = n_input_lanes(s)
            out.append((list(range(c, c + nl)), c + nl))
            c += nl + 1
        else:
            out.append(([], None))
    return out


def packed_width(key_width: int, specs: Sequence[AggSpec]) -> int:
    lay = packed_layout(key_width, specs)
    last = key_width + 1
    for cols, vc in lay:
        for i in cols:
            last = max(last, i)
        if vc is not None:
            last = max(last, vc)
    return last + 1


def pack_chunk(key_width: int, specs: Sequence[AggSpec],
               key_lanes: np.ndarray, signs: np.ndarray, vis: np.ndarray,
               inputs: Sequence) -> np.ndarray:
    """Host-side chunk → one int32[N, W] matrix (vectorized column writes).

    `inputs` is per call (value lane arrays, valid mask); count(*) calls
    contribute no columns.
    """
    n = len(signs)
    m = np.empty((n, packed_width(key_width, specs)), dtype=np.int32)
    m[:, :key_width] = key_lanes
    m[:, key_width] = signs
    m[:, key_width + 1] = vis
    for (cols, vc), (in_lanes, valid) in zip(
            packed_layout(key_width, specs), inputs):
        for c, a in zip(cols, in_lanes):
            a = np.asarray(a)
            m[:, c] = a.view(np.int32) if a.dtype == np.float32 else a
        if vc is not None:
            m[:, vc] = np.asarray(valid)
    return m


def build_apply(key_width: int, specs: Sequence[AggSpec],
                prelude=None):
    """Compile the per-chunk step for a fixed agg plan.

    step(state, packed int32[N, W]) → (state, n_inserted int32 scalar).
    The packed matrix comes from ``pack_chunk``; jit-cached per (cap, N).
    The insert counter is the sync-free occupancy feed: the host wrapper
    fetches it asynchronously (jaxtools.fetch) so growth decisions never
    block on the device queue.

    With ``prelude`` (ops/fused.py build_agg_prelude), the step takes
    the RAW int64 chunk matrix instead and the whole fragment chain —
    filter, project, key/lane encode — inlines ahead of the accumulator
    updates: ONE jitted dataflow step per dispatch, state donated. The
    fused step additionally returns per-logical-stage visible-row
    counts (int64[n_stages]) for metrics attribution.
    """
    specs = tuple(specs)
    slices = _call_slices(specs)
    call_cols = packed_layout(key_width, specs)

    def core(state: AggState, key_lanes, s32, vis, call_inputs):
        cap = state.table.capacity
        table, slots, ins = ht.probe_insert(state.table, key_lanes, vis)
        scat = jnp.where(vis, slots, cap)   # invisible rows dropped
        group_rows = state.group_rows.at[scat].add(s32, mode="drop")
        dirty = state.dirty.at[scat].set(True, mode="drop")
        accs = list(state.accs)
        all_true = jnp.ones(key_lanes.shape[0], dtype=bool)
        for spec, sl, (in_lanes, val_ok) in zip(specs, slices,
                                                call_inputs):
            _update_call(spec, accs, sl, in_lanes,
                         all_true if val_ok is None else val_ok,
                         slots, vis, s32, cap)
        new_state = AggState(table, group_rows, dirty, tuple(accs),
                             state.emitted_valid, state.emitted_rows,
                             state.emitted_accs)
        return new_state, ins

    if prelude is not None:
        def step(state: AggState, raw):
            key_lanes, s32, vis, call_inputs, stage_rows = prelude(raw)
            new_state, ins = core(state, key_lanes, s32, vis,
                                  call_inputs)
            return new_state, ins, stage_rows

        return jaxtools.instrumented_jit(step, "hash_agg.apply_fused",
                                         donate_argnums=(0,))

    def step(state: AggState, packed):
        key_lanes = packed[:, :key_width]
        s32 = packed[:, key_width]
        vis = packed[:, key_width + 1].astype(bool)
        call_inputs = []
        for spec, (lc, vc) in zip(specs, call_cols):
            if spec.is_float_sum:
                in_lanes = tuple(jax.lax.bitcast_convert_type(
                    packed[:, i], jnp.float32) for i in lc)
            else:
                in_lanes = tuple(packed[:, i] for i in lc)
            call_inputs.append(
                (in_lanes,
                 None if vc is None else packed[:, vc].astype(bool)))
        return core(state, key_lanes, s32, vis, tuple(call_inputs))

    return jaxtools.instrumented_jit(step, "hash_agg.apply",
                                     donate_argnums=(0,))


def _col_i32(a: jnp.ndarray) -> jnp.ndarray:
    if a.dtype == jnp.float32:
        return jax.lax.bitcast_convert_type(a, jnp.int32)
    if a.dtype == jnp.bool_:
        return a.astype(jnp.int32)
    return a


def gather_packed(state: AggState, flush_cap: int) -> jnp.ndarray:
    """Traced barrier-flush gather: ONE packed device→host array.

    → int32[1 + flush_cap, W]. Row 0 is the header [n_dirty, n_groups,
    0…]; rows 1..1+n are the dirty slots: slot idx | keys | group_rows |
    accs | emitted_valid | emitted_rows | emitted accs (f32 accs
    bitcast). Dirty-slot compaction happens ON DEVICE (cumsum positions)
    so the host never fetches the dirty bitmap; the whole barrier costs
    one transfer. If n_dirty > flush_cap the host retries with a doubled
    flush_cap (header tells it so). Module-level so the sharded kernel
    can wrap it in shard_map (one gather per shard, one fetch total).
    """
    cap = state.table.capacity
    key_width = state.table.key_width
    dirty = state.dirty
    d32 = dirty.astype(jnp.int32)
    pos = jnp.cumsum(d32, dtype=jnp.int32) - 1
    n_dirty = jnp.sum(d32, dtype=jnp.int32)
    scat = jnp.where(dirty & (pos < flush_cap), pos, flush_cap)
    slot_ids = jnp.arange(cap, dtype=jnp.int32)
    idx = jnp.zeros(flush_cap, dtype=jnp.int32) \
        .at[scat].set(slot_ids, mode="drop")
    cols = [idx]
    for k in range(key_width):
        cols.append(state.table.keys[idx, k])
    cols.append(state.group_rows[idx])
    for a in state.accs:
        cols.append(_col_i32(a[idx]))
    cols.append(state.emitted_valid[idx].astype(jnp.int32))
    cols.append(state.emitted_rows[idx])
    for a in state.emitted_accs:
        cols.append(_col_i32(a[idx]))
    mat = jnp.stack(cols, axis=1)
    n_groups = jnp.sum(state.table.occ, dtype=jnp.int32)
    header = jnp.zeros((1, mat.shape[1]), dtype=jnp.int32) \
        .at[0, 0].set(n_dirty).at[0, 1].set(n_groups)
    return jnp.concatenate([header, mat], axis=0)


def build_gather_packed(key_width: int):
    del key_width   # derived from the state shape at trace time
    return jaxtools.instrumented_jit(gather_packed,
                                     "hash_agg.flush_gather",
                                     static_argnums=(1,))


def _rebuild_live(state: AggState, live: jnp.ndarray, new_cap: int,
                  fills) -> Tuple[AggState, jnp.ndarray]:
    """Traced same-or-larger-capacity rehash keeping only ``live`` slots.

    Open-addressing linear probing cannot free slots in place — an
    emptied slot truncates the probe chain of every key that collided
    past it, orphaning live groups — so both growth and watermark
    retirement rebuild the table by re-inserting survivors.
    """
    new_table = ht.make_state(new_cap, state.table.key_width)
    new_table, old_to_new, n_live = ht.probe_insert(
        new_table, state.table.keys, live)
    new_state = AggState(
        table=new_table,
        group_rows=remap_slots(state.group_rows, old_to_new, new_cap, 0),
        dirty=remap_slots(state.dirty, old_to_new, new_cap, 0),
        accs=tuple(remap_slots(a, old_to_new, new_cap, f)
                   for a, f in zip(state.accs, fills)),
        emitted_valid=remap_slots(state.emitted_valid, old_to_new,
                                  new_cap, 0),
        emitted_rows=remap_slots(state.emitted_rows, old_to_new,
                                 new_cap, 0),
        emitted_accs=tuple(remap_slots(a, old_to_new, new_cap, f)
                           for a, f in zip(state.emitted_accs, fills)),
    )
    return new_state, n_live


# int constant, NOT jnp.int32: a module-level jnp scalar initializes
# the JAX backend at IMPORT — and a plan-only process (the distributed
# frontend) must never touch the TPU. XLA folds the Python int the same.
_I32_SIGN_FLIP = -0x80000000


def retire_state(state: AggState, wm_hi, wm_lo, lane_off: int,
                 fills) -> Tuple[AggState, jnp.ndarray]:
    """Traced watermark retirement (state_table.rs:894 state-cleaning
    analog, device side): drop every group whose watermark key column is
    strictly below the watermark, by rebuilding the table from survivors
    in ONE device step (no host transfer; the count refreshes at the
    next flush).

    The key columns are 3 lanes each (keys.py): (hi = v>>32,
    lo = uint32 image, valid). Order compare is (hi signed, lo
    unsigned); the sign-flip XOR makes int32 compares act unsigned.
    NULL keys (valid=0) are never below a watermark.
    """
    keys = state.table.keys
    hi = keys[:, lane_off]
    lo = keys[:, lane_off + 1] ^ _I32_SIGN_FLIP
    nonnull = keys[:, lane_off + 2] != 0
    wlo = wm_lo ^ _I32_SIGN_FLIP
    below = (hi < wm_hi) | ((hi == wm_hi) & (lo < wlo))
    closed = state.table.occ & nonnull & below
    live = state.table.occ & ~closed & (
        (state.group_rows != 0) | state.dirty | state.emitted_valid)
    return _rebuild_live(state, live, state.table.capacity, fills)


def build_retire(key_width: int, specs: Sequence[AggSpec]):
    del key_width
    fills = tuple(f for _dt, f in dev_layout(specs))
    jitted = jaxtools.instrumented_jit(
        retire_state, "hash_agg.retire", static_argnums=(3, 4),
        donate_argnums=(0,))

    def retire(state, wm_hi, wm_lo, lane_off):
        return jitted(state, wm_hi, wm_lo, lane_off, fills)

    return retire


def evict_state(state: AggState, key_lanes: jnp.ndarray,
                valid: jnp.ndarray, fills
                ) -> Tuple[AggState, jnp.ndarray]:
    """Traced cold-tier eviction (state/tier.py): drop the given keys'
    groups by rebuilding the table from the survivors in ONE device
    step — the same rebuild path watermark retirement uses. The caller
    guarantees the evicted groups are CLEAN (flushed + advanced), so
    dropping their device slots loses nothing the state table does not
    hold."""
    slots = ht.lookup(state.table, key_lanes, valid)
    cap = state.table.capacity
    scat = jnp.where(slots >= 0, slots, cap)
    dropped = jnp.zeros(cap, dtype=bool).at[scat].set(True, mode="drop")
    live = state.table.occ & ~dropped & (
        (state.group_rows != 0) | state.dirty | state.emitted_valid)
    return _rebuild_live(state, live, cap, fills)


def build_evict(specs: Sequence[AggSpec]):
    fills = tuple(f for _dt, f in dev_layout(specs))
    jitted = jaxtools.instrumented_jit(
        evict_state, "hash_agg.evict", static_argnums=(3,),
        donate_argnums=(0,))

    def evict(state, key_lanes, valid):
        return jitted(state, key_lanes, valid, fills)

    return evict


def advance_state(state: AggState) -> AggState:
    """Traced post-flush snapshot advance — fully on device, no host
    index round-trip: emitted := current for every dirty slot."""
    d = state.dirty
    ev = jnp.where(d, state.group_rows > 0, state.emitted_valid)
    er = jnp.where(d, state.group_rows, state.emitted_rows)
    ea = tuple(jnp.where(d, a, e)
               for a, e in zip(state.accs, state.emitted_accs))
    return AggState(state.table, state.group_rows,
                    jnp.zeros_like(d), state.accs, ev, er, ea)


def build_advance():
    return jaxtools.instrumented_jit(advance_state, "hash_agg.advance",
                                     donate_argnums=(0,))


def encode_patch_cols(specs: Sequence[AggSpec], decoded,
                      raw_accs) -> List[np.ndarray]:
    """Corrected (value, nn) pairs → device acc columns for a patch.

    `decoded[j]` is (value, nn) for a corrected call, or None for an
    untouched one — untouched calls pass their RAW gathered device
    columns through bit-for-bit (re-encoding a float sum through the
    decoded f64 would perturb the (hi, lo) pair). Shared by the
    single-chip and sharded kernels so the encoding can never drift."""
    slices = _call_slices(specs)
    dev_cols: List[np.ndarray] = []
    for j, (s, d) in enumerate(zip(specs, decoded)):
        if d is None:
            assert raw_accs is not None, \
                "raw accs needed for passthrough"
            dev_cols.extend(raw_accs[slices[j]])
        else:
            v, nn = d
            dev_cols.extend(s.encode_acc(v, nn))
    return dev_cols


def build_patch(specs: Sequence[AggSpec]):
    """Compile the host→device acc patch (retractable MIN/MAX recompute
    writes corrected extremes back before the snapshot advances)."""

    def patch(state: AggState, idx, new_accs):
        accs = tuple(a.at[idx].set(v, mode="drop")
                     for a, v in zip(state.accs, new_accs))
        return state._replace(accs=accs)

    return jaxtools.instrumented_jit(patch, "hash_agg.patch")


def remap_slots(arr: jnp.ndarray, old_to_new: jnp.ndarray,
                new_cap: int, fill) -> jnp.ndarray:
    """Re-scatter a slot-indexed array after a table rehash.

    `old_to_new[i]` is the new slot of old slot i (-1 for unoccupied)."""
    if arr.dtype == jnp.bool_:
        init = jnp.full(new_cap, bool(fill), dtype=arr.dtype)
    else:
        init = jnp.full(new_cap, fill, dtype=arr.dtype)
    safe = jnp.where(old_to_new >= 0, old_to_new, new_cap)
    return init.at[safe].set(arr, mode="drop")




@dataclass
class FlushResult:
    """Host view of the dirty groups at a barrier (decoded values)."""

    n: int
    keys: np.ndarray                 # int32[n, K] raw key lanes
    group_rows: np.ndarray           # int64[n] — current
    outs: List[np.ndarray]           # per call decoded output value
    nulls: List[np.ndarray]          # per call output-is-NULL
    nns: List[Optional[np.ndarray]]  # per call non-null count (None: cnt*)
    was_emitted: np.ndarray          # bool[n]
    prev_rows: np.ndarray
    prev_outs: List[np.ndarray]
    prev_nulls: List[np.ndarray]
    prev_nns: List[Optional[np.ndarray]]
    # device-layout acc columns from the flush gather (None on empty)
    raw_accs: Optional[List[np.ndarray]] = None
    prev_raw_accs: Optional[List[np.ndarray]] = None

    @staticmethod
    def empty(specs: Sequence[AggSpec], key_width: int) -> "FlushResult":
        z = np.zeros(0, dtype=np.int64)
        zb = np.zeros(0, dtype=bool)
        vals = [np.zeros(0, dtype=s.out_dtype) for s in specs]
        nns = [None if (s.kind in (AggKind.COUNT,
                                   AggKind.APPROX_COUNT_DISTINCT)
                        or s.kind in HOST_AGG_KINDS)
               else z.copy() for s in specs]
        return FlushResult(
            0, np.zeros((0, key_width), dtype=np.int32), z.copy(),
            list(vals), [zb.copy() for _ in specs], list(nns),
            zb.copy(), z.copy(),
            [v.copy() for v in vals], [zb.copy() for _ in specs],
            [None if n is None else n.copy() for n in nns])


def _unpack_acc_cols(specs: Sequence[AggSpec], data: np.ndarray,
                     c0: int) -> List[np.ndarray]:
    """Packed i32 matrix columns → device-layout acc arrays."""
    out = []
    for dt, _fill in dev_layout(specs):
        col = np.ascontiguousarray(data[:, c0])
        if dt == np.dtype(np.float32):
            col = col.view(np.float32)
        out.append(col)
        c0 += 1
    return out


def decode_flush_data(specs: Sequence[AggSpec], key_width: int,
                      data: np.ndarray) -> FlushResult:
    """Decode gathered dirty-slot rows (gather_packed layout minus the
    header) into a host FlushResult. Shared by the single-chip and
    sharded kernels — sharded flushes concatenate per-shard segments
    first (keys never span shards, so concat is a disjoint union)."""
    p = data.shape[0]
    k = key_width
    keys = data[:, 1:1 + k]
    rows = np.ascontiguousarray(data[:, 1 + k])
    if not (rows >= 0).all():
        raise RuntimeError(
            "group_rows wrapped int32 — a group exceeded 2^31 rows")
    n_acc = len(dev_layout(specs))
    accs = _unpack_acc_cols(specs, data, 2 + k)
    was = np.ascontiguousarray(data[:, 2 + k + n_acc]).astype(bool)
    prows = np.ascontiguousarray(data[:, 3 + k + n_acc])
    paccs = _unpack_acc_cols(specs, data, 4 + k + n_acc)
    outs, nulls = decode_outputs(specs, accs)
    pouts, pnulls = decode_outputs(specs, paccs)
    return FlushResult(
        n=p, keys=keys,
        group_rows=rows.astype(np.int64),
        outs=outs, nulls=nulls, nns=_nns_of(specs, accs),
        was_emitted=was,
        prev_rows=prows.astype(np.int64),
        prev_outs=pouts, prev_nulls=pnulls,
        prev_nns=_nns_of(specs, paccs),
        raw_accs=accs, prev_raw_accs=paccs)


def _nns_of(specs, dev_cols) -> List[Optional[np.ndarray]]:
    out = []
    for s, sl in zip(specs, _call_slices(specs)):
        plain = s.kind in (AggKind.COUNT,
                           AggKind.APPROX_COUNT_DISTINCT) \
            or s.kind in HOST_AGG_KINDS
        out.append(None if plain
                   else dev_cols[sl][-1].astype(np.int64))
    return out


class GroupedAggKernel:
    """Host wrapper: growth scheduling, flush bookkeeping, jit caches.

    The executor drives it: ``apply`` per chunk (ONE host→device transfer,
    no syncs), ``flush`` per barrier (ONE device→host transfer),
    ``rebuild`` on recovery.

    Occupancy accounting is **sync-free**: every apply step returns its
    exact device-side insert count, fetched asynchronously (the DMA is
    kicked at dispatch; ``_drain_ready`` folds in whichever counters have
    landed without blocking). The growth bound is then
    ``exact_count_of_drained + rows_of_undrained`` — tight within a few
    in-flight chunks, so a table sized for its group count never blocks,
    and a genuinely-filling table blocks only on counters whose DMA is
    already in flight. On the tunneled TPU a blocking read costs 70ms+
    (utils/jaxtools.py docstring) — this scheme is the difference between
    54K and >1M events/s on q7.
    """

    # pressure growth (see _reserve) stops doubling past this capacity:
    # ~15 int32 arrays × 2^21 ≈ 125MB HBM, far under a v5e's 16GB but
    # enough to absorb million-row epochs without a mid-epoch drain
    PRESSURE_GROW_CEILING = 1 << 21

    # Default table size: big enough that typical epochs never hit the
    # pessimistic-bound drain or the growth ladder (each growth step
    # costs a rehash + fresh trace/compile of every program — ~0.5s even
    # warm). Sized for TWO in-flight 32K batches of pessimistic inserts
    # plus real occupancy: 2^18 slots ≈ 16MB HBM for a 2-call plan.
    DEFAULT_CAPACITY = 1 << 18

    def __init__(self, key_width: int, specs: Sequence[AggSpec],
                 capacity: Optional[int] = None,
                 flush_capacity: int = 1 << 10,
                 prelude=None, raw_width: Optional[int] = None,
                 metrics_label: Optional[str] = None,
                 expand_units: int = 1):
        if capacity is None:
            capacity = self.DEFAULT_CAPACITY
        capacity = max(next_pow2(capacity), ht.MIN_CAPACITY)
        # expand_units (hop-absorbing preludes) is advisory: the
        # traced step multiplies raw rows `units`× before the scatter.
        # Shrinking the raw backlog to match was measured SLOWER on
        # CPU (more dispatches beat bigger ones only on the tunneled
        # device) — kept as a parameter so device rounds can tune it.
        self._expand_units = expand_units
        self.specs = tuple(specs)
        self.key_width = key_width
        self.state = make_agg_state(capacity, key_width, self.specs)
        # fused-fragment mode (ops/fused.py): the backlog holds RAW
        # int64 chunk matrices and the jitted step runs the whole
        # filter→project→encode→update chain in one dispatch
        self._prelude = prelude
        self._raw_width = raw_width
        # real-dispatch metrics attribution (fused mode counts at the
        # ACTUAL jit-invocation sites — one per backlog flush)
        self.metrics_label = metrics_label
        # epoch-trace identity stamped on every dispatch span
        self._span_label = metrics_label or "GroupedAggKernel"
        self._apply = build_apply(key_width, self.specs,
                                  prelude=prelude)
        self._gather = build_gather_packed(key_width)
        self._advance = build_advance()
        self._patch = build_patch(self.specs)
        self._retire = build_retire(key_width, self.specs)
        self._evict = build_evict(self.specs)
        fills = tuple(f for _dt, f in dev_layout(self.specs))
        self._grow_step = jaxtools.instrumented_jit(
            lambda st, cap: _rebuild_live(
                st, st.table.occ & ((st.group_rows != 0) | st.dirty
                                    | st.emitted_valid), cap, fills),
            "hash_agg.grow", static_argnums=(1,), donate_argnums=(0,))
        self._flush_cap = next_pow2(flush_capacity)
        self._counters = jaxtools.PendingCounters()
        self._backlog: List[np.ndarray] = []   # packed, not yet shipped
        self._backlog_rows = 0
        self._backlog_vis = 0                  # visible rows (raw mode)
        # per-stage visible-row vectors from fused dispatches (DMA'd
        # alongside the insert counters; drained at flush)
        self._stage_pending: List = []
        self._flush_idx: Optional[np.ndarray] = None

    @property
    def capacity(self) -> int:
        return self.state.table.capacity

    # -- hot path -------------------------------------------------------
    # Chunks accumulate host-side and dispatch as ONE padded device step:
    # a tunneled device_put has ~5ms fixed host cost and each dispatch
    # ~2ms of python, so per-chunk applies cap throughput around 1M
    # rows/s before the device does any work. The fixed BATCH_ROWS shape
    # also means exactly one compiled (cap, N) program. Correctness is
    # unaffected — aggregation state is only observed at barrier flush,
    # which drains the backlog first.
    BATCH_ROWS = 1 << 15

    def apply(self, key_lanes: np.ndarray, signs: np.ndarray,
              vis: np.ndarray, inputs: Sequence) -> None:
        assert self._prelude is None, \
            "fused kernel takes raw chunks (apply_raw)"
        with LEDGER.phase("host_pack", kernel=self._span_label):
            packed = pack_chunk(self.key_width, self.specs,
                                np.asarray(key_lanes),
                                np.asarray(signs),
                                np.asarray(vis), inputs)
        # split-fill the batch slab (ISSUE 12): accumulator scatters
        # are row-independent (U-/U+ halves are just ±1 rows — pair
        # adjacency only matters in fused raw mode, which keeps chunk
        # boundaries), so a packed chunk may straddle two dispatches.
        # Without this, chunk sizes that don't divide BATCH_ROWS
        # (hop-expanded 4-copy groups, coalesced odd sizes) quantize
        # each dispatch to ~60% fill and pad the rest on device.
        n = len(signs)
        at = 0
        while at < n:
            room = self.BATCH_ROWS - self._backlog_rows
            if room <= 0:
                self._dispatch_backlog()
                continue
            take = min(n - at, room)
            self._backlog.append(
                packed if take == n else packed[at:at + take])
            self._backlog_rows += take
            at += take
            if self._backlog_rows >= self.BATCH_ROWS:
                self._dispatch_backlog()

    def apply_raw(self, raw: np.ndarray, n_visible: int) -> None:
        """Fused-fragment hot path: backlog one RAW chunk matrix
        (ops/fused.py encode_raw_chunk) plus an always-invisible
        separator row — the traced chain's shifted compares must never
        marry rows across chunk boundaries. Dispatch granularity and
        padding match `apply` exactly."""
        assert self._prelude is not None, \
            "apply_raw needs a fused (prelude) kernel"
        n = raw.shape[0] + 1
        if self._backlog_rows + n > self.BATCH_ROWS:
            self._dispatch_backlog()
        self._backlog.append(raw)
        self._backlog.append(np.zeros((1, raw.shape[1]),
                                      dtype=np.int64))   # separator
        self._backlog_rows += n
        self._backlog_vis += int(n_visible)
        if self._backlog_rows >= self.BATCH_ROWS:
            self._dispatch_backlog()

    def _dispatch_backlog(self) -> None:
        if not self._backlog:
            return
        mats, n = self._backlog, self._backlog_rows
        n_vis = self._backlog_vis
        self._backlog, self._backlog_rows = [], 0
        self._backlog_vis = 0
        self._reserve(n)
        raw_mode = self._prelude is not None
        # epoch-staging codec: backlog reassembly into the fixed-shape
        # batch matrix is host_pack; the upload that follows is h2d
        with LEDGER.phase("host_pack", kernel=self._span_label):
            w = mats[0].shape[1]
            cap_rows = self.BATCH_ROWS if n <= self.BATCH_ROWS \
                else next_pow2(n)
            packed = np.zeros((cap_rows, w),
                              dtype=np.int64 if raw_mode else np.int32)
            at = 0                   # pad rows: vis=0
            for m in mats:
                packed[at:at + m.shape[0]] = m
                at += m.shape[0]
        from risingwave_tpu.utils.ledger import note_backlog
        note_backlog(self._span_label, n)
        if raw_mode:
            with spans.dispatch_span(self._span_label, n_vis,
                                     batch_rows=n):
                self.state, ins, stage_rows = self._apply(
                    self.state,
                    jaxtools.upload(packed, kernel=self._span_label))
            jaxtools.start_fetch(stage_rows)
            self._stage_pending.append(stage_rows)
            if self.metrics_label is not None:
                # REAL dispatch accounting: the fused path launches one
                # traced program per backlog flush — count it there,
                # with the batch's true visible-row density
                from risingwave_tpu.utils.metrics import STREAMING
                STREAMING.device_dispatch.inc(
                    1, executor=self.metrics_label)
                STREAMING.rows_per_dispatch.observe(
                    float(n_vis), executor=self.metrics_label)
        else:
            with spans.dispatch_span(self._span_label, n,
                                     batch_rows=n):
                self.state, ins = self._apply(
                    self.state,
                    jaxtools.upload(packed, kernel=self._span_label))
        self._counters.push(ins, n)

    def drain_stage_rows(self) -> Optional[np.ndarray]:
        """Sum of per-stage visible-row counts since the last drain
        (fused mode; call at barrier flush — the gather already
        synchronized the queue, so these fetches are landed DMAs)."""
        if not self._stage_pending:
            return None
        total = None
        for v in self._stage_pending:
            a = jaxtools.fetch1(v)
            total = a if total is None else total + a
        self._stage_pending = []
        return np.asarray(total)

    # -- growth ---------------------------------------------------------
    def _reserve(self, n: int) -> None:
        self._counters.drain_ready()
        if self._counters.bound() + n <= ht.MAX_LOAD * self.capacity:
            return
        # bound crossed: collapse it exactly, then grow as needed
        self._counters.drain_all()
        grew = False
        while self._counters.count() + n > ht.MAX_LOAD * self.capacity:
            self._grow()
            grew = True
        if not grew and self.capacity < self.PRESSURE_GROW_CEILING:
            # pressure growth: the blocking drain was caused by the
            # LOOSE bound (counter DMAs lag ~70ms-1s over the tunnel),
            # not by real occupancy. Doubling the table lets the bound
            # absorb a whole epoch of pessimistic inserts — HBM is
            # cheap, blocked host reads are not. Converges in log2
            # steps to a capacity that never drains mid-epoch (the
            # ceiling bounds HBM for adversarially huge epochs).
            self._grow()

    def _grow(self) -> None:
        """Rehash into a doubled table, reclaiming dead groups.

        A slot is live iff its group has rows OR a flush hasn't retired
        it yet (dirty / still-emitted) — tumbling-window churn leaves
        fully retracted groups behind, and carrying them forever would
        grow the table without bound.

        Occupancy accounting: rehash can only RECLAIM (live ⊆ occupied),
        so the pre-grow count stays a valid upper bound — keeping it
        avoids a blocking n_live readback (70ms-1s on the tunnel); the
        next flush header collapses it to exact for free."""
        self.state, _n_live = self._grow_step(
            self.state, self.state.table.capacity * 2)

    def retire_below(self, group_pos: int, wm_i64: int) -> None:
        """Watermark state cleaning: drop groups whose ``group_pos``-th
        key column is strictly below the watermark (device-side rebuild,
        no transfers). Call after ``advance`` — a dirty group must emit
        before it can be retired."""
        if self._backlog_rows:
            raise RuntimeError("retire_below with undispatched backlog")
        hi, lo = lanes.split_i64(np.asarray([wm_i64], dtype=np.int64))
        self.state, _n_live = self._retire(
            self.state, jnp.int32(hi[0]), jnp.int32(lo[0]),
            group_pos * 3)

    # -- cold tier (state/tier.py) ---------------------------------------
    def evict_keys(self, key_lanes: np.ndarray) -> None:
        """Drop the given groups' device slots (cold-tier eviction;
        their rows stay durable in the value-state table). Call only at
        a barrier, after flush+advance, with no backlog — the tier
        sweeps only there, so the evicted groups are provably clean."""
        if self._backlog_rows:
            raise RuntimeError("evict_keys with undispatched backlog")
        n = len(key_lanes)
        if n == 0:
            return
        cap_n = next_pow2(n)
        lanes = np.zeros((cap_n, self.key_width), dtype=np.int32)
        lanes[:n] = key_lanes
        valid = np.zeros(cap_n, dtype=bool)
        valid[:n] = True
        self.state, _n_live = self._evict(self.state,
                                          jnp.asarray(lanes),
                                          jnp.asarray(valid))
        # occupancy: the rebuild can only RECLAIM (live ⊆ occupied), so
        # the standing upper bound stays valid — same argument as _grow;
        # the next flush header collapses it to exact for free

    def load_groups(self, keys: np.ndarray, group_rows: np.ndarray,
                    acc_cols: Sequence[np.ndarray]) -> None:
        """Reload evicted groups from committed state rows into the
        LIVE table (cold-tier reload-on-touch). Mirrors ``rebuild``'s
        insert without resetting resident state; reloaded groups are
        marked emitted — their outputs were committed downstream before
        eviction, so the next flush derives update pairs, not fresh
        inserts. Dispatches BEFORE the touching chunk's apply (the
        caller drains the backlog via this call)."""
        n = len(group_rows)
        if n == 0:
            return
        # the reload must land before any buffered chunk that may touch
        # the same (still-cold-looking) keys could dispatch after it
        self._dispatch_backlog()
        self._reserve(n)
        dev_cols = encode_host_accs(self.specs, acc_cols)
        table, slots, ins = ht._probe_insert_jit(
            self.state.table, jnp.asarray(keys),
            jnp.ones(n, dtype=bool))
        self._counters.push(ins, n)
        rows32 = jnp.asarray(group_rows, dtype=jnp.int32)
        accs = tuple(a.at[slots].set(jnp.asarray(col))
                     for a, col in zip(self.state.accs, dev_cols))
        self.state = AggState(
            table=table,
            group_rows=self.state.group_rows.at[slots].set(rows32),
            dirty=self.state.dirty,
            accs=accs,
            emitted_valid=self.state.emitted_valid.at[slots].set(True),
            emitted_rows=self.state.emitted_rows.at[slots].set(rows32),
            emitted_accs=tuple(
                a.at[slots].set(jnp.asarray(col))
                for a, col in zip(self.state.emitted_accs, dev_cols)),
        )

    # -- barrier flush ---------------------------------------------------
    def flush(self) -> FlushResult:
        """Gather dirty groups to host and decode — ONE device→host
        transfer. Call ``advance`` after consuming (optionally
        ``patch_accs`` in between)."""
        self._dispatch_backlog()
        while True:
            with spans.dispatch_span(f"{self._span_label}.flush",
                                     self._counters.bound()):
                mat = jaxtools.fetch1(
                    self._gather(self.state, self._flush_cap))
            p = int(mat[0, 0])
            # the gather runs after every queued apply, so its header
            # count subsumes all pending insert counters
            self._counters.reset(int(mat[0, 1]))
            if p <= self._flush_cap:
                break
            self._flush_cap = max(self._flush_cap * 2, next_pow2(p))
        if p == 0:
            self._flush_idx = np.zeros(0, dtype=np.int32)
            return FlushResult.empty(self.specs, self.key_width)
        with LEDGER.phase("host_emit", kernel=self._span_label):
            data = mat[1:1 + p]
            self._flush_idx = np.ascontiguousarray(data[:, 0])
            return decode_flush_data(self.specs, self.key_width, data)

    def patch_accs(self, decoded: List[Optional[
            Tuple[np.ndarray, np.ndarray]]],
                   raw_accs: Optional[List[np.ndarray]] = None) -> None:
        """Overwrite flushed groups' accumulators (minput recompute).

        See encode_patch_cols for the passthrough contract."""
        idx = self._flush_idx
        assert idx is not None and len(idx) > 0
        dev_cols = encode_patch_cols(self.specs, decoded, raw_accs)
        pad = next_pow2(len(idx))
        idx_padded = np.full(pad, self.capacity, dtype=np.int32)
        idx_padded[:len(idx)] = idx
        padded = tuple(
            np.concatenate([c, np.zeros(pad - len(idx), dtype=c.dtype)])
            for c in dev_cols)
        self.state = self._patch(self.state, jnp.asarray(idx_padded),
                                 padded)

    def advance(self) -> None:
        """Snapshot emitted := current for every dirty slot; clear dirty.
        Fully on device — no transfers."""
        assert self._flush_idx is not None, "flush() first"
        self._flush_idx = None
        self.state = self._advance(self.state)

    # -- recovery ---------------------------------------------------------
    def rebuild(self, keys: np.ndarray, group_rows: np.ndarray,
                acc_cols: Sequence[np.ndarray]) -> None:
        """Reload from committed value-state rows (boot/recovery).

        `acc_cols` uses the HOST layout (acc_dtypes: per call value
        [+ nn]). Restored groups are marked emitted — their outputs were
        committed downstream before the recovery epoch.
        """
        n = len(group_rows)
        cap = max(self.capacity, next_pow2(int(n / ht.MAX_LOAD) + 1))
        self.state = make_agg_state(cap, self.key_width, self.specs)
        self._counters.reset(n)
        self._backlog = []
        self._backlog_rows = 0
        self._backlog_vis = 0
        self._stage_pending = []
        if n == 0:
            return
        dev_cols = encode_host_accs(self.specs, acc_cols)
        table, slots, _ = ht._probe_insert_jit(
            self.state.table, jnp.asarray(keys), jnp.ones(n, dtype=bool))
        accs = tuple(a.at[slots].set(jnp.asarray(col))
                     for a, col in zip(self.state.accs, dev_cols))
        rows_dev = self.state.group_rows.at[slots].set(
            jnp.asarray(group_rows, dtype=jnp.int32))
        self.state = AggState(
            table=table, group_rows=rows_dev, dirty=self.state.dirty,
            accs=accs,
            emitted_valid=self.state.emitted_valid.at[slots].set(True),
            # distinct buffers: the apply step donates the state, and a
            # buffer may be donated at most once per call
            emitted_rows=jnp.copy(rows_dev),
            emitted_accs=tuple(jnp.copy(a) for a in accs),
        )
