"""CLI: `python -m risingwave_tpu` — the unified-binary analog.

Reference parity: src/cmd_all/src/bin/risingwave.rs playground /
standalone modes — one process hosting frontend (pgwire), meta (barrier
loop + catalog/DDL log) and compute (actors + device kernels), with
hummock-on-local-FS persistence when --data-dir is given.

    python -m risingwave_tpu playground                # in-memory
    python -m risingwave_tpu serve --data-dir ./rwdata # durable
"""

from __future__ import annotations

import argparse
import asyncio
import sys


async def _serve(args) -> None:
    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.frontend.pgwire import PgServer

    if args.data_dir:
        from risingwave_tpu.storage.hummock import HummockLite
        from risingwave_tpu.storage.object_store import LocalFsObjectStore
        store = HummockLite(LocalFsObjectStore(args.data_dir))
    else:
        from risingwave_tpu.state.store import MemoryStateStore
        store = MemoryStateStore()
    fe = Frontend(store)
    replayed = await fe.recover()
    if replayed:
        print(f"recovered {replayed} DDL statements", file=sys.stderr)
    srv = PgServer(fe)
    await srv.serve(args.host, args.port)
    print(f"listening on {args.host}:{srv.port} "
          f"(psql -h {args.host} -p {srv.port})", file=sys.stderr)
    hb = asyncio.ensure_future(fe.run_heartbeat())
    try:
        # serve until the heartbeat dies — a failed heartbeat means
        # checkpoints stopped; better to crash than serve stale MVs
        await asyncio.wait({hb}, return_when=asyncio.FIRST_COMPLETED)
        hb.result()
    finally:
        hb.cancel()
        await srv.close()


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="risingwave_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)
    for name in ("playground", "serve"):
        sp = sub.add_parser(name)
        sp.add_argument("--host", default="127.0.0.1")
        sp.add_argument("--port", type=int, default=4566)
        if name == "serve":               # playground is in-memory only
            sp.add_argument("--data-dir", required=True)
    args = p.parse_args(argv)
    if not hasattr(args, "data_dir"):
        args.data_dir = None
    asyncio.run(_serve(args))


if __name__ == "__main__":
    main()
