"""CLI: `python -m risingwave_tpu` — the unified-binary analog.

Reference parity: src/cmd_all/src/bin/risingwave.rs playground /
standalone modes — one process hosting frontend (pgwire), meta (barrier
loop + catalog/DDL log) and compute (actors + device kernels), with
hummock-on-local-FS persistence when --data-dir is given — plus the
risectl verb family (src/ctl/) for offline cluster inspection and
backup operations against a data directory:

    python -m risingwave_tpu playground                # in-memory
    python -m risingwave_tpu serve --data-dir ./rwdata # durable
    python -m risingwave_tpu serve-cluster --data-dir ./rw \
        --workers 2                                    # N-worker
    python -m risingwave_tpu ctl --data-dir D meta catalog
    python -m risingwave_tpu ctl --data-dir D hummock version
    python -m risingwave_tpu ctl --data-dir D hummock list-ssts
    python -m risingwave_tpu ctl --data-dir D table scan <name> [-n N]
    python -m risingwave_tpu ctl --data-dir D metrics [--steps K]
    python -m risingwave_tpu ctl --data-dir D trace [--steps K] \
        [--out trace.json]    # Chrome trace-event JSON (Perfetto):
                              # X/s/f span+flow events, phase lanes,
                              # and 'C' counter tracks (transfer
                              # bytes, uploader queue depth, backlog
                              # rows) sampled at each epoch seal
    python -m risingwave_tpu ctl --data-dir D phases [--steps K]
                              # epoch phase ledger: per-epoch
                              # host/device time+bytes breakdown,
                              # conservation coverage, kernel costs
    python -m risingwave_tpu ctl --data-dir D top [--steps K] \
        [--watch N]           # live-ops view: actor utilization
                              # tricolor (busy/backpressure/idle,
                              # sorted busiest first), per-MV
                              # event-time freshness, and each
                              # domain's current bottleneck with its
                              # one-line diagnosis
    python -m risingwave_tpu ctl --data-dir D autoscale [--steps K]
                              # elastic control loop: the
                              # rw_autoscaler decision ledger plus
                              # the bottleneck/freshness signals a
                              # decision would read (live decisions
                              # ride the serving coordinator — SET
                              # stream_autoscale=on there)
    python -m risingwave_tpu ctl --data-dir D compaction [--steps K] \
        [--watch N]           # leveled-compaction view: per-level
                              # topology (L0 run count, L1 runs,
                              # tombstone density), space amp, and
                              # the dedicated-arm task ledger
                              # (rw_compaction) over a recovered
                              # clone driven with the off-path arm
    python -m risingwave_tpu ctl --data-dir D sinks [--steps K]
                              # exactly-once sink view (rw_sinks):
                              # per-sink committed epoch, staged-but-
                              # uncommitted epochs/bytes, writer lag —
                              # listing-driven from each sink's root
    python -m risingwave_tpu ctl --data-dir D backup create|list|
        delete <id> | restore <id> --target T
"""

from __future__ import annotations

import argparse
import asyncio
import sys


async def _serve(args) -> None:
    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.frontend.pgwire import PgServer

    if args.data_dir:
        from risingwave_tpu.storage.hummock import HummockLite
        from risingwave_tpu.storage.object_store import (
            LocalFsObjectStore, RetryingObjectStore,
        )
        # serving deployments absorb transient PUT/GET faults in place
        # (jittered-backoff retries) instead of failing a barrier round
        store = HummockLite(
            RetryingObjectStore(LocalFsObjectStore(args.data_dir)))
    else:
        from risingwave_tpu.state.store import MemoryStateStore
        store = MemoryStateStore()
    fe = Frontend(store)
    replayed = await fe.recover()
    if replayed:
        print(f"recovered {replayed} DDL statements", file=sys.stderr)
    srv = PgServer(fe)
    await srv.serve(args.host, args.port)
    print(f"listening on {args.host}:{srv.port} "
          f"(psql -h {args.host} -p {srv.port})", file=sys.stderr)
    hb = asyncio.ensure_future(fe.run_heartbeat())
    try:
        # serve until the heartbeat dies — a failed heartbeat means
        # checkpoints stopped; better to crash than serve stale MVs
        await asyncio.wait({hb}, return_when=asyncio.FIRST_COMPLETED)
        hb.result()
    finally:
        hb.cancel()
        await srv.close()


async def _serve_cluster(args) -> None:
    """pgwire over the DISTRIBUTED session: N worker processes under
    one data dir, MVs fragment across them, psql talks to the
    coordinator (frontend-node shape)."""
    from risingwave_tpu.cluster.session import DistFrontend
    from risingwave_tpu.frontend.pgwire import PgServer

    fe = DistFrontend(args.data_dir, n_workers=args.workers,
                      parallelism=args.parallelism or args.workers)
    srv = PgServer(fe)
    hb = None
    try:
        await fe.start()
        # inside the try: a bind failure must still stop the worker
        # subprocesses fe.start() just spawned
        await srv.serve(args.host, args.port)
        print(f"cluster of {args.workers} workers; listening on "
              f"{args.host}:{srv.port} "
              f"(psql -h {args.host} -p {srv.port})", file=sys.stderr)
        hb = asyncio.ensure_future(fe.run_heartbeat())
        await asyncio.wait({hb}, return_when=asyncio.FIRST_COMPLETED)
        hb.result()
    finally:
        if hb is not None:
            hb.cancel()
        await srv.close()
        await fe.close()


def _ctl(args) -> int:
    """Offline inspection/ops against a data directory (risectl)."""
    import json
    import os

    # ctl needs no device kernels: default to CPU so inspection never
    # blocks on a TPU tunnel another process may hold (the operator
    # can still export JAX_PLATFORMS to override)
    if "JAX_PLATFORMS" not in os.environ:
        import jax
        jax.config.update("jax_platforms", "cpu")
    if not os.path.isdir(args.data_dir):
        # an inspection tool must refuse to MINT a cluster: a typo'd
        # path reporting an empty-but-healthy catalog is worse than
        # an error
        print(f"error: data dir {args.data_dir!r} does not exist",
              file=sys.stderr)
        return 1

    from risingwave_tpu.storage.object_store import LocalFsObjectStore

    obj = LocalFsObjectStore(args.data_dir)
    verb = args.ctl_cmd

    if verb == "meta" and args.what == "catalog":
        if obj.exists("meta/ddl.json"):
            for line in json.loads(obj.read("meta/ddl.json").decode()):
                print(line)
        return 0
    if verb == "hummock" and args.what == "version":
        if not obj.exists("meta/CURRENT"):
            print("no committed version")
            return 1
        vid = int(obj.read("meta/CURRENT").decode())
        print(json.dumps(json.loads(
            obj.read(f"meta/v{vid}.json").decode()), indent=2))
        return 0
    if verb == "hummock" and args.what == "list-ssts":
        for path in obj.list("data/"):
            print(f"{path}\t{obj.size(path)}B")
        return 0
    if verb == "table":
        return asyncio.run(_ctl_scan(obj, args))
    if verb == "metrics":
        return asyncio.run(_ctl_metrics(obj, args))
    if verb == "memory":
        return asyncio.run(_ctl_memory(obj, args))
    if verb == "trace":
        return asyncio.run(_ctl_trace(obj, args))
    if verb == "phases":
        return asyncio.run(_ctl_phases(obj, args))
    if verb == "top":
        return asyncio.run(_ctl_top(obj, args))
    if verb == "autoscale":
        return asyncio.run(_ctl_autoscale(obj, args))
    if verb == "cost":
        return asyncio.run(_ctl_cost(obj, args))
    if verb == "compaction":
        return asyncio.run(_ctl_compaction(obj, args))
    if verb == "sinks":
        return asyncio.run(_ctl_sinks(obj, args))
    if verb == "backup":
        from risingwave_tpu.meta.backup import (
            create_backup, delete_backup, list_backups, restore_backup,
        )
        if args.what in ("delete", "restore") and not args.ident:
            print(f"error: backup {args.what} needs a backup id",
                  file=sys.stderr)
            return 2
        if args.what == "create":
            print(create_backup(obj))
        elif args.what == "list":
            for b in list_backups(obj):
                print(b)
        elif args.what == "delete":
            if args.ident not in list_backups(obj):
                print(f"error: no backup {args.ident!r}",
                      file=sys.stderr)
                return 1
            print(delete_backup(obj, args.ident), "objects deleted")
        elif args.what == "restore":
            if not args.target:
                print("error: backup restore needs --target",
                      file=sys.stderr)
                return 2
            if args.ident not in list_backups(obj):
                print(f"error: no backup {args.ident!r}",
                      file=sys.stderr)
                return 1
            try:
                restore_backup(obj, args.ident,
                               LocalFsObjectStore(args.target))
            except ValueError as e:      # non-empty target
                print(f"error: {e}", file=sys.stderr)
                return 1
            print(f"restored backup {args.ident} into {args.target}")
        return 0
    return 2


def _snapshot_clone(obj):
    """In-memory clone of the CURRENT version's CLOSURE (the backup
    helper's consistency argument: versions are immutable and vacuum
    is deferred), so it is a true snapshot even beside a live serve
    process racing compactions — a bare list-then-read-all could see
    a torn CURRENT or a just-vacuumed SST. The copy runs unmetered:
    the tooling traffic must not inflate the object-store op counters
    a later metrics dump reports."""
    from risingwave_tpu.meta.backup import _closure
    from risingwave_tpu.storage.object_store import (
        MemObjectStore, unmetered,
    )

    clone = MemObjectStore()
    with unmetered():
        for path in _closure(obj):
            clone.upload(path, obj.read(path))
    return clone


async def _ctl_scan(obj, args) -> int:
    """READ-ONLY scan: recovery replays DDL through deploy, which
    commits checkpoint versions — so recover over an in-memory
    snapshot clone."""
    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.storage.hummock import HummockLite

    fe = Frontend(HummockLite(_snapshot_clone(obj)))
    await fe.recover()
    try:
        rows = await fe.execute(
            f"SELECT * FROM {args.ident} LIMIT {args.limit}")
    finally:
        await fe.close()
    for r in rows:
        print("\t".join("NULL" if v is None else str(v) for v in r))
    return 0


async def _ctl_metrics(obj, args) -> int:
    """Recover the cluster into an in-memory clone (same snapshot
    discipline as `table scan`), drive a couple of checkpoints so
    every metric family has live series, and dump the Prometheus text
    exposition — what a scraper would see on a serving node."""
    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.utils.metrics import GLOBAL

    fe = Frontend(HummockLite(_snapshot_clone(obj)))
    await fe.recover()
    try:
        await fe.step(args.steps)
        # render BEFORE teardown: close() removes the liveness series
        # (stream_actor_count, queue depths) the dump is for
        text = GLOBAL.render()
    finally:
        await fe.close()
    print(text, end="")
    return 0


async def _ctl_memory(obj, args) -> int:
    """Recover into an in-memory clone (same snapshot discipline as
    `table scan`), drive a couple of checkpoints, and dump the host-
    memory accounting: MemoryContext.sizes() per cache plus per-
    executor state-tier residency (cap / resident / evicted / reloads
    / bytes) — what the memory manager and the tier see on a serving
    node."""
    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.state.tier import GLOBAL as TIER
    from risingwave_tpu.state.topology import TOPOLOGY
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.utils.memory import GLOBAL as MEM

    fe = Frontend(HummockLite(_snapshot_clone(obj)))
    await fe.recover()
    try:
        await fe.step(args.steps)
        sizes = MEM.sizes()
        total = sum(sizes.values())
        limit = MEM.soft_limit
        print(f"accounted host state: {total}B"
              + ("" if limit is None else f" (soft limit {limit}B)"))
        for name in sorted(sizes, key=lambda n: -sizes[n]):
            print(f"  {sizes[name]:>12}B  {name}")
        rows = sorted(TIER.stats_rows())
        if rows:
            print("state tier (cap/resident/evicted/reloads/bytes):")
            for name, cap, res, ev, rl, nb in rows:
                cap_s = "-" if cap < 0 else str(cap)
                print(f"  {name}: cap={cap_s} resident={res} "
                      f"evicted={ev} reloads={rl} bytes={nb}")
        stats = TOPOLOGY.table_stats()
        if stats:
            print("state topology (per-table, hottest vnodes):")
            for t, mv, nrows, nbytes, vns, imb in stats:
                print(f"  table {t} ({mv or '?'}): {nrows} rows, "
                      f"{nbytes}B over {vns} vnodes, "
                      f"imbalance {imb:.2f}")
                for vn, vrows, vbytes in TOPOLOGY.top_vnodes(t, 8):
                    print(f"    vnode {vn:>5}: {vrows:>8} rows "
                          f"{vbytes:>12}B")
    finally:
        await fe.close()
    return 0


async def _ctl_trace(obj, args) -> int:
    """Recover into an in-memory clone (same snapshot discipline as
    `table scan`), drive a few checkpoints so the flight recorder
    holds live epoch traces, and export them as Chrome trace-event
    JSON — open the file at ui.perfetto.dev (or chrome://tracing) to
    walk an epoch from barrier inject to commit."""
    import json

    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.utils.spans import EPOCH_TRACER

    fe = Frontend(HummockLite(_snapshot_clone(obj)))
    await fe.recover()
    try:
        await fe.step(args.steps)
        trace = EPOCH_TRACER.export_chrome()
    finally:
        await fe.close()
    text = json.dumps(trace, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        n = sum(1 for e in trace["traceEvents"] if e["ph"] == "X")
        print(f"wrote {n} spans across "
              f"{len(EPOCH_TRACER.epochs())} epochs to {args.out}",
              file=sys.stderr)
    else:
        print(text)
    return 0


async def _ctl_phases(obj, args) -> int:
    """Recover into an in-memory clone (same snapshot discipline as
    `table scan`), drive a few checkpoints so the phase ledger holds
    sealed epochs, and print the per-epoch breakdown: how every
    millisecond of each barrier interval splits across host_ingest /
    host_pack / h2d / device_compute / d2h / host_emit / barrier_wait,
    the conservation coverage, transfer bytes, and the compiled
    kernels' cost-analysis yardsticks."""
    import json

    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.utils.jaxtools import (
        kernel_cost_rows, publish_kernel_costs,
    )
    from risingwave_tpu.utils.ledger import LEDGER

    fe = Frontend(HummockLite(_snapshot_clone(obj)))
    await fe.recover()
    try:
        await fe.step(args.steps)
        report = LEDGER.report(last_n=args.steps + 2)
        agg = LEDGER.phase_breakdown()
        publish_kernel_costs()
        costs = kernel_cost_rows()
    finally:
        await fe.close()
    print(report)
    print("aggregate (steady epochs):")
    print(json.dumps(agg, indent=1))
    if costs:
        print("compiled kernel costs (flops / bytes accessed):")
        for label, flops, nbytes in costs:
            print(f"  {label}: {flops:.3g} flops, {nbytes:.3g} B")
    return 0


async def _ctl_top(obj, args) -> int:
    """Recover into an in-memory clone (same snapshot discipline as
    `table scan`), drive a few checkpoints per refresh, and print the
    live-ops view: actor utilization tricolor sorted busiest first,
    per-MV event-time freshness, and each barrier domain's current
    walked bottleneck. ``--watch N`` repeats the drive+print cycle N
    times (a poor man's `top` refresh over the recovered pipelines)."""
    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.stream.bottleneck import BOTTLENECKS
    from risingwave_tpu.stream.freshness import FRESHNESS
    from risingwave_tpu.stream.monitor import UTILIZATION

    fe = Frontend(HummockLite(_snapshot_clone(obj)))
    await fe.recover()
    try:
        for cycle in range(max(1, args.watch)):
            await fe.step(args.steps)
            if cycle:
                print()
            print(f"== refresh {cycle + 1} — actor utilization "
                  f"(share of last barrier) ==")
            print(f"{'actor':>6} {'node':>4} {'busy':>6} {'bp':>6} "
                  f"{'idle':>6}  fragment / executor")
            for (a, frag, node, ex, _e, _i, busy, bp,
                 idle) in UTILIZATION.rows():
                print(f"{a:>6} {node:>4} {busy:>6.1%} {bp:>6.1%} "
                      f"{idle:>6.1%}  {frag} / {ex}")
            print("== per-MV freshness ==")
            print(f"{'lag_s':>8} {'wall_s':>8} {'p99_s':>8} "
                  f"{'n':>5}  mv (domain)")
            for (mv, dom, n, _e, lag, wall, _p50, p99,
                 _wp99) in FRESHNESS.rows():
                if not n:
                    continue
                print(f"{lag:>8.3f} {wall:>8.3f} {p99:>8.3f} "
                      f"{n:>5}  {mv}"
                      + (f" ({dom})" if dom else ""))
            print("== bottlenecks ==")
            for (dom, op, _frag, actor, _node, busy, bp, streak,
                 sustained, _e, diag) in BOTTLENECKS.rows():
                label = dom or "(global)"
                if op is None:
                    print(f"{label}: no sustained bottleneck")
                else:
                    print(f"{label}: {op} (actor {actor}) busy "
                          f"{busy:.0%}, downstream bp {bp:.0%}, "
                          f"streak {streak}"
                          + (" [SUSTAINED]" if sustained else ""))
                    if diag:
                        print(f"    {diag}")
    finally:
        await fe.close()
    return 0


async def _ctl_autoscale(obj, args) -> int:
    """Recover into an in-memory clone (same snapshot discipline as
    `table scan`), drive a few checkpoints, and print the elastic
    control loop's view: the decision ledger (rw_autoscaler — on a
    serving cluster this holds the live history; offline it shows what
    this inspection process decided, normally nothing) and the signals
    a decision would read — per-domain bottleneck verdicts and per-MV
    freshness. The live workflow: ``SET stream_autoscale = on`` on the
    serving session, then ``SELECT * FROM rw_autoscaler`` /
    ``rw_recovery`` over pgwire."""
    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.meta.autoscaler import autoscaler_rows
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.stream.bottleneck import BOTTLENECKS
    from risingwave_tpu.stream.freshness import FRESHNESS

    fe = Frontend(HummockLite(_snapshot_clone(obj)))
    await fe.recover()
    try:
        await fe.step(args.steps)
        rows = autoscaler_rows()
        print("== autoscaler decision ledger ==")
        if not rows:
            print("(empty — decisions live on the serving "
                  "coordinator; query rw_autoscaler there)")
        for (seq, mv, frag, op, direction, fp, tp, outcome, reason,
             _e, dur, detail) in rows:
            print(f"#{seq} {mv}/f{frag} {direction} {fp}->{tp} "
                  f"[{outcome}] {dur:.2f}s  {reason}"
                  + (f"  ({detail})" if detail else ""))
        print("== signals a decision would read ==")
        for (dom, op, _frag, actor, _node, busy, bp, streak,
             sustained, _e, diag) in BOTTLENECKS.rows():
            label = dom or "(global)"
            if op is None:
                print(f"{label}: no sustained bottleneck")
            else:
                print(f"{label}: {op} busy {busy:.0%} streak {streak}"
                      + (" [SUSTAINED — actionable]" if sustained
                         else " (not sustained — ignored)"))
        for (mv, dom, n, _e, lag, wall, _p50, _p99,
             wp99) in FRESHNESS.rows():
            if n:
                print(f"freshness {mv}: lag {lag:.3f}s wall "
                      f"{wall:.3f}s wall_p99 {wp99:.3f}s")
    finally:
        await fe.close()
    return 0


async def _ctl_cost(obj, args) -> int:
    """Recover into an in-memory clone (same snapshot discipline as
    `table scan`), drive a few checkpoints per refresh, and print the
    serving-cost attribution view: the per-MV resource ledger
    (device-seconds, transfer bytes, resident state, compile-cache
    economics, rescale/recovery charge-back), each MV's worst
    hot-vnode imbalance, and the hottest keys per executor input.
    ``--watch N`` repeats the drive+print cycle N times. On a serving
    cluster, ``SELECT * FROM rw_mv_costs`` / ``rw_hot_keys`` /
    ``rw_state_topology`` over pgwire see the live books."""
    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.state.topology import TOPOLOGY
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.stream.costs import COSTS
    from risingwave_tpu.stream.hotkeys import HOTKEYS

    fe = Frontend(HummockLite(_snapshot_clone(obj)))
    await fe.recover()
    try:
        for cycle in range(max(1, args.watch)):
            await fe.step(args.steps)
            if cycle:
                print()
            imb = TOPOLOGY.imbalance_by_mv()
            print(f"== refresh {cycle + 1} — per-MV serving cost ==")
            print(f"{'device_s':>10} {'h2d_B':>12} {'d2h_B':>12} "
                  f"{'state_B':>12} {'compile':>12} {'charge_s':>9} "
                  f"{'imb':>5}  mv (domain)")
            rows = sorted(COSTS.rows(), key=lambda r: -r[2])
            for (mv, dom, dev, h2d, d2h, state, hits, misses,
                 shared, rescale_s, recovery_s) in rows:
                comp = f"{hits}h/{misses}m"
                if shared:
                    comp += f"/{shared}s"
                print(f"{dev:>10.4f} {h2d:>12} {d2h:>12} "
                      f"{state:>12} {comp:>12} "
                      f"{rescale_s + recovery_s:>9.2f} "
                      f"{imb.get(mv, 1.0):>5.2f}  {mv}"
                      + (f" ({dom})" if dom else ""))
            if not rows:
                print("(no attributed epochs yet — is stream_costs "
                      "off?)")
            hot = HOTKEYS.rows()
            if hot:
                print("== hot keys (top rank per input) ==")
                for (mv, ex, rank, key, est, share, err) in hot:
                    if rank:
                        continue
                    print(f"  {share:>6.1%} (±{err:.1%}) "
                          f"{key!r}  {mv} / {ex}")
    finally:
        await fe.close()
    return 0


async def _ctl_compaction(obj, args) -> int:
    """Recover into an in-memory clone (same snapshot discipline as
    `table scan`), flip the DEDICATED arm on, drive a few checkpoints
    per refresh, and print the compaction view: per-level topology
    (L0 run count, L1 runs with tombstone density), the space-amp
    gauge, and the task ledger (rw_compaction) the clone's manager
    produced. ``--watch N`` repeats the drive+print cycle N times. On
    a serving cluster, ``SET storage_compaction='dedicated'`` there
    and ``SELECT * FROM rw_compaction`` over pgwire see the live
    ledger."""
    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.meta.compaction import compaction_rows
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.utils.metrics import STORAGE

    store = HummockLite(_snapshot_clone(obj))
    fe = Frontend(store)
    await fe.recover()
    try:
        await fe.execute("SET storage_compaction = 'dedicated'")
        for cycle in range(max(1, args.watch)):
            await fe.step(args.steps)
            if cycle:
                print()
            snap = store.level_snapshot()
            l0, l1 = snap["l0"], snap["l1"]
            print(f"== refresh {cycle + 1} — level topology "
                  f"(version {snap['version_id']}) ==")
            print(f"L0: {len(l0)} runs, "
                  f"{sum(i.get('size', 0) for i in l0)}B")
            for i in l1:
                n = i.get("count", 0) or 1
                print(f"L1 sst {i['id']}: {i.get('size', 0)}B "
                      f"{i.get('count', 0)} keys, tombstones "
                      f"{i.get('tombstones', 0) / n:.0%}")
            if snap.get("reserved"):
                print(f"reserved under in-flight tasks: "
                      f"{snap['reserved']}")
            print(f"space_amp {STORAGE.storage_space_amp.get():.3f}  "
                  f"pending "
                  f"{STORAGE.compaction_pending_tasks.get():.0f}")
            rows = compaction_rows()
            print("== compaction task ledger ==")
            if not rows:
                print("(no tasks — levels below every picker's "
                      "trigger)")
            for (tid, ns, picker, state, ins, outs, br, bw, att,
                 dur, detail) in rows:
                print(f"#{tid} [{ns}] {picker} {state} in=[{ins}] "
                      f"out=[{outs}] read {br}B wrote {bw}B "
                      f"attempts {att} {dur:.2f}s"
                      + (f"  ({detail})" if detail else ""))
    finally:
        await fe.close()
    return 0


async def _ctl_sinks(obj, args) -> int:
    """Recover into an in-memory clone (same snapshot discipline as
    `table scan`) and print the sink view (rw_sinks): per-sink mode,
    committed epoch, staged-but-uncommitted epochs/bytes, and writer
    lag — all listing-driven from each sink's own object-store root,
    so the numbers are the REAL sink's, not the clone's. Note: DDL
    replay runs the standard recovery sweep on each epochlog sink
    (promote floor-covered staging, truncate the rest), exactly as a
    serving restart would. ``--steps K`` additionally drives K
    checkpoints, which APPENDS real rows to the sinks — default 0
    keeps inspection read-only."""
    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.storage.hummock import HummockLite

    store = HummockLite(_snapshot_clone(obj))
    fe = Frontend(store)
    await fe.recover()
    try:
        if args.steps:
            await fe.step(args.steps)
        rows = await fe.execute("SELECT * FROM rw_sinks")
        print("== sinks ==")
        if not rows:
            print("(no sinks)")
        for (name, connector, mode, epoch, staged, nbytes, lag) in rows:
            print(f"{name} [{connector}/{mode or 'legacy'}] "
                  f"committed_epoch {int(epoch):#x} "
                  f"staged_epochs {staged} staged {nbytes}B "
                  f"writer_lag {lag}")
    finally:
        await fe.close()
    return 0


def main(argv=None) -> None:
    # the axon sitecustomize rewrites jax_platforms at interpreter
    # start, overriding JAX_PLATFORMS=cpu — honor the env var so ctl /
    # CI runs never block on a TPU tunnel they did not ask for
    import os
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    p = argparse.ArgumentParser(prog="risingwave_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)
    sc = sub.add_parser("serve-cluster",
                        help="pgwire over an N-worker cluster")
    sc.add_argument("--data-dir", required=True)
    sc.add_argument("--workers", type=int, default=2)
    sc.add_argument("--parallelism", type=int, default=None)
    sc.add_argument("--host", default="127.0.0.1")
    sc.add_argument("--port", type=int, default=4566)
    for name in ("playground", "serve"):
        sp = sub.add_parser(name)
        sp.add_argument("--host", default="127.0.0.1")
        sp.add_argument("--port", type=int, default=4566)
        if name == "serve":               # playground is in-memory only
            sp.add_argument("--data-dir", required=True)
    ctl = sub.add_parser("ctl")
    ctl.add_argument("--data-dir", required=True)
    csub = ctl.add_subparsers(dest="ctl_cmd", required=True)
    meta = csub.add_parser("meta")
    meta.add_argument("what", choices=["catalog"])
    hm = csub.add_parser("hummock")
    hm.add_argument("what", choices=["version", "list-ssts"])
    tb = csub.add_parser("table")
    tb.add_argument("what", choices=["scan"])
    tb.add_argument("ident")
    tb.add_argument("-n", "--limit", type=int, default=20)
    mt = csub.add_parser(
        "metrics", help="recover + dump the Prometheus exposition")
    mt.add_argument("--steps", type=int, default=2,
                    help="checkpoint barriers to drive before the dump")
    mm = csub.add_parser(
        "memory",
        help="recover + dump host-memory accounting and state-tier "
             "residency")
    mm.add_argument("--steps", type=int, default=2,
                    help="checkpoint barriers to drive before the dump")
    tr = csub.add_parser(
        "trace",
        help="recover + export epoch-causal traces as Chrome "
             "trace-event JSON (Perfetto-loadable; includes phase "
             "lanes and byte/queue-depth counter tracks)")
    tr.add_argument("--steps", type=int, default=4,
                    help="checkpoint barriers to drive before export")
    tr.add_argument("--out", default=None,
                    help="write the JSON here instead of stdout")
    ph = csub.add_parser(
        "phases",
        help="recover + print the epoch phase ledger: per-barrier "
             "host/device time+bytes breakdown, conservation "
             "coverage, compiled-kernel cost yardsticks")
    ph.add_argument("--steps", type=int, default=4,
                    help="checkpoint barriers to drive before the "
                         "report")
    tp = csub.add_parser(
        "top",
        help="recover + print the live-ops view: actor utilization "
             "tricolor (busy/backpressure/idle), per-MV event-time "
             "freshness, and each domain's walked bottleneck")
    tp.add_argument("--steps", type=int, default=4,
                    help="checkpoint barriers to drive per refresh")
    tp.add_argument("--watch", type=int, default=1,
                    help="refresh cycles to print (drive+print each)")
    asc = csub.add_parser(
        "autoscale",
        help="recover + print the elastic control loop's view: the "
             "rw_autoscaler decision ledger and the bottleneck/"
             "freshness signals a decision would read")
    asc.add_argument("--steps", type=int, default=4,
                     help="checkpoint barriers to drive before the "
                          "report")
    co = csub.add_parser(
        "cost",
        help="recover + print the serving-cost attribution view: "
             "per-MV device-seconds / transfer / state / compile-"
             "cache ledger, hot-vnode imbalance, and heavy-hitter "
             "keys")
    co.add_argument("--steps", type=int, default=4,
                    help="checkpoint barriers to drive per refresh")
    co.add_argument("--watch", type=int, default=1,
                    help="refresh cycles to print (drive+print each)")
    cp = csub.add_parser(
        "compaction",
        help="recover + print the leveled-compaction view: per-level "
             "topology, tombstone density, space amp, and the "
             "dedicated-arm task ledger (rw_compaction)")
    cp.add_argument("--steps", type=int, default=4,
                    help="checkpoint barriers to drive per refresh")
    cp.add_argument("--watch", type=int, default=1,
                    help="refresh cycles to print (drive+print each)")
    sk = csub.add_parser(
        "sinks",
        help="recover + print the sink view (rw_sinks): per-sink "
             "committed epoch, staged-but-uncommitted epochs/bytes, "
             "writer lag — listing-driven from each sink's root")
    sk.add_argument("--steps", type=int, default=0,
                    help="checkpoint barriers to drive first (writes "
                         "real sink rows; default 0 = read-only)")
    bk = csub.add_parser("backup")
    bk.add_argument("what",
                    choices=["create", "list", "delete", "restore"])
    bk.add_argument("ident", nargs="?")
    bk.add_argument("--target")
    args = p.parse_args(argv)
    if args.cmd == "ctl":
        sys.exit(_ctl(args))
    if not hasattr(args, "data_dir"):
        args.data_dir = None
    if args.cmd == "serve-cluster":
        asyncio.run(_serve_cluster(args))
        return
    asyncio.run(_serve(args))


if __name__ == "__main__":
    main()
