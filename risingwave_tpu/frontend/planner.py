"""Planner: bound SELECT → streaming executor chain or batch tree.

Reference parity: src/frontend/src/planner/ + optimizer/mod.rs:346
(gen_stream_plan) + the fragmenter — collapsed: the supported SQL
surface maps directly onto executor chains (source → [tumble-project]
→ [filter] → [join] → [pre-agg project → hash-agg] → project →
materialize), so the logical/physical split and exchange insertion are
not yet needed (single-fragment plans; the dispatch layer exists under
stream/ for when the fragmenter lands).

Supported streaming shapes: MV over one source (optionally TUMBLE) or
over another MV (backfill chain), WHERE conjuncts as filters over the
join chain (the frontend/opt filter_pushdown rule sinks them below
joins, gated by join kind), multi-way left-deep
INNER/LEFT/RIGHT/FULL joins of sources on equi-keys, GROUP BY with
count/sum/min/max/avg (+DISTINCT) over arbitrary expressions, ORDER
BY/LIMIT TopN, EXPLAIN. Batch: scan/filter/project/agg/join/order/
limit over committed MV snapshots.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from risingwave_tpu.common.types import DataType, Field, Interval, Schema
from risingwave_tpu.expr.expr import (
    BinaryOp, Cast, Expression, InputRef, tumble_start,
)
from risingwave_tpu.frontend import ast
from risingwave_tpu.frontend.binder import (
    BindError, Binder, Scope, expr_name,
)
from risingwave_tpu.frontend.catalog import Catalog, MvCatalog, SourceCatalog
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.stream.executor import Executor
from risingwave_tpu.stream.executors.hash_agg import (
    AggCall, HashAggExecutor, agg_state_schema,
)
from risingwave_tpu.stream.executors.hash_join import (
    HashJoinExecutor, JoinType,
)
from risingwave_tpu.stream.executors.materialize import MaterializeExecutor
from risingwave_tpu.stream.executors.row_id_gen import RowIdGenExecutor
from risingwave_tpu.stream.executors.simple import (
    FilterExecutor, ProjectExecutor,
)
from risingwave_tpu.stream.executors.source import SourceExecutor

SPLIT_STATE_SCHEMA = Schema([Field("split_id", DataType.VARCHAR),
                             Field("offset", DataType.INT64)])


class PlanError(ValueError):
    pass


@dataclass
class StreamPlan:
    """Everything the session needs to deploy one MV pipeline."""

    consumer: MaterializeExecutor
    mv: MvCatalog
    readers: Dict[int, object]          # actor_id → split reader
    # MV-on-MV chain edges: (upstream actor id, Output) to attach at
    # deploy (NOT at plan time — a failed plan must leak nothing)
    attaches: List[tuple] = field(default_factory=list)


@dataclass
class SinkPlan:
    consumer: Executor                  # SinkExecutor chain
    deps: List[str]
    readers: Dict[int, object]
    attaches: List[tuple] = field(default_factory=list)
    # exactly-once epoch-segment sinks (connector='epochlog'): the
    # derived record mode and the built encoder — the SESSION
    # registers the encoder on its SinkCoordinator after the plan
    # validates (a failed plan must leak no registration), with the
    # store's committed floor as the recovery sweep point
    mode: str = ""                      # "append" | "upsert" | legacy ""
    encoder: object = None


def validate_sink_options(options: Dict[str, str]) -> None:
    """Pre-plan option validation (CREATE SINK fails before any
    barrier sender registers)."""
    if options.get("connector", "").lower() == "epochlog":
        if not options.get("path"):
            raise PlanError("epochlog sink needs path='...'")
        return
    make_sink_writer(options)


def make_sink_writer(options: Dict[str, str]):
    """connector= blackhole | file | filelog (build_sink analog)."""
    from risingwave_tpu.stream.executors.sink import (
        BlackholeSink, FileSink, FilelogSink,
    )
    connector = options.get("connector", "").lower()
    if connector == "blackhole":
        return BlackholeSink()
    if connector == "file":
        path = options.get("path")
        if not path:
            raise PlanError("file sink needs path='...'")
        return FileSink(path)
    if connector == "filelog":
        path = options.get("path")
        topic = options.get("topic")
        if not path or not topic:
            raise PlanError(
                "filelog sink needs path='...' and topic='...'")
        return FilelogSink(path, topic,
                           partition=int(options.get("partition", 0)))
    raise PlanError(f"unknown sink connector {connector!r}")


def _source_reader(src: SourceCatalog):
    opts = src.options
    connector = opts.get("connector", "").lower()
    if connector == "nexmark":
        from risingwave_tpu.connectors.nexmark import (
            NexmarkConfig, NexmarkSplitReader,
        )
        cfg = NexmarkConfig(
            table_type=opts.get("nexmark.table.type", "bid"),
            event_num=int(opts.get("nexmark.event.num", 1 << 62)),
            max_chunk_size=int(opts.get("nexmark.max.chunk.size", 1024)),
            min_event_gap_in_ns=int(
                opts.get("nexmark.min.event.gap.in.ns", 100_000)),
            seed=int(opts.get("nexmark.seed", 0x5EED0)),
            generate_strings=str(opts.get(
                "nexmark.generate.strings", "true")).lower()
            not in ("false", "0"),
        )
        return NexmarkSplitReader(cfg)
    if connector == "datagen":
        from risingwave_tpu.connectors.datagen import (
            DatagenConfig, DatagenSplitReader,
        )
        return DatagenSplitReader(DatagenConfig.from_options(opts))
    if connector == "filelog":
        from risingwave_tpu.connectors.filelog import (
            FileLogEnumerator, FileLogSplitReader,
        )
        path = opts.get("path")
        topic = opts.get("topic", src.name)
        if not path:
            raise PlanError("filelog source needs path='...'")
        part = int(opts.get("partition", 0))
        if opts.get("segmented", "").lower() in ("true", "1"):
            # a filelog SINK's output: immutable per-epoch segments
            from risingwave_tpu.connectors.filelog import (
                SegmentedFileLogReader,
            )
            return SegmentedFileLogReader(
                path, topic, part, src.schema,
                fmt=opts.get("format", "json"),
                max_chunk_size=int(opts.get("max.chunk.size", 1024)),
                options=opts)
        if "partitions" in opts:
            # explicit split subset (the scheduler stamps each source
            # actor's assignment here — the split-rebalancing
            # contract); "" is a legal EMPTY assignment: scale-out
            # past the partition count leaves idle source actors
            from risingwave_tpu.connectors.filelog import (
                FileLogMultiReader,
            )
            spec = str(opts["partitions"]).strip()
            parts = [int(p) for p in spec.split(",") if p != ""]
            return FileLogMultiReader(
                path, topic, parts, src.schema,
                fmt=opts.get("format", "json"),
                max_chunk_size=int(opts.get("max.chunk.size", 1024)),
                options=opts)
        splits = FileLogEnumerator(path, topic).list_splits()
        # bare single-pipeline sources: one reader drives partition 0
        # (the distributed scheduler assigns explicit partition sets)
        if splits and not any(
                int(s.split_id.rsplit("-", 1)[1]) == part
                for s in splits):
            raise PlanError(
                f"filelog partition {part} not found in {path!r}")
        return FileLogSplitReader(
            path, topic, part, src.schema,
            fmt=opts.get("format", "json"),
            max_chunk_size=int(opts.get("max.chunk.size", 1024)),
            options=opts)
    if connector == "tpch":
        from risingwave_tpu.connectors.tpch import (
            TpchConfig, TpchSplitReader,
        )
        return TpchSplitReader(TpchConfig(
            table=opts.get("tpch.table", "lineitem"),
            customers=int(opts.get("tpch.customers", 1500)),
            orders=int(opts.get("tpch.orders", 15000)),
            max_chunk_size=int(opts.get("tpch.max.chunk.size", 1024)),
        ))
    raise PlanError(f"unknown connector {connector!r}")


def source_schema(options: Dict[str, str],
                  columns=None) -> Schema:
    connector = options.get("connector", "").lower()
    if columns is not None:
        fields = []
        for name, type_name in columns:
            try:
                fields.append(Field(name, DataType.from_sql(type_name)))
            except KeyError:
                raise PlanError(f"unknown type {type_name!r}")
        return Schema(fields)
    if connector == "nexmark":
        from risingwave_tpu.connectors.nexmark import TABLE_SCHEMAS
        return TABLE_SCHEMAS[options.get("nexmark.table.type", "bid")]
    if connector == "datagen":
        from risingwave_tpu.connectors.datagen import DatagenConfig
        return DatagenConfig.from_options(options).schema
    if connector == "tpch":
        from risingwave_tpu.connectors.tpch import TABLE_SCHEMAS
        return TABLE_SCHEMAS[options.get("tpch.table", "lineitem")]
    if connector == "filelog":
        raise PlanError(
            "filelog sources need an explicit column list: "
            "CREATE SOURCE t (a INT, ...) WITH (...)")
    raise PlanError(f"unknown connector {connector!r}")


class StreamPlanner:
    """Plans one CREATE MATERIALIZED VIEW into an executor chain."""

    def __init__(self, catalog: Catalog, store, local, definition: str,
                 mesh=None, actors=None, dist_parallelism: int = 1,
                 join_state_cap=None, inline_mvs=None,
                 chunk_target_rows: Optional[int] = None,
                 coalesce_linger_chunks: Optional[int] = None,
                 state_tier_cap: Optional[int] = None):
        from risingwave_tpu.stream.coalesce import (
            DEFAULT_MAX_CHUNKS, DEFAULT_TARGET_ROWS,
        )
        self.catalog = catalog
        self.store = store
        # adaptive coalescing in front of keyed executors (session var
        # stream_chunk_target_rows; 0 disables — the oracle-equivalence
        # tests compare on vs off)
        self.chunk_target_rows = DEFAULT_TARGET_ROWS \
            if chunk_target_rows is None else chunk_target_rows
        self.coalesce_linger_chunks = DEFAULT_MAX_CHUNKS \
            if coalesce_linger_chunks is None else coalesce_linger_chunks
        self.local = local           # LocalBarrierManager
        self.definition = definition
        self.mesh = mesh             # non-None ⇒ sharded GROUP BY plans
        # > 1 ⇒ the plan deploys over N cluster actors: eligible
        # GROUP BYs split into local partial + global merge aggs
        # (logical_agg.rs two-phase), with the hash exchange between
        # them inserted by the fragmenter
        self.dist_parallelism = max(1, dist_parallelism)
        # resident-row cap per join side: INNER joins get the
        # cold-state tier (evict to the state table, reload on probe
        # miss — managed_state/join/mod.rs:379-420)
        self.join_state_cap = join_state_cap
        # unified state-tiering cap (SET state_tier_cap, state/tier.py):
        # resident-key cap per stateful executor cache — applies to
        # hash-agg groups AND join sides (where it takes precedence
        # over the legacy join_state_cap)
        self.state_tier_cap = state_tier_cap
        # name → (select AST, eowc): FROM <mv> replans the view's
        # definition INLINE instead of attaching to its live actor —
        # the distributed session's MV-on-MV form (classic view
        # expansion; no cross-job edges needed, every fragment ships)
        self.inline_mvs = dict(inline_mvs or {})
        self.actors = actors or {}   # actor_id → Actor (MV-on-MV attach)
        self.readers: Dict[int, object] = {}
        # chain edges produced by _chain_upstream_mv, attached by the
        # session once the WHOLE plan has validated
        self.pending_attaches: List[tuple] = []
        self.registered_senders: List[int] = []   # cleanup on failure
        self._actor_id = 0           # downstream actor id (Output tag)
        self._edge_seq = 0           # per-channel edge-label uniquifier

    def _edge_label(self, kind: str, name: str) -> str:
        """Unique exchange-edge label: kind:name->actor[.seq]."""
        self._edge_seq += 1
        base = f"{kind}:{name}->{self._actor_id}"
        return base if self._edge_seq == 1 else \
            f"{base}.{self._edge_seq}"

    def _coalesced(self, ex: Executor) -> Executor:
        """Adaptive coalescing in front of a keyed executor's input:
        every device dispatch then carries a dense target-sized batch
        instead of per-upstream-chunk slivers (stream/coalesce.py).
        Disabled when stream_chunk_target_rows = 0."""
        if not self.chunk_target_rows or self.chunk_target_rows <= 0:
            return ex
        from risingwave_tpu.stream.coalesce import CoalesceExecutor
        return CoalesceExecutor(ex, self.chunk_target_rows,
                                self.coalesce_linger_chunks)

    # -- source chains ---------------------------------------------------
    def _base_chain(self, item, rate_limit: Optional[int],
                    min_chunks: Optional[int]
                    ) -> Tuple[Executor, Scope, List[str]]:
        """FROM item → executor + scope (+ dependent source names)."""
        from risingwave_tpu.stream.exchange import channel_for_test

        if isinstance(item, ast.Subquery):
            return self._plan_derived(item.select, item.alias,
                                      rate_limit, min_chunks)
        if isinstance(item, (ast.Tumble, ast.Hop)):
            ref, alias = item.table, item.alias or item.table.name
        elif isinstance(item, ast.TableRef):
            ref, alias = item, item.alias or item.name
        else:
            raise PlanError(f"unsupported FROM item {item!r}")
        obj = self.catalog.resolve(ref.name)
        if isinstance(obj, MvCatalog):
            if isinstance(item, (ast.Tumble, ast.Hop)):
                raise PlanError(
                    "TUMBLE/HOP over an MV not supported yet")
            inline = self.inline_mvs.get(obj.name)
            if inline is not None:
                sel_i, eowc_i = inline
                if eowc_i:
                    raise PlanError(
                        "cannot inline an EMIT ON WINDOW CLOSE view")
                ex, scope, deps = self._plan_derived(
                    sel_i, alias, rate_limit, min_chunks)
                # the VIEW name joins the dep list: DROP of the base
                # view must refuse while this consumer runs (the
                # in-process chain branch records it the same way)
                return ex, scope, deps + [obj.name]
            ex, scope = self._chain_upstream_mv(obj, alias)
            return ex, scope, [obj.name]
        assert isinstance(obj, SourceCatalog)
        reader = _source_reader(obj)
        # edge labels are unique per CHANNEL (consumer actor id + a
        # per-plan sequence for self-joins of one source): sharing a
        # series would merge independent pipes, and teardown of one
        # would remove the other's queue-depth gauge
        tx, rx = channel_for_test(edge=self._edge_label("barrier",
                                                        obj.name))
        split_state = StateTable(self.catalog.next_id(),
                                 SPLIT_STATE_SCHEMA, [0], self.store)
        # source sender id: unique per source instance (shares the
        # catalog id space; the barrier manager only needs uniqueness)
        sid = self.catalog.next_id()
        self.local.register_sender(sid, tx)
        self.registered_senders.append(sid)
        ex: Executor = SourceExecutor(
            reader, rx, split_state, actor_id=sid,
            rate_limit_chunks_per_barrier=rate_limit,
            min_chunks_per_barrier=min_chunks,
            # freshness accounting key (stream/freshness.py): the
            # CATALOG name, so per-MV lag joins source frontiers by
            # the name the MV's dependency list carries
            freshness_key=obj.name)
        # connector options ride along for the fragmenter: the shipped
        # source IR node rebuilds the reader worker-side from these
        ex.ir_connector = dict(obj.options)
        self.readers[sid] = reader
        scope = Scope.of(obj.schema, alias)
        # event-time watermarks from SQL: WITH (watermark.column='ts',
        # watermark.delay='4 seconds') — the WATERMARK FOR clause's
        # role (source/watermark.rs), driving state cleaning and EOWC
        wm_col_name = obj.options.get("watermark.column")
        wm_idx = None
        self._wm_scope_cols = set()
        if wm_col_name:
            from risingwave_tpu.stream.executors.watermark_filter \
                import WATERMARK_STATE_SCHEMA, WatermarkFilterExecutor
            wm_idx, wdt = scope.find(wm_col_name, None)
            if wdt not in (DataType.TIMESTAMP, DataType.TIMESTAMPTZ):
                raise PlanError(
                    "watermark.column must be a timestamp")
            delay = _parse_interval_opt(
                obj.options.get("watermark.delay", "0 seconds"))
            wm_state = StateTable(self.catalog.next_id(),
                                  WATERMARK_STATE_SCHEMA, [0],
                                  self.store)
            ex = WatermarkFilterExecutor(ex, wm_idx, delay, wm_state)
            self._wm_scope_cols.add(wm_idx)
        if isinstance(item, ast.Tumble):
            idx, dt = scope.find(item.time_col, None)
            if dt not in (DataType.TIMESTAMP, DataType.TIMESTAMPTZ):
                raise PlanError("TUMBLE time column must be a timestamp")
            exprs = [InputRef(i, f.data_type)
                     for i, f in enumerate(scope.schema)]
            names = [f.name for f in scope.schema]
            exprs.append(tumble_start(InputRef(idx, dt),
                                      Interval(usecs=item.window_usecs)))
            names.append("window_start")
            derivs = {}
            if wm_idx is not None:
                # identity for the raw column AND (when it is the
                # tumble column) the floored window_start image — one
                # input watermark derives both outputs
                derivs[wm_idx] = [wm_idx]
                if wm_idx == idx:
                    w = item.window_usecs
                    derivs[idx].append(
                        (len(exprs) - 1,
                         (lambda v, _w=w: v - v % _w)))
                    self._wm_scope_cols.add(len(exprs) - 1)
            ex = ProjectExecutor(ex, exprs, names,
                                 watermark_derivations=derivs)
            scope = Scope(ex.schema,
                          scope.qualifiers + [alias])
        elif isinstance(item, ast.Hop):
            from risingwave_tpu.stream.executors.hop_window import (
                HopWindowExecutor,
            )
            idx, dt = scope.find(item.time_col, None)
            if dt not in (DataType.TIMESTAMP, DataType.TIMESTAMPTZ):
                raise PlanError("HOP time column must be a timestamp")
            ex = HopWindowExecutor(
                ex, idx, Interval(usecs=item.slide_usecs),
                Interval(usecs=item.size_usecs))
            # schema gains window_start/window_end, same qualifier
            scope = Scope(ex.schema,
                          scope.qualifiers + [alias, alias])
        return ex, scope, [obj.name]

    def _plan_derived(self, sel, alias, rate_limit, min_chunks):
        """Derived table: plan an inner SELECT as this fragment's
        upstream chain (binder/bind_query subquery analog — shared by
        FROM-subqueries and inlined views). Hidden pk columns stay in
        the executor schema but out of the visible scope; the derived
        pk is STAMPED onto the executor so a consumer join keys its
        state by it — fresh row ids instead would orphan every U-
        retraction half and leave stale rows in join state."""
        from risingwave_tpu.stream.executor import ExecutorInfo

        ex, pk, deps, n_vis = self._plan_query(
            sel, self._actor_id, rate_limit, min_chunks)
        ex._info = ExecutorInfo(ex.schema, list(pk), ex.identity)
        self._wm_scope_cols = set()   # wm feed unproven through
        self._eowc_wm_col = None      # inner value is meaningless
        #                               against the OUTER schema
        vis = Schema(list(ex.schema)[:n_vis])
        return ex, Scope(vis, [alias] * n_vis), deps

    def _chain_upstream_mv(self, mv: MvCatalog, alias: str):
        """FROM <mv>: attach a new output to the upstream MV's actor
        (chain.rs:28) and backfill its committed snapshot
        (no_shuffle_backfill.rs:68). The attach happens under the
        session's barrier lock — the pipeline is quiescent between
        barrier rounds, so mutating the dispatcher's output set is the
        Mutation::Add analog without the RPC hop."""
        from risingwave_tpu.stream.dispatch import Output
        from risingwave_tpu.stream.exchange import channel_for_test
        from risingwave_tpu.stream.executor import ExecutorInfo
        from risingwave_tpu.stream.executors.backfill import (
            PROGRESS_SCHEMA, BackfillExecutor,
        )
        from risingwave_tpu.stream.executors.simple import (
            ReceiverExecutor,
        )

        upstream = self.actors.get(mv.actor_id)
        if upstream is None or not upstream.dispatchers:
            raise PlanError(
                f"upstream MV {mv.name!r} has no attachable actor")
        tx, rx = channel_for_test(edge=self._edge_label("chain",
                                                        mv.name))
        # deferred: the session attaches AFTER the whole plan validates
        # (a failed CREATE must not leave an orphan output that blocks
        # the upstream on exhausted permits), tagged with the DOWNSTREAM
        # actor id so drops can detach exactly this edge
        self.pending_attaches.append(
            (mv.actor_id, Output(self._actor_id, tx)))
        recv = ReceiverExecutor(
            ExecutorInfo(mv.schema, list(mv.pk_indices),
                         f"Chain({mv.name})"), rx)
        mv_read = StateTable(mv.table_id, mv.schema, mv.pk_indices,
                             self.store)
        progress = StateTable(self.catalog.next_id(), PROGRESS_SCHEMA,
                              [0], self.store)
        ex = BackfillExecutor(recv, mv_read, progress,
                              identity=f"Backfill({mv.name})")
        # the backfill snapshot replays the MV's TABLE as inserts and
        # the live tail is the MV's changelog — the chain is
        # append-only exactly when the MV's own changelog is
        # (_derive_append_only reads this hint at the chain boundary)
        ex.append_only_hint = mv.append_only
        # expose only the MV's user-facing columns (hidden _row_id /
        # group-key plumbing stays out of downstream scopes)
        return ex, Scope.of(mv.visible_schema, alias)

    # -- the main plan ---------------------------------------------------
    def plan(self, name: str, sel: ast.Select, actor_id: int,
             rate_limit: Optional[int] = 8,
             min_chunks: Optional[int] = None,
             emit_on_window_close: bool = False) -> StreamPlan:
        self._actor_id = actor_id
        self._eowc_wm_col = None
        ex, pk, deps, nvis = self._plan_query(sel, actor_id,
                                              rate_limit, min_chunks)
        if emit_on_window_close:
            # gate results behind the window watermark (sort_buffer.rs
            # / AggGroup::create_eowc semantics as a downstream gate)
            from risingwave_tpu.stream.executors.eowc import (
                EowcGateExecutor,
            )
            wm_col = self._eowc_wm_col
            if wm_col is None:
                raise PlanError(
                    "EMIT ON WINDOW CLOSE needs a windowed GROUP BY "
                    "whose first group key is projected and carries a "
                    "watermark (e.g. TUMBLE window_start)")
            gate_pk = [wm_col] + [p for p in pk if p != wm_col]
            gate_state = StateTable(self.catalog.next_id(), ex.schema,
                                    gate_pk, self.store)
            ex = EowcGateExecutor(ex, wm_col, gate_state,
                                  actor_id=actor_id)
        mv_table = StateTable(self.catalog.next_id(), ex.schema, pk,
                              self.store)
        mat = MaterializeExecutor(ex, mv_table, mv_name=name)
        mv = MvCatalog(name, mv_table.table_id, ex.schema, pk,
                       self.definition, actor_id, deps,
                       n_visible=nvis if nvis < len(ex.schema) else None,
                       append_only=self._derive_append_only(ex))
        return StreamPlan(mat, mv, self.readers, self.pending_attaches)

    def plan_sink(self, sel: ast.Select, options: Dict[str, str],
                  actor_id: int, rate_limit: Optional[int] = 8,
                  min_chunks: Optional[int] = None,
                  sink_name: str = "",
                  append_only: Optional[bool] = None,
                  coordinator=None, writer_id: int = 0,
                  n_writers: int = 1) -> SinkPlan:
        """CREATE SINK AS SELECT: same chain, terminal SinkExecutor."""
        from risingwave_tpu.stream.executors.sink import SinkExecutor

        self._actor_id = actor_id
        ex, pk, deps, nvis = self._plan_query(sel, actor_id,
                                              rate_limit, min_chunks)
        # _plan_query appends hidden _pk columns even when the stream
        # key is already visibly projected (e.g. SELECT * over a
        # group-by MV re-emits the group key as _pk0).  A sink drops
        # hidden columns, so remap each hidden pk ref to its visible
        # twin when both project the same upstream column.
        if pk and nvis < len(ex.schema) and isinstance(ex, ProjectExecutor):
            vis_by_ref = {e.index: v
                          for v, e in enumerate(ex.exprs[:nvis])
                          if isinstance(e, InputRef)}
            remapped = []
            for p in pk:
                if p < nvis:
                    remapped.append(p)
                    continue
                e = ex.exprs[p] if p < len(ex.exprs) else None
                if isinstance(e, InputRef) and e.index in vis_by_ref:
                    remapped.append(vis_by_ref[e.index])
                else:
                    remapped = None
                    break
            if remapped is not None:
                pk = remapped
        if nvis < len(ex.schema):
            # hidden plumbing columns (_row_id, unprojected group keys)
            # must not reach an EXTERNAL sink — emit exactly the
            # declared SELECT list
            ex = ProjectExecutor(
                ex, [InputRef(i, f.data_type)
                     for i, f in enumerate(list(ex.schema)[:nvis])],
                [f.name for f in list(ex.schema)[:nvis]])
        if options.get("connector", "").lower() == "epochlog":
            return self._plan_epoch_sink(
                ex, pk, deps, options, sink_name=sink_name,
                append_only=append_only, coordinator=coordinator,
                writer_id=writer_id, n_writers=n_writers)
        writer = make_sink_writer(options)
        # durable stream-position counter: the exactly-once writers'
        # recovery reconciliation anchor (sink coordinator epoch-log);
        # built only for writers that reconcile — an unread counter
        # would cost a table id + a write per checkpoint for nothing
        sink_state = None
        if hasattr(writer, "reset_stream_position"):
            sink_state = StateTable(
                self.catalog.next_id(),
                Schema([Field("_k", DataType.INT64),
                        Field("_count", DataType.INT64)]),
                [0], self.store)
        return SinkPlan(SinkExecutor(ex, writer, state=sink_state),
                        deps, self.readers, self.pending_attaches)

    def _plan_epoch_sink(self, ex: Executor, pk: List[int],
                         deps: List[str], options: Dict[str, str],
                         sink_name: str,
                         append_only: Optional[bool],
                         coordinator, writer_id: int,
                         n_writers: int) -> SinkPlan:
        """connector='epochlog': the exactly-once epoch-segment sink
        (connectors/sink.py). Derives the record mode from the input
        chain — provably append-only ⇒ insert-only records; anything
        else ⇒ keyed upsert records folded per epoch — and builds the
        terminal CoordinatedSinkExecutor. Registration on the
        coordinator is the CALLER's job post-validation."""
        from risingwave_tpu.connectors.sink import (
            AppendSegmentSink, UpsertSegmentSink, make_sink_target,
        )
        from risingwave_tpu.stream.executors.sink import (
            CoordinatedSinkExecutor,
        )
        derived = self._derive_append_only(ex)
        if append_only and not derived \
                and options.get("force", "").lower() != "true":
            raise PlanError(
                "sink declared AS APPEND-ONLY but the query is not "
                "provably append-only; add force='true' to override "
                "(retractions then fail the sink loudly)")
        mode = "append" if (append_only or derived) else "upsert"
        names = [f.name for f in ex.schema]
        pk_indices: List[int] = []
        if mode == "upsert":
            if options.get("primary_key"):
                want = [c.strip() for c in
                        options["primary_key"].split(",") if c.strip()]
                missing = [c for c in want if c not in names]
                if missing:
                    raise PlanError(
                        f"primary_key column(s) {missing} not in sink "
                        f"schema {names}")
                pk_indices = [names.index(c) for c in want]
            else:
                if not pk or any(i >= len(names) for i in pk):
                    raise PlanError(
                        "upsert sink needs a key: the query's stream "
                        "key is hidden or absent — name one with "
                        "primary_key='col1,col2' in WITH (...)")
                pk_indices = list(pk)
        try:
            target = make_sink_target(options, mode, names)
        except ValueError as e:
            raise PlanError(str(e)) from e
        encoder = (AppendSegmentSink(target) if mode == "append"
                   else UpsertSegmentSink(target, pk_indices))
        consumer = CoordinatedSinkExecutor(
            ex, sink_name, encoder, writer=writer_id,
            n_writers=n_writers, coordinator=coordinator)
        return SinkPlan(consumer, deps, self.readers,
                        self.pending_attaches, mode=mode,
                        encoder=encoder)

    def _plan_query(self, sel: ast.Select, actor_id: int,
                    rate_limit: Optional[int],
                    min_chunks: Optional[int]):
        if sel.from_item is None:
            raise PlanError("a streaming job needs a FROM clause")
        ex, scope, deps = self._base_chain(sel.from_item,
                                           rate_limit, min_chunks)
        join_pk_cols: Optional[List[int]] = None
        conjuncts = _flatten_and(sel.where) if sel.where is not None \
            else []
        if sel.joins:
            # Optimizer v0 (multi-way planning, collapsed): a
            # left-deep chain of HashJoins in syntax order. WHERE
            # conjuncts bind AFTER the chain against the full scope
            # (ambiguous unqualified columns raise properly — ADVICE
            # r3) and land as filters ABOVE the joins; the
            # filter_pushdown rewrite rule (frontend/opt/rules.py, the
            # former inline pushdown) then sinks each one below every
            # side its join never null-pads.
            left, lscope = self._joinable(ex, scope)
            rights = []
            for jn in sel.joins:
                rex, rscope, rdeps = self._base_chain(
                    jn.item, rate_limit, min_chunks)
                deps += rdeps
                if getattr(jn, "temporal", False):
                    # temporal join: the right side IS a versioned
                    # table (MV chain with a pk) probed as-of process
                    # time — no row-id wrapping, no join state
                    if not rex.pk_indices:
                        raise PlanError(
                            "temporal join (FOR SYSTEM_TIME AS OF "
                            "PROCTIME()) needs a materialized view "
                            "on the right side")
                    if jn.kind not in ("inner", "left"):
                        raise PlanError(
                            "temporal join supports INNER and LEFT "
                            "only")
                    rights.append((jn, rex, rscope))
                    continue
                right, rscope = self._joinable(rex, rscope)
                rights.append((jn, right, rscope))
            for jn, right, rscope in rights:
                if getattr(jn, "temporal", False):
                    from risingwave_tpu.stream.executors.temporal_join \
                        import TemporalJoinExecutor
                    lkeys, rkeys = _equi_keys(jn.on, lscope, rscope)
                    if sorted(rkeys) != sorted(right.pk_indices):
                        raise PlanError(
                            "temporal join ON keys must equal the "
                            "right table's primary key")
                    if not self._derive_append_only(left):
                        raise PlanError(
                            "temporal join left input must be "
                            "append-only")
                    left = TemporalJoinExecutor(
                        left, right, lkeys, rkeys,
                        outer=(jn.kind == "left"),
                        actor_id=actor_id)
                    lscope = lscope.concat(rscope)
                    continue
                lkeys, rkeys = _equi_keys(jn.on, lscope, rscope)
                jt = {"inner": JoinType.INNER,
                      "left": JoinType.LEFT_OUTER,
                      "right": JoinType.RIGHT_OUTER,
                      "full": JoinType.FULL_OUTER}[jn.kind]
                # cold-tier eligibility: INNER or OUTER (outer-side
                # degrees recompute on reload — semi/anti transition
                # history cannot be evicted) + single-chip AND both
                # inputs PROVABLY append-only — a retraction for an
                # evicted key cannot be applied against device state
                # (ADVICE r5 high: the silent-skip would leave
                # already-emitted join outputs permanently stale), so
                # a retracting input runs uncapped instead
                tierable = (jt in (JoinType.INNER, JoinType.LEFT_OUTER,
                                   JoinType.RIGHT_OUTER,
                                   JoinType.FULL_OUTER)
                            and self.mesh is None
                            # distributed joins are fine: the
                            # fragmenter ships state_cap on the
                            # hash_join IR node, and worker rebuilds
                            # run the same single-chip epoch-batched
                            # path (per-actor cap)
                            and self._derive_append_only(left)
                            and self._derive_append_only(right))
                cap = (self.state_tier_cap or self.join_state_cap) \
                    if tierable else None
                if cap is not None:
                    # cold tier: state-table pks lead with the join
                    # keys so evicted keys reload by prefix scan
                    lpk = lkeys + [p for p in left.pk_indices
                                   if p not in lkeys]
                    rpk = rkeys + [p for p in right.pk_indices
                                   if p not in rkeys]
                    lt = StateTable(self.catalog.next_id(),
                                    left.schema, lpk, self.store,
                                    dist_key_indices=lkeys)
                    rt = StateTable(self.catalog.next_id(),
                                    right.schema, rpk, self.store,
                                    dist_key_indices=rkeys)
                else:
                    lt = StateTable(self.catalog.next_id(), left.schema,
                                    list(left.pk_indices), self.store,
                                    dist_key_indices=None)
                    rt = StateTable(self.catalog.next_id(), right.schema,
                                    list(right.pk_indices), self.store)
                # parallel plan: the hash exchange feeding N parallel
                # join actors (dispatch.rs:582) is the sharded kernel's
                # in-program all_to_all — same wiring as the agg path
                left = HashJoinExecutor(self._coalesced(left),
                                        self._coalesced(right),
                                        lkeys, rkeys, lt,
                                        rt, actor_id=actor_id,
                                        join_type=jt, mesh=self.mesh,
                                        state_cap=cap)
                lscope = lscope.concat(rscope)
            ex = left
            scope = lscope
            join_pk_cols = list(ex.pk_indices)
            # join output watermark indices are combined/re-based; the
            # EOWC feed proof does not track through joins yet
            self._wm_scope_cols = set()
        for c in conjuncts:
            ex = FilterExecutor(ex, Binder(scope).bind(c))
        projections = _expand_star(sel.projections, scope)
        if any(isinstance(e, ast.Call)
               and e.name in ("generate_series", "unnest")
               for e, _a in projections):
            return self._plan_project_set(ex, scope, sel, projections,
                                          deps)
        from risingwave_tpu.frontend.binder import contains_agg
        binder = Binder(scope, allow_aggs=True)
        names = [a or expr_name(e, f"col{i}")
                 for i, (e, a) in enumerate(projections)]
        has_agg = (bool(sel.group_by) or sel.having is not None
                   or any(contains_agg(e) for e, _a in projections))
        if has_agg:
            if any(isinstance(e, ast.Over) for e, _a in projections):
                raise PlanError("window functions cannot be mixed "
                                "with GROUP BY / aggregates (yet)")
            ex, out_exprs, having_pred = self._plan_agg(
                ex, scope, sel, binder, projections)
            # MV/stream key = the FULL group-key set. Unprojected group
            # keys ride along as hidden trailing columns (nexmark q4's
            # inner query groups by (id, category) but projects only
            # category — without the hidden id the change stream would
            # collide distinct groups). Global aggs carry ONE synthetic
            # constant key (set by _plan_agg).
            g = self._agg_group_arity
            proj_of_group: Dict[int, int] = {}
            for pos, e in enumerate(out_exprs):
                if isinstance(e, InputRef) and e.index < g \
                        and e.index not in proj_of_group:
                    proj_of_group[e.index] = pos
            for gi in range(g):
                if gi not in proj_of_group:
                    proj_of_group[gi] = len(out_exprs)
                    out_exprs.append(
                        InputRef(gi, ex.schema[gi].data_type))
                    names.append(f"_g{gi}")
            pk = [proj_of_group[gi] for gi in range(g)]
            # plain group-key outputs carry the agg's watermarks (the
            # EOWC gate and downstream window ops depend on them)
            derivs = {e.index: j for j, e in enumerate(out_exprs)
                      if isinstance(e, InputRef)}
            if having_pred is not None:
                # HAVING filters the agg's change stream BEFORE the
                # output projection (logical_agg.rs plans it as a
                # LogicalFilter over the agg)
                ex = FilterExecutor(ex, having_pred)
            ex = ProjectExecutor(ex, out_exprs, names,
                                 watermark_derivations=derivs)
            # EOWC window column: the first group key that PROVABLY
            # carries a watermark all the way from the source (a gate
            # with no watermark feed would hold results forever)
            self._eowc_wm_col = next(
                (derivs[pos] for pos in self._agg_wm_positions
                 if pos in derivs), None)
        else:
            bound = [binder.bind_projection(e) for e, _a in projections]
            if binder.window_calls:
                ex, bound = self._plan_over_window(ex, binder, bound)
            exprs = list(bound)
            base_pk = list(ex.pk_indices)
            if join_pk_cols is not None:
                pk = list(range(len(exprs),
                                len(exprs) + len(join_pk_cols)))
                exprs += [InputRef(c, scope.schema[c].data_type)
                          for c in join_pk_cols]
                names += [f"_row_id_{j}"
                          for j in range(len(join_pk_cols))]
                ex = ProjectExecutor(ex, exprs, names)
            elif base_pk:
                # pk-keyed upstream (MV chain): carry its pk through as
                # hidden columns — a generated row id would turn every
                # upstream update pair into a fresh row (duplicates)
                pk = list(range(len(exprs), len(exprs) + len(base_pk)))
                # ex.schema, not scope.schema: the chain may have grown
                # columns past the bind scope (row-id gen, window cols)
                exprs += [InputRef(c, ex.schema[c].data_type)
                          for c in base_pk]
                names += [f"_pk{j}" for j in range(len(base_pk))]
                ex = ProjectExecutor(ex, exprs, names)
            else:
                ex = RowIdGenExecutor(ProjectExecutor(ex, exprs, names))
                pk = [len(exprs)]
                names = names + ["_row_id"]
        if sel.limit is not None or (sel.offset or 0) > 0:
            # ORDER BY alone is a no-op for a pk-keyed MV (pg drops it
            # too) — only a real window needs the TopN executor.
            # append-only-ness is DERIVED over the chain (agg outputs
            # and outer joins retract; inner chains of append-only
            # sources do not) — TopN prunes beyond-window state only
            # when provably append-only (top_n_appendonly analog)
            ex = self._plan_topn(ex, sel, pk,
                                 append_only=self._derive_append_only(ex))
        return ex, pk, deps, len(projections)

    @staticmethod
    def _joinable(ex: Executor, scope: Scope) -> Tuple[Executor, Scope]:
        """Make one join input key-stable with a scope covering its
        whole schema. A pk-less (append-only) chain gets a generated
        row id; a pk-keyed input KEEPS its pk — retractions replay by
        pk, so join state updates consistently. Hidden columns beyond
        the bind scope are projected down to visible + pk so scope and
        executor schema stay index-aligned (the join's output offsets
        are schema offsets)."""
        from risingwave_tpu.stream.executor import ExecutorInfo

        if not ex.pk_indices:
            ex2: Executor = RowIdGenExecutor(ex)
            return ex2, Scope(ex2.schema, scope.qualifiers + [None])
        n_vis = len(scope.schema)
        if n_vis == len(ex.schema):
            return ex, scope
        keep_hidden = [i for i in ex.pk_indices if i >= n_vis]
        exprs = [InputRef(i, ex.schema[i].data_type)
                 for i in range(n_vis)]
        names = [f.name for f in scope.schema]
        for k, i in enumerate(keep_hidden):
            exprs.append(InputRef(i, ex.schema[i].data_type))
            names.append(f"_jpk{k}")
        proj = ProjectExecutor(ex, exprs, names)
        new_pk = [i if i < n_vis else n_vis + keep_hidden.index(i)
                  for i in ex.pk_indices]
        proj._info = ExecutorInfo(proj.schema, new_pk, proj.identity)
        return proj, Scope(proj.schema,
                           scope.qualifiers + [None] * len(keep_hidden))

    def _plan_topn(self, ex: Executor, sel: ast.Select,
                   pk: List[int], append_only: bool = False) -> Executor:
        """ORDER BY [+ LIMIT/OFFSET] MV → streaming TopN (top_n_plain
        analog): maintains the window incrementally, emitting deltas."""
        from risingwave_tpu.stream.executors.top_n import (
            GroupTopNExecutor,
        )
        post = Scope.of(ex.schema, None)
        order = []
        for e_ast, desc in sel.order_by:
            b = Binder(post).bind(e_ast)
            if not isinstance(b, InputRef):
                raise PlanError(
                    "MV ORDER BY must reference output columns")
            order.append((b.index, desc))
        if not order:
            # LIMIT without ORDER BY: deterministic order by pk
            order = [(i, False) for i in pk]
        state = StateTable(self.catalog.next_id(), ex.schema, pk,
                           self.store)
        return GroupTopNExecutor(
            ex, order, offset=sel.offset or 0, limit=sel.limit,
            state=state, pk_indices=pk, append_only=append_only)

    @staticmethod
    def _derive_append_only(ex: Executor) -> bool:
        """Conservative append-only derivation over the executor chain
        (the reference's input_append_only on StreamHashAgg,
        logical_agg.rs). Append-only ⇢ the cheap device agg path; any
        possibility of retraction ⇢ the minput path. Unknown executors
        default to False — silent wrongness is the only unacceptable
        outcome (VERDICT r3 #7)."""
        # chained-MV edges carry the upstream MV's own proof (stamped
        # in _chain_upstream_mv from MvCatalog.append_only) — the
        # chain boundary would otherwise hit the Backfill default and
        # lose provably-append-only upstreams
        hint = getattr(ex, "append_only_hint", None)
        if hint is not None:
            return bool(hint)
        from risingwave_tpu.stream.executors.source import SourceExecutor
        from risingwave_tpu.stream.executors.simple import (
            FilterExecutor, ProjectExecutor,
        )
        from risingwave_tpu.stream.executors.row_id_gen import (
            RowIdGenExecutor,
        )
        if isinstance(ex, SourceExecutor):
            return True
        if isinstance(ex, HashJoinExecutor):
            # inner joins of append-only inputs emit only inserts;
            # any outer/semi/anti kind emits padded-row flips
            return (ex.join_type == JoinType.INNER
                    and StreamPlanner._derive_append_only(ex.left_in)
                    and StreamPlanner._derive_append_only(ex.right_in))
        from risingwave_tpu.stream.executors.temporal_join import (
            TemporalJoinExecutor,
        )
        if isinstance(ex, TemporalJoinExecutor):
            # temporal output is append-only by construction
            return StreamPlanner._derive_append_only(ex.left_in)
        from risingwave_tpu.stream.executors.hop_window import (
            HopWindowExecutor,
        )
        if isinstance(ex, (ProjectExecutor, FilterExecutor,
                           RowIdGenExecutor, HopWindowExecutor)):
            return StreamPlanner._derive_append_only(ex.input)
        from risingwave_tpu.stream.executors.watermark_filter import (
            WatermarkFilterExecutor,
        )
        if isinstance(ex, WatermarkFilterExecutor):
            return StreamPlanner._derive_append_only(ex.input)
        from risingwave_tpu.stream.coalesce import CoalesceExecutor
        if isinstance(ex, CoalesceExecutor):
            # pure re-batching: op multiset is untouched
            return StreamPlanner._derive_append_only(ex.input)
        from risingwave_tpu.stream.executors.project_set import (
            ProjectSetExecutor,
        )
        if isinstance(ex, ProjectSetExecutor):
            # deterministic expansion of inserts is inserts
            return StreamPlanner._derive_append_only(ex.input)
        from risingwave_tpu.stream.executors.fused import (
            FusedFragmentExecutor,
        )
        if isinstance(ex, FusedFragmentExecutor):
            # a fused block composes filter/project/row_id_gen/
            # watermark_filter stages — each append-only-transparent,
            # so the block is too
            return StreamPlanner._derive_append_only(ex.input)
        # HashAgg/TopN/Backfill/DynamicFilter/unknown: assume retracting
        return False

    def _plan_project_set(self, ex: Executor, scope: Scope,
                          sel: ast.Select, projections, deps):
        """SELECT list with set-returning functions → ProjectSet
        (src/stream/src/executor/project_set.rs parity): each row
        expands to the rows its table functions return, and the
        hidden _projected_row_id joins the stream key so equal
        per-element rows retract exactly."""
        from risingwave_tpu.expr.expr import Literal
        from risingwave_tpu.stream.executors.project_set import (
            ProjectSetExecutor,
        )
        if sel.group_by or sel.having is not None:
            raise PlanError("set-returning functions cannot be mixed "
                            "with GROUP BY / HAVING")
        binder = Binder(scope)      # aggregates raise naturally
        items, names = [], []
        ints = (DataType.INT16, DataType.INT32, DataType.INT64)
        for i, (e, a) in enumerate(projections):
            if isinstance(e, ast.Call) and e.name == "unnest":
                raise PlanError(
                    "unnest is not supported yet — LIST columns do "
                    "not carry an element type")
            if isinstance(e, ast.Call) and e.name == "generate_series":
                if len(e.args) not in (2, 3):
                    raise PlanError(
                        "generate_series(start, stop [, step])")
                args = [binder.bind(x) for x in e.args]
                for b in args:
                    if b.return_type not in ints:
                        raise PlanError("generate_series arguments "
                                        "must be integers")
                if len(args) == 2:
                    args.append(Literal(1, DataType.INT64))
                step = args[2]
                if isinstance(step, Literal) and int(step.value) == 0:
                    raise PlanError(
                        "generate_series step must be nonzero")
                items.append(("series", tuple(args)))
                names.append(a or "generate_series")
            else:
                items.append(("scalar", binder.bind(e)))
                names.append(a or expr_name(e, f"col{i}"))
        seen: dict = {}
        for idx, n in enumerate(names):
            k = seen.get(n, 0)
            seen[n] = k + 1
            if k:
                # two unaliased series items share a name; uniquify so
                # the MV's columns stay addressable (SELECT * binds by
                # name downstream)
                names[idx] = f"{n}_{k}"
        base_pk = list(ex.pk_indices)
        if not base_pk:
            ex = RowIdGenExecutor(ex)
            base_pk = [len(ex.schema) - 1]
        ex = ProjectSetExecutor(ex, items, names, pass_pk=base_pk)
        pk = list(ex.pk_indices)
        # expansion re-keys rows; the EOWC feed proof stops here
        self._wm_scope_cols = set()
        if sel.limit is not None or (sel.offset or 0) > 0:
            ex = self._plan_topn(
                ex, sel, pk,
                append_only=self._derive_append_only(ex))
        return ex, pk, deps, len(names)

    def _plan_over_window(self, ex: Executor, binder: Binder, bound):
        """Insert an OverWindowExecutor (optimizer/plan_node/
        stream_over_window.rs analog): output = input + one column per
        window call; ('win', j) projection items map to those columns.
        State pk = partition | order | input pk (general.rs:59)."""
        from risingwave_tpu.stream.executors.over_window import (
            OverWindowExecutor,
        )
        if not ex.pk_indices:
            ex = RowIdGenExecutor(ex)
        n_in = len(ex.schema)
        pk = [i for i in ex.pk_indices]
        order = list(binder.window_order)
        partition = list(binder.window_partition)
        # state pk = partition | order | input-pk tie-break suffix
        # (pk columns that double as partition/order keys drop out of
        # the suffix — rows are then unique by their order key alone);
        # the executor's OUTPUT identity stays the FULL input pk
        suffix = [i for i in pk if i not in partition
                  and i not in [o for o, _ in order]]
        state = StateTable(self.catalog.next_id(), ex.schema,
                           partition + [i for i, _d in order] + suffix,
                           self.store, dist_key_indices=partition)
        win = OverWindowExecutor(ex, partition, order,
                                 binder.window_calls, state,
                                 input_pk=pk,
                                 actor_id=self._actor_id)
        out = [InputRef(n_in + b[1],
                        win.schema[n_in + b[1]].data_type)
               if isinstance(b, tuple) and b[0] == "win" else b
               for b in bound]
        return win, out

    def _plan_agg(self, ex: Executor, scope: Scope, sel: ast.Select,
                  binder: Binder, projections) -> Tuple[Executor, List, object]:
        """Insert pre-agg projection + HashAggExecutor; returns
        (agg executor, output exprs over the agg row, HAVING predicate
        over the agg row or None). SELECT items and HAVING bind through
        PostAggBinder, so expressions OVER aggregates (sum(x)+1,
        avg(q.final), HAVING count(*) > 5) work — the reference resolves
        these in LogicalAgg planning (logical_agg.rs)."""
        from risingwave_tpu.frontend.binder import PostAggBinder
        group_bound = [Binder(scope).bind(g) for g in sel.group_by]
        if not group_bound:
            # global aggregation: a synthetic constant group key routes
            # it through the SAME hash-agg machinery — one real group,
            # full retraction support (minput MIN/MAX, host aggs); the
            # hidden-group-key logic keys the single-row MV by it.
            # (simple_agg.rs covers the append-only fast path; the
            # planner prefers the general one.)
            from risingwave_tpu.expr.expr import Literal
            group_bound = [Literal(0, DataType.INT32)]
        self._agg_group_arity = len(group_bound)
        group_reprs = [repr(g) for g in group_bound]
        pab = PostAggBinder(binder, group_reprs)
        bound = [pab.bind(e) for e, _a in projections]
        having_pred = None
        if sel.having is not None:
            having_pred = pab.bind(sel.having)
            if having_pred.return_type != DataType.BOOLEAN:
                raise PlanError("HAVING must be a boolean expression")
        # pre-agg projection: group exprs, then each agg input column
        pre_exprs: List[Expression] = list(group_bound)
        pre_names = [f"_g{i}" for i in range(len(group_bound))]
        remapped: List[AggCall] = []
        in_expr_idx: Dict[str, int] = {}
        for call, in_expr in zip(binder.agg_calls, binder.agg_inputs):
            if in_expr is None:            # count(*)
                remapped.append(call)
                continue
            # identical input expressions share one projected column —
            # count(DISTINCT x) + sum(DISTINCT x) then share their
            # dedup table and per-chunk gating in the executor
            k = repr(in_expr)
            if k not in in_expr_idx:
                pre_exprs.append(in_expr)
                pre_names.append(f"_a{len(pre_exprs) - 1}")
                in_expr_idx[k] = len(pre_exprs) - 1
            remapped.append(AggCall(call.kind, in_expr_idx[k],
                                    distinct=call.distinct,
                                    delimiter=call.delimiter))
        # plain-column group keys pass their watermarks through the
        # pre-agg projection (EOWC and agg state cleaning need them)
        pre_derivs = {e.index: j for j, e in enumerate(group_bound)
                      if isinstance(e, InputRef)}
        pre = ProjectExecutor(ex, pre_exprs, pre_names,
                              watermark_derivations=pre_derivs)
        # group positions fed by a source watermark (EOWC validation)
        wm_cols = getattr(self, "_wm_scope_cols", set())
        self._agg_wm_positions = [
            pos for pos, gb in enumerate(group_bound)
            if isinstance(gb, InputRef) and gb.index in wm_cols]
        g = len(group_bound)
        calls = remapped
        # append-only-ness decides the agg mode (VERDICT r3 #7: the
        # old hardcoded append_only=True was silently wrong over
        # retracting upstreams, e.g. GROUP BY over an outer join)
        append_only = self._derive_append_only(ex)
        from risingwave_tpu.ops.hash_agg import AggKind as _AK
        if (self.dist_parallelism > 1 and self.mesh is None
                and all(c.kind in (_AK.COUNT, _AK.SUM, _AK.MIN,
                                   _AK.MAX) and not c.distinct
                        for c in calls)):
            return self._plan_two_phase_agg(
                pre, g, calls, append_only, bound, having_pred)
        sch, agg_pk = agg_state_schema(pre.schema, list(range(g)), calls)
        table = StateTable(self.catalog.next_id(), sch, agg_pk,
                           self.store,
                           dist_key_indices=list(range(len(agg_pk))))
        from risingwave_tpu.stream.executors.hash_agg import (
            agg_aux_tables,
        )
        distinct_tables, minput_tables = agg_aux_tables(
            pre.schema, list(range(g)), calls, append_only, self.store,
            dedup_table_id=lambda _col: self.catalog.next_id(),
            minput_table_id=lambda _j: self.catalog.next_id())
        kernel = None
        if self.mesh is not None:
            # parallel plan: the hash exchange that the reference's
            # fragmenter inserts before a parallel agg
            # (stream_fragmenter/mod.rs:199, dispatch.rs:582) is the
            # sharded kernel's in-program all_to_all. Retracting
            # upstreams shard too (signed scatters + sharded acc
            # patching for minput MIN/MAX recompute); host aggs keep
            # their executor-side multiset path under any kernel.
            # NOTE: this block allocates no catalog ids, so its
            # position does not disturb the id-base replay contract.
            from risingwave_tpu.parallel.agg import ShardedAggKernel
            from risingwave_tpu.stream.executors.keys import LANES_PER_KEY
            kernel = ShardedAggKernel(
                self.mesh, key_width=LANES_PER_KEY * g,
                specs=[c.spec(pre.schema) for c in calls])
        agg = HashAggExecutor(self._coalesced(pre), list(range(g)),
                              calls, table,
                              append_only=append_only, kernel=kernel,
                              minput_tables=minput_tables,
                              distinct_tables=distinct_tables,
                              # cold tier: single-chip lazy kernel only
                              # (the sharded kernel has no targeted
                              # evict path)
                              tier_cap=self.state_tier_cap
                              if kernel is None else None)
        # bound items are already typed refs over the agg output row
        return agg, bound, having_pred

    def _plan_two_phase_agg(self, pre: Executor, g: int,
                            calls: List[AggCall], append_only: bool,
                            bound, having_pred):
        """Two-phase aggregation for distributed plans
        (logical_agg.rs two-phase split): a LOCAL partial agg stays
        colocated with its input fragment (the fragmenter cuts at the
        GLOBAL agg's input, so the hash exchange carries per-group
        partials instead of raw rows), and the global agg merges —
        COUNT partials by SUM, SUM/MIN/MAX by themselves. The global
        side is never append-only (local updates retract), so merged
        MIN/MAX get materialized-input tables automatically."""
        from risingwave_tpu.ops.hash_agg import AggKind
        from risingwave_tpu.stream.executor import ExecutorInfo
        from risingwave_tpu.stream.executors.hash_agg import (
            agg_aux_tables,
        )

        group = list(range(g))
        lsch, lpk = agg_state_schema(pre.schema, group, calls)
        ltable = StateTable(self.catalog.next_id(), lsch, lpk,
                            self.store,
                            dist_key_indices=list(range(len(lpk))))
        ldistinct, lminput = agg_aux_tables(
            pre.schema, group, calls, append_only, self.store,
            dedup_table_id=lambda _c: self.catalog.next_id(),
            minput_table_id=lambda _j: self.catalog.next_id())
        local = HashAggExecutor(self._coalesced(pre), group, calls,
                                ltable,
                                append_only=append_only,
                                distinct_tables=ldistinct,
                                minput_tables=lminput,
                                tier_cap=self.state_tier_cap)
        local._info = ExecutorInfo(local.schema,
                                   list(local.pk_indices),
                                   "HashAggExecutor(phase=local)")
        # the fragmenter colocates the local phase with its input
        # (no exchange cut) — that IS the point of the split
        local.two_phase_role = "local"
        merge = [AggCall(AggKind.SUM if c.kind == AggKind.COUNT
                         else c.kind, g + j)
                 for j, c in enumerate(calls)]
        gsch, gpk = agg_state_schema(local.schema, group, merge)
        gtable = StateTable(self.catalog.next_id(), gsch, gpk,
                            self.store,
                            dist_key_indices=list(range(len(gpk))))
        gdistinct, gminput = agg_aux_tables(
            local.schema, group, merge, False, self.store,
            dedup_table_id=lambda _c: self.catalog.next_id(),
            minput_table_id=lambda _j: self.catalog.next_id())
        agg = HashAggExecutor(local, group, merge, gtable,
                              append_only=False,
                              distinct_tables=gdistinct,
                              minput_tables=gminput,
                              tier_cap=self.state_tier_cap)
        agg._info = ExecutorInfo(agg.schema, list(agg.pk_indices),
                                 "HashAggExecutor(phase=global)")
        return agg, bound, having_pred


def _expand_star(projections, scope: Scope):
    out = []
    for e, a in projections:
        if isinstance(e, ast.ColRef) and e.name == "*":
            for i, f in enumerate(scope.schema):
                out.append((ast.ColRef(f.name, scope.qualifiers[i]), None))
        else:
            out.append((e, a))
    return out


def _parse_interval_opt(s: str) -> Interval:
    """'4 seconds' / '500 milliseconds' / raw µs number → Interval.
    Shares the SQL parser's unit table (one source of truth)."""
    from risingwave_tpu.frontend.parser import _INTERVAL_UNITS
    s = str(s).strip()
    parts = s.split()
    if len(parts) == 2 and parts[1].lower() in _INTERVAL_UNITS:
        return Interval(
            usecs=int(parts[0]) * _INTERVAL_UNITS[parts[1].lower()])
    if s.isdigit():
        return Interval(usecs=int(s))
    raise PlanError(f"bad interval option {s!r}")


def _flatten_and(e: ast.Expr) -> List[ast.Expr]:
    """WHERE → list of AND conjuncts (pushdown granularity)."""
    if isinstance(e, ast.Bin) and e.op == "and":
        return _flatten_and(e.left) + _flatten_and(e.right)
    return [e]




def explain_tree(ex, indent: int = 0) -> List[str]:
    """Executor chain → indented plan text (planner_test snapshot
    style; the EXPLAIN statement surfaces it). Walks the same
    `executor_children` set install_monitoring wraps."""
    from risingwave_tpu.stream.executor import executor_children
    label = getattr(ex, "identity", None) or type(ex).__name__
    out = [("  " * indent) + label]
    for _attr, _i, child in executor_children(ex):
        out += explain_tree(child, indent + 1)
    return out


def _equi_keys(on: ast.Expr, lscope: Scope, rscope: Scope
               ) -> Tuple[List[int], List[int]]:
    """ON conjunction of col=col → (left key idxs, right key idxs)."""
    conj: List[ast.Expr] = []

    def flatten(e):
        if isinstance(e, ast.Bin) and e.op == "and":
            flatten(e.left)
            flatten(e.right)
        else:
            conj.append(e)

    flatten(on)
    lkeys, rkeys = [], []
    for c in conj:
        if not (isinstance(c, ast.Bin) and c.op == "="
                and isinstance(c.left, ast.ColRef)
                and isinstance(c.right, ast.ColRef)):
            raise PlanError("JOIN ON must be a conjunction of "
                            "column = column")
        sides = []
        for col in (c.left, c.right):
            try:
                sides.append(("l", lscope.find(col.name, col.table)[0]))
            except BindError:
                sides.append(("r", rscope.find(col.name, col.table)[0]))
        tags = {s[0] for s in sides}
        if tags != {"l", "r"}:
            raise PlanError("JOIN ON must compare the two sides")
        for tag, idx in sides:
            (lkeys if tag == "l" else rkeys).append(idx)
    return lkeys, rkeys


def _system_catalog_rows(name: str, catalog: Catalog, profiler=None):
    """rw_catalog-style system tables (src/frontend/src/catalog/
    system_catalog/ analog, bare-named): introspection over the live
    catalog AND the process metrics registry, served as batch values.
    Returns (schema, rows) or None. `profiler` is the session barrier
    loop's EpochProfiler (rw_barrier_latency's source); sessions that
    don't thread one through still serve the metric-backed tables."""
    from risingwave_tpu.utils.metrics import STREAMING

    n = name.lower()
    if n == "rw_actor_metrics":
        # live actors (stream_actor_count series — torn-down actors'
        # series are removed) joined with the per-executor counters
        sch = Schema([Field("actor_id", DataType.INT64),
                      Field("fragment", DataType.VARCHAR),
                      Field("executor", DataType.VARCHAR),
                      Field("node", DataType.INT64),
                      Field("row_count", DataType.INT64),
                      Field("chunk_count", DataType.INT64),
                      Field("busy_seconds", DataType.FLOAT64),
                      Field("device_dispatch_count", DataType.INT64)])
        live = {labels["actor"]: labels.get("fragment", "")
                for labels, _v in STREAMING.actor_count.series()
                if "actor" in labels}
        per_exec: Dict[tuple, List[float]] = {}
        for metric, slot in ((STREAMING.executor_rows, 0),
                             (STREAMING.executor_chunks, 1),
                             (STREAMING.executor_busy, 2)):
            for labels, v in metric.series():
                a = labels.get("actor")
                if a not in live:
                    continue
                key = (a, labels.get("executor", ""),
                       labels.get("node", ""))
                per_exec.setdefault(key, [0.0, 0.0, 0.0])[slot] += v
        # keyed executors label device dispatches by identity alone
        # (identity embeds the actor, e.g. "HashAggExecutor(actor=N)")
        # — join on the executor name the monitor also labels with
        dispatches = {labels.get("executor", ""): v for labels, v in
                      STREAMING.device_dispatch.series()}
        rows = []
        seen_actors = set()
        for (a, ex_name, node), (nrows, nchunks, busy) in \
                per_exec.items():
            seen_actors.add(a)
            rows.append((int(a), live[a], ex_name,
                         int(node) if node else 0,
                         int(nrows), int(nchunks), busy,
                         int(dispatches.get(ex_name, 0))))
        for a, frag in live.items():
            if a not in seen_actors:    # deployed but unmonitored
                rows.append((int(a), frag, "", 0, 0, 0, 0.0, 0))
        return sch, sorted(rows)
    if n == "rw_fragment_backpressure":
        sch = Schema([Field("edge", DataType.VARCHAR),
                      Field("send_count", DataType.INT64),
                      Field("backpressure_seconds", DataType.FLOAT64),
                      Field("queue_depth", DataType.INT64)])
        edges: Dict[str, List[float]] = {}
        for metric, slot in ((STREAMING.exchange_send_count, 0),
                             (STREAMING.exchange_backpressure, 1),
                             (STREAMING.exchange_queue_depth, 2)):
            for labels, v in metric.series():
                e = labels.get("edge")
                if e is not None:
                    edges.setdefault(e, [0.0, 0.0, 0.0])[slot] += v
        rows = [(e, int(s[0]), s[1], int(s[2]))
                for e, s in edges.items()]
        return sch, sorted(rows)
    if n == "rw_barrier_latency":
        sch = Schema([Field("epoch", DataType.INT64),
                      Field("kind", DataType.VARCHAR),
                      Field("inject_to_collect_s", DataType.FLOAT64),
                      Field("collect_to_commit_s", DataType.FLOAT64),
                      Field("total_s", DataType.FLOAT64),
                      Field("in_flight", DataType.INT64),
                      Field("slowest_actor", DataType.INT64),
                      Field("slowest_actor_lag_s", DataType.FLOAT64),
                      Field("upload_s", DataType.FLOAT64),
                      Field("queue_depth", DataType.INT64),
                      Field("domain", DataType.VARCHAR)])
        rows = list(profiler.rows()) if profiler is not None else []
        return sch, rows
    if n == "rw_state_tier":
        # state-tiering residency (state/tier.py): one row per
        # registered executor cache; cap = -1 means uncapped
        # (pressure-only governance)
        from risingwave_tpu.state.tier import GLOBAL as _TIER
        sch = Schema([Field("executor", DataType.VARCHAR),
                      Field("cap", DataType.INT64),
                      Field("resident_keys", DataType.INT64),
                      Field("evicted_total", DataType.INT64),
                      Field("reload_total", DataType.INT64),
                      Field("resident_bytes", DataType.INT64)])
        return sch, sorted(_TIER.stats_rows())
    if n == "rw_epoch_trace":
        # epoch-causal traces (utils/spans.py flight recorder +
        # retained slow-barrier store): one row per span, plus one
        # cat='diagnosis' row per retained trace carrying the
        # straggler line. Joins rw_barrier_latency on epoch.
        from risingwave_tpu.utils.spans import EPOCH_TRACER
        sch = Schema([Field("epoch", DataType.INT64),
                      Field("span_id", DataType.INT64),
                      Field("parent_id", DataType.INT64),
                      Field("name", DataType.VARCHAR),
                      Field("cat", DataType.VARCHAR),
                      Field("worker", DataType.VARCHAR),
                      Field("actor", DataType.INT64),
                      Field("start_s", DataType.FLOAT64),
                      Field("dur_s", DataType.FLOAT64),
                      Field("retained", DataType.INT64),
                      Field("detail", DataType.VARCHAR)])
        return sch, EPOCH_TRACER.rows()
    if n == "rw_metrics_history":
        # bounded per-barrier time series (utils/metrics.HISTORY, fed
        # at every ledger seal): counter deltas, gauge values and the
        # epoch phase breakdown per barrier — the telemetry history
        # the elastic-serving control loop (ROADMAP item 3) reads.
        # Long format: one row per (barrier, series).
        from risingwave_tpu.utils.metrics import HISTORY
        sch = Schema([Field("seq", DataType.INT64),
                      Field("epoch", DataType.INT64),
                      Field("ts", DataType.FLOAT64),
                      Field("interval_s", DataType.FLOAT64),
                      Field("name", DataType.VARCHAR),
                      Field("value", DataType.FLOAT64),
                      Field("domain", DataType.VARCHAR)])
        return sch, HISTORY.rows()
    if n == "rw_mv_freshness":
        # per-MV event-time freshness (stream/freshness.py): how far
        # the materialized result lags the data's own timestamps, per
        # barrier, with percentiles over the retained sample ring —
        # the consumer-experience half of the observability stack
        from risingwave_tpu.stream.freshness import FRESHNESS
        sch = Schema([Field("mv", DataType.VARCHAR),
                      Field("domain", DataType.VARCHAR),
                      Field("samples", DataType.INT64),
                      Field("epoch", DataType.INT64),
                      Field("lag_s", DataType.FLOAT64),
                      Field("wall_lag_s", DataType.FLOAT64),
                      Field("lag_p50_s", DataType.FLOAT64),
                      Field("lag_p99_s", DataType.FLOAT64),
                      Field("wall_lag_p99_s", DataType.FLOAT64)])
        return sch, FRESHNESS.rows()
    if n == "rw_bottlenecks":
        # bottleneck walker (stream/bottleneck.py): the ranked
        # per-domain culprit table — operator, busy share, downstream
        # backpressure evidence, contiguous-barrier streak and a
        # one-line diagnosis (the autoscaler's target signal)
        from risingwave_tpu.stream.bottleneck import BOTTLENECKS
        sch = Schema([Field("domain", DataType.VARCHAR),
                      Field("operator", DataType.VARCHAR),
                      Field("fragment", DataType.VARCHAR),
                      Field("actor_id", DataType.INT64),
                      Field("node", DataType.INT64),
                      Field("busy_ratio", DataType.FLOAT64),
                      Field("downstream_backpressure",
                            DataType.FLOAT64),
                      Field("streak", DataType.INT64),
                      Field("sustained", DataType.INT64),
                      Field("epoch", DataType.INT64),
                      Field("diagnosis", DataType.VARCHAR)])
        return sch, BOTTLENECKS.rows()
    if n == "rw_actor_utilization":
        # utilization tricolor (stream/monitor.py): busy /
        # backpressure / idle shares of the last barrier interval per
        # (actor, executor) — sorted busiest first, the `ctl top` feed
        from risingwave_tpu.stream.monitor import UTILIZATION
        sch = Schema([Field("actor_id", DataType.INT64),
                      Field("fragment", DataType.VARCHAR),
                      Field("node", DataType.INT64),
                      Field("executor", DataType.VARCHAR),
                      Field("epoch", DataType.INT64),
                      Field("interval_s", DataType.FLOAT64),
                      Field("busy_ratio", DataType.FLOAT64),
                      Field("backpressure_ratio", DataType.FLOAT64),
                      Field("idle_ratio", DataType.FLOAT64)])
        return sch, UTILIZATION.rows()
    if n == "rw_kernel_costs":
        # compiled-program cost analysis (utils/jaxtools.KERNELS):
        # flops / bytes-accessed from each kernel's lowered program —
        # the yardstick the ledger's device_compute measurements are
        # sanity-checked against
        from risingwave_tpu.utils.jaxtools import kernel_cost_rows
        sch = Schema([Field("kernel", DataType.VARCHAR),
                      Field("flops", DataType.FLOAT64),
                      Field("bytes_accessed", DataType.FLOAT64)])
        return sch, kernel_cost_rows()
    if n == "rw_recovery":
        # supervised-recovery event log (meta/supervisor.py): one row
        # per recovery with its classified cause, graduated action,
        # touched worker slots, recovered-to epoch and MTTR sample.
        # Joins rw_epoch_trace on epoch for the recovery.* span chain.
        from risingwave_tpu.meta.supervisor import recovery_rows
        sch = Schema([Field("seq", DataType.INT64),
                      Field("cause", DataType.VARCHAR),
                      Field("action", DataType.VARCHAR),
                      Field("workers", DataType.VARCHAR),
                      Field("epoch", DataType.INT64),
                      Field("duration_s", DataType.FLOAT64),
                      Field("ok", DataType.INT64),
                      Field("attempt", DataType.INT64),
                      Field("detail", DataType.VARCHAR)])
        return sch, recovery_rows()
    if n == "rw_compaction":
        # dedicated-compaction task log (meta/compaction.py): one row
        # per task with its picker, lifecycle state (pending/running/
        # applied/aborted/requeued/failed), frozen inputs, landed
        # outputs and merge I/O — `ctl compaction` reads this
        from risingwave_tpu.meta.compaction import compaction_rows
        sch = Schema([Field("task_id", DataType.INT64),
                      Field("namespace", DataType.VARCHAR),
                      Field("picker", DataType.VARCHAR),
                      Field("state", DataType.VARCHAR),
                      Field("inputs", DataType.VARCHAR),
                      Field("outputs", DataType.VARCHAR),
                      Field("bytes_read", DataType.INT64),
                      Field("bytes_written", DataType.INT64),
                      Field("attempts", DataType.INT64),
                      Field("duration_s", DataType.FLOAT64),
                      Field("detail", DataType.VARCHAR)])
        return sch, compaction_rows()
    if n == "rw_autoscaler":
        # elastic-control-loop decision ledger (meta/autoscaler.py):
        # one row per completed scaling decision — direction, the
        # signal that triggered it, and the guarded-rescale outcome
        # (applied / rolled_back / rollback_failed / storm_disabled).
        # Joins rw_recovery on wall time for the rollback story.
        from risingwave_tpu.meta.autoscaler import autoscaler_rows
        sch = Schema([Field("seq", DataType.INT64),
                      Field("mv", DataType.VARCHAR),
                      Field("fragment", DataType.INT64),
                      Field("operator", DataType.VARCHAR),
                      Field("direction", DataType.VARCHAR),
                      Field("from_parallelism", DataType.INT64),
                      Field("to_parallelism", DataType.INT64),
                      Field("outcome", DataType.VARCHAR),
                      Field("reason", DataType.VARCHAR),
                      Field("epoch", DataType.INT64),
                      Field("duration_s", DataType.FLOAT64),
                      Field("detail", DataType.VARCHAR)])
        return sch, autoscaler_rows()
    if n == "rw_mv_costs":
        # per-MV resource ledger (stream/costs.py, ISSUE 16): the
        # barrier-interval device/transfer split by owning MV, joined
        # at read time with state bytes (topology), compile-cache
        # attribution and recovery/rescale charge-back — `ctl cost`
        # and the marginal-cost bench read this
        from risingwave_tpu.stream.costs import COSTS
        sch = Schema([Field("mv", DataType.VARCHAR),
                      Field("domain", DataType.VARCHAR),
                      Field("device_seconds", DataType.FLOAT64),
                      Field("h2d_bytes", DataType.INT64),
                      Field("d2h_bytes", DataType.INT64),
                      Field("state_bytes", DataType.INT64),
                      Field("compile_hits", DataType.INT64),
                      Field("compile_misses", DataType.INT64),
                      Field("shared_compile_hits", DataType.INT64),
                      Field("rescale_s", DataType.FLOAT64),
                      Field("recovery_s", DataType.FLOAT64)])
        return sch, COSTS.rows()
    if n == "rw_hot_keys":
        # heavy-hitter telemetry (stream/hotkeys.py): sustained hot
        # keys per hash-join/hash-agg input with share estimates —
        # max_share_err bounds the space-saving overcount, so
        # share - max_share_err is a guaranteed lower bound
        from risingwave_tpu.stream.hotkeys import HOTKEYS
        sch = Schema([Field("mv", DataType.VARCHAR),
                      Field("executor", DataType.VARCHAR),
                      Field("rank", DataType.INT64),
                      Field("key", DataType.VARCHAR),
                      Field("est_count", DataType.INT64),
                      Field("share", DataType.FLOAT64),
                      Field("max_share_err", DataType.FLOAT64)])
        return sch, HOTKEYS.rows()
    if n == "rw_state_topology":
        # per-(table, vnode) state footprint (state/topology.py):
        # maintained incrementally at flush — the rescale planner's
        # move-cost input and `ctl memory`'s breakdown
        from risingwave_tpu.state.topology import TOPOLOGY
        sch = Schema([Field("table_id", DataType.INT64),
                      Field("mv", DataType.VARCHAR),
                      Field("vnode", DataType.INT64),
                      Field("rows", DataType.INT64),
                      Field("bytes", DataType.INT64)])
        return sch, TOPOLOGY.rows()
    if n == "rw_plan_rewrites":
        # plan-rewrite firing log (frontend/opt engine): one row per
        # (job, rule) application, FALLBACK rows record checker trips
        from risingwave_tpu.frontend.opt import rewrite_history_rows
        sch = Schema([Field("seq", DataType.INT64),
                      Field("job", DataType.VARCHAR),
                      Field("rule", DataType.VARCHAR),
                      Field("fired", DataType.INT64),
                      Field("detail", DataType.VARCHAR)])
        return sch, sorted(rewrite_history_rows())
    if n in ("rw_materialized_views", "rw_tables"):
        want_tables = n == "rw_tables"
        sch = Schema([Field("name", DataType.VARCHAR),
                      Field("table_id", DataType.INT64),
                      Field("actor_id", DataType.INT64),
                      Field("definition", DataType.VARCHAR)])
        rows = [(m.name, m.table_id, m.actor_id, m.definition or "")
                for m in catalog.mvs.values()
                if m.is_table == want_tables]
        return sch, sorted(rows)
    if n == "rw_sources":
        sch = Schema([Field("name", DataType.VARCHAR),
                      Field("connector", DataType.VARCHAR),
                      Field("columns", DataType.INT64)])
        rows = [(s.name, s.options.get("connector", ""),
                 len(s.schema))
                for s in catalog.sources.values()]
        return sch, sorted(rows)
    if n == "rw_sinks":
        # exactly-once sinks report their commit frontier straight off
        # the object-store listing (meta/sink_coordinator.sink_stats)
        # — usable from any process without an RPC to the coordinator;
        # legacy writers show NULL-ish zeros
        sch = Schema([Field("name", DataType.VARCHAR),
                      Field("connector", DataType.VARCHAR),
                      Field("mode", DataType.VARCHAR),
                      Field("committed_epoch", DataType.INT64),
                      Field("staged_epochs", DataType.INT64),
                      Field("staged_bytes", DataType.INT64),
                      Field("writer_lag", DataType.INT64)])
        rows = []
        for s in catalog.sinks.values():
            conn = s.options.get("connector", "")
            stats = {"committed_epoch": 0, "staged_epochs": 0,
                     "staged_bytes": 0, "writer_lag": 0}
            if conn == "epochlog":
                from risingwave_tpu.connectors.sink import (
                    make_sink_target,
                )
                from risingwave_tpu.meta.sink_coordinator import (
                    sink_stats,
                )
                try:
                    stats = sink_stats(
                        make_sink_target(s.options, s.mode or "append",
                                         []),
                        s.n_writers, name=s.name, mode=s.mode)
                except OSError:
                    pass             # path gone: keep the zero row
            rows.append((s.name, conn, s.mode,
                         stats["committed_epoch"],
                         stats["staged_epochs"], stats["staged_bytes"],
                         stats["writer_lag"]))
        return sch, sorted(rows)
    return None


# -- batch planning -------------------------------------------------------


def plan_batch(sel: ast.Select, catalog: Catalog, store, epoch: int,
               profiler=None):
    """SELECT over committed snapshots → batch executor tree.

    `profiler` (the session's EpochProfiler, optional) backs the
    rw_barrier_latency system table."""
    from risingwave_tpu.batch import (
        BatchFilter, BatchHashAgg, BatchHashJoin, BatchLimit,
        BatchOrderBy, BatchProject, BatchValues, RowSeqScan, StorageTable,
    )

    def scan(item) -> Tuple[object, Scope]:
        if isinstance(item, ast.TableFn):
            # table functions (src/expr/src/table_function/ parity:
            # generate_series); evaluated to rows at plan time — args
            # are constant expressions
            if item.name != "generate_series":
                raise PlanError(
                    f"unknown table function {item.name!r}")
            if len(item.args) not in (2, 3):
                raise PlanError(
                    "generate_series(start, stop [, step])")
            binder = Binder(Scope.of(Schema([]), None))
            vals = []
            for a in item.args:
                b = binder.bind(a)
                from risingwave_tpu.expr.expr import Literal, UnaryOp
                if isinstance(b, Literal):
                    vals.append(int(b.value))
                elif isinstance(b, UnaryOp) and b.op == "neg" and \
                        isinstance(b.child, Literal):
                    vals.append(-int(b.child.value))
                else:
                    raise PlanError(
                        "generate_series arguments must be integer "
                        "literals")
            start, stop = vals[0], vals[1]
            step = vals[2] if len(vals) == 3 else 1
            if step == 0:
                raise PlanError("generate_series step must be nonzero")
            rows = [(v,) for v in range(start, stop + (1 if step > 0
                                                       else -1), step)]
            # pg: the alias names BOTH the table and the single column
            col = item.alias or "generate_series"
            sch = Schema([Field(col, DataType.INT64)])
            return (BatchValues(sch, rows), Scope.of(sch, col))
        if isinstance(item, ast.Subquery):
            sub = plan_batch(item.select, catalog, store, epoch,
                             profiler)
            return sub, Scope.of(sub.schema, item.alias)
        if not isinstance(item, ast.TableRef):
            raise PlanError("batch FROM supports tables/MVs")
        try:
            obj = catalog.resolve(item.name)
        except Exception:
            # USER objects win over system catalogs (pg search-path
            # spirit); only an unresolved name falls through to rw_*
            sysrows = _system_catalog_rows(item.name, catalog,
                                           profiler)
            if sysrows is None:
                raise
            sch, rows = sysrows
            return (BatchValues(sch, rows),
                    Scope.of(sch, item.alias or item.name))
        if isinstance(obj, SourceCatalog):
            raise PlanError("cannot batch-scan a pure source; "
                            "create a materialized view over it")
        st = StorageTable(obj.table_id, obj.schema, obj.pk_indices, store)
        ex = RowSeqScan(st, epoch)
        vis = obj.visible_schema
        if len(vis) < len(obj.schema):
            # hidden trailing columns (_row_id, unprojected group keys)
            # must leave the EXECUTOR schema too, not just the binding
            # scope — a downstream join concatenates executor schemas,
            # and a width mismatch would shift every right-side index
            ex = BatchProject(ex, [InputRef(i, f.data_type)
                                   for i, f in enumerate(vis)])
        return ex, Scope.of(vis, item.alias or item.name)

    if sel.from_item is None:
        # SELECT <exprs>: evaluate over one synthetic row
        from risingwave_tpu.common.types import Schema as Sch
        binder = Binder(Scope.of(Sch([]), None))
        exprs = [binder.bind(e) for e, _ in sel.projections]
        from risingwave_tpu.common.chunk import DataChunk
        import numpy as np
        one = DataChunk.empty(Sch([]), capacity=8)
        one.visibility[0] = True
        cols = [e.eval(one) for e in exprs]
        row = tuple(
            None if (c.validity is not None and not c.validity[0])
            else (c.values[0].item() if hasattr(c.values[0], "item")
                  else c.values[0])
            for c in cols)
        names = [a or expr_name(e, f"col{i}")
                 for i, (e, a) in enumerate(sel.projections)]
        sch = Sch([Field(n, c.data_type) for n, c in zip(names, cols)])
        return BatchValues(sch, [row])

    ex, scope = scan(sel.from_item)
    for jn in sel.joins:
        rex, rscope = scan(jn.item)
        lkeys, rkeys = _equi_keys(jn.on, scope, rscope)
        ex = BatchHashJoin(ex, rex, lkeys, rkeys)
        scope = scope.concat(rscope)
    if sel.where is not None:
        ex = BatchFilter(ex, Binder(scope).bind(sel.where))
    projections = _expand_star(sel.projections, scope)
    from risingwave_tpu.frontend.binder import PostAggBinder, contains_agg
    binder = Binder(scope, allow_aggs=True)
    names = [a or expr_name(e, f"col{i}")
             for i, (e, a) in enumerate(projections)]
    has_agg = (bool(sel.group_by) or sel.having is not None
               or any(contains_agg(e) for e, _a in projections))
    if has_agg:
        group_bound = [Binder(scope).bind(g) for g in sel.group_by]
        group_reprs = [repr(g) for g in group_bound]
        pab = PostAggBinder(binder, group_reprs)
        bound = [pab.bind(e) for e, _a in projections]
        having_pred = None
        if sel.having is not None:
            having_pred = pab.bind(sel.having)
            if having_pred.return_type != DataType.BOOLEAN:
                raise PlanError("HAVING must be a boolean expression")
        pre_exprs = list(group_bound)
        remapped = []
        for call, in_expr in zip(binder.agg_calls, binder.agg_inputs):
            if in_expr is None:            # count(*)
                remapped.append(call)
                continue
            pre_exprs.append(in_expr)      # agg over any expression
            remapped.append(AggCall(call.kind, len(pre_exprs) - 1,
                                    distinct=call.distinct,
                                    delimiter=call.delimiter))
        pre = BatchProject(ex, pre_exprs)
        g = len(group_bound)
        ex = BatchHashAgg(pre, list(range(g)), remapped)
        if having_pred is not None:
            ex = BatchFilter(ex, having_pred)
        ex = BatchProject(ex, bound, names)
        post_scope = Scope.of(ex.schema, None)
    else:
        bound = [binder.bind_projection(e) for e, _a in projections]
        ex = BatchProject(ex, bound, names)
        post_scope = Scope.of(ex.schema, None)
    if sel.order_by:
        cols = []
        for e, desc in sel.order_by:
            b = Binder(post_scope).bind(e)
            if not isinstance(b, InputRef):
                raise PlanError("ORDER BY must reference output columns")
            cols.append((b.index, desc))
        ex = BatchOrderBy(ex, cols)
    if sel.limit is not None or sel.offset is not None:
        ex = BatchLimit(ex, sel.limit if sel.limit is not None else 1 << 62,
                        sel.offset or 0)
    return ex
