"""SQL AST nodes (sqlparser-rs analog, scaled to the supported surface)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


# -- expressions ---------------------------------------------------------


@dataclass
class Expr:
    pass


@dataclass
class Lit(Expr):
    value: object            # int | float-string | str | bool | None
    kind: str                # "number" | "string" | "bool" | "null"


@dataclass
class IntervalLit(Expr):
    usecs: int


@dataclass
class ColRef(Expr):
    name: str
    table: Optional[str] = None


@dataclass
class Bin(Expr):
    op: str
    left: Expr
    right: Expr


@dataclass
class Un(Expr):
    op: str                  # "not" | "neg"
    child: Expr


@dataclass
class Call(Expr):
    name: str                # lowercased
    args: List[Expr]
    star: bool = False       # count(*)
    distinct: bool = False   # count(DISTINCT x) etc.
    # aggregate FILTER (WHERE cond) clause (pg); bound as a CASE
    # rewrite in the binder
    filter_where: object = None


@dataclass
class CastExpr(Expr):
    child: Expr
    type_name: str           # lowercased SQL type name


@dataclass
class Over(Expr):
    """fn(args) OVER (PARTITION BY ... ORDER BY ...)."""

    call: Call
    partition_by: List[Expr]
    order_by: List[Tuple[Expr, bool]]    # (expr, desc)


@dataclass
class Explain:
    select: "Select"


# -- statements ----------------------------------------------------------


@dataclass
class TableRef:
    name: str
    alias: Optional[str] = None


@dataclass
class Tumble:
    """TUMBLE(source, time_col, INTERVAL ...) — streaming window source."""

    table: TableRef
    time_col: str
    window_usecs: int
    alias: Optional[str] = None


@dataclass
class Hop:
    """HOP(source, time_col, INTERVAL slide, INTERVAL size)."""

    table: TableRef
    time_col: str
    slide_usecs: int
    size_usecs: int
    alias: Optional[str] = None


@dataclass
class TableFn:
    """FROM-clause table function: generate_series(...) etc."""

    name: str
    args: List[Expr]
    alias: Optional[str] = None


@dataclass
class Subquery:
    """Derived table: FROM (SELECT ...) alias."""

    select: "Select"
    alias: str


FromItem = object            # TableRef | Tumble | Hop | TableFn | Subquery


@dataclass
class Join:
    item: FromItem
    on: Expr
    kind: str = "inner"   # inner|left|right|full (OUTER implied)
    # JOIN ... FOR SYSTEM_TIME AS OF PROCTIME(): probe the right side
    # as a versioned table at process time (temporal join)
    temporal: bool = False


@dataclass
class Select:
    projections: List[Tuple[Expr, Optional[str]]]   # (expr, alias)
    from_item: Optional[FromItem]
    joins: List[Join] = field(default_factory=list)
    where: Optional[Expr] = None
    group_by: List[Expr] = field(default_factory=list)
    order_by: List[Tuple[Expr, bool]] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    having: Optional[Expr] = None


@dataclass
class CreateSource:
    name: str
    options: Dict[str, str]            # WITH (connector='nexmark', ...)
    # explicit (col_name, sql_type) list for external connectors
    columns: Optional[List[Tuple[str, str]]] = None


@dataclass
class CreateMaterializedView:
    name: str
    select: Select
    # EMIT ON WINDOW CLOSE: results emit once, when the watermark
    # passes the window column (default: emit-on-update changelog)
    emit_on_window_close: bool = False


@dataclass
class CreateSink:
    name: str
    select: Select
    options: Dict[str, str]
    # CREATE SINK ... FROM <mv> sugar: the select above is the
    # synthesized SELECT * FROM <mv>; the name is kept for catalog
    # dependency tracking and mode derivation off the MV's own
    # append-only proof
    from_mv: Optional[str] = None
    # AS APPEND-ONLY asserted by the user: the planner must PROVE the
    # input append-only or refuse (force='true' in options overrides —
    # retractions then fail loudly at the sink, never silently drop).
    # None = derive the mode automatically
    append_only: Optional[bool] = None


@dataclass
class DropSink:
    name: str
    if_exists: bool = False


@dataclass
class DropMaterializedView:
    name: str
    if_exists: bool = False


@dataclass
class DropSource:
    name: str
    if_exists: bool = False


@dataclass
class AlterParallelism:
    """ALTER MATERIALIZED VIEW name SET PARALLELISM = n."""

    name: str
    parallelism: int


@dataclass
class CreateTable:
    name: str
    columns: List[Tuple[str, str]]     # (col_name, sql type)
    pk_cols: List[str]                 # PRIMARY KEY columns ([] = none)


@dataclass
class DropTable:
    name: str
    if_exists: bool = False


@dataclass
class Insert:
    table: str
    rows: List[List["Expr"]]           # VALUES rows (expressions)
    select: Optional[Select] = None    # INSERT INTO t SELECT ...


@dataclass
class Delete:
    table: str
    where: Optional["Expr"] = None


@dataclass
class Update:
    table: str
    sets: List[Tuple[str, "Expr"]]     # SET col = expr
    where: Optional["Expr"] = None


@dataclass
class Show:
    what: str    # "tables" | "materialized views" | "sources" |
    #              "sinks" | "all" (session vars) | "var:<name>"


@dataclass
class SetVar:
    """SET <name> = <value> — session configuration
    (src/common/src/session_config/ analog)."""

    name: str
    value: object


@dataclass
class Flush:
    pass
