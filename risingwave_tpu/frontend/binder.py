"""Binder: AST expressions → typed Expression trees over a scope.

Reference parity: src/frontend/src/binder/ — name resolution against
the catalog, type derivation, aggregate-call extraction (the reference
splits these across binder + logical agg planning; here the bind pass
returns both the bound scalar expression and any extracted AggCalls).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from risingwave_tpu.common.types import DataType, Field, Interval, Schema
from risingwave_tpu.expr.expr import (
    BinaryOp, Case, Expression, FuncCall, InputRef, Literal,
    UnaryOp, lit, tumble_end, tumble_start,
)
from risingwave_tpu.frontend import ast
from risingwave_tpu.ops.hash_agg import AggKind
from risingwave_tpu.stream.executors.hash_agg import AggCall


class BindError(ValueError):
    pass


@dataclass
class Scope:
    """Visible columns: (qualifier, name) → (index, type)."""

    schema: Schema
    qualifiers: List[Optional[str]]     # per column: its table alias

    @staticmethod
    def of(schema: Schema, alias: Optional[str] = None) -> "Scope":
        return Scope(schema, [alias] * len(schema))

    def concat(self, other: "Scope") -> "Scope":
        return Scope(Schema(list(self.schema) + list(other.schema)),
                     self.qualifiers + other.qualifiers)

    def find(self, name: str, table: Optional[str]) -> Tuple[int, DataType]:
        hits = []
        for i, f in enumerate(self.schema):
            if f.name != name:
                continue
            if table is not None and self.qualifiers[i] != table:
                continue
            hits.append((i, f.data_type))
        if not hits:
            raise BindError(f"column {name!r} not found"
                            + (f" in {table!r}" if table else ""))
        if len(hits) > 1:
            raise BindError(f"column {name!r} is ambiguous")
        return hits[0]


_AGG_KINDS = {"count": AggKind.COUNT, "sum": AggKind.SUM,
              "min": AggKind.MIN, "max": AggKind.MAX,
              "approx_count_distinct": AggKind.APPROX_COUNT_DISTINCT}


class Binder:
    """Binds scalar expressions; collects aggregate calls on demand."""

    def __init__(self, scope: Scope, allow_aggs: bool = False):
        self.scope = scope
        self.allow_aggs = allow_aggs
        self.agg_calls: List[AggCall] = []
        # bound input EXPRESSION per call (None for count(*)) — aggs
        # over arbitrary expressions pre-project through these
        self.agg_inputs: List[Optional[object]] = []
        # bound agg call → position (dedup: COUNT(*) used twice = one)
        self._agg_index: Dict[Tuple, int] = {}
        # window (OVER) calls: all items share ONE window spec in v1
        # (the reference plans one OverWindow node per distinct window)
        self.window_calls: List[object] = []      # expr.window.WindowCall
        self.window_partition: Optional[List[int]] = None
        self.window_order: Optional[List[Tuple[int, bool]]] = None

    def _register(self, call: AggCall, key: Tuple,
                  input_expr=None) -> int:
        if key not in self._agg_index:
            self._agg_index[key] = len(self.agg_calls)
            self.agg_calls.append(call)
            self.agg_inputs.append(input_expr)
        return self._agg_index[key]

    def agg_out_type(self, j: int) -> DataType:
        """Output type of registered agg call j — computable at bind
        time from the bound input expression (the executor later
        derives the identical type from the pre-agg schema; both go
        through agg_result_type)."""
        from risingwave_tpu.stream.executors.hash_agg import (
            agg_result_type,
        )
        call, in_expr = self.agg_calls[j], self.agg_inputs[j]
        t = None if in_expr is None else in_expr.return_type
        try:
            return agg_result_type(call.kind, t)
        except TypeError as e:
            raise BindError(str(e))

    # returns (Expression | ("agg", index), ...)
    def bind(self, e: ast.Expr) -> Expression:
        out = self._bind(e)
        if isinstance(out, tuple):
            raise BindError("aggregate not allowed here")
        return out

    def bind_projection(self, e: ast.Expr):
        """Bind a projection item: Expression, ('agg', call_index) or
        ('win', call_index)."""
        if isinstance(e, ast.Over):
            return self._bind_over(e)
        return self._bind(e)

    _WINDOW_KINDS = ("row_number", "rank", "dense_rank", "lag", "lead",
                     "sum", "count", "min", "max", "first_value",
                     "last_value")

    def _bind_over(self, e: ast.Over):
        from risingwave_tpu.expr.window import WindowCall, WindowFuncKind

        if getattr(e.call, "filter_where", None) is not None:
            raise BindError(
                "FILTER (WHERE ...) on window functions is not "
                "supported yet")
        name = e.call.name
        if name == "avg":
            raise BindError("avg() OVER is not supported yet — use "
                            "sum()/count() OVER")
        if name not in self._WINDOW_KINDS:
            raise BindError(f"{name}() is not a window function")
        if e.call.distinct:
            raise BindError(
                f"{name}(DISTINCT ...) OVER is not supported")
        kind = WindowFuncKind(name)

        def col_idx(a: ast.Expr, what: str) -> int:
            b = self.bind(a)
            if not isinstance(b, InputRef):
                raise BindError(
                    f"window {what} must be a plain column (got "
                    f"{a!r})")
            return b.index

        partition = [col_idx(a, "PARTITION BY") for a in e.partition_by]
        order = [(col_idx(a, "ORDER BY"), desc)
                 for a, desc in e.order_by]
        if not order:
            raise BindError("window functions need ORDER BY in OVER()")
        if self.window_partition is None:
            self.window_partition = partition
            self.window_order = order
        elif (self.window_partition != partition
              or self.window_order != order):
            raise BindError(
                "all window functions in one SELECT must share the "
                "same PARTITION BY / ORDER BY (for now)")
        input_idx = None
        offset = 1
        if kind.needs_input:
            if kind == WindowFuncKind.COUNT and (e.call.star
                                                 or not e.call.args):
                input_idx = None             # count(*): counts rows
            else:
                if not e.call.args:
                    raise BindError(f"{name}() OVER needs an argument")
                input_idx = col_idx(e.call.args[0], "argument")
                if kind in (WindowFuncKind.SUM, WindowFuncKind.MIN,
                            WindowFuncKind.MAX):
                    dt = self.scope.schema[input_idx].data_type
                    if not dt.is_device:
                        raise BindError(
                            f"{name}() OVER needs a numeric/time "
                            f"argument (got {dt.name})")
                if kind in (WindowFuncKind.LAG, WindowFuncKind.LEAD) \
                        and len(e.call.args) > 2:
                    raise BindError(
                        f"{name}() default-value argument is not "
                        "supported yet")
                if len(e.call.args) > 1 and kind not in (
                        WindowFuncKind.LAG, WindowFuncKind.LEAD):
                    raise BindError(
                        f"{name}() OVER takes one argument")
                if kind in (WindowFuncKind.LAG, WindowFuncKind.LEAD) \
                        and len(e.call.args) > 1:
                    off = e.call.args[1]
                    try:
                        offset = int(off.value) if (
                            isinstance(off, ast.Lit)
                            and off.kind == "number") else None
                    except ValueError:
                        offset = None
                    if offset is None:
                        raise BindError(
                            f"{name}() offset must be an integer "
                            "literal")
        self.window_calls.append(
            WindowCall(kind, input_idx=input_idx, offset=offset))
        return ("win", len(self.window_calls) - 1)

    def _bind(self, e: ast.Expr):
        if isinstance(e, ast.Lit):
            return _bind_lit(e)
        if isinstance(e, ast.IntervalLit):
            return Literal(Interval(usecs=e.usecs), DataType.INTERVAL)
        if isinstance(e, ast.ColRef):
            idx, dt = self.scope.find(e.name, e.table)
            return InputRef(idx, dt)
        if isinstance(e, ast.Un):
            child = self.bind(e.child)
            return UnaryOp("not" if e.op == "not" else "neg", child)
        if isinstance(e, ast.Bin):
            left, right = self.bind(e.left), self.bind(e.right)
            return BinaryOp(e.op, left, right)
        if isinstance(e, ast.Call):
            return self._bind_call(e)
        if isinstance(e, ast.CastExpr):
            from risingwave_tpu.common.types import DataType as _DT
            from risingwave_tpu.expr.expr import Cast
            try:
                to = _DT.from_sql(e.type_name)
            except KeyError:
                raise BindError(f"unknown type {e.type_name!r}")
            return Cast(self.bind(e.child), to)
        raise BindError(f"unsupported expression {e!r}")

    def _bind_call(self, e: ast.Call):
        if getattr(e, "filter_where", None) is not None:
            e = _rewrite_filter_clause(e)
        name = e.name
        if name == "avg":
            # AVG rewrites to SUM/COUNT at bind time (the reference's
            # logical_agg does the same rewrite in the optimizer)
            if not self.allow_aggs:
                raise BindError("aggregate avg() not allowed here")
            if e.star or not e.args:
                raise BindError("avg(*) is not valid")
            arg = self.bind(e.args[0])
            # avg(DISTINCT x) = sum(DISTINCT x) / count(DISTINCT x):
            # both calls dedup over the same value multiset
            d = e.distinct
            akey = repr(arg)
            sj = self._register(
                AggCall(AggKind.SUM, None, distinct=d),
                ("sum", akey, d), input_expr=arg)
            cj = self._register(
                AggCall(AggKind.COUNT, None, distinct=d),
                ("count", akey, d), input_expr=arg)
            return ("avg", sj, cj)
        if name in ("string_agg", "array_agg"):
            if not self.allow_aggs:
                raise BindError(f"aggregate {name}() not allowed here")
            if e.star or not e.args:
                raise BindError(f"{name}() needs an argument")
            if e.distinct:
                raise BindError(
                    f"{name}(DISTINCT ...) is not supported yet")
            arg = self.bind(e.args[0])
            delimiter = ","
            if name == "string_agg":
                if len(e.args) != 2 or not (
                        isinstance(e.args[1], ast.Lit)
                        and e.args[1].kind == "string"):
                    raise BindError(
                        "string_agg(expr, 'delimiter') needs a string "
                        "literal delimiter")
                delimiter = str(e.args[1].value)
            elif len(e.args) != 1:
                raise BindError("array_agg() takes one argument")
            kind = AggKind.STRING_AGG if name == "string_agg" \
                else AggKind.ARRAY_AGG
            call = AggCall(kind, None, delimiter=delimiter)
            return ("agg", self._register(
                call, (name, repr(arg), delimiter), input_expr=arg))
        if name in _AGG_KINDS:
            if not self.allow_aggs:
                raise BindError(f"aggregate {name}() not allowed here")
            if e.star or not e.args:
                if name != "count":
                    raise BindError(f"{name}(*) is not valid")
                call = AggCall(AggKind.COUNT, None)
                key = ("count_star",)
            else:
                arg = self.bind(e.args[0])
                # MIN/MAX(DISTINCT) ≡ MIN/MAX — drop the flag there
                distinct = e.distinct and name in ("count", "sum")
                call = AggCall(_AGG_KINDS[name], None,
                               distinct=distinct)
                return ("agg", self._register(
                    call, (name, repr(arg), distinct), input_expr=arg))
            return ("agg", self._register(call, key))
        if name in ("tumble_start", "tumble_end"):
            ts = self.bind(e.args[0])
            iv = e.args[1]
            if not isinstance(iv, ast.IntervalLit):
                raise BindError(f"{name} needs an INTERVAL literal")
            mk = tumble_start if name == "tumble_start" else tumble_end
            return mk(ts, Interval(usecs=iv.usecs))
        if name == "case":
            return _bind_case(self.bind, e.args)
        # generic registered scalar function (sig/ analog: name →
        # arity + return type; the expr registry holds the kernel)
        sig = _SCALAR_SIGS.get(name)
        if sig is None:
            raise BindError(f"unknown function {name!r}")
        lo, hi, rt = sig
        if not (lo <= len(e.args) <= hi):
            raise BindError(
                f"{name}() takes {lo}"
                + (f"..{hi}" if hi != lo else "")
                + f" arguments, got {len(e.args)}")
        args = [self.bind(a) for a in e.args]
        _check_scalar_args(name, e.args, args)
        return FuncCall(name, args, rt)


def _bind_case(bind, args_ast):
    """CASE binding with NULL-branch unification: a bare NULL branch
    (incl. the implicit ELSE NULL) adopts the case's value type — a
    raw NULL literal binds INT64 and would fail Case's same-type
    invariant for varchar/decimal branches."""
    from risingwave_tpu.expr.expr import Case, Literal

    args = [bind(a) for a in args_ast]
    whens = list(zip(args[:-1:2], args[1:-1:2]))
    else_ = args[-1]
    vals = [v for _c, v in whens] + [else_]
    vt = next((v.return_type for v in vals
               if not (isinstance(v, Literal) and v.value is None)),
              None)
    if vt is not None:
        def unify(v):
            if isinstance(v, Literal) and v.value is None \
                    and v.return_type != vt:
                return Literal(None, vt)
            return v
        whens = [(c, unify(v)) for c, v in whens]
        else_ = unify(else_)
    return Case(whens, else_)


def _rewrite_filter_clause(e):
    """Aggregate FILTER (WHERE c) → CASE rewrite (pg semantics:
    count(*) counts matches; sum/min/max/avg see NULL for
    non-matches, so empty matches yield NULL — except count, 0)."""
    fw = e.filter_where
    if e.name == "count" and (e.star or not e.args):
        return ast.Call("sum", [ast.Call(
            "case", [fw, ast.Lit(1, "number"), ast.Lit(0, "number")])])
    if e.name in ("sum", "min", "max", "avg") and e.args \
            and not e.distinct:
        return ast.Call(e.name, [ast.Call(
            "case", [fw, e.args[0], ast.Lit(None, "null")])])
    raise BindError(
        "FILTER (WHERE ...) is supported for count(*)/sum/min/max/avg"
        " (without DISTINCT)")


# scalar signatures: name → (min args, max args, return type)
_SCALAR_SIGS = {
    "lower": (1, 1, DataType.VARCHAR),
    "upper": (1, 1, DataType.VARCHAR),
    "char_length": (1, 1, DataType.INT64),
    "length": (1, 1, DataType.INT64),
    "substr": (2, 3, DataType.VARCHAR),
    "split_part": (3, 3, DataType.VARCHAR),
    "replace": (3, 3, DataType.VARCHAR),
    "concat": (1, 64, DataType.VARCHAR),
    "to_char": (2, 2, DataType.VARCHAR),
    "date_part": (2, 2, DataType.INT64),
    "date_trunc": (2, 2, DataType.TIMESTAMP),
    "extract_epoch": (1, 1, DataType.DECIMAL),
}

_DATE_FIELDS = {"second", "minute", "hour", "year", "month", "day"}
_TRUNC_FIELDS = {"second", "minute", "hour", "day"}


# argument positions the kernels treat as SCALARS (evaluated once for
# the whole chunk) — they must be constants, or row 0's value would
# silently apply to every row
_CONST_ARG_POSITIONS = {
    "substr": (1, 2), "split_part": (1, 2), "replace": (1, 2),
    "to_char": (1,), "date_part": (0,), "date_trunc": (0,),
}


def _check_scalar_args(name, raw_args, bound) -> None:
    """Bind-time validation: scalar-treated argument positions must be
    literals, and a bad field name or position must fail the
    statement, not crash-loop the deployed actor at eval time."""
    from risingwave_tpu.expr.expr import Literal

    for i in _CONST_ARG_POSITIONS.get(name, ()):
        if i < len(bound) and not isinstance(bound[i], Literal):
            raise BindError(
                f"{name}() argument {i + 1} must be a constant")

    def lit_of(i):
        b = bound[i]
        return b.value if isinstance(b, Literal) else None

    if name in ("date_part", "date_trunc"):
        f = lit_of(0)
        if f is not None:
            allowed = _DATE_FIELDS if name == "date_part" \
                else _TRUNC_FIELDS
            if str(f).lower() not in allowed:
                raise BindError(
                    f"{name} field {f!r} unsupported (one of "
                    f"{sorted(allowed)})")
    if name == "split_part":
        k = lit_of(2)
        if k is not None and int(k) == 0:
            raise BindError("split_part position must not be zero")


def _bind_lit(e: ast.Lit) -> Literal:
    if e.kind == "number":
        text = str(e.value)
        if "." in text:
            return lit(text, DataType.DECIMAL)
        return lit(int(text), DataType.INT64)
    if e.kind == "string":
        return lit(str(e.value), DataType.VARCHAR)
    if e.kind == "bool":
        return lit(bool(e.value), DataType.BOOLEAN)
    return Literal(None, DataType.INT64)       # bare NULL


_AGG_NAMES = set(_AGG_KINDS) | {"avg", "string_agg", "array_agg"}


def contains_agg(e: ast.Expr) -> bool:
    """AST walk: does the expression contain an aggregate call?
    OVER windows are opaque (their calls are window functions)."""
    if isinstance(e, ast.Over):
        return False
    if isinstance(e, ast.Call):
        return e.name in _AGG_NAMES or any(contains_agg(a)
                                           for a in e.args)
    if isinstance(e, ast.Bin):
        return contains_agg(e.left) or contains_agg(e.right)
    if isinstance(e, ast.Un):
        return contains_agg(e.child)
    if isinstance(e, ast.CastExpr):
        return contains_agg(e.child)
    return False


def contains_colref(e: ast.Expr) -> bool:
    if isinstance(e, ast.ColRef):
        return True
    if isinstance(e, ast.Over):
        return True
    if isinstance(e, ast.Call):
        return any(contains_colref(a) for a in e.args)
    if isinstance(e, ast.Bin):
        return contains_colref(e.left) or contains_colref(e.right)
    if isinstance(e, ast.Un):
        return contains_colref(e.child)
    if isinstance(e, ast.CastExpr):
        return contains_colref(e.child)
    return False


class PostAggBinder:
    """Binds a post-aggregation expression (SELECT item or HAVING)
    into an Expression over the agg OUTPUT row: group-expression
    matches become column refs 0..g-1, aggregate calls become refs
    g+j, and scalar operators recurse (the reference folds this into
    LogicalAgg planning, logical_agg.rs rewrite_with_agg_calls).

    Registers agg calls on the shared `binder` as it goes — run every
    post-agg bind BEFORE constructing the HashAggExecutor."""

    def __init__(self, binder: Binder, group_reprs: List[str]):
        self.binder = binder
        self.group_reprs = group_reprs
        self.g = len(group_reprs)

    def bind(self, e: ast.Expr):
        from risingwave_tpu.expr.expr import Cast
        # aggregate call at this node → agg output column(s)
        if isinstance(e, ast.Call) and e.name in _AGG_NAMES:
            b = self.binder._bind_call(e)
            if isinstance(b, tuple) and b[0] == "agg":
                j = b[1]
                return InputRef(self.g + j, self.binder.agg_out_type(j))
            if isinstance(b, tuple) and b[0] == "avg":
                _tag, sj, cj = b
                s = Cast(InputRef(self.g + sj,
                                  self.binder.agg_out_type(sj)),
                         DataType.FLOAT64)
                c = Cast(InputRef(self.g + cj,
                                  self.binder.agg_out_type(cj)),
                         DataType.FLOAT64)
                return BinaryOp("/", s, c)
            return b
        # whole expression matches a GROUP BY expression → group col
        try:
            plain = Binder(self.binder.scope).bind(e)
        except BindError:
            plain = None
        if plain is not None:
            r = repr(plain)
            if r in self.group_reprs:
                i = self.group_reprs.index(r)
                return InputRef(i, plain.return_type)
            if not contains_colref(e):
                return plain           # constant — valid anywhere
        # recurse: some subtree must be grouped or aggregated
        if isinstance(e, ast.Bin):
            return BinaryOp(e.op, self.bind(e.left), self.bind(e.right))
        if isinstance(e, ast.Un):
            return UnaryOp("not" if e.op == "not" else "neg",
                           self.bind(e.child))
        if isinstance(e, ast.CastExpr):
            from risingwave_tpu.expr.expr import Cast
            try:
                to = DataType.from_sql(e.type_name)
            except KeyError:
                raise BindError(f"unknown type {e.type_name!r}")
            return Cast(self.bind(e.child), to)
        if isinstance(e, ast.Call):
            if getattr(e, "filter_where", None) is not None:
                # anything reaching here is NOT an aggregate (those
                # bound through the whole-expression pass) — pg:
                # "FILTER specified, but <fn> is not an aggregate"
                raise BindError(
                    f"FILTER specified, but {e.name}() is not an "
                    "aggregate function")
            if e.name == "case":
                return _bind_case(self.bind, e.args)
            sig = _SCALAR_SIGS.get(e.name)
            if sig is None:
                raise BindError(f"unknown function {e.name!r}")
            args = [self.bind(a) for a in e.args]
            _check_scalar_args(e.name, e.args, args)
            return FuncCall(e.name, args, sig[2])
        raise BindError(
            f"expression {e!r} is neither grouped nor aggregated")


def expr_name(e: ast.Expr, fallback: str) -> str:
    """Default output column name (pg-ish)."""
    if isinstance(e, ast.ColRef):
        return e.name
    if isinstance(e, ast.Call):
        return e.name
    return fallback
