"""pgwire: PostgreSQL wire-protocol (v3) server over asyncio.

Reference parity: src/utils/pgwire/src/{pg_protocol.rs,pg_server.rs}
— the simple-query protocol surface a psql client needs: startup
handshake (SSL probe declined, AuthenticationOk, ParameterStatus,
ReadyForQuery), 'Q' simple queries answered with RowDescription /
DataRow / CommandComplete, errors as ErrorResponse, 'X' terminate.
Extended protocol (parse/bind/execute) is declined politely. All
values ship in text format (what psql uses).
"""

from __future__ import annotations

import asyncio
import struct
from typing import List, Optional, Tuple

from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.frontend.session import Frontend

_OID = {
    DataType.BOOLEAN: 16,
    DataType.INT16: 21, DataType.INT32: 23, DataType.INT64: 20,
    DataType.SERIAL: 20,
    DataType.FLOAT32: 700, DataType.FLOAT64: 701,
    DataType.DECIMAL: 1700,
    DataType.VARCHAR: 25,
    DataType.DATE: 1082, DataType.TIME: 1083,
    DataType.TIMESTAMP: 1114, DataType.TIMESTAMPTZ: 1184,
    DataType.INTERVAL: 1186, DataType.BYTEA: 17, DataType.JSONB: 3802,
}

SSL_REQUEST = 80877103
CANCEL_REQUEST = 80877102
PROTOCOL_V3 = 196608


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack(">I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


class PgServer:
    """Serves one Frontend session per connection's statements.

    All connections share the session's catalog and barrier loop (the
    reference shares via meta; we share in-process)."""

    def __init__(self, frontend: Frontend):
        self.frontend = frontend
        self._server: Optional[asyncio.AbstractServer] = None

    async def serve(self, host: str = "127.0.0.1", port: int = 4566):
        self._server = await asyncio.start_server(
            self._handle, host, port)
        return self._server

    @property
    def port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # -- connection loop --------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            if not await self._startup(reader, writer):
                return
            while True:
                hdr = await reader.readexactly(5)
                tag = hdr[0:1]
                ln = struct.unpack(">I", hdr[1:5])[0]
                payload = await reader.readexactly(ln - 4)
                if tag == b"X":
                    return
                if tag == b"Q":
                    sql = payload.rstrip(b"\x00").decode()
                    await self._simple_query(writer, sql)
                else:
                    writer.write(_error(
                        f"unsupported message {tag!r} (extended "
                        "protocol not implemented)"))
                    writer.write(_ready())
                    await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    async def _startup(self, reader, writer) -> bool:
        while True:
            ln, code = struct.unpack(
                ">II", await reader.readexactly(8))
            if code == SSL_REQUEST:
                writer.write(b"N")            # no TLS
                await writer.drain()
                continue
            if code == CANCEL_REQUEST:
                return False
            if code != PROTOCOL_V3:
                writer.write(_error(f"unsupported protocol {code}"))
                await writer.drain()
                return False
            await reader.readexactly(ln - 8)  # user/database params
            break
        out = _msg(b"R", struct.pack(">I", 0))       # AuthenticationOk
        for k, v in (("server_version", "13.0 (risingwave-tpu)"),
                     ("client_encoding", "UTF8"),
                     ("server_encoding", "UTF8"),
                     ("DateStyle", "ISO")):
            out += _msg(b"S", _cstr(k) + _cstr(v))
        out += _msg(b"K", struct.pack(">II", 0, 0))  # BackendKeyData
        out += _ready()
        writer.write(out)
        await writer.drain()
        return True

    async def _simple_query(self, writer, sql: str) -> None:
        try:
            result = await self.frontend.execute(sql)
            schema = getattr(self.frontend, "last_select_schema", None)
        except (Exception,) as e:                    # noqa: BLE001
            writer.write(_error(str(e)))
            writer.write(_ready())
            await writer.drain()
            return
        if isinstance(result, str):                  # DDL/command
            writer.write(_msg(b"C", _cstr(result.replace("_", " "))))
        else:
            writer.write(_row_description(result, schema))
            types = ([f.data_type for f in schema]
                     if schema is not None else None)
            for row in result:
                writer.write(_data_row(row, types))
            writer.write(_msg(b"C", _cstr(f"SELECT {len(result)}")))
        writer.write(_ready())
        await writer.drain()


def _ready() -> bytes:
    return _msg(b"Z", b"I")


def _error(message: str) -> bytes:
    fields = b"SERROR\x00" + b"CXX000\x00" + b"M" + _cstr(message) + b"\x00"
    return _msg(b"E", fields)


def _row_description(rows: List[tuple],
                     schema: Optional[Schema]) -> bytes:
    if schema is not None:
        cols: List[Tuple[str, int]] = [
            (f.name, _OID.get(f.data_type, 25)) for f in schema]
    else:
        width = len(rows[0]) if rows else 0
        cols = [(f"col{i}", 25) for i in range(width)]
    payload = struct.pack(">H", len(cols))
    for name, oid in cols:
        payload += _cstr(name) + struct.pack(
            ">IHIhih", 0, 0, oid, -1, -1, 0)
    return _msg(b"T", payload)


def _data_row(row: tuple,
              types: Optional[List[DataType]] = None) -> bytes:
    payload = struct.pack(">H", len(row))
    for i, v in enumerate(row):
        if v is None:
            payload += struct.pack(">i", -1)
        else:
            dt = types[i] if types is not None and i < len(types) else None
            b = _pg_text(v, dt).encode()
            payload += struct.pack(">I", len(b)) + b
    return _msg(b"D", payload)


_USECS_PER_SEC = 1_000_000
_SECS_PER_DAY = 86_400


def _fmt_usec_of_day(usecs: int) -> str:
    s, us = divmod(usecs, _USECS_PER_SEC)
    h, rem = divmod(s, 3600)
    m, sec = divmod(rem, 60)
    out = f"{h:02d}:{m:02d}:{sec:02d}"
    return out + (f".{us:06d}" if us else "")


def _fmt_date(days: int) -> str:
    import datetime
    d = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(days))
    return d.isoformat()


def _pg_text(v, dt: Optional[DataType] = None) -> str:
    """Text-format one value. Physical time types (raw ints — see
    common/types.py:119-122) are rendered ISO-8601 so psql/psycopg can
    parse them under the advertised OIDs (ADVICE r2)."""
    if v is True:
        return "t"
    if v is False:
        return "f"
    if dt == DataType.DATE:
        return _fmt_date(int(v))
    if dt == DataType.TIME:
        return _fmt_usec_of_day(int(v))
    if dt in (DataType.TIMESTAMP, DataType.TIMESTAMPTZ):
        usecs = int(v)
        day, of_day = divmod(usecs, _SECS_PER_DAY * _USECS_PER_SEC)
        out = f"{_fmt_date(day)} {_fmt_usec_of_day(of_day)}"
        return out + "+00" if dt == DataType.TIMESTAMPTZ else out
    return str(v)
