"""pgwire: PostgreSQL wire-protocol (v3) server over asyncio.

Reference parity: src/utils/pgwire/src/{pg_protocol.rs,pg_server.rs}
— the protocol surface psql AND driver clients need: startup
handshake (SSL probe declined, AuthenticationOk, ParameterStatus,
ReadyForQuery), 'Q' simple queries answered with RowDescription /
DataRow / CommandComplete, errors as ErrorResponse, 'X' terminate,
plus the EXTENDED protocol (Parse/Bind/Describe/Execute/Close/Sync)
that psycopg-style drivers use: $n parameters substitute as quoted
text literals at Bind (per-bind re-plan; prepared-plan caching is a
later increment), failures skip to Sync. All values ship in text
format.
"""

from __future__ import annotations

import asyncio
import re
import struct
from typing import List, Optional, Tuple

from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.frontend.session import Frontend

_OID = {
    DataType.BOOLEAN: 16,
    DataType.INT16: 21, DataType.INT32: 23, DataType.INT64: 20,
    DataType.SERIAL: 20,
    DataType.FLOAT32: 700, DataType.FLOAT64: 701,
    DataType.DECIMAL: 1700,
    DataType.VARCHAR: 25,
    DataType.DATE: 1082, DataType.TIME: 1083,
    DataType.TIMESTAMP: 1114, DataType.TIMESTAMPTZ: 1184,
    DataType.INTERVAL: 1186, DataType.BYTEA: 17, DataType.JSONB: 3802,
}

SSL_REQUEST = 80877103
CANCEL_REQUEST = 80877102
PROTOCOL_V3 = 196608


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack(">I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


class PgServer:
    """Serves one Frontend session per connection's statements.

    All connections share the session's catalog and barrier loop (the
    reference shares via meta; we share in-process)."""

    def __init__(self, frontend: Frontend,
                 password: Optional[str] = None):
        self.frontend = frontend
        # cleartext password auth (pg_protocol.rs startup handshake;
        # AuthenticationCleartextPassword). None ⇒ trust (no auth).
        self.password = password
        self._server: Optional[asyncio.AbstractServer] = None

    async def serve(self, host: str = "127.0.0.1", port: int = 4566):
        self._server = await asyncio.start_server(
            self._handle, host, port)
        return self._server

    @property
    def port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # -- connection loop --------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        # extended-protocol state (pg_protocol.rs): prepared statements
        # and portals are per-connection; after an error the backend
        # discards messages until Sync
        stmts: dict = {}      # name → sql
        portals: dict = {}    # name → ["rows", rows, schema, pos]|["cmd", s]
        # Describe(statement) results reusable by the following Bind.
        # PER CONNECTION (prepared statements are per-connection) and
        # invalidated whenever Parse redefines the name (ADVICE r3:
        # a server-global cache could hand one connection another
        # connection's rows, or stale rows after re-Parse of "")
        describe_cache: dict = {}
        failed = False
        try:
            if not await self._startup(reader, writer):
                return
            while True:
                hdr = await reader.readexactly(5)
                tag = hdr[0:1]
                ln = struct.unpack(">I", hdr[1:5])[0]
                payload = await reader.readexactly(ln - 4)
                if tag == b"X":
                    return
                if tag == b"S":                       # Sync
                    failed = False
                    writer.write(_ready())
                    await writer.drain()
                    continue
                if failed:
                    continue                          # skip until Sync
                if tag == b"Q":
                    sql = payload.rstrip(b"\x00").decode()
                    await self._simple_query(writer, sql)
                    continue
                try:
                    if tag == b"P":
                        self._parse_msg(payload, stmts, describe_cache)
                        writer.write(_msg(b"1", b""))  # ParseComplete
                    elif tag == b"B":
                        await self._bind_msg(payload, stmts, portals,
                                             describe_cache)
                        writer.write(_msg(b"2", b""))  # BindComplete
                    elif tag == b"D":
                        await self._describe_msg(payload, stmts, portals,
                                                 describe_cache, writer)
                    elif tag == b"E":
                        self._execute_msg(payload, portals, writer)
                    elif tag == b"C":                  # Close
                        kind = payload[0:1]
                        name, _ = self._read_cstr(payload, 1)
                        (stmts if kind == b"S" else portals).pop(
                            name, None)
                        writer.write(_msg(b"3", b""))  # CloseComplete
                    elif tag == b"H":                  # Flush
                        pass
                    else:
                        raise ValueError(
                            f"unsupported message {tag!r}")
                    await writer.drain()
                except (Exception,) as e:              # noqa: BLE001
                    writer.write(_error(str(e)))
                    await writer.drain()
                    failed = True
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    # -- extended protocol -------------------------------------------------
    _QUOTED = re.compile(r"'(?:[^']|'')*'")
    _PARAM = re.compile(r"\$(\d+)")

    @classmethod
    def _sub_params_sql(cls, sql: str, params) -> str:
        """Token-aware $n substitution: quoted regions are untouched,
        and substituted values can never be re-scanned for $n (each
        segment is processed exactly once)."""
        def sub_segment(seg: str) -> str:
            def repl(m):
                i = int(m.group(1))
                if not (1 <= i <= len(params)):
                    raise ValueError(f"parameter ${i} not bound")
                v = params[i - 1]
                return "NULL" if v is None else \
                    "'" + v.replace("'", "''") + "'"
            return cls._PARAM.sub(repl, seg)

        out = []
        at = 0
        for m in cls._QUOTED.finditer(sql):
            out.append(sub_segment(sql[at:m.start()]))
            out.append(m.group(0))
            at = m.end()
        out.append(sub_segment(sql[at:]))
        return "".join(out)

    @classmethod
    def _param_count(cls, sql: str) -> int:
        n = 0
        at = 0
        for m in cls._QUOTED.finditer(sql):
            for pm in cls._PARAM.finditer(sql[at:m.start()]):
                n = max(n, int(pm.group(1)))
            at = m.end()
        for pm in cls._PARAM.finditer(sql[at:]):
            n = max(n, int(pm.group(1)))
        return n

    @staticmethod
    def _read_cstr(payload: bytes, at: int):
        end = payload.index(b"\x00", at)
        return payload[at:end].decode(), end + 1

    def _parse_msg(self, payload: bytes, stmts: dict,
                   describe_cache: dict) -> None:
        name, at = self._read_cstr(payload, 0)
        sql, at = self._read_cstr(payload, at)
        # declared parameter-type OIDs are accepted and ignored (text
        # parameters are substituted at bind time)
        stmts[name] = sql
        describe_cache.pop(name, None)   # re-Parse invalidates

    async def _bind_msg(self, payload: bytes, stmts: dict,
                        portals: dict, describe_cache: dict) -> None:
        portal, at = self._read_cstr(payload, 0)
        stmt, at = self._read_cstr(payload, at)
        cached = describe_cache.pop(stmt, None)
        sql = stmts[stmt]
        nfmt = struct.unpack_from(">H", payload, at)[0]
        fmts = struct.unpack_from(f">{nfmt}H", payload, at + 2) \
            if nfmt else ()
        if any(f == 1 for f in fmts):
            raise ValueError(
                "binary-format parameters are not supported — bind "
                "parameters as text")
        at += 2 + 2 * nfmt
        nparams = struct.unpack_from(">H", payload, at)[0]
        at += 2
        params = []
        for _ in range(nparams):
            plen = struct.unpack_from(">i", payload, at)[0]
            at += 4
            if plen < 0:
                params.append(None)
            else:
                params.append(payload[at:at + plen].decode())
                at += plen
        # $n substitution with SQL-quoted text literals (the statement
        # re-plans per bind; prepared-plan caching is a later increment)
        if cached is not None and not params:
            portals[portal] = ["rows", cached[1], cached[2], 0]
            return
        sql = self._sub_params_sql(sql, params)
        result = await self.frontend.execute(sql)
        if isinstance(result, str):
            portals[portal] = ["cmd", result]
        else:
            schema = getattr(self.frontend, "last_select_schema", None)
            portals[portal] = ["rows", result, schema, 0]

    async def _describe_msg(self, payload: bytes, stmts: dict,
                            portals: dict, describe_cache: dict,
                            writer) -> None:
        kind = payload[0:1]
        name, _ = self._read_cstr(payload, 1)
        if kind == b"S":
            sql = stmts.get(name, "")
            nparams = self._param_count(sql)
            # parameter types are unknown (OID 0 = unspecified); the
            # COUNT must be right or count-validating drivers bail
            writer.write(_msg(b"t", struct.pack(
                f">H{nparams}I", nparams, *([0] * nparams))))
            head = sql.lstrip().split(None, 1)
            is_select = bool(head) and head[0].lower() in (
                "select", "show", "explain")
            if is_select and nparams == 0:
                # parameterless SELECT: run it now for real metadata
                # and cache the rows — Bind reuses them instead of
                # executing the same query twice per round trip
                rows = await self.frontend.execute(sql)
                schema = getattr(self.frontend,
                                 "last_select_schema", None)
                describe_cache[name] = ("rows", rows, schema)
                writer.write(_row_description(rows, schema))
            else:
                # parameterized (shape unknown until Bind — portal
                # Describe returns the real RowDescription) or a
                # command: NoData
                writer.write(_msg(b"n", b""))
            return
        p = portals[name]
        if p[0] == "cmd":
            writer.write(_msg(b"n", b""))              # NoData
        else:
            writer.write(_row_description(p[1], p[2]))

    def _execute_msg(self, payload: bytes, portals: dict,
                     writer) -> None:
        name, at = self._read_cstr(payload, 0)
        # fetch-size pagination (ADVICE r3): honor the int32 max-rows
        # field — JDBC setFetchSize / psycopg server-side cursors expect
        # PortalSuspended between partial result sets
        max_rows = struct.unpack_from(">i", payload, at)[0]
        p = portals[name]
        if p[0] == "cmd":
            writer.write(_msg(b"C", _cstr(p[1].replace("_", " "))))
            return
        rows, schema, pos = p[1], p[2], p[3]
        types = ([f.data_type for f in schema]
                 if schema is not None else None)
        end = len(rows) if max_rows <= 0 else min(len(rows),
                                                  pos + max_rows)
        for row in rows[pos:end]:
            writer.write(_data_row(row, types))
        p[3] = end
        if end < len(rows):
            writer.write(_msg(b"s", b""))            # PortalSuspended
        else:
            writer.write(_msg(b"C", _cstr(f"SELECT {end - pos}")))

    async def _startup(self, reader, writer) -> bool:
        while True:
            ln, code = struct.unpack(
                ">II", await reader.readexactly(8))
            if code == SSL_REQUEST:
                writer.write(b"N")            # no TLS
                await writer.drain()
                continue
            if code == CANCEL_REQUEST:
                return False
            if code != PROTOCOL_V3:
                writer.write(_error(f"unsupported protocol {code}"))
                await writer.drain()
                return False
            await reader.readexactly(ln - 8)  # user/database params
            break
        if self.password is not None:
            # AuthenticationCleartextPassword → expect PasswordMessage
            writer.write(_msg(b"R", struct.pack(">I", 3)))
            await writer.drain()
            hdr = await reader.readexactly(5)
            if hdr[0:1] != b"p":
                writer.write(_error("expected PasswordMessage"))
                await writer.drain()
                return False
            ln = struct.unpack(">I", hdr[1:5])[0]
            pw = (await reader.readexactly(ln - 4)).rstrip(b"\x00")
            if pw.decode(errors="replace") != self.password:
                writer.write(_error("password authentication failed"))
                await writer.drain()
                return False
        out = _msg(b"R", struct.pack(">I", 0))       # AuthenticationOk
        for k, v in (("server_version", "13.0 (risingwave-tpu)"),
                     ("client_encoding", "UTF8"),
                     ("server_encoding", "UTF8"),
                     ("DateStyle", "ISO")):
            out += _msg(b"S", _cstr(k) + _cstr(v))
        out += _msg(b"K", struct.pack(">II", 0, 0))  # BackendKeyData
        out += _ready()
        writer.write(out)
        await writer.drain()
        return True

    async def _simple_query(self, writer, sql: str) -> None:
        try:
            result = await self.frontend.execute(sql)
            schema = getattr(self.frontend, "last_select_schema", None)
        except (Exception,) as e:                    # noqa: BLE001
            writer.write(_error(str(e)))
            writer.write(_ready())
            await writer.drain()
            return
        if isinstance(result, str):                  # DDL/command
            writer.write(_msg(b"C", _cstr(result.replace("_", " "))))
        else:
            writer.write(_row_description(result, schema))
            types = ([f.data_type for f in schema]
                     if schema is not None else None)
            for row in result:
                writer.write(_data_row(row, types))
            writer.write(_msg(b"C", _cstr(f"SELECT {len(result)}")))
        writer.write(_ready())
        await writer.drain()


def _ready() -> bytes:
    return _msg(b"Z", b"I")


def _error(message: str) -> bytes:
    fields = b"SERROR\x00" + b"CXX000\x00" + b"M" + _cstr(message) + b"\x00"
    return _msg(b"E", fields)


def _row_description(rows: List[tuple],
                     schema: Optional[Schema]) -> bytes:
    if schema is not None:
        cols: List[Tuple[str, int]] = [
            (f.name, _OID.get(f.data_type, 25)) for f in schema]
    else:
        width = len(rows[0]) if rows else 0
        cols = [(f"col{i}", 25) for i in range(width)]
    payload = struct.pack(">H", len(cols))
    for name, oid in cols:
        payload += _cstr(name) + struct.pack(
            ">IHIhih", 0, 0, oid, -1, -1, 0)
    return _msg(b"T", payload)


def _data_row(row: tuple,
              types: Optional[List[DataType]] = None) -> bytes:
    payload = struct.pack(">H", len(row))
    for i, v in enumerate(row):
        if v is None:
            payload += struct.pack(">i", -1)
        else:
            dt = types[i] if types is not None and i < len(types) else None
            b = _pg_text(v, dt).encode()
            payload += struct.pack(">I", len(b)) + b
    return _msg(b"D", payload)


_USECS_PER_SEC = 1_000_000
_SECS_PER_DAY = 86_400


def _fmt_usec_of_day(usecs: int) -> str:
    s, us = divmod(usecs, _USECS_PER_SEC)
    h, rem = divmod(s, 3600)
    m, sec = divmod(rem, 60)
    out = f"{h:02d}:{m:02d}:{sec:02d}"
    return out + (f".{us:06d}" if us else "")


def _fmt_date(days: int) -> str:
    import datetime
    d = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(days))
    return d.isoformat()


def _pg_list(v) -> str:
    """array_agg output → pg array text: NULL elements literal, and
    quoting whenever the element could be misread (delimiters, quotes,
    backslashes, empty strings, or the literal word NULL)."""
    parts = []
    for x in v:
        if x is None:
            parts.append("NULL")
            continue
        # element type is unknown (LIST carries none yet): scalar
        # formatting handles bool/nested; physical time ints pass
        # through un-rendered until LIST gains an element type
        s = _pg_text(x)
        if s == "" or s.upper() == "NULL" or any(
                c in s for c in ',{}"\\ '):
            s = s.replace("\\", "\\\\").replace('"', '\\"')
            parts.append(f'"{s}"')
        else:
            parts.append(s)
    return "{" + ",".join(parts) + "}"


def _pg_text(v, dt: Optional[DataType] = None) -> str:
    """Text-format one value. Physical time types (raw ints — see
    common/types.py:119-122) are rendered ISO-8601 so psql/psycopg can
    parse them under the advertised OIDs (ADVICE r2)."""
    if v is True:
        return "t"
    if v is False:
        return "f"
    if dt == DataType.LIST or isinstance(v, (tuple, list)):
        return _pg_list(v)
    if dt == DataType.DATE:
        return _fmt_date(int(v))
    if dt == DataType.TIME:
        return _fmt_usec_of_day(int(v))
    if dt in (DataType.TIMESTAMP, DataType.TIMESTAMPTZ):
        usecs = int(v)
        day, of_day = divmod(usecs, _SECS_PER_DAY * _USECS_PER_SEC)
        out = f"{_fmt_date(day)} {_fmt_usec_of_day(of_day)}"
        return out + "+00" if dt == DataType.TIMESTAMPTZ else out
    return str(v)
