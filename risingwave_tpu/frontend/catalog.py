"""Catalog: named sources / materialized views + id allocation.

Reference parity: src/meta/src/manager/catalog/mod.rs:135 (the meta
CatalogManager) + the frontend's read mirror — collapsed to one
in-process structure for the single-node deployment shape.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from risingwave_tpu.common.types import Schema


@dataclass
class SourceCatalog:
    name: str
    source_id: int
    schema: Schema
    options: Dict[str, str]


@dataclass
class MvCatalog:
    name: str
    table_id: int
    schema: Schema
    pk_indices: List[int]
    definition: str
    actor_id: int = 0
    dependent_sources: List[str] = field(default_factory=list)
    # catalog id-counter value when this MV was planned: a reschedule
    # replans the same definition from the same base so every state
    # table gets its ORIGINAL id back (state survives the replan)
    id_base: int = -1
    # user-facing column count; trailing columns past it are hidden
    # plumbing (_row_id, unprojected group keys) that SELECT * and
    # downstream scopes must not expose (None = all visible)
    n_visible: Optional[int] = None
    # CREATE TABLE jobs share this registry; system catalogs and SHOW
    # split on it
    is_table: bool = False
    # planner-proved append-only changelog (no retractions ever):
    # sinks chained FROM this MV derive their mode from this proof
    # without re-walking the MV's executor tree
    append_only: bool = False

    @property
    def visible_schema(self) -> Schema:
        if self.n_visible is None:
            return self.schema
        return Schema(list(self.schema)[:self.n_visible])


@dataclass
class SinkCatalog:
    name: str
    actor_id: int
    options: Dict[str, str]
    definition: str = ""
    dependent_sources: List[str] = field(default_factory=list)
    # exactly-once epoch-segment sinks (connectors/sink.py): the
    # derived record mode and writer count, kept so ctl/rw_sinks can
    # rebuild the target from options without replanning
    mode: str = ""               # "append" | "upsert" | "" (legacy)
    n_writers: int = 1


class Catalog:
    def __init__(self) -> None:
        self.sources: Dict[str, SourceCatalog] = {}
        self.mvs: Dict[str, MvCatalog] = {}
        self.sinks: Dict[str, SinkCatalog] = {}
        self._next_id = 1

    def next_id(self) -> int:
        i = self._next_id
        self._next_id += 1
        return i

    def _check_free(self, name: str) -> None:
        if name in self.sources or name in self.mvs or name in self.sinks:
            raise ValueError(f"catalog object {name!r} already exists")

    def add_source(self, name: str, schema: Schema,
                   options: Dict[str, str]) -> SourceCatalog:
        self._check_free(name)
        sc = SourceCatalog(name, self.next_id(), schema, options)
        self.sources[name] = sc
        return sc

    def add_mv(self, mv: MvCatalog) -> None:
        self._check_free(mv.name)
        self.mvs[mv.name] = mv

    def add_sink(self, sk: SinkCatalog) -> None:
        self._check_free(sk.name)
        self.sinks[sk.name] = sk

    def resolve(self, name: str):
        if name in self.sources:
            return self.sources[name]
        if name in self.mvs:
            return self.mvs[name]
        raise KeyError(f"unknown relation {name!r}")
