"""Executor-graph rewrite rules (the rule library).

Each rule is a pure tree-to-tree function `rule(root) -> (new_root,
fired, detail)`: it never mutates the input plan (changed paths are
rebuilt, untouched subtrees are shared), so a checker violation can
always fall back to the pre-rule tree. Rules:

- filter_pushdown     WHERE filters sink below joins (kind-gated: only
                      past sides the join never null-pads) and through
                      projections of plain column refs — the planner's
                      former inline pushdown, migrated here.
- project_fusion      Project∘Project composes into one projection
                      (watermark derivations compose too); a Filter
                      over a ref-only Project evaluates before it.
- noop_project_elision identity projections (same columns, same names)
                      drop out of the chain.
- column_pruning      live lanes are computed top-down; join inputs,
                      agg feeds and source scans narrow to the columns
                      actually referenced above — joins rebuild with
                      remapped keys and same-id narrowed state tables,
                      sources grow a narrowing projection.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Set, Tuple

from risingwave_tpu.frontend.opt.checker import expr_refs
from risingwave_tpu.stream.executor import ExecutorInfo, executor_children


# -- expression surgery ---------------------------------------------------


def remap_expr(e, mapping: Dict[int, int]):
    """Rebuild `e` with every InputRef index sent through `mapping`."""
    from risingwave_tpu.expr.expr import (
        BinaryOp, Case, Cast, FuncCall, InputRef, Literal, UnaryOp,
    )
    if isinstance(e, InputRef):
        return InputRef(mapping[e.index], e.return_type)
    if isinstance(e, Literal):
        return e
    if isinstance(e, BinaryOp):
        return BinaryOp(e.op, remap_expr(e.left, mapping),
                        remap_expr(e.right, mapping))
    if isinstance(e, UnaryOp):
        return UnaryOp(e.op, remap_expr(e.child, mapping))
    if isinstance(e, Cast):
        return Cast(remap_expr(e.child, mapping), e.return_type)
    if isinstance(e, Case):
        return Case([(remap_expr(c, mapping), remap_expr(v, mapping))
                     for c, v in e.whens], remap_expr(e.else_, mapping))
    if isinstance(e, FuncCall):
        return FuncCall(e.name, [remap_expr(a, mapping) for a in e.args],
                        e.return_type)
    raise TypeError(f"unrewritable expression {type(e).__name__}")


def subst_expr(e, exprs: List):
    """Replace every InputRef(i) in `e` with exprs[i] (projection
    composition / pushdown-through-project)."""
    from risingwave_tpu.expr.expr import (
        BinaryOp, Case, Cast, FuncCall, InputRef, Literal, UnaryOp,
    )
    if isinstance(e, InputRef):
        return exprs[e.index]
    if isinstance(e, Literal):
        return e
    if isinstance(e, BinaryOp):
        return BinaryOp(e.op, subst_expr(e.left, exprs),
                        subst_expr(e.right, exprs))
    if isinstance(e, UnaryOp):
        return UnaryOp(e.op, subst_expr(e.child, exprs))
    if isinstance(e, Cast):
        return Cast(subst_expr(e.child, exprs), e.return_type)
    if isinstance(e, Case):
        return Case([(subst_expr(c, exprs), subst_expr(v, exprs))
                     for c, v in e.whens], subst_expr(e.else_, exprs))
    if isinstance(e, FuncCall):
        return FuncCall(e.name, [subst_expr(a, exprs) for a in e.args],
                        e.return_type)
    raise TypeError(f"unrewritable expression {type(e).__name__}")


# -- generic tree plumbing ------------------------------------------------


def _swap_child(ex, attr: str, idx: Optional[int], new_child):
    """Shallow-copied parent with one child replaced (the child's
    schema is unchanged in every caller, so parent metadata holds)."""
    new = copy.copy(ex)
    if idx is None:
        setattr(new, attr, new_child)
    else:
        lst = list(getattr(ex, attr))
        lst[idx] = new_child
        setattr(new, attr, lst)
    return new


def _has_watermark_source(ex) -> bool:
    """Does any executor below emit watermarks? (They originate at
    WatermarkFilterExecutor only.)"""
    from risingwave_tpu.stream.executors.watermark_filter import (
        WatermarkFilterExecutor,
    )
    if isinstance(ex, WatermarkFilterExecutor):
        return True
    return any(_has_watermark_source(c)
               for _a, _i, c in executor_children(ex))


def _wm_spec_list(specs) -> list:
    if specs is None:
        return []
    return specs if isinstance(specs, list) else [specs]


# -- rule: noop project elision -------------------------------------------


def _is_noop_project(p) -> bool:
    from risingwave_tpu.expr.expr import InputRef
    from risingwave_tpu.stream.executors.simple import ProjectExecutor
    if not isinstance(p, ProjectExecutor):
        return False
    inp = p.input
    if len(p.exprs) != len(inp.schema):
        return False
    for i, (e, f, g) in enumerate(zip(p.exprs, p.schema, inp.schema)):
        if not (isinstance(e, InputRef) and e.index == i
                and f.name == g.name and f.data_type == g.data_type):
            return False
    if p.pk_indices and list(p.pk_indices) != list(inp.pk_indices):
        return False
    # watermark contract: a projection DROPS underivable watermarks;
    # eliding one is only transparent when its derivations are the
    # full identity, or nothing below produces watermarks at all
    wd = p.watermark_derivations
    identity = all(
        any((spec if not isinstance(spec, tuple) else -1) == i
            for spec in _wm_spec_list(wd.get(i)))
        for i in range(len(inp.schema)))
    return identity or not _has_watermark_source(inp)


def elide_noop_projects(root) -> Tuple[object, int, str]:
    fired = 0

    def walk(ex):
        nonlocal fired
        new = ex
        for attr, idx, child in executor_children(ex):
            c2 = walk(child)
            while _is_noop_project(c2):
                fired += 1
                c2 = c2.input
            if c2 is not child:
                new = _swap_child(new, attr, idx, c2)
        return new

    return walk(root), fired, f"{fired} identity projection(s) elided"


# -- rule: project/filter fusion ------------------------------------------


def _compose_derivations(p1, p2) -> dict:
    """Watermark derivations of Project(p2 ∘ p1): input col → specs in
    p2's output, transforms composed."""
    out: dict = {}
    for in_col, specs1 in p1.watermark_derivations.items():
        for s1 in _wm_spec_list(specs1):
            mid, f1 = s1 if isinstance(s1, tuple) else (s1, None)
            for s2 in _wm_spec_list(
                    p2.watermark_derivations.get(mid)):
                tgt, f2 = s2 if isinstance(s2, tuple) else (s2, None)
                if f1 is None and f2 is None:
                    spec = tgt
                elif f1 is None:
                    spec = (tgt, f2)
                elif f2 is None:
                    spec = (tgt, f1)
                else:
                    spec = (tgt,
                            (lambda v, _a=f1, _b=f2: _b(_a(v))))
                out.setdefault(in_col, []).append(spec)
    return out


def _ref_counts(e, counts: Dict[int, int]) -> None:
    """InputRef occurrence counts WITH multiplicity (a single expr
    referencing one column twice counts twice)."""
    from risingwave_tpu.expr.expr import InputRef
    from risingwave_tpu.frontend.opt.checker import _expr_children
    if isinstance(e, InputRef):
        counts[e.index] = counts.get(e.index, 0) + 1
        return
    for c in _expr_children(e):
        _ref_counts(c, counts)


def _fusable(p1, p2) -> bool:
    """Gate: composing must not duplicate non-trivial computation —
    every p1 expr that is not a bare ref/literal may be referenced at
    most once across p2's expressions (occurrences, not exprs)."""
    from risingwave_tpu.expr.expr import InputRef, Literal
    counts: Dict[int, int] = {}
    for e in p2.exprs:
        _ref_counts(e, counts)
    return all(isinstance(p1.exprs[i], (InputRef, Literal))
               for i, n in counts.items() if n > 1)


def fuse_projects(root) -> Tuple[object, int, str]:
    from risingwave_tpu.stream.executors.simple import (
        FilterExecutor, ProjectExecutor,
    )
    from risingwave_tpu.expr.expr import InputRef
    fired = 0

    def try_fuse(c2):
        """One local fusion step at node c2 (or None)."""
        if isinstance(c2, ProjectExecutor) and \
                isinstance(c2.input, ProjectExecutor) and \
                _fusable(c2.input, c2):
            p1, p2 = c2.input, c2
            fused = ProjectExecutor(
                p1.input,
                [subst_expr(e, p1.exprs) for e in p2.exprs],
                [f.name for f in p2.schema],
                watermark_derivations=_compose_derivations(p1, p2))
            if p2.pk_indices:
                fused._info = ExecutorInfo(fused.schema,
                                           list(p2.pk_indices),
                                           fused.identity)
            return fused
        if isinstance(c2, FilterExecutor) and \
                isinstance(c2.input, ProjectExecutor):
            p = c2.input
            if all(isinstance(p.exprs[i], InputRef)
                   for i in expr_refs(c2.predicate)):
                # Filter(Project(X)) → Project(Filter(X)): the filter
                # runs before the projection materializes new columns
                inner = FilterExecutor(p.input,
                                       subst_expr(c2.predicate,
                                                  p.exprs))
                return _swap_child(p, "input", None, inner)
        return None

    def walk(ex):
        nonlocal fired
        new = ex
        for attr, idx, child in executor_children(ex):
            c2 = walk(child)
            while True:
                f = try_fuse(c2)
                if f is None:
                    break
                fired += 1
                c2 = f
            if c2 is not child:
                new = _swap_child(new, attr, idx, c2)
        return new

    return walk(root), fired, f"{fired} projection/filter fusion(s)"


# -- rule: filter pushdown below joins ------------------------------------


def _push_into_side(side_ex, pred):
    """Insert a filter below a join input, under its coalescer if one
    wraps the side (filtering before batching keeps batches dense)."""
    from risingwave_tpu.stream.coalesce import CoalesceExecutor
    from risingwave_tpu.stream.executors.simple import FilterExecutor
    if isinstance(side_ex, CoalesceExecutor):
        return _swap_child(side_ex, "input", None,
                           FilterExecutor(side_ex.input, pred))
    return FilterExecutor(side_ex, pred)


def push_filters(root) -> Tuple[object, int, str]:
    from risingwave_tpu.stream.executors.hash_join import (
        HashJoinExecutor, JoinType,
    )
    from risingwave_tpu.stream.executors.simple import FilterExecutor
    from risingwave_tpu.stream.executors.temporal_join import (
        TemporalJoinExecutor,
    )
    fired = 0

    def try_push(f):
        """Filter f moves one level down (returns the replacement)."""
        j = f.input
        if isinstance(j, HashJoinExecutor) and \
                any(s.fused_input is not None for s in j.sides):
            # the join's input executors sit in the absorbed run's RAW
            # space — a join-space conjunct cannot move below them
            return None
        if isinstance(j, HashJoinExecutor) and j.join_type in (
                JoinType.INNER, JoinType.LEFT_OUTER,
                JoinType.RIGHT_OUTER, JoinType.FULL_OUTER):
            refs = expr_refs(f.predicate)
            n_left = j.n_left
            # legality by join kind: a conjunct may move below a side
            # only if that side is NOT null-padded by this join
            if refs <= set(range(n_left)) and j.join_type in (
                    JoinType.INNER, JoinType.LEFT_OUTER):
                new_j = copy.copy(j)
                new_j.left_in = _push_into_side(j.left_in, f.predicate)
                return new_j
            if refs and min(refs) >= n_left and j.join_type in (
                    JoinType.INNER, JoinType.RIGHT_OUTER):
                pred = remap_expr(f.predicate,
                                  {i: i - n_left for i in refs})
                new_j = copy.copy(j)
                new_j.right_in = _push_into_side(j.right_in, pred)
                return new_j
            return None
        if isinstance(j, TemporalJoinExecutor):
            # left side is never null-padded (inner and LEFT forms
            # both pad the right side only)
            n_left = len(j.left_in.schema)
            if expr_refs(f.predicate) <= set(range(n_left)):
                new_j = copy.copy(j)
                new_j.left_in = _push_into_side(j.left_in, f.predicate)
                return new_j
        return None

    def walk(ex):
        nonlocal fired
        new = ex
        for attr, idx, child in executor_children(ex):
            c2 = walk(child)
            while isinstance(c2, FilterExecutor):
                pushed = try_push(c2)
                if pushed is None:
                    break
                fired += 1
                c2 = pushed
            if c2 is not child:
                new = _swap_child(new, attr, idx, c2)
        return new

    # sink to fixpoint WITHIN one application: each walk moves a
    # filter at most one join level (the pushed filter lands inside a
    # rebuilt subtree the same walk does not revisit), and deep join
    # chains must not depend on the engine's round budget
    total = 0
    while True:
        before = fired
        root = walk(root)
        total += fired - before
        if fired == before:
            break
    return root, total, f"{total} filter(s) pushed below joins"


# -- rule: column pruning -------------------------------------------------


class _PruneStats:
    def __init__(self):
        self.pruned = 0


def prune_columns(root) -> Tuple[object, int, str]:
    """Top-down live-lane analysis + bottom-up narrowing rebuild.

    `_prune(ex, live)` returns (new_ex, mapping, changed): `mapping`
    maps every surviving old column index to its new index, or None
    for identity (schema untouched). Executors the pass does not
    understand recurse with full liveness — narrowing still propagates
    through reference bottlenecks (projections, join inputs, agg
    feeds) below them, but their own schema never changes."""
    stats = _PruneStats()
    new_root, mapping, _changed = _prune(root, None, stats)
    assert mapping is None, "pruning must not change the root schema"
    return (new_root, stats.pruned,
            f"{stats.pruned} column lane(s) pruned")


def _identity_or(mapping, n: int) -> Dict[int, int]:
    return mapping if mapping is not None else {i: i for i in range(n)}


def _prune(ex, live: Optional[Set[int]], stats,
           narrow_leaf: bool = True) -> tuple:
    """live=None means every output column is required. `narrow_leaf`
    is False when the caller is itself a projection: a source below
    one needs no extra narrowing projection (the projection already
    bounds what flows up — inserting another would never converge)."""
    from risingwave_tpu.stream.coalesce import CoalesceExecutor
    from risingwave_tpu.stream.executors.hash_agg import HashAggExecutor
    from risingwave_tpu.stream.executors.hash_join import (
        HashJoinExecutor,
    )
    from risingwave_tpu.stream.executors.row_id_gen import (
        RowIdGenExecutor,
    )
    from risingwave_tpu.stream.executors.simple import (
        FilterExecutor, ProjectExecutor,
    )
    from risingwave_tpu.stream.executors.source import SourceExecutor
    from risingwave_tpu.stream.executors.watermark_filter import (
        WatermarkFilterExecutor,
    )

    n_out = len(ex.schema)
    live_full = (set(range(n_out)) if live is None
                 else set(live) | set(ex.pk_indices))

    if isinstance(ex, ProjectExecutor):
        return _prune_project(ex, live_full, stats)
    if isinstance(ex, FilterExecutor):
        req = live_full | expr_refs(ex.predicate)
        child, cmap, changed = _prune(ex.input, req, stats)
        if cmap is None:
            if not changed:
                return ex, None, False
            return _swap_child(ex, "input", None, child), None, True
        return (FilterExecutor(child,
                               remap_expr(ex.predicate, cmap)),
                cmap, True)
    if isinstance(ex, CoalesceExecutor):
        child, cmap, changed = _prune(ex.input, live_full, stats)
        if cmap is None:
            if not changed:
                return ex, None, False
            return _swap_child(ex, "input", None, child), None, True
        return (CoalesceExecutor(child, ex.target_rows,
                                 ex.max_chunks), cmap, True)
    if isinstance(ex, WatermarkFilterExecutor):
        from risingwave_tpu.common.types import Interval
        req = live_full | {ex.time_col}
        child, cmap, changed = _prune(ex.input, req, stats)
        if cmap is None:
            if not changed:
                return ex, None, False
            return _swap_child(ex, "input", None, child), None, True
        return (WatermarkFilterExecutor(
            child, cmap[ex.time_col], Interval(usecs=ex.delay),
            ex.state), cmap, True)
    if isinstance(ex, RowIdGenExecutor):
        rid = n_out - 1
        req = {i for i in live_full if i != rid}
        child, cmap, changed = _prune(ex.input, req, stats)
        if cmap is None:
            if not changed:
                return ex, None, False
            return _swap_child(ex, "input", None, child), None, True
        from risingwave_tpu.stream.executors.row_id_gen import (
            _SHARD_BITS,
        )
        new = RowIdGenExecutor(child,
                               vnode_base=ex._shard >> (63 - _SHARD_BITS))
        mapping = dict(cmap)
        mapping[rid] = len(child.schema)
        return new, mapping, True
    if isinstance(ex, HashJoinExecutor):
        return _prune_join(ex, live_full, stats)
    if isinstance(ex, HashAggExecutor):
        return _prune_agg(ex, stats)
    if isinstance(ex, SourceExecutor):
        if not narrow_leaf or len(live_full) >= n_out:
            return ex, None, False
        keep = sorted(live_full)
        from risingwave_tpu.expr.expr import InputRef
        proj = ProjectExecutor(
            ex, [InputRef(i, ex.schema[i].data_type) for i in keep],
            [ex.schema[i].name for i in keep],
            watermark_derivations={o: p for p, o in enumerate(keep)})
        stats.pruned += n_out - len(keep)
        return proj, {o: p for p, o in enumerate(keep)}, True
    # opaque executor: recurse with full liveness — children may still
    # narrow below their own reference bottlenecks, but this node's
    # schema (and therefore its parent's view) is untouched
    new = ex
    changed_any = False
    for attr, idx, child in executor_children(ex):
        c2, cmap, changed = _prune(child, None, stats)
        assert cmap is None
        if changed:
            new = _swap_child(new, attr, idx, c2)
            changed_any = True
    return new, None, changed_any


def _prune_project(p, live_full: Set[int], stats) -> tuple:
    from risingwave_tpu.stream.executors.simple import ProjectExecutor
    n_out = len(p.schema)
    keep = sorted(live_full)
    req: Set[int] = set()
    for i in keep:
        req |= expr_refs(p.exprs[i])
    kept_set = set(keep)
    wd_kept = {}
    for in_col, specs in p.watermark_derivations.items():
        kept_specs = [
            s for s in _wm_spec_list(specs)
            if (s[0] if isinstance(s, tuple) else s) in kept_set]
        if kept_specs:
            wd_kept[in_col] = kept_specs
            req.add(in_col)
    child, cmap, changed = _prune(p.input, req, stats,
                                  narrow_leaf=False)
    if len(keep) == n_out and cmap is None:
        if not changed:
            return p, None, False
        return _swap_child(p, "input", None, child), None, True
    cmap = _identity_or(cmap, len(p.input.schema))
    out_map = {o: i for i, o in enumerate(keep)}
    new_wd: dict = {}
    for in_col, specs in wd_kept.items():
        new_wd[cmap[in_col]] = [
            (out_map[s[0]], s[1]) if isinstance(s, tuple)
            else out_map[s] for s in specs]
    new = ProjectExecutor(
        child, [remap_expr(p.exprs[i], cmap) for i in keep],
        [p.schema[i].name for i in keep],
        watermark_derivations=new_wd)
    if p.pk_indices:
        new._info = ExecutorInfo(new.schema,
                                 [out_map[i] for i in p.pk_indices],
                                 new.identity)
    stats.pruned += n_out - len(keep)
    if len(keep) == n_out:         # only the input was remapped
        return new, None, True
    return new, out_map, True


def _prune_join(j, live_full: Set[int], stats) -> tuple:
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.stream.executors.hash_join import (
        HashJoinExecutor, JoinType,
    )
    if j.join_type not in (JoinType.INNER, JoinType.LEFT_OUTER,
                           JoinType.RIGHT_OUTER, JoinType.FULL_OUTER):
        # semi/anti outputs one side only; leave those plans alone
        return _prune_opaque_2(j, stats)
    if any(s.fused_input is not None for s in j.sides):
        # a fused input side's index space is the absorbed run's
        # OUTPUT schema — narrowing the raw input would unbind the
        # run (and fusion runs LAST, so this only happens on later
        # fixpoint rounds; the fused shape is final)
        return _prune_opaque_2(j, stats)
    left_side, right_side = j.sides
    n_left = j.n_left
    lreq = ({i for i in live_full if i < n_left}
            | set(left_side.key_indices)
            | set(left_side.table.pk_indices))
    rreq = ({i - n_left for i in live_full if i >= n_left}
            | set(right_side.key_indices)
            | set(right_side.table.pk_indices))
    lnew, lmap, lch = _prune(j.left_in, lreq, stats)
    rnew, rmap, rch = _prune(j.right_in, rreq, stats)
    if lmap is None and rmap is None:
        if not (lch or rch):
            return j, None, False
        new = copy.copy(j)
        new.left_in, new.right_in = lnew, rnew
        return new, None, True
    lmap = _identity_or(lmap, len(j.left_in.schema))
    rmap = _identity_or(rmap, len(j.right_in.schema))

    def table_for(t, m, schema):
        return StateTable(
            t.table_id, schema, [m[p] for p in t.pk_indices], t.store,
            dist_key_indices=([m[d] for d in t.dist_key_indices]
                              if t.dist_key_indices else None))

    lt = table_for(left_side.table, lmap, lnew.schema)
    rt = table_for(right_side.table, rmap, rnew.schema)
    inv_l = {v: k for k, v in lmap.items()}
    inv_r = {v: k for k, v in rmap.items()}
    old_fields = list(j.schema)
    names = ([old_fields[inv_l[p]].name
              for p in range(len(lnew.schema))]
             + [old_fields[n_left + inv_r[p]].name
                for p in range(len(rnew.schema))])
    opts = getattr(j, "rebuild_opts", {})
    new = HashJoinExecutor(
        lnew, rnew,
        [lmap[k] for k in left_side.key_indices],
        [rmap[k] for k in right_side.key_indices],
        lt, rt, output_names=names, join_type=j.join_type,
        actor_id=opts.get("actor_id", 0), mesh=opts.get("mesh"),
        shard_opts=opts.get("shard_opts"),
        state_cap=opts.get("state_cap"),
        device_payload=opts.get("device_payload", True),
        epoch_batch=opts.get("epoch_batch"))
    mapping = {old: new_i for old, new_i in lmap.items()}
    n_left_new = len(lnew.schema)
    for old, new_i in rmap.items():
        mapping[n_left + old] = n_left_new + new_i
    return new, mapping, True


def _prune_opaque_2(ex, stats) -> tuple:
    new = ex
    changed_any = False
    for attr, idx, child in executor_children(ex):
        c2, cmap, changed = _prune(child, None, stats)
        assert cmap is None
        if changed:
            new = _swap_child(new, attr, idx, c2)
            changed_any = True
    return new, None, changed_any


def _prune_agg(agg, stats) -> tuple:
    """Aggs keep every output (state layout is frozen at plan time);
    their input feed narrows to group keys + call inputs. SQL plans
    put a pre-agg projection there already, so the feed mapping stays
    identity and the narrowing continues below it — a non-identity
    mapping (hand-built chains) falls back to full liveness."""
    req = set(agg.group_indices) | {
        c.input_idx for c in agg.agg_calls if c.input_idx is not None}
    saved = stats.pruned
    child, cmap, changed = _prune(agg.input, req, stats)
    if cmap is not None:
        # bail path: the discarded pass's counts must not leak into
        # the rule's fired total (a phantom count would re-fire the
        # rule every round on an unchanged tree)
        stats.pruned = saved
        child, cmap, changed = _prune(agg.input, None, stats)
        assert cmap is None
    if not changed:
        return agg, None, False
    return _swap_child(agg, "input", None, child), None, True
