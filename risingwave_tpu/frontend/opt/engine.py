"""Fixpoint rewrite driver + rule registry + rewrite observability.

`rewrite_stream_plan` applies the enabled executor-graph rules round-
robin until none fires (bounded rounds). After EVERY rule application
the plan-property checker re-derives the invariants; a violation (or a
rule crash) falls back to the last good plan and disables the rule for
the rest of the run — in strict mode (tier-1 conftest) it raises
instead, so a broken rule fails the suite loudly.

Observability: every fired rule increments
`rewrite_rule_fired_total{rule=...}` (column pruning also bumps
`plan_columns_pruned`), and the per-job firing log lands in the
process-global history backing the `rw_plan_rewrites` system table.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from risingwave_tpu.frontend.opt import checker as _checker
from risingwave_tpu.frontend.opt import rules as _rules
from risingwave_tpu.frontend.opt.checker import CheckError

MAX_ROUNDS = 8

# applied in registry order each round: pushdown first (filters reach
# their sources before liveness is computed), fusion + elision shrink
# the chain, pruning runs over the settled shape; fragment fusion LAST
# (opt/fusion.py) — it freezes the settled chain into traces
EXECUTOR_RULES = {
    "filter_pushdown": _rules.push_filters,
    "project_fusion": _rules.fuse_projects,
    "noop_project_elision": _rules.elide_noop_projects,
    "column_pruning": _rules.prune_columns,
}
EXECUTOR_RULE_NAMES = tuple(EXECUTOR_RULES)
FRAGMENT_RULE_NAMES = ("exchange_elision",)
RULE_NAMES = EXECUTOR_RULE_NAMES + FRAGMENT_RULE_NAMES

# fragment fusion rides its own knob (SET stream_fusion = on|off), not
# the stream_rewrite_rules csv — it changes the EXECUTION substrate
# (traced megakernel vs interpretive chain), not just the plan shape
FUSION_RULE_NAME = "fusion_grouping"


def parse_rules(spec: Optional[str]):
    """'all' | 'none' | 'a,b,c' → frozenset of enabled rule names.
    Raises PlanError on an unknown rule (SET-time validation)."""
    from risingwave_tpu.frontend.planner import PlanError
    s = (spec or "all").strip().lower()
    if s in ("all", ""):
        return frozenset(RULE_NAMES)
    if s == "none":
        return frozenset()
    names = [p.strip() for p in s.split(",") if p.strip()]
    unknown = [n for n in names if n not in RULE_NAMES]
    if unknown:
        raise PlanError(
            f"unknown rewrite rule(s) {unknown}; known: "
            f"{', '.join(RULE_NAMES)}")
    return frozenset(names)


def parse_fusion(spec: Optional[str]) -> bool:
    """SET stream_fusion validator: 'on' | 'off' → bool."""
    from risingwave_tpu.frontend.planner import PlanError
    s = (spec or "on").strip().lower()
    if s in ("on", "true", "1"):
        return True
    if s in ("off", "false", "0"):
        return False
    raise PlanError(
        f"stream_fusion must be 'on' or 'off', got {spec!r}")


class RewriteReport:
    """What one rewrite run did: per-rule fire counts + fallbacks."""

    def __init__(self, label: str = ""):
        self.label = label
        self.fired: Dict[str, int] = {}
        self.details: List[Tuple[str, str]] = []   # (rule, detail)
        self.fallbacks: List[Tuple[str, str]] = []  # (rule, reason)

    @property
    def total_fired(self) -> int:
        return sum(self.fired.values())

    def summary(self) -> str:
        if not self.fired and not self.fallbacks:
            return "no rewrites fired"
        parts = [f"{n}={c}" for n, c in sorted(self.fired.items())]
        for rule, _reason in self.fallbacks:
            parts.append(f"{rule}=FALLBACK")
        return ", ".join(parts)


# process-global firing log (the metrics registries are process-global
# too); rw_plan_rewrites serves it over the ordinary batch surface
_HISTORY: List[tuple] = []          # (seq, job, rule, fired, detail)
_HISTORY_CAP = 4096
_SEQ = [0]


def _record_history(job: str, rule: str, fired: int,
                    detail: str) -> None:
    _SEQ[0] += 1
    _HISTORY.append((_SEQ[0], job, rule, fired, detail))
    del _HISTORY[:-_HISTORY_CAP]


def rewrite_history_rows() -> List[tuple]:
    return list(_HISTORY)


def rewrite_stream_plan(root, spec: Optional[str] = "all",
                        label: str = "",
                        record: bool = True,
                        extra_rules: Optional[dict] = None,
                        fusion: bool = False,
                        dist_parallelism: int = 1
                        ) -> Tuple[object, RewriteReport]:
    """Rewrite one planned executor tree to fixpoint. Returns the
    (possibly identical) new root and a report; never raises in
    fallback mode — a rule that misbehaves is dropped, the plan that
    deployed yesterday still deploys today. ``fusion`` enables the
    fragment-fusion rule (SET stream_fusion; opt/fusion.py) on top of
    whatever ``spec`` enables — including spec='none', so fusion can
    be measured in isolation. ``dist_parallelism`` is the distributed
    session's actor parallelism: above 1 the fusion rule refuses runs
    whose hash-cut keys do not map back to raw input columns (the
    fragmenter's fused cut ships raw rows — opt/fusion.py)."""
    from risingwave_tpu.utils.metrics import STREAMING
    report = RewriteReport(label)
    enabled = parse_rules(spec) & set(EXECUTOR_RULE_NAMES)
    registry = dict(EXECUTOR_RULES)
    if fusion:
        import functools

        from risingwave_tpu.frontend.opt.fusion import fuse_fragments
        registry[FUSION_RULE_NAME] = functools.partial(
            fuse_fragments, dist_parallelism=dist_parallelism)
        enabled = enabled | {FUSION_RULE_NAME}
    if extra_rules:
        registry.update(extra_rules)
        enabled = enabled | set(extra_rules)
    if not enabled:
        return root, report
    baseline = _checker.snapshot(root)
    disabled: set = set()
    for _round in range(MAX_ROUNDS):
        progressed = False
        for name in registry:
            if name not in enabled or name in disabled:
                continue
            try:
                new_root, fired, detail = registry[name](root)
                if not fired:
                    continue
                _checker.check(new_root, baseline)
            except Exception as e:          # noqa: BLE001 — fallback
                if _checker.strict_checker():
                    raise AssertionError(
                        f"rewrite rule {name!r} broke a plan "
                        f"invariant: {e}") from e
                report.fallbacks.append((name, repr(e)[:200]))
                if record:
                    _record_history(label, name, 0,
                                    f"FALLBACK: {repr(e)[:160]}")
                disabled.add(name)
                continue
            root = new_root
            progressed = True
            report.fired[name] = report.fired.get(name, 0) + fired
            report.details.append((name, detail))
            if record:
                # record=False (EXPLAIN) keeps deploy-time counters
                # honest: only rewrites of plans that ship count
                STREAMING.rewrite_rule_fired.inc(fired, rule=name)
                if name == "column_pruning":
                    STREAMING.plan_columns_pruned.inc(fired)
        if not progressed:
            break
    if record:
        for name, count in sorted(report.fired.items()):
            detail = "; ".join(d for n, d in report.details
                               if n == name)
            _record_history(label, name, count, detail)
    return root, report


def apply_rewrites(plan, spec: Optional[str],
                   label: str = "",
                   fusion: bool = False,
                   dist_parallelism: int = 1) -> RewriteReport:
    """Rewrite a StreamPlan/SinkPlan's consumer in place — the ONE
    deploy-path seam every session path (create MV/sink, reschedule,
    distributed create) goes through, so a future engine argument
    lands everywhere at once."""
    plan.consumer, report = rewrite_stream_plan(
        plan.consumer, spec, label=label, fusion=fusion,
        dist_parallelism=dist_parallelism)
    return report


def explain_with_rewrite(consumer, spec: Optional[str],
                         fusion: bool = False,
                         dist_parallelism: int = 1) -> List[tuple]:
    """EXPLAIN body shared by Frontend and DistFrontend: pre-rewrite
    tree, per-rule annotations (fusion groups included), post-rewrite
    tree, lane stats."""
    from risingwave_tpu.frontend.planner import explain_tree

    def stats_line(tag, root):
        s = plan_lane_stats(root)
        return (f"-- {tag} plan stats: executors={s['executors']} "
                f"total_lanes={s['total_lanes']} "
                f"max_width={s['max_lane_width']}",)

    pre = explain_tree(consumer)
    new_consumer, report = rewrite_stream_plan(
        consumer, spec, label="__explain__", record=False,
        fusion=fusion, dist_parallelism=dist_parallelism)
    rows = [("-- streaming plan (pre-rewrite):",)]
    rows += [(line,) for line in pre]
    rows.append(stats_line("pre-rewrite", consumer))
    rows.append((f"-- rewritten plan ({report.summary()}):",))
    for rule, detail in report.details:
        rows.append((f"--   rule {rule}: {detail}",))
    for rule, reason in report.fallbacks:
        rows.append((f"--   rule {rule}: FELL BACK ({reason})",))
    rows += [(line,) for line in explain_tree(new_consumer)]
    rows.append(stats_line("post-rewrite", new_consumer))
    # compiled-kernel cost footer (utils/jaxtools.KERNELS): programs
    # this process has already compiled, with the HLO cost model's
    # flops / bytes-accessed — what the deployed plan's device steps
    # SHOULD cost, next to the tree that dispatches them. Empty on a
    # fresh process (nothing compiled yet).
    from risingwave_tpu.utils.jaxtools import kernel_cost_rows
    costs = kernel_cost_rows()
    if costs:
        rows.append(("-- compiled kernel costs "
                     "(flops / bytes accessed):",))
        rows += [(f"--   {label}: {flops:.3g} flops, "
                  f"{nbytes:.3g} B", )
                 for label, flops, nbytes in costs]
    return rows


def plan_lane_stats(root) -> Dict[str, float]:
    """Carried-lane stats over an executor tree: how many column lanes
    the plan moves between executors (EXPLAIN + bench surface them so
    a rewrite's narrowing is visible next to events/sec)."""
    from risingwave_tpu.stream.executor import executor_children
    widths: List[int] = []

    def walk(ex):
        widths.append(len(ex.schema))
        for _a, _i, c in executor_children(ex):
            walk(c)

    walk(root)
    total = sum(widths)
    return {
        "executors": len(widths),
        "total_lanes": total,
        "max_lane_width": max(widths) if widths else 0,
        "avg_lane_width": round(total / len(widths), 2)
        if widths else 0.0,
    }
