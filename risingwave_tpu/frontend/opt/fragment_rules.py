"""Fragment-graph rewrite rules: exchange elision on the shipped IR.

A hash exchange between two fragments is pure overhead when the
producer's rows are ALREADY placed so that the consumer's keys
colocate: (a) both fragments are singletons (one actor each — any
exchange between them just re-frames chunks over the wire), or (b)
both run at the same parallelism and the producer's own hash
distribution, tracked column-by-column through its node chain, is a
subset of the consumer's keys — rows with equal consumer keys carry
equal producer keys and therefore already live on the same actor.

The rule fuses such a consumer fragment into its producer (splicing
the consumer's IR nodes onto the producer's tail) and drops the cut
edge; when the fused placement is keyed by a strict subset of the
consumer's keys, the materialize `dist_key` is stripped so the vnode-
sliced rescale path never assumes a placement that no longer holds.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Tuple

from risingwave_tpu.frontend.opt import checker as _checker
from risingwave_tpu.frontend.opt.checker import CheckError

_PASSTHROUGH_OPS = frozenset({
    "filter", "coalesce", "watermark_filter", "dedup", "eowc_gate",
    "top_n", "materialize", "row_id_gen",
})


def _stages_width(w: Optional[int], stages) -> Optional[int]:
    """Output arity through a serialized fused-stage list (ISSUE 10:
    fused nodes exist at parallelism > 1, so the derivation must walk
    their absorbed runs)."""
    if w is None:
        return None
    for st in stages:
        k = st["kind"]
        if k == "project":
            w = len(st["exprs"])
        elif k == "row_id_gen":
            w = w + 1
        # filter / watermark_filter keep the arity
    return w


def _stages_dist(d: Optional[List[set]], stages) -> Optional[List[set]]:
    """Track a hash distribution through a serialized fused-stage
    list: projects remap key-carrying columns (bare input refs only,
    the same rule as the `project` IR node); filters/watermark_filter
    pass through; row_id_gen appends a column (indices unchanged)."""
    if d is None:
        return None
    for st in stages:
        if st["kind"] != "project":
            continue
        ref_cols: Dict[int, set] = {}
        for j, e in enumerate(st["exprs"]):
            if e.get("t") == "input":
                ref_cols.setdefault(e["i"], set()).add(j)
        d = [set().union(*(ref_cols.get(c, set()) for c in s))
             if s else set() for s in d]
        if any(not s for s in d):
            return None
    return d


def _node_widths(frag) -> List[Optional[int]]:
    """Output arity per IR node (None where not derivable)."""
    widths: List[Optional[int]] = []
    for node in frag.nodes:
        op = node["op"]
        w: Optional[int] = None
        if op == "source":
            w = len(node["schema"])
        elif op == "exchange_in":
            w = len(frag.inputs[node["port"]].schema)
        elif op == "project":
            w = len(node["exprs"])
        elif op == "fused":
            w = _stages_width(widths[node["input"]], node["stages"])
        elif op in _PASSTHROUGH_OPS:
            inw = widths[node["input"]]
            w = inw if op != "row_id_gen" else (
                inw + 1 if inw is not None else None)
        elif op == "hash_agg":
            w = len(node["group"]) + len(node["calls"])
        elif op in ("hash_join", "temporal_join"):
            lw, rw = widths[node["left"]], widths[node["right"]]
            if node.get("left_fused"):
                lw = _stages_width(lw, node["left_fused"])
            if node.get("right_fused"):
                rw = _stages_width(rw, node["right_fused"])
            w = lw + rw if lw is not None and rw is not None else None
        elif op == "over_window":
            inw = widths[node["input"]]
            w = (inw + len(node["calls"])
                 if inw is not None else None)
        widths.append(w)
    return widths


def fragment_output_dist(frag) -> Optional[List[set]]:
    """Hash-distribution of a fragment's output rows, derived through
    its node chain: one set of output-column indices per original key
    position (every column in a set carries that key's value), or
    None when the placement is not derivable from the output."""
    if not frag.inputs or any(i.mode != "hash" or not i.keys
                              for i in frag.inputs):
        return None
    widths = _node_widths(frag)
    dists: List[Optional[List[set]]] = []
    for idx, node in enumerate(frag.nodes):
        op = node["op"]
        d: Optional[List[set]] = None
        if op == "exchange_in":
            d = [{k} for k in frag.inputs[node["port"]].keys]
        elif op == "project":
            ind = dists[node["input"]]
            if ind is not None:
                ref_cols: Dict[int, set] = {}
                for j, e in enumerate(node["exprs"]):
                    if e.get("t") == "input":
                        ref_cols.setdefault(e["i"], set()).add(j)
                d = [set().union(*(ref_cols.get(c, set())
                                   for c in s)) if s else set()
                     for s in ind]
                if any(not s for s in d):
                    d = None
        elif op == "fused":
            d = _stages_dist(dists[node["input"]], node["stages"])
        elif op in _PASSTHROUGH_OPS:
            d = dists[node["input"]]
        elif op == "hash_agg":
            ind = dists[node["input"]]
            # a fused agg's group indices live in the absorbed run's
            # OUTPUT space — map the input distribution through it
            if node.get("fused_stages"):
                ind = _stages_dist(ind, node["fused_stages"])
            group = list(node["group"])
            if ind is not None:
                d = [{group.index(c) for c in s if c in group}
                     for s in ind]
                if any(not s for s in d):
                    d = None
        elif op == "hash_join":
            # both inputs are hashed on the join keys; every output
            # row carries the key value in its left AND right column.
            # Fused sides: the exchange dispatched RAW rows on raw-
            # mapped key columns; key positions (and the left width)
            # live in each run's OUTPUT space — map through the run.
            lind = dists[node["left"]]
            rind = dists[node["right"]]
            n_left = widths[node["left"]]
            if node.get("left_fused"):
                lind = _stages_dist(lind, node["left_fused"])
                n_left = _stages_width(widths[node["left"]],
                                       node["left_fused"])
            if node.get("right_fused"):
                rind = _stages_dist(rind, node["right_fused"])
            lk = list(node["left_keys"])
            rk = list(node["right_keys"])
            if (n_left is not None
                    and lind == [{k} for k in lk]
                    and rind == [{k} for k in rk]):
                d = [{lc, n_left + rc} for lc, rc in zip(lk, rk)]
        elif op == "temporal_join":
            lind = dists[node["left"]]
            lk = list(node["left_keys"])
            if lind == [{k} for k in lk]:
                d = [{k} for k in lk]
        dists.append(d)
    return dists[-1] if dists else None


def _fuse(graph, u: int, f: int, edge, strip_dist: bool) -> None:
    """Splice fragment f's nodes onto fragment u's tail, dropping the
    cut edge; rewire every other fragment's upstream references."""
    from risingwave_tpu.frontend.fragmenter import Fragment
    from risingwave_tpu.stream.plan_ir import remap_node_refs
    P, F = graph.fragments[u], graph.fragments[f]
    tail = len(P.nodes) - 1
    new_nodes = [dict(n) for n in P.nodes]
    remap: Dict[int, int] = {}
    for i, node in enumerate(F.nodes):
        if i == edge.node_idx:
            remap[i] = tail
            continue
        n2 = remap_node_refs(node, remap)
        if strip_dist and n2["op"] == "materialize":
            n2.pop("dist_key", None)
        new_nodes.append(n2)
        remap[i] = len(new_nodes) - 1
    graph.fragments[u] = Fragment(
        nodes=new_nodes,
        parallelism=max(P.parallelism, F.parallelism),
        inputs=list(P.inputs))
    del graph.fragments[f]
    for frag in graph.fragments:
        for inp in frag.inputs:
            if inp.up_frag == f:
                inp.up_frag = u
            elif inp.up_frag > f:
                inp.up_frag -= 1


def elide_exchanges(graph) -> Tuple[object, int, List[str]]:
    """Apply exchange elision to fixpoint on a COPY of the graph."""
    g = copy.deepcopy(graph)
    fired = 0
    details: List[str] = []
    progress = True
    while progress:
        progress = False
        for fi, frag in enumerate(g.fragments):
            if len(frag.inputs) != 1:
                continue
            edge = frag.inputs[0]
            u = edge.up_frag
            up = g.fragments[u]
            if len(g.consumers_of(u)) != 1:
                continue
            if up.parallelism == 1 and frag.parallelism == 1:
                strip = False
                why = "singleton producer and consumer"
            elif (up.parallelism == frag.parallelism
                    and edge.mode == "hash" and edge.keys):
                dist = fragment_output_dist(up)
                ckeys = set(edge.keys)
                if dist is None or not all(s & ckeys for s in dist):
                    continue
                covered = set().union(*(s & ckeys for s in dist))
                # dist_key survives only when the producer hashed the
                # SAME key tuple in the same order (identical vnodes)
                exact = (len(dist) == len(edge.keys)
                         and all(edge.keys[p] in dist[p]
                                 for p in range(len(dist))))
                strip = not exact
                why = (f"producer distribution {sorted(covered)} "
                       f"satisfies consumer keys {sorted(ckeys)}")
            else:
                continue
            _fuse(g, u, fi, edge, strip)
            fired += 1
            details.append(f"fragment {fi} fused into {u} ({why})")
            progress = True
            break
    return g, fired, details


def rewrite_fragment_graph(graph, spec: Optional[str] = "all",
                           label: str = "", record: bool = True):
    """Fragment-graph rewrite entry point (DistFrontend deploys call
    it between the fragmenter and the scheduler). Same fallback /
    strict contract as the executor-graph engine."""
    from risingwave_tpu.frontend.opt.engine import (
        _record_history, parse_rules,
    )
    from risingwave_tpu.utils.metrics import STREAMING
    if "exchange_elision" not in parse_rules(spec):
        return graph, 0
    try:
        new_graph, fired, details = elide_exchanges(graph)
        if fired:
            _checker.check_fragment_graph(new_graph)
    except Exception as e:              # noqa: BLE001 — fallback
        if _checker.strict_checker():
            raise AssertionError(
                f"exchange_elision broke the fragment graph: {e}"
            ) from e
        if record:
            _record_history(label, "exchange_elision", 0,
                            f"FALLBACK: {repr(e)[:160]}")
        return graph, 0
    if not fired:
        return graph, 0
    if record:
        # record=False (plan previews) keeps deploy-time counters
        # honest — same contract as the executor-graph engine
        STREAMING.rewrite_rule_fired.inc(fired,
                                         rule="exchange_elision")
        STREAMING.plan_exchanges_elided.inc(fired)
        _record_history(label, "exchange_elision", fired,
                        "; ".join(details))
    return new_graph, fired


def fragment_plan_stats(graph) -> dict:
    """Exchange-hop and exchanged-lane-width stats for one fragment
    graph (bench + tests compare these with rewrites on vs off)."""
    hops = 0
    lanes = 0
    for frag in graph.fragments:
        for inp in frag.inputs:
            hops += 1
            lanes += len(inp.schema)
    return {
        "fragments": len(graph.fragments),
        "exchange_hops": hops,
        "exchanged_lanes": lanes,
        "avg_exchanged_lane_width": round(lanes / hops, 2)
        if hops else 0.0,
    }
