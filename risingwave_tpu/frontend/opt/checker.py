"""Plan-property checker: re-derive invariants after every rewrite.

The correctness-tooling half of the rewrite subsystem. A rule's output
is never trusted: after each rule application the engine re-checks

- structural integrity — every expression's InputRefs bind inside its
  input's arity with matching types, join keys/pks index their side
  schemas and agree with the state tables, agg group/call indices
  stay in range, pass-through executors keep their input schema;
- the root contract — the rewritten subtree feeds the SAME Materialize
  schema and stream key it fed before (the MV's shape is frozen at
  plan time; a rewrite may change how rows are produced, never what
  the table holds);
- append-only-ness — any HashAgg planned on the cheap append-only
  path must still provably sit over an append-only chain, and the
  root's derived append-only-ness must not weaken (downstream plan
  decisions were made against the original derivation).

On any violation the engine falls back to the pre-rule plan; in
strict mode (tests) the violation raises instead — a rule that breaks
an invariant fails the suite loudly rather than silently degrading.
"""

from __future__ import annotations

from typing import Iterable, List, Set

_STRICT = False


def set_strict_checker(on: bool) -> None:
    """Assert-don't-fallback mode (tier-1 conftest arms this)."""
    global _STRICT
    _STRICT = bool(on)


def strict_checker() -> bool:
    return _STRICT


class CheckError(ValueError):
    """A rewrite broke a plan invariant."""


def expr_refs(e) -> Set[int]:
    """Input column indices an expression reads."""
    from risingwave_tpu.expr.expr import (
        BinaryOp, Case, Cast, FuncCall, InputRef, Literal, UnaryOp,
    )
    if isinstance(e, InputRef):
        return {e.index}
    if isinstance(e, Literal):
        return set()
    if isinstance(e, BinaryOp):
        return expr_refs(e.left) | expr_refs(e.right)
    if isinstance(e, (UnaryOp, Cast)):
        return expr_refs(e.child)
    if isinstance(e, Case):
        out = expr_refs(e.else_)
        for c, v in e.whens:
            out |= expr_refs(c) | expr_refs(v)
        return out
    if isinstance(e, FuncCall):
        out: Set[int] = set()
        for a in e.args:
            out |= expr_refs(a)
        return out
    raise CheckError(f"unknown expression node {type(e).__name__}")


def _check_expr(e, schema, where: str) -> None:
    """Refs in range + ref types equal to the input field types."""
    from risingwave_tpu.expr.expr import InputRef
    n = len(schema)
    for i in sorted(expr_refs(e)):
        if not (0 <= i < n):
            raise CheckError(f"{where}: InputRef({i}) out of range "
                             f"(input arity {n})")

    def walk(x):
        if isinstance(x, InputRef):
            if schema[x.index].data_type != x.return_type:
                raise CheckError(
                    f"{where}: InputRef({x.index}) typed "
                    f"{x.return_type} but input column is "
                    f"{schema[x.index].data_type}")
            return
        for c in _expr_children(x):
            walk(c)

    walk(e)


def _expr_children(e) -> Iterable:
    from risingwave_tpu.expr.expr import (
        BinaryOp, Case, Cast, FuncCall, UnaryOp,
    )
    if isinstance(e, BinaryOp):
        return (e.left, e.right)
    if isinstance(e, (UnaryOp, Cast)):
        return (e.child,)
    if isinstance(e, Case):
        return tuple(x for w in e.whens for x in w) + (e.else_,)
    if isinstance(e, FuncCall):
        return tuple(e.args)
    return ()


def _same_schema(a, b) -> bool:
    return (len(a) == len(b)
            and all(fa.name == fb.name and fa.data_type == fb.data_type
                    for fa, fb in zip(a, b)))


def _same_types(a, b) -> bool:
    return (len(a) == len(b)
            and all(fa.data_type == fb.data_type
                    for fa, fb in zip(a, b)))


def snapshot(root) -> dict:
    """Baseline facts about the plan the rewrite must preserve."""
    from risingwave_tpu.frontend.planner import StreamPlanner
    return {
        "root_type": type(root),
        "schema": [(f.name, f.data_type) for f in root.schema],
        "pk": list(root.pk_indices),
        "append_only": StreamPlanner._derive_append_only(root),
    }


def check(root, baseline: dict) -> None:
    """Full invariant sweep; raises CheckError on the first violation."""
    if type(root) is not baseline["root_type"]:
        raise CheckError(
            f"rewrite replaced the plan root: {baseline['root_type']}"
            f" -> {type(root)}")
    got = [(f.name, f.data_type) for f in root.schema]
    if got != baseline["schema"]:
        raise CheckError(f"root schema changed: {baseline['schema']} "
                         f"-> {got}")
    if list(root.pk_indices) != baseline["pk"]:
        raise CheckError(f"root stream key changed: {baseline['pk']} "
                         f"-> {list(root.pk_indices)}")
    from risingwave_tpu.frontend.planner import StreamPlanner
    if baseline["append_only"] and \
            not StreamPlanner._derive_append_only(root):
        raise CheckError("rewrite weakened derived append-only-ness")
    _verify(root, seen=set())


def _verify(ex, seen: Set[int]) -> None:
    """Per-executor structural invariants, recursively."""
    from risingwave_tpu.stream.executor import executor_children
    if id(ex) in seen:
        raise CheckError(
            f"executor {ex.identity} appears twice in the plan tree "
            "(a rule shared a rebuilt subtree)")
    seen.add(id(ex))
    for _attr, _i, child in executor_children(ex):
        _verify(child, seen)
    _verify_node(ex)


def _verify_node(ex) -> None:
    from risingwave_tpu.stream.executors.hash_agg import (
        HashAggExecutor, agg_state_schema,
    )
    from risingwave_tpu.stream.executors.hash_join import (
        HashJoinExecutor,
    )
    from risingwave_tpu.stream.executors.materialize import (
        MaterializeExecutor,
    )
    from risingwave_tpu.stream.executors.row_id_gen import (
        RowIdGenExecutor,
    )
    from risingwave_tpu.stream.executors.simple import (
        FilterExecutor, ProjectExecutor,
    )

    name = type(ex).__name__
    for p in ex.pk_indices:
        if not (0 <= p < len(ex.schema)):
            raise CheckError(f"{name}: pk index {p} out of range")

    if isinstance(ex, ProjectExecutor):
        if len(ex.exprs) != len(ex.schema):
            raise CheckError("Project: expr/schema arity mismatch")
        for e, f in zip(ex.exprs, ex.schema):
            _check_expr(e, ex.input.schema, "Project")
            if e.return_type != f.data_type:
                raise CheckError(
                    f"Project: column {f.name} typed {f.data_type} "
                    f"but expr returns {e.return_type}")
        n_out = len(ex.schema)
        for in_col, specs in ex.watermark_derivations.items():
            if not (0 <= in_col < len(ex.input.schema)):
                raise CheckError(
                    f"Project: watermark derivation from input col "
                    f"{in_col} out of range")
            for spec in (specs if isinstance(specs, list) else [specs]):
                out = spec[0] if isinstance(spec, tuple) else spec
                if not (0 <= out < n_out):
                    raise CheckError(
                        f"Project: watermark derivation to output "
                        f"{out} out of range")
        return
    if isinstance(ex, FilterExecutor):
        from risingwave_tpu.common.types import DataType
        _check_expr(ex.predicate, ex.input.schema, "Filter")
        if ex.predicate.return_type != DataType.BOOLEAN:
            raise CheckError("Filter: predicate is not boolean")
        if not _same_schema(ex.schema, ex.input.schema):
            raise CheckError("Filter: schema differs from input")
        return
    if isinstance(ex, RowIdGenExecutor):
        if len(ex.schema) != len(ex.input.schema) + 1 or \
                not _same_schema(list(ex.schema)[:-1],
                                 list(ex.input.schema)):
            raise CheckError("RowIdGen: schema is not input + _row_id")
        return
    if isinstance(ex, HashJoinExecutor):
        left, right = ex.sides
        eff_arity = 0
        for idx, (side, inp, lbl) in enumerate(
                ((left, ex.left_in, "left"),
                 (right, ex.right_in, "right"))):
            # a fused input side (opt/fusion.py try_fuse_join): the
            # side's index space is the absorbed run's OUTPUT schema,
            # and the run itself must re-verify against the raw input
            # actually feeding it
            if side.fused_input is not None:
                _verify_fused_stages(side.fused_input, inp.schema,
                                     f"HashJoin[{lbl} fused]")
                from risingwave_tpu.frontend.opt.fusion import (
                    join_side_ineligible_reason,
                )
                r = join_side_ineligible_reason(ex, idx)
                if r is not None:
                    raise CheckError(
                        f"HashJoin[{lbl} fused]: ineligible ({r})")
                eff = side.fused_input.out_schema
            else:
                eff = inp.schema
            eff_arity += len(eff)
            if not _same_types(side.schema, eff):
                raise CheckError(
                    f"HashJoin: {lbl} side schema drifted from its "
                    "input")
            for k in side.key_indices:
                if not (0 <= k < len(eff)):
                    raise CheckError(
                        f"HashJoin: {lbl} key {k} out of range")
            if not _same_types(side.table.schema, eff):
                raise CheckError(
                    f"HashJoin: {lbl} state-table schema drifted")
            for p in side.table.pk_indices:
                if not (0 <= p < len(eff)):
                    raise CheckError(
                        f"HashJoin: {lbl} state pk {p} out of range")
        lt = [left.schema[i].data_type for i in left.key_indices]
        rt = [right.schema[i].data_type for i in right.key_indices]
        if lt != rt:
            raise CheckError("HashJoin: key types differ across sides")
        if ex.join_type.subject is None and \
                len(ex.schema) != eff_arity:
            raise CheckError("HashJoin: output arity != left + right")
        return
    if isinstance(ex, HashAggExecutor):
        # fused aggs (opt/fusion.py) absorb a filter/project run: the
        # agg's index space is the run's OUTPUT schema, and the run
        # itself must re-verify (traceable + planned against the raw
        # input actually feeding it)
        if ex.fused_stages is not None:
            _verify_fused_stages(ex.fused_stages, ex.input.schema,
                                 "HashAgg[fused]")
            from risingwave_tpu.frontend.opt.fusion import (
                agg_ineligible_reason,
            )
            r = agg_ineligible_reason(ex)
            if r is not None:
                raise CheckError(f"HashAgg[fused]: ineligible ({r})")
            in_schema = ex.fused_stages.out_schema
        else:
            in_schema = ex.input.schema
        n_in = len(in_schema)
        for g in ex.group_indices:
            if not (0 <= g < n_in):
                raise CheckError(f"HashAgg: group index {g} out of "
                                 "range")
        for c in ex.agg_calls:
            if c.input_idx is not None and not (0 <= c.input_idx < n_in):
                raise CheckError(
                    f"HashAgg: call input {c.input_idx} out of range")
        sch, pk = agg_state_schema(in_schema,
                                   list(ex.group_indices),
                                   list(ex.agg_calls))
        if not _same_types(sch, ex.table.schema) or \
                pk != list(ex.table.pk_indices):
            raise CheckError("HashAgg: state-table schema/pk no longer "
                             "matches the input")
        if ex.append_only:
            from risingwave_tpu.frontend.planner import StreamPlanner
            if not StreamPlanner._derive_append_only(ex.input):
                raise CheckError(
                    "HashAgg: planned append-only but the rewritten "
                    "input is not provably append-only")
        return
    from risingwave_tpu.stream.executors.fused import (
        FusedFragmentExecutor,
    )
    if isinstance(ex, FusedFragmentExecutor):
        _verify_fused_stages(ex.fused_stages, ex.input.schema,
                             "FusedFragment")
        if not _same_types(ex.schema, ex.fused_stages.out_schema):
            raise CheckError(
                "FusedFragment: executor schema drifted from the "
                "composed run's output schema")
        return
    if isinstance(ex, MaterializeExecutor):
        if not _same_types(ex.schema, ex.input.schema):
            raise CheckError("Materialize: input schema drifted from "
                             "the MV table schema")
        return
    # other executor types carry no rewrite-visible contract beyond
    # the recursive child checks (rules never rebuild them)


def _verify_fused_stages(fs, input_schema, where: str) -> None:
    """A fused run must still bind against the raw input actually
    feeding it AND stay traceable — the fallback contract of SET
    stream_fusion: any violation reverts to the interpretive chain."""
    if not _same_types(fs.in_schema, input_schema):
        raise CheckError(
            f"{where}: fused run planned against a different input "
            "schema than the one feeding it")
    # composed exprs bind against the BODY schema: synthetic runtime
    # columns (absorbed row ids, watermark thresholds) and an absorbed
    # hop's window columns are legal refs past the real input
    for p in fs.preds:
        _check_expr(p, fs.body_schema, f"{where} pred")
    for j, e in enumerate(fs.out_exprs or []):
        _check_expr(e, fs.body_schema, f"{where} expr")
    r = fs.fusable_reason()
    if r is not None:
        raise CheckError(f"{where}: run is not traceable ({r})")


def check_fragment_graph(graph) -> None:
    """Structural integrity of a (possibly rewritten) fragment graph:
    topological input edges, bijective exchange ports, node refs in
    range, exactly one materialize in the final fragment."""
    from risingwave_tpu.stream.plan_ir import NODE_REF_KEYS
    frags = graph.fragments
    if not frags:
        raise CheckError("empty fragment graph")
    for fi, frag in enumerate(frags):
        ports = []
        for idx, node in enumerate(frag.nodes):
            refs = [node.get(key) for key in NODE_REF_KEYS]
            if isinstance(node.get("inputs"), list):
                refs += list(node["inputs"])
            for v in refs:
                if isinstance(v, int) and not (0 <= v < idx):
                    raise CheckError(
                        f"fragment {fi} node {idx}: ref {v} does "
                        "not reference an earlier node")
            if node["op"] == "exchange_in":
                ports.append((node["port"], idx))
        if sorted(p for p, _ in ports) != list(range(len(frag.inputs))):
            raise CheckError(
                f"fragment {fi}: exchange ports {sorted(ports)} do "
                f"not match its {len(frag.inputs)} inputs")
        for p, idx in ports:
            if frag.inputs[p].node_idx != idx:
                raise CheckError(
                    f"fragment {fi}: input {p} points at node "
                    f"{frag.inputs[p].node_idx}, placeholder is {idx}")
        for inp in frag.inputs:
            if not (0 <= inp.up_frag < fi):
                raise CheckError(
                    f"fragment {fi}: upstream {inp.up_frag} is not an "
                    "earlier fragment")
    mats: List[int] = [fi for fi, f in enumerate(frags)
                       for n in f.nodes if n["op"] == "materialize"]
    if mats and mats[-1] != len(frags) - 1:
        raise CheckError("materialize is not in the final fragment")
