"""Plan-rewrite engine: local-rewrite optimizer over the stream plan.

The paper's dataflow graphs pay for every lane shipped through an
exchange and every column resident in an HBM hash table; this package
applies small, independently-verifiable rewrites ("Optimizing Stateful
Dataflow with Local Rewrites", arxiv 2306.10585) to fixpoint between
the StreamPlanner and deployment:

- executor-graph rules (engine.py / rules.py): filter pushdown below
  joins (the planner's former inline pushdown, now a rule),
  project/filter fusion, noop-project elision, and live-lane column
  pruning that narrows join inputs, agg feeds and source scans down to
  the referenced columns;
- fragment-graph rules (fragment_rules.py): exchange elision — fuse
  adjacent fragments when the producer's hash distribution already
  satisfies the consumer's keys;
- fragment fusion (fusion.py, SET stream_fusion): collapse maximal
  filter/project runs into ONE traced dataflow step — inlined into the
  agg kernel's jitted apply with donated state, or a standalone
  FusedFragmentExecutor for join/materialize feeds (TiLT shape,
  arxiv 2301.12030);
- a plan-property checker (checker.py) that recomputes schema,
  append-only-ness and structural invariants after EVERY rewrite and
  falls back to the unrewritten plan on any violation (strict mode
  turns the fallback into a loud assertion — armed by tier-1 conftest).
"""

from risingwave_tpu.frontend.opt.checker import (    # noqa: F401
    CheckError, set_strict_checker, strict_checker,
)
from risingwave_tpu.frontend.opt.engine import (     # noqa: F401
    EXECUTOR_RULE_NAMES, FRAGMENT_RULE_NAMES, FUSION_RULE_NAME,
    RULE_NAMES, RewriteReport, apply_rewrites, explain_with_rewrite,
    parse_fusion, parse_rules, plan_lane_stats, rewrite_history_rows,
    rewrite_stream_plan,
)
from risingwave_tpu.frontend.opt.fragment_rules import (  # noqa: F401
    fragment_plan_stats, rewrite_fragment_graph,
)
