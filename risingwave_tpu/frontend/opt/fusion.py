"""fusion_grouping: mark maximal fusable runs, emit fused executors.

The plan-rewrite engine's fusion rule (ISSUE 6 tentpole; TiLT shape,
arxiv 2301.12030). Walks the planned executor chain of each fragment
and collapses maximal source/coalesce → filter → project runs feeding a
keyed executor into ONE traced dataflow step:

- run ends at an ELIGIBLE HashAgg → the agg absorbs the stages as a
  kernel prelude (ops/fused.py build_agg_prelude): raw chunk upload →
  filter → project → key/lane encode → accumulator update, one jitted
  dispatch with donated state. A CoalesceExecutor directly under the
  agg is absorbed too — the kernel's raw backlog IS the batcher now
  (BATCH_ROWS), so the interpretive coalescer would only add a copy.
- any other run of ≥2 consecutive filter/project stages (join input
  sides, materialize feeds) → a standalone FusedFragmentExecutor: the
  same composed chain as one jit per chunk, host passthrough columns
  riding around the trace.

Eligibility is checked BEFORE mutating anything (traceable_reason per
expression, device group keys, no host state mirrors on the agg); an
ineligible run is simply left interpretive — and the engine's property
checker re-derives every plan invariant after the rule fires, falling
back to the unfused chain if fusion broke one (opt/checker.py grew
fused-shape checks for exactly this).

Runs last in the registry: pushdown/projection-fusion/pruning settle
the chain shape first, fusion freezes it into traces.
"""

from __future__ import annotations

import copy
from typing import List, Optional, Tuple

from risingwave_tpu.stream.executor import executor_children


def _as_stage(ex):
    """FilterExecutor/ProjectExecutor → FusedStage, else None."""
    from risingwave_tpu.ops.fused import FusedStage
    from risingwave_tpu.stream.executors.simple import (
        FilterExecutor, ProjectExecutor,
    )
    if isinstance(ex, FilterExecutor):
        return FusedStage("filter", "FilterExecutor",
                          exprs=(ex.predicate,))
    if isinstance(ex, ProjectExecutor):
        return FusedStage(
            "project", "ProjectExecutor",
            exprs=tuple(ex.exprs),
            names=tuple(f.name for f in ex.schema),
            watermark_derivations=dict(ex.watermark_derivations))
    return None


def _collect_run(top) -> Tuple[list, object]:
    """Maximal consecutive filter/project run starting at `top` going
    downstream→upstream. Returns (stages in DATAFLOW order, base)."""
    rev: List = []
    node = top
    while True:
        st = _as_stage(node)
        if st is None:
            break
        rev.append(st)
        node = node.input
    return list(reversed(rev)), node


def agg_ineligible_reason(agg) -> Optional[str]:
    """THE eligibility predicate — the one copy. The rule gates on it
    before fusing, HashAggExecutor's constructor/adopt guards call it,
    and the checker re-verifies it on ALREADY-fused aggs after every
    later rewrite round (so `fused_stages is not None` is deliberately
    NOT a condition here)."""
    if agg._kernel is not None:
        return "sharded/injected kernel"
    if agg.minput or agg.distinct_tables:
        return "retractable MIN/MAX or DISTINCT (host multisets)"
    if agg._hll_calls or agg._host_calls:
        return "host-side agg state (HLL/string_agg/array_agg)"
    if agg.tier_cap is not None:
        return "cold-tier governed (per-chunk host touch)"
    if agg.key_codec.interners:
        return "host-typed group keys (interning)"
    return None


def agg_fusable_reason(agg) -> Optional[str]:
    """None iff this HashAggExecutor can absorb a stage prelude NOW
    (rule-side gate: refuses re-fusing on later fixpoint rounds)."""
    if agg.fused_stages is not None:
        return "already fused"
    return agg_ineligible_reason(agg)


def fuse_fragments(root) -> Tuple[object, int, str]:
    """The rule entry point (engine registry signature). Non-
    destructive: copy-on-write along every mutated path so the engine's
    fallback plan stays intact."""
    from risingwave_tpu.ops.fused import FusedStages
    from risingwave_tpu.stream.coalesce import CoalesceExecutor
    from risingwave_tpu.stream.executors.fused import (
        FusedFragmentExecutor,
    )
    from risingwave_tpu.stream.executors.hash_agg import HashAggExecutor
    details: List[str] = []

    def try_fuse_agg(agg):
        """Eligible agg + run below (coalesce absorbed) → fused copy."""
        if agg_fusable_reason(agg) is not None:
            return None
        node = agg.input
        if isinstance(node, CoalesceExecutor):
            node = node.input
        stages, base = _collect_run(node)
        if not stages:
            return None
        fs = FusedStages(base.schema, stages)
        reason = fs.fusable_reason()
        if reason is not None:
            details.append(f"agg run NOT fused ({reason})")
            return None
        new_agg = copy.copy(agg)
        new_agg.adopt_fused_stages(fs, base)
        new_agg._info = copy.copy(agg._info)
        new_agg._info.identity = (
            f"{agg.identity}[fused:{fs.describe()}]")
        details.append(f"agg absorbed {fs.describe()}")
        return new_agg

    def try_fuse_standalone(top):
        """≥2-stage run not feeding an eligible agg → fused block."""
        stages, base = _collect_run(top)
        if len(stages) < 2:
            return None
        fs = FusedStages(base.schema, stages)
        reason = fs.fusable_reason()
        if reason is not None:
            details.append(f"run NOT fused ({reason})")
            return None
        details.append(f"block {fs.describe()}")
        return FusedFragmentExecutor(base, fs)

    def walk(ex):
        """Top-down: an eligible agg absorbs its run BEFORE the
        generic descent could carve a standalone block out of it; the
        walk then resumes below the absorbed base. Returns a (possibly
        new) executor; originals are never mutated."""
        from risingwave_tpu.frontend.opt.rules import _swap_child
        nonlocal fired
        if isinstance(ex, HashAggExecutor):
            fused = try_fuse_agg(ex)
            if fused is not None:
                fired += 1
                fused.input = walk(fused.input)   # fused is a copy
                return fused
        elif _as_stage(ex) is not None:
            fused = try_fuse_standalone(ex)
            if fused is not None:
                fired += 1
                fused.input = walk(fused.input)
                return fused
        out = ex
        for attr, idx, child in executor_children(ex):
            new_child = walk(child)
            if new_child is not child:
                out = _swap_child(out, attr, idx, new_child)
        return out

    fired = 0
    new_root = walk(root)
    return new_root, fired, "; ".join(details)
