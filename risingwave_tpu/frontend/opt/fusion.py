"""fusion_grouping: mark maximal fusable runs, emit fused executors.

The plan-rewrite engine's fusion rule (ISSUE 6 tentpole; TiLT shape,
arxiv 2301.12030). Walks the planned executor chain of each fragment
and collapses maximal source/coalesce → filter → project runs feeding a
keyed executor into ONE traced dataflow step:

- run ends at an ELIGIBLE HashAgg → the agg absorbs the stages as a
  kernel prelude (ops/fused.py build_agg_prelude): raw chunk upload →
  filter → project → key/lane encode → accumulator update, one jitted
  dispatch with donated state. A CoalesceExecutor directly under the
  agg is absorbed too — the kernel's raw backlog IS the batcher now
  (BATCH_ROWS), so the interpretive coalescer would only add a copy.
- any other run of ≥2 consecutive filter/project stages (join input
  sides, materialize feeds) → a standalone FusedFragmentExecutor: the
  same composed chain as one jit per chunk, host passthrough columns
  riding around the trace.

Eligibility is checked BEFORE mutating anything (traceable_reason per
expression, device group keys, no host state mirrors on the agg); an
ineligible run is simply left interpretive — and the engine's property
checker re-derives every plan invariant after the rule fires, falling
back to the unfused chain if fusion broke one (opt/checker.py grew
fused-shape checks for exactly this).

Runs last in the registry: pushdown/projection-fusion/pruning settle
the chain shape first, fusion freezes it into traces.
"""

from __future__ import annotations

import copy
from typing import List, Optional, Tuple

from risingwave_tpu.stream.executor import executor_children


# which executor kinds each absorption shape accepts: agg preludes
# take filter/project plus a head-of-run hop_window (ISSUE 12: the
# units× row expansion and window-lane synthesis happen INSIDE the
# jitted apply — the watermark transform is per-message host work the
# executor's derive_watermarks path already runs); join input runs add
# row_id_gen (the generated pk column rides the raw matrix as a
# synthetic device input) but NOT hop_window — the expansion changes
# cardinality, which the join's host-built per-row aux flags cannot
# follow; standalone blocks additionally absorb watermark_filter (the
# block's own message loop does the watermark emission/persistence
# the absorbed executor used to) and hop_window
AGG_KINDS = frozenset({"filter", "project", "hop_window"})
JOIN_KINDS = frozenset({"filter", "project", "row_id_gen"})
BLOCK_KINDS = JOIN_KINDS | {"watermark_filter", "hop_window"}


def _as_stage(ex, kinds=BLOCK_KINDS):
    """Fusable executor → FusedStage (kind-gated), else None."""
    from risingwave_tpu.ops.fused import FusedStage
    from risingwave_tpu.stream.executors.row_id_gen import (
        RowIdGenExecutor,
    )
    from risingwave_tpu.stream.executors.simple import (
        FilterExecutor, ProjectExecutor,
    )
    from risingwave_tpu.stream.executors.watermark_filter import (
        WatermarkFilterExecutor,
    )
    if isinstance(ex, FilterExecutor) and "filter" in kinds:
        return FusedStage("filter", "FilterExecutor",
                          exprs=(ex.predicate,))
    if isinstance(ex, ProjectExecutor) and "project" in kinds:
        return FusedStage(
            "project", "ProjectExecutor",
            exprs=tuple(ex.exprs),
            names=tuple(f.name for f in ex.schema),
            watermark_derivations=dict(ex.watermark_derivations))
    if isinstance(ex, RowIdGenExecutor) and "row_id_gen" in kinds:
        return FusedStage("row_id_gen", "RowIdGenExecutor",
                          runtime=ex)
    if isinstance(ex, WatermarkFilterExecutor) \
            and "watermark_filter" in kinds:
        return FusedStage("watermark_filter", "WatermarkFilterExecutor",
                          time_col=ex.time_col, delay_usecs=ex.delay,
                          runtime=ex)
    from risingwave_tpu.stream.executors.hop_window import (
        HopWindowExecutor,
    )
    if isinstance(ex, HopWindowExecutor) and "hop_window" in kinds:
        return FusedStage("hop_window", "HopWindowExecutor",
                          time_col=ex.time_col,
                          slide_usecs=ex.slide, size_usecs=ex.size)
    return None


def _collect_run(top, kinds=BLOCK_KINDS) -> Tuple[list, object]:
    """Maximal consecutive fusable run starting at `top` going
    downstream→upstream. Returns (stages in DATAFLOW order, base)."""
    rev: List = []
    node = top
    while True:
        st = _as_stage(node, kinds)
        if st is None:
            break
        rev.append(st)
        node = node.input
        if st.kind == "hop_window":
            # a hop must HEAD the run (everything downstream composes
            # in its output space) — stop extending upstream so the
            # collected run ends exactly at the expansion
            break
    return list(reversed(rev)), node


def agg_ineligible_reason(agg) -> Optional[str]:
    """THE eligibility predicate — the one copy. The rule gates on it
    before fusing, HashAggExecutor's constructor/adopt guards call it,
    and the checker re-verifies it on ALREADY-fused aggs after every
    later rewrite round (so `fused_stages is not None` is deliberately
    NOT a condition here).

    Injected SHARDED kernels are eligible since ISSUE 10: the sharded
    apply grew a prelude path (the absorbed run traces before vnode
    routing inside the same SPMD step) — only a kernel that already
    saw data, or an injected kernel with no prelude support at all,
    refuses."""
    k = agg._kernel
    if k is not None and not getattr(k, "supports_prelude", False):
        return "injected kernel without a prelude path"
    if agg.minput or agg.distinct_tables:
        return "retractable MIN/MAX or DISTINCT (host multisets)"
    if agg._hll_calls or agg._host_calls:
        return "host-side agg state (HLL/string_agg/array_agg)"
    if agg.tier_cap is not None:
        return "cold-tier governed (per-chunk host touch)"
    if agg.key_codec.interners:
        return "host-typed group keys (interning)"
    return None


def agg_fusable_reason(agg) -> Optional[str]:
    """None iff this HashAggExecutor can absorb a stage prelude NOW
    (rule-side gate: refuses re-fusing on later fixpoint rounds)."""
    if agg.fused_stages is not None:
        return "already fused"
    return agg_ineligible_reason(agg)


def join_side_ineligible_reason(join, side_idx: int) -> Optional[str]:
    """THE join-side eligibility predicate (rule, adopt guard, and
    checker all call it — the checker re-verifies ALREADY-fused sides,
    so `fused_input is not None` is deliberately not a condition).
    The fused path needs the EPOCH dispatches (the prelude inlines
    there — since ISSUE 10 the sharded kernels have them too, so the
    old single-chip-only gate is gone), host-typed keys would need
    interning inside the trace, and the cold tier reads buffered key
    lanes the raw matrix no longer carries."""
    side = join.sides[side_idx]
    if not join._epoch_batch:
        return "per-chunk dispatch path (epoch batching off)"
    if join.rebuild_opts.get("state_cap") is not None:
        return ("cold-tier governed join (reload reads the buffered "
                "key lanes)")
    for i in side.key_indices:
        if not side.schema[i].data_type.is_device:
            return (f"host-typed join key column "
                    f"{side.schema[i].data_type.value} (interned)")
    return None


def join_side_fusable_reason(join, side_idx: int) -> Optional[str]:
    """None iff this join side can absorb its input run NOW."""
    if join.sides[side_idx].fused_input is not None:
        return "already fused"
    return join_side_ineligible_reason(join, side_idx)


def fuse_fragments(root, dist_parallelism: int = 1
                   ) -> Tuple[object, int, str]:
    """The rule entry point (engine registry signature; the engine
    registers a partial binding ``dist_parallelism``). Non-
    destructive: copy-on-write along every mutated path so the engine's
    fallback plan stays intact.

    At distributed parallelism > 1 the fragmenter's hash-exchange cut
    lands BELOW an absorbed run (raw rows ship, the prelude runs on
    the consumer actors), so the cut's hash keys must map back through
    the run to raw input columns (FusedStages.input_positions) — a key
    computed by a non-trivial projection cannot be dispatched on and
    the run stays interpretive. Value equality makes the raw-column
    hash partition the post-stage keys consistently."""
    from risingwave_tpu.ops.fused import FusedStages
    from risingwave_tpu.stream.coalesce import CoalesceExecutor
    from risingwave_tpu.stream.executors.fused import (
        FusedFragmentExecutor,
    )
    from risingwave_tpu.stream.executors.hash_agg import HashAggExecutor
    from risingwave_tpu.stream.executors.hash_join import (
        HashJoinExecutor,
    )
    details: List[str] = []

    def try_fuse_agg(agg):
        """Eligible agg + run below (coalesce absorbed) → fused copy."""
        if agg_fusable_reason(agg) is not None:
            return None
        node = agg.input
        if isinstance(node, CoalesceExecutor):
            node = node.input
        stages, base = _collect_run(node, AGG_KINDS)
        if not stages:
            return None
        fs = FusedStages(base.schema, stages)
        reason = fs.fusable_reason()
        if reason is not None:
            details.append(f"agg run NOT fused ({reason})")
            return None
        if fs.hop is not None and agg._kernel is not None:
            # injected (sharded) kernels size their vnode routing for
            # the UPLOADED row count — a hop prelude multiplies rows
            # in-trace past those shapes. Single-chip lazy kernels
            # (the _kernel-is-None case) expand freely.
            details.append(
                "agg run NOT fused (hop expansion needs the "
                "single-chip lazy kernel — sharded routing shapes "
                "are sized pre-expansion)")
            return None
        if dist_parallelism > 1 and \
                getattr(agg, "two_phase_role", None) != "local" and \
                fs.input_positions(agg.group_indices) is None:
            details.append(
                "agg run NOT fused (group keys do not map to raw "
                "input columns — parallelism>1 cut dispatches raw "
                "rows)")
            return None
        new_agg = copy.copy(agg)
        new_agg.adopt_fused_stages(fs, base)
        new_agg._info = copy.copy(agg._info)
        new_agg._info.identity = (
            f"{agg.identity}[fused:{fs.describe()}]")
        details.append(f"agg absorbed {fs.describe()}")
        return new_agg

    def try_fuse_standalone(top):
        """≥2-stage run not feeding an eligible agg → fused block."""
        stages, base = _collect_run(top, BLOCK_KINDS)
        if len(stages) < 2:
            return None
        fs = FusedStages(base.schema, stages)
        reason = fs.fusable_reason()
        if reason is not None:
            details.append(f"run NOT fused ({reason})")
            return None
        details.append(f"block {fs.describe()}")
        return FusedFragmentExecutor(base, fs)

    def try_fuse_join(join):
        """Eligible join sides absorb their input runs (coalesce
        absorbed — the epoch buffer IS the batcher) into the side's
        epoch apply+probe dispatches. Returns a fused COPY (join +
        adopted sides) or None; each side fuses independently."""
        import copy as _copy
        new_join = None
        for s, attr in ((0, "left_in"), (1, "right_in")):
            r = join_side_fusable_reason(join, s)
            if r is not None:
                continue
            node = getattr(new_join if new_join is not None else join,
                           attr)
            if isinstance(node, CoalesceExecutor):
                node = node.input
            stages, base = _collect_run(node, JOIN_KINDS)
            if not stages:
                continue
            fs = FusedStages(base.schema, stages)
            reason = fs.fusable_reason()
            if reason is not None:
                details.append(
                    f"join side {s} run NOT fused ({reason})")
                continue
            if dist_parallelism > 1 and fs.input_positions(
                    join.sides[s].key_indices) is None:
                details.append(
                    f"join side {s} run NOT fused (join keys do not "
                    "map to raw input columns — parallelism>1 cut "
                    "dispatches raw rows)")
                continue
            if new_join is None:
                new_join = _copy.copy(join)
                new_join.sides = tuple(_copy.copy(sd)
                                       for sd in join.sides)
                new_join._info = _copy.copy(join._info)
            new_join.adopt_fused_input(s, fs, base)
            details.append(f"join side {s} absorbed {fs.describe()}")
        if new_join is not None:
            descs = "; ".join(
                ("L:" if i == 0 else "R:") + sd.fused_input.describe()
                for i, sd in enumerate(new_join.sides)
                if sd.fused_input is not None)
            new_join._info.identity = \
                f"{join.identity}[fused:{descs}→join]"
        return new_join

    def walk(ex):
        """Top-down: an eligible agg/join absorbs its run BEFORE the
        generic descent could carve a standalone block out of it; the
        walk then resumes below the absorbed base. Returns a (possibly
        new) executor; originals are never mutated."""
        from risingwave_tpu.frontend.opt.rules import _swap_child
        nonlocal fired
        if isinstance(ex, HashAggExecutor):
            fused = try_fuse_agg(ex)
            if fused is not None:
                fired += 1
                fused.input = walk(fused.input)   # fused is a copy
                return fused
        elif isinstance(ex, HashJoinExecutor):
            fused = try_fuse_join(ex)
            if fused is not None:
                fired += 1
                ex = fused            # descend below the fused copy
        elif _as_stage(ex) is not None:
            fused = try_fuse_standalone(ex)
            if fused is not None:
                fired += 1
                fused.input = walk(fused.input)
                return fused
        out = ex
        for attr, idx, child in executor_children(ex):
            new_child = walk(child)
            if new_child is not child:
                out = _swap_child(out, attr, idx, new_child)
        return out

    fired = 0
    new_root = walk(root)
    return new_root, fired, "; ".join(details)
