"""Fragmenter: executor plan → distributable fragment graph of plan IR.

Reference parity: src/frontend/src/stream_fragmenter/mod.rs:115,199 —
the reference splits the stream plan at exchanges into a
StreamFragmentGraph whose fragments meta schedules onto compute nodes
(meta/src/stream/stream_graph/schedule.rs:195-251). TPU re-design: the
planner's EXECUTOR tree is already the physical plan, so the fragmenter
walks it and serializes each segment to plan IR (stream/plan_ir.py),
cutting where the reference inserts a hash exchange — before every
HashAgg (dist keys = group keys) and on both inputs of every HashJoin
(dist keys = join keys). Everything else stays colocated with its
input (NoShuffle), including the terminal Materialize, so each parallel
actor materializes its vnode slice into its worker's namespace.

The cut carries `keys` in the UPSTREAM fragment's output schema; the
scheduler (cluster/scheduler.py) turns each cut edge into a
HashDispatcher on the upstream actors and remote_input+merge nodes on
the downstream actors.

Cuts are not final: the plan-rewrite engine's exchange-elision pass
(frontend/opt/fragment_rules.py) runs over this graph before
scheduling and fuses adjacent fragments whose distribution already
satisfies the consumer's keys — the fragmenter cuts wherever the
reference would, the rewrite removes the cuts that prove redundant.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from risingwave_tpu.stream.executors.hash_agg import HashAggExecutor
from risingwave_tpu.stream.executors.hash_join import HashJoinExecutor
from risingwave_tpu.stream.executors.materialize import (
    MaterializeExecutor,
)
from risingwave_tpu.stream.executors.row_id_gen import RowIdGenExecutor
from risingwave_tpu.stream.executors.simple import (
    FilterExecutor, ProjectExecutor,
)
from risingwave_tpu.stream.executors.source import SourceExecutor
from risingwave_tpu.stream.plan_ir import expr_to_ir, schema_to_ir


class FragmentError(ValueError):
    """Plan shape the distributed lowering cannot express (yet)."""


@dataclass
class FragInput:
    """One cut edge: this fragment consumes `up_frag`'s output hashed
    on `keys` (indices into the upstream OUTPUT schema), or fully
    REPLICATED when mode="broadcast" (temporal-join arrangements need
    every row on every actor — dispatch.rs:507)."""

    up_frag: int
    keys: List[int]
    schema: List[dict]              # IR schema of the exchanged rows
    node_idx: int                   # index of the exchange_in placeholder
    mode: str = "hash"              # "hash" | "broadcast"
    # downstream fan-in re-coalescing target + linger bound
    # (stream/coalesce.py); rows=0 disables — the scheduler copies
    # both onto the merge node
    coalesce_rows: int = 0
    coalesce_chunks: int = 0


@dataclass
class Fragment:
    """A deployable pipeline segment. `nodes` is plan IR where
    {"op": "exchange_in", "port": k} placeholders stand for the k-th
    entry of `inputs`; the scheduler expands each into per-upstream-
    actor remote_input nodes plus a merge."""

    nodes: List[dict] = field(default_factory=list)
    parallelism: int = 1
    inputs: List[FragInput] = field(default_factory=list)


@dataclass
class FragmentGraph:
    """Fragments in topological order (every FragInput.up_frag precedes
    its consumer). The LAST fragment holds the Materialize."""

    fragments: List[Fragment] = field(default_factory=list)

    def consumers_of(self, frag_idx: int) -> List[tuple]:
        """[(down_frag_idx, FragInput)] — at most one in a tree plan."""
        out = []
        for di, f in enumerate(self.fragments):
            for inp in f.inputs:
                if inp.up_frag == frag_idx:
                    out.append((di, inp))
        return out


def _stages_ir(fs) -> List[dict]:
    """FusedStages → serializable stage list ({"op":"fused"} payload
    and the hash_agg node's "fused_stages"); plan_ir rebuilds the
    composed normal form from it."""
    out = []
    for st in fs.stages:
        if st.kind == "filter":
            out.append({"kind": "filter",
                        "pred": expr_to_ir(st.exprs[0])})
        elif st.kind == "project":
            out.append({"kind": "project",
                        "exprs": [expr_to_ir(e) for e in st.exprs],
                        "names": list(st.names)})
        elif st.kind == "row_id_gen":
            # runtime = the absorbed RowIdGenExecutor (host) — the
            # worker rebuilds a bare RowIdCounter with the same shard
            out.append({"kind": "row_id_gen",
                        "vnode_base": st.runtime.vnode_base})
        elif st.kind == "watermark_filter":
            out.append({"kind": "watermark_filter",
                        "time_col": st.time_col,
                        "delay_usecs": st.delay_usecs,
                        "table_id": (st.runtime.state.table_id
                                     if st.runtime.state is not None
                                     else None)})
        elif st.kind == "hop_window":
            out.append({"kind": "hop_window",
                        "time_col": st.time_col,
                        "slide_usecs": st.slide_usecs,
                        "size_usecs": st.size_usecs})
        else:
            raise FragmentError(f"unknown fused stage kind {st.kind!r}")
    return out


def _agg_call_ir(c) -> dict:
    d = {"kind": c.kind.value}
    if c.input_idx is not None:
        d["input_idx"] = c.input_idx
    if c.distinct:
        d["distinct"] = True
    if c.delimiter != ",":
        d["delimiter"] = c.delimiter
    return d


class Fragmenter:
    """One-shot walker over a planned executor tree."""

    def __init__(self, parallelism: int,
                 merge_coalesce_rows: Optional[int] = None,
                 merge_coalesce_chunks: Optional[int] = None):
        from risingwave_tpu.stream.coalesce import (
            DEFAULT_MAX_CHUNKS, DEFAULT_TARGET_ROWS,
        )
        self.parallelism = max(1, parallelism)
        # fan-in re-coalescing knobs stamped on every cut edge (the
        # session's stream_chunk_target_rows /
        # stream_coalesce_linger_chunks; rows=0 disables end to end)
        self.merge_coalesce_rows = DEFAULT_TARGET_ROWS \
            if merge_coalesce_rows is None else int(merge_coalesce_rows)
        self.merge_coalesce_chunks = DEFAULT_MAX_CHUNKS \
            if merge_coalesce_chunks is None \
            else int(merge_coalesce_chunks)
        self.graph = FragmentGraph()

    def lower(self, consumer) -> FragmentGraph:
        self._lower(consumer)
        return self.graph

    # -- helpers ----------------------------------------------------------
    def _new_fragment(self, parallelism: int) -> int:
        self.graph.fragments.append(Fragment(parallelism=parallelism))
        return len(self.graph.fragments) - 1

    def _append(self, fi: int, node: dict) -> int:
        self.graph.fragments[fi].nodes.append(node)
        return len(self.graph.fragments[fi].nodes) - 1

    def _cut(self, up_fi: int, keys: List[int], schema,
             parallelism: int, mode: str = "hash") -> tuple:
        """Close `up_fi` at its current tail and start a new fragment
        consuming it through an exchange. Returns (new_frag_idx,
        node_idx of the exchange_in placeholder)."""
        fi = self._new_fragment(parallelism)
        frag = self.graph.fragments[fi]
        port = len(frag.inputs)
        ni = self._append(fi, {"op": "exchange_in", "port": port})
        frag.inputs.append(FragInput(up_fi, list(keys),
                                     schema_to_ir(schema), ni, mode,
                                     self.merge_coalesce_rows,
                                     self.merge_coalesce_chunks))
        return fi, ni

    def _cut_into(self, fi: int, up_fi: int, keys: List[int],
                  schema, mode: str = "hash") -> int:
        """Add another exchange port to an existing fragment (the
        second input of a join)."""
        frag = self.graph.fragments[fi]
        port = len(frag.inputs)
        ni = self._append(fi, {"op": "exchange_in", "port": port})
        frag.inputs.append(FragInput(up_fi, list(keys),
                                     schema_to_ir(schema), ni, mode,
                                     self.merge_coalesce_rows,
                                     self.merge_coalesce_chunks))
        return ni

    # -- the walk ---------------------------------------------------------
    def _lower(self, ex) -> tuple:
        """Returns (frag_idx, node_idx) of ex's IR node."""
        if isinstance(ex, SourceExecutor):
            opts = getattr(ex, "ir_connector", None)
            if opts is None:
                raise FragmentError(
                    "source executor carries no connector options "
                    "(ir_connector) — planned outside the frontend?")
            if ex.split_state is None:
                raise FragmentError("distributed source needs durable "
                                    "split state")
            fi = self._new_fragment(1)
            ni = self._append(fi, {
                "op": "source", "name": ex.identity,
                "connector": dict(opts),
                "schema": schema_to_ir(ex.schema),
                "actor_id": 0,              # scheduler assigns
                "split_table_id": ex.split_state.table_id,
                "rate_limit": ex.rate_limit,
                "min_chunks": ex.min_chunks,
                # freshness accounting key (stream/freshness.py): the
                # worker-side rebuild keeps the CATALOG source name so
                # the coordinator merge joins MV ↔ source frontiers
                "freshness_key": ex.freshness_key,
            })
            return fi, ni
        if isinstance(ex, ProjectExecutor):
            # note: watermark_derivations may hold host lambdas (tumble
            # floor transforms) — fine in process, not shippable; the
            # distributed plan drops derivations (EOWC rejects upstream)
            fi, ci = self._lower(ex.input)
            ni = self._append(fi, {
                "op": "project", "input": ci,
                "exprs": [expr_to_ir(e) for e in ex.exprs],
                "names": [f.name for f in ex.schema]})
            return fi, ni
        if isinstance(ex, FilterExecutor):
            fi, ci = self._lower(ex.input)
            ni = self._append(fi, {"op": "filter", "input": ci,
                                   "pred": expr_to_ir(ex.predicate)})
            return fi, ni
        from risingwave_tpu.stream.coalesce import CoalesceExecutor
        if isinstance(ex, CoalesceExecutor):
            # keyed-input coalescing ships with the plan: on the
            # upstream side of a cut it densifies the exchange send
            # path; the downstream merge re-coalesces post-dispatch
            # slivers (scheduler merge nodes carry their own knob)
            fi, ci = self._lower(ex.input)
            ni = self._append(fi, {
                "op": "coalesce", "input": ci,
                "target_rows": ex.target_rows,
                "max_chunks": ex.max_chunks})
            return fi, ni
        if isinstance(ex, RowIdGenExecutor):
            fi, ci = self._lower(ex.input)
            ni = self._append(fi, {"op": "row_id_gen", "input": ci})
            return fi, ni
        from risingwave_tpu.stream.executors.fused import (
            FusedFragmentExecutor,
        )
        if isinstance(ex, FusedFragmentExecutor):
            # fused filter/project block: ship the ORIGINAL stage list
            # (plan_ir re-composes the normal form on the worker, so
            # the traced program there is byte-equivalent). Watermark
            # derivations drop like plain distributed projects do.
            fi, ci = self._lower(ex.input)
            ni = self._append(fi, {
                "op": "fused", "input": ci,
                "stages": _stages_ir(ex.fused_stages)})
            return fi, ni
        from risingwave_tpu.stream.executors.watermark_filter import (
            WatermarkFilterExecutor,
        )
        if isinstance(ex, WatermarkFilterExecutor):
            fi, ci = self._lower(ex.input)
            ni = self._append(fi, {
                "op": "watermark_filter", "input": ci,
                "time_col": ex.time_col, "delay_usecs": ex.delay,
                "table_id": (ex.state.table_id
                             if ex.state is not None else None)})
            return fi, ni
        from risingwave_tpu.stream.executors.hop_window import (
            HopWindowExecutor,
        )
        if isinstance(ex, HopWindowExecutor):
            fi, ci = self._lower(ex.input)
            ni = self._append(fi, {
                "op": "hop_window", "input": ci,
                "time_col": ex.time_col,
                "slide_usecs": ex.slide, "size_usecs": ex.size})
            return fi, ni
        if isinstance(ex, HashAggExecutor):
            up_fi, ci = self._lower(ex.input)
            node = {
                "op": "hash_agg", "input": None,
                "group": list(ex.group_indices),
                "calls": [_agg_call_ir(c) for c in ex.agg_calls],
                "table_id": ex.table.table_id,
                "append_only": ex.append_only,
                "output_names": [f.name for f in ex.schema],
                "dedup_table_ids": {
                    col: t.table_id
                    for col, t in ex.distinct_tables.items()},
                # sketch tables ride in the same map: the executor's
                # __init__ POPPED approx_count_distinct entries out of
                # minput into hll_tables, but the worker-side rebuild
                # (plan_ir agg_aux_tables) transports them through
                # minput_table_ids — omitting them made every
                # distributed CREATE MV with approx_count_distinct
                # fail at build ("ship minput_table_ids[j]")
                "minput_table_ids": {
                    **{j: t.table_id for j, t in ex.minput.items()},
                    **{j: t.table_id
                       for j, t in ex.hll_tables.items()}},
                # cold-tier resident-group cap (state/tier.py): worker
                # fragments rebuild with the same memory governance the
                # coordinator planned
                "tier_cap": ex.tier_cap,
            }
            if ex.fused_stages is not None:
                # the agg's index space is the run's OUTPUT schema —
                # worker rebuild re-composes the prelude from this
                node["fused_stages"] = _stages_ir(ex.fused_stages)
            if self.parallelism > 1 and \
                    getattr(ex, "two_phase_role", None) != "local":
                if ex.fused_stages is not None:
                    # fused cut (ISSUE 10): the exchange ships RAW
                    # rows, hashed on the group keys mapped back
                    # through the absorbed run — value-equal columns,
                    # so the partition is consistent; the fusion rule
                    # refused any run whose keys don't map, so a None
                    # here is a planner bug, not a user error
                    keys = ex.fused_stages.input_positions(
                        ex.group_indices)
                    if keys is None:
                        raise FragmentError(
                            "fused agg group keys do not map to raw "
                            "input columns — the fusion rule should "
                            "have refused this run")
                else:
                    keys = list(ex.group_indices)
                fi, xi = self._cut(up_fi, keys, ex.input.schema,
                                   self.parallelism)
                node["input"] = xi
            else:
                # parallelism 1, or the LOCAL phase of a two-phase
                # split: colocate with the input chain (NoShuffle) —
                # the local phase exists precisely to pre-reduce
                # before the exchange
                fi, node["input"] = up_fi, ci
            ni = self._append(fi, node)
            return fi, ni
        if isinstance(ex, HashJoinExecutor):
            left, right = ex.sides
            l_fi, _ = self._lower(ex.left_in)
            r_fi, _ = self._lower(ex.right_in)
            # a fused side's key positions live in the absorbed run's
            # OUTPUT space; the exchange ships RAW rows. At
            # parallelism 1 the single consumer makes routing trivial
            # (no hash keys); above 1 the keys map back through the
            # run to raw columns (ISSUE 10 — the fusion rule refused
            # any run whose keys don't map, so None is a planner bug)
            def _side_cut(side):
                if side.fused_input is None:
                    return list(side.key_indices)
                if self.parallelism <= 1:
                    return []
                keys = side.fused_input.input_positions(
                    side.key_indices)
                if keys is None:
                    raise FragmentError(
                        "fused join keys do not map to raw input "
                        "columns — the fusion rule should have "
                        "refused this run")
                return keys

            l_cut = _side_cut(left)
            r_cut = _side_cut(right)
            fi, lxi = self._cut(l_fi, l_cut, ex.left_in.schema,
                                self.parallelism)
            rxi = self._cut_into(fi, r_fi, r_cut, ex.right_in.schema)
            node = {
                "op": "hash_join", "left": lxi, "right": rxi,
                "left_keys": list(left.key_indices),
                "right_keys": list(right.key_indices),
                "left_table_id": left.table.table_id,
                "right_table_id": right.table.table_id,
                "left_pk": list(left.table.pk_indices),
                "right_pk": list(right.table.pk_indices),
                "join_type": ex.join_type.value,
                # cold-tier resident-key cap (state/tier.py): the
                # shipped pks are already key-prefixed when set, and
                # worker rebuilds run the same epoch-batched path
                "state_cap": left.state_cap,
                "output_names": [f.name for f in ex.schema]}
            if left.fused_input is not None:
                node["left_fused"] = _stages_ir(left.fused_input)
            if right.fused_input is not None:
                node["right_fused"] = _stages_ir(right.fused_input)
            ni = self._append(fi, node)
            return fi, ni
        from risingwave_tpu.stream.executors.temporal_join import (
            TemporalJoinExecutor,
        )
        if isinstance(ex, TemporalJoinExecutor):
            l_fi, _ = self._lower(ex.left_in)
            r_fi, _ = self._lower(ex.right_in)
            # left: hash on the probe keys; right: BROADCAST — every
            # actor maintains the full arrangement (lookup.rs delta-
            # join spirit; the dim side is small by design)
            fi, lxi = self._cut(l_fi, list(ex.left_keys),
                                ex.left_in.schema, self.parallelism)
            rxi = self._cut_into(fi, r_fi, [], ex.right_in.schema,
                                 mode="broadcast")
            ni = self._append(fi, {
                "op": "temporal_join", "left": lxi, "right": rxi,
                "left_keys": list(ex.left_keys),
                "right_keys": list(ex.right_keys),
                "outer": ex.outer,
                "output_names": [f.name for f in ex.schema]})
            return fi, ni
        from risingwave_tpu.stream.executors.top_n import (
            GroupTopNExecutor,
        )
        if isinstance(ex, GroupTopNExecutor):
            up_fi, ci = self._lower(ex.input)
            node = {
                "op": "top_n", "input": None,
                "order_by": [[i, d] for i, d in ex.order_by],
                "offset": ex.offset, "limit": ex.limit,
                "table_id": ex.state.table_id,
                "group": list(ex.group_indices),
                "append_only": ex.append_only,
                "pk": list(ex.pk_indices)}
            if len(self.graph.fragments[up_fi].nodes) > 1 or \
                    self.parallelism > 1:
                # TopN is a SINGLETON: a global window cannot split
                # across actors; grouped top-n would need group ⊆ dist
                # keys — a singleton fragment is always correct
                # (DispatcherType::SIMPLE, stream_graph/schedule.rs
                # singleton placement)
                keys = list(ex.group_indices)
                fi, xi = self._cut(up_fi, keys, ex.input.schema, 1)
                node["input"] = xi
            else:
                fi, node["input"] = up_fi, ci
            ni = self._append(fi, node)
            return fi, ni
        from risingwave_tpu.stream.executors.over_window import (
            OverWindowExecutor,
        )
        if isinstance(ex, OverWindowExecutor):
            up_fi, ci = self._lower(ex.input)
            node = {
                "op": "over_window", "input": None,
                "partition": list(ex.partition_indices),
                "order_by": [[i, d] for i, d in ex.order_by],
                "calls": [{"kind": c.kind.value,
                           "input_idx": c.input_idx,
                           "offset": c.offset} for c in ex.calls],
                "table_id": ex.state.table_id,
                "input_pk": list(ex.input_pk),
                "output_names": [f.name for f in ex.schema]}
            if self.parallelism > 1 and ex.partition_indices:
                # hash exchange on the partition keys — each actor
                # owns whole partitions
                fi, xi = self._cut(up_fi, list(ex.partition_indices),
                                   ex.input.schema, self.parallelism)
                node["input"] = xi
            elif self.parallelism > 1:
                fi, xi = self._cut(up_fi, [], ex.input.schema, 1)
                node["input"] = xi        # unpartitioned → singleton
            else:
                fi, node["input"] = up_fi, ci
            ni = self._append(fi, node)
            return fi, ni
        from risingwave_tpu.stream.executors.project_set import (
            ProjectSetExecutor,
        )
        if isinstance(ex, ProjectSetExecutor):
            fi, ci = self._lower(ex.input)
            items = []
            for kind, payload in ex.items:
                if kind == "scalar":
                    items.append(["scalar", expr_to_ir(payload)])
                else:
                    items.append([kind,
                                  [expr_to_ir(e) for e in payload]])
            ni = self._append(fi, {
                "op": "project_set", "input": ci, "items": items,
                "names": list(ex.names), "pass_pk": list(ex.pass_pk)})
            return fi, ni
        from risingwave_tpu.stream.executors.eowc import (
            EowcGateExecutor,
        )
        if isinstance(ex, EowcGateExecutor):
            fi, ci = self._lower(ex.input)
            ni = self._append(fi, {
                "op": "eowc_gate", "input": ci, "wm_col": ex.wm_col,
                "table_id": ex.state.table_id,
                "pk": list(ex.state.pk_indices)})
            return fi, ni
        from risingwave_tpu.stream.executors.dedup import (
            AppendOnlyDedupExecutor,
        )
        if isinstance(ex, AppendOnlyDedupExecutor):
            fi, ci = self._lower(ex.input)
            ni = self._append(fi, {
                "op": "dedup", "input": ci,
                "keys": list(ex.dedup_indices),
                "table_id": ex.state.table_id})
            return fi, ni
        from risingwave_tpu.stream.executors.sink import (
            CoordinatedSinkExecutor,
        )
        if isinstance(ex, CoordinatedSinkExecutor):
            # terminal sink writer: colocated with its input (NoShuffle,
            # like Materialize) — each parallel actor is one of N
            # writers staging its slice per epoch; the scheduler stamps
            # writer=rank and n_writers=parallelism per actor, and the
            # coordinator (meta side) commits from the listing
            fi, ci = self._lower(ex.input)
            ni = self._append(fi, {
                "op": "sink", "input": ci,
                "sink_name": ex.sink_name,
                "mode": ex.encoder.mode,
                "path": ex.encoder.target.store.root,
                "pk": list(getattr(ex.encoder, "pk_indices", []))})
            return fi, ni
        if isinstance(ex, MaterializeExecutor):
            fi, ci = self._lower(ex.input)
            node = {
                "op": "materialize", "input": ci,
                "table_id": ex.table.table_id,
                "pk": list(ex.table.pk_indices),
                "mv_name": ex.mv_name}
            # vnode-partition the MV by its GROUP-KEY pk columns when
            # this is an exchange-fed agg fragment: the planner orders
            # the MV pk by group index, and agg output group j carries
            # the SAME value as dispatched key j — so hashing the pk
            # columns in pk order reproduces the dispatcher's vnode
            # exactly (exchange keys index the UPSTREAM schema and
            # must NOT be used as MV positions). Rescale then slices
            # every fragment table by one consistent mapping.
            frag = self.graph.fragments[fi]
            if (frag.inputs
                    and all(i.mode == "hash" for i in frag.inputs)
                    and sum(n["op"] == "hash_agg"
                            for n in frag.nodes) == 1
                    and node["pk"]
                    and len(frag.inputs[0].keys) == len(node["pk"])):
                node["dist_key"] = list(node["pk"])
            ni = self._append(fi, node)
            return fi, ni
        raise FragmentError(
            f"{type(ex).__name__} has no distributed lowering yet "
            "(deploy this MV on the in-process session)")
