"""Session variables: one SET/SHOW implementation for both sessions.

Reference parity: src/common/src/session_config/ — typed knobs with
defaults, SET <name> = <value> | TO DEFAULT, SHOW <name>, SHOW ALL.
Typed (integer) knobs bind to attributes on the owning session so
future CREATE statements read them; free-form vars stay strings.
"""

from __future__ import annotations

from typing import Dict, Optional


class SessionVars:
    """Owner-attached variable surface shared by Frontend and
    DistFrontend (their SET semantics must not drift)."""

    def __init__(self, owner, attr_map: Dict[str, str],
                 string_defaults: Optional[Dict[str, str]] = None,
                 validators: Optional[Dict[str, object]] = None):
        self.owner = owner
        self.attr_map = dict(attr_map)           # name → owner attr
        self.defaults = {n: getattr(owner, a)
                         for n, a in self.attr_map.items()}
        self.strings = dict(string_defaults or {})
        self._string_vals: Dict[str, str] = {}
        # name → callable(value) raising PlanError on a bad value —
        # SET-time validation for free-form string vars (e.g.
        # stream_rewrite_rules rejects unknown rule names)
        self.validators = dict(validators or {})

    def names(self):
        return sorted(set(self.attr_map) | set(self.strings))

    def known(self, name: str) -> bool:
        return name in self.attr_map or name in self.strings

    @staticmethod
    def _display(v) -> str:
        return "" if v is None else str(v)

    def get(self, name: str) -> str:
        if name in self.attr_map:
            return self._display(getattr(self.owner,
                                         self.attr_map[name]))
        return self._display(self._string_vals.get(
            name, self.strings[name]))

    def show_all(self):
        return [(n, self.get(n)) for n in self.names()]

    def set(self, name: str, value) -> None:
        """value=None means TO DEFAULT."""
        from risingwave_tpu.frontend.planner import PlanError
        if name in self.attr_map:
            if value is None:
                value = self.defaults[name]
            elif not isinstance(value, int) or isinstance(value, bool):
                raise PlanError(f"{name} must be an integer")
            setattr(self.owner, self.attr_map[name], value)
        elif name in self.strings:
            if value is None:
                self._string_vals.pop(name, None)
            else:
                check = self.validators.get(name)
                if check is not None:
                    check(str(value))
                self._string_vals[name] = str(value)
        else:
            raise PlanError(
                f"unrecognized configuration parameter {name!r}")
