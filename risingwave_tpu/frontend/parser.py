"""Hand-written recursive-descent SQL parser.

Reference parity: src/sqlparser/src/parser.rs:157 — same architecture
(tokenizer + recursive descent with precedence climbing), original
implementation scoped to the supported statement surface. Streaming
extensions: TUMBLE(...) table function, INTERVAL literals.
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

from risingwave_tpu.frontend import ast

_TOKEN_RE = re.compile(r"""
      (?P<ws>\s+)
    | (?P<comment>--[^\n]*)
    | (?P<number>\d+(?:\.\d+)?)
    | (?P<string>'(?:[^']|'')*')
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    | (?P<op><>|<=|>=|!=|\|\||[+\-*/%(),.;=<>])
""", re.VERBOSE)

KEYWORDS = {
    "select", "from", "where", "group", "by", "order", "limit", "offset",
    "as", "and", "or", "not", "join", "inner", "on", "create", "drop",
    "show", "materialized", "view", "views", "source", "sources", "table",
    "tables", "with", "interval", "tumble", "hop", "asc", "desc",
    "null", "true",
    "false", "if", "exists", "flush", "second", "seconds", "minute",
    "minutes", "hour", "hours", "day", "days", "millisecond",
    "milliseconds", "case", "when", "then", "else", "end", "cast",
    "sink", "sinks", "left", "right", "full", "outer", "distinct",
    "explain", "over", "partition", "alter", "set", "parallelism",
    "for", "emit", "window", "close", "insert", "into", "values",
    "delete", "update", "primary", "key", "having", "between",
}

# keywords that can never start a primary expression (a column named
# "second" still works: non-reserved keywords fall through to idents)
RESERVED = {
    "select", "from", "where", "group", "by", "order", "limit", "offset",
    "as", "and", "or", "not", "join", "inner", "on", "create", "drop",
    "when", "then", "else", "end", "with", "having",
}

_INTERVAL_UNITS = {
    "second": 1_000_000, "seconds": 1_000_000,
    "minute": 60_000_000, "minutes": 60_000_000,
    "hour": 3_600_000_000, "hours": 3_600_000_000,
    "day": 86_400_000_000, "days": 86_400_000_000,
    "millisecond": 1_000, "milliseconds": 1_000,
}


class ParseError(ValueError):
    pass


class Tokenizer:
    def __init__(self, sql: str):
        self.tokens: List[Tuple[str, str]] = []
        pos = 0
        while pos < len(sql):
            m = _TOKEN_RE.match(sql, pos)
            if not m:
                raise ParseError(f"bad character at {sql[pos:pos+10]!r}")
            pos = m.end()
            kind = m.lastgroup
            if kind in ("ws", "comment"):
                continue
            text = m.group()
            if kind == "ident" and text.lower() in KEYWORDS:
                self.tokens.append(("kw", text.lower()))
            else:
                self.tokens.append((kind, text))


class Parser:
    """One statement per parse() call; `;` tolerated."""

    def __init__(self, sql: str):
        self.toks = Tokenizer(sql).tokens
        self.i = 0

    # -- token helpers ---------------------------------------------------
    def _peek(self, k: int = 0) -> Tuple[str, str]:
        j = self.i + k
        return self.toks[j] if j < len(self.toks) else ("eof", "")

    def _next(self) -> Tuple[str, str]:
        t = self._peek()
        self.i += 1
        return t

    def _kw(self, *words: str) -> bool:
        """Consume keywords if they match (lookahead, all-or-nothing)."""
        for k, w in enumerate(words):
            kind, text = self._peek(k)
            if kind != "kw" or text != w:
                return False
        self.i += len(words)
        return True

    def _expect_kw(self, *words: str) -> None:
        if not self._kw(*words):
            raise ParseError(
                f"expected {' '.join(words).upper()} at {self._peek()}")

    def _expect_op(self, op: str) -> None:
        kind, text = self._next()
        if kind != "op" or text != op:
            raise ParseError(f"expected {op!r}, got {text!r}")

    def _op(self, op: str) -> bool:
        kind, text = self._peek()
        if kind == "op" and text == op:
            self.i += 1
            return True
        return False

    def _ident(self) -> str:
        kind, text = self._next()
        if kind == "ident":
            return text.lower()
        if kind == "kw":          # non-reserved use of a keyword
            return text
        raise ParseError(f"expected identifier, got {text!r}")

    def _string(self) -> str:
        kind, text = self._next()
        if kind != "string":
            raise ParseError(f"expected string literal, got {text!r}")
        return text[1:-1].replace("''", "'")

    # -- entry -----------------------------------------------------------
    def parse(self):
        stmt = self._statement()
        self._op(";")
        if self._peek()[0] != "eof":
            raise ParseError(f"trailing tokens at {self._peek()}")
        return stmt

    def _statement(self):
        if self._kw("alter", "materialized", "view"):
            name = self._ident()
            self._expect_kw("set")
            self._expect_kw("parallelism")
            self._expect_op("=")
            kind, text = self._next()
            if kind != "number" or int(text) < 1:
                raise ParseError(
                    f"PARALLELISM must be a positive integer, "
                    f"got {text!r}")
            return ast.AlterParallelism(name, int(text))
        if self._kw("create", "source"):
            return self._create_source()
        if self._kw("create", "table"):
            return self._create_table()
        if self._kw("drop", "table"):
            if_exists = self._kw("if", "exists")
            return ast.DropTable(self._ident(), if_exists)
        if self._kw("insert"):
            self._expect_kw("into")
            name = self._ident()
            if self._peek() == ("kw", "select"):
                return ast.Insert(name, [], select=self._select())
            self._expect_kw("values")
            rows = []
            while True:
                self._expect_op("(")
                row = [self._expr()]
                while self._op(","):
                    row.append(self._expr())
                self._expect_op(")")
                rows.append(row)
                if not self._op(","):
                    break
            return ast.Insert(name, rows)
        if self._kw("delete"):
            self._expect_kw("from")
            name = self._ident()
            where = self._expr() if self._kw("where") else None
            return ast.Delete(name, where)
        if self._kw("update"):
            name = self._ident()
            self._expect_kw("set")
            sets = []
            while True:
                col = self._ident()
                self._expect_op("=")
                sets.append((col, self._expr()))
                if not self._op(","):
                    break
            where = self._expr() if self._kw("where") else None
            return ast.Update(name, sets, where)
        if self._kw("create", "materialized", "view"):
            name = self._ident()
            self._expect_kw("as")
            sel = self._select()
            eowc = False
            if self._kw("emit"):
                self._expect_kw("on")
                self._expect_kw("window")
                self._expect_kw("close")
                eowc = True
            return ast.CreateMaterializedView(
                name, sel, emit_on_window_close=eowc)
        if self._kw("create", "sink"):
            name = self._ident()
            from_mv = None
            append_only = None
            if self._kw("from"):
                # CREATE SINK s FROM mv [AS APPEND-ONLY] WITH (...) —
                # sugar for SELECT * FROM mv; the MV name is kept so
                # the planner can derive the mode from the MV's own
                # append-only proof
                from_mv = self._ident()
                sel = ast.Select(
                    projections=[(ast.ColRef("*"), None)],
                    from_item=ast.TableRef(from_mv))
                if self._kw("as"):
                    # "append"/"only" are plain idents; the hyphen in
                    # APPEND-ONLY is an op token (APPEND ONLY also
                    # accepted)
                    kind, text = self._next()
                    if kind != "ident" or text.lower() != "append":
                        raise ParseError(
                            f"expected APPEND-ONLY, got {text!r}")
                    self._op("-")
                    kind, text = self._next()
                    if kind != "ident" or text.lower() != "only":
                        raise ParseError(
                            f"expected APPEND-ONLY, got {text!r}")
                    append_only = True
            else:
                self._expect_kw("as")
                sel = self._select()
            self._expect_kw("with")
            self._expect_op("(")
            options = {}
            while True:
                key = self._ident()
                while self._op("."):
                    key += "." + self._ident()
                self._expect_op("=")
                kind, _text = self._peek()
                options[key] = (self._string() if kind == "string"
                                else self._next()[1])
                if not self._op(","):
                    break
            self._expect_op(")")
            return ast.CreateSink(name, sel, options,
                                  from_mv=from_mv,
                                  append_only=append_only)
        if self._kw("drop", "sink"):
            if_exists = self._kw("if", "exists")
            return ast.DropSink(self._ident(), if_exists)
        if self._kw("drop", "materialized", "view"):
            if_exists = self._kw("if", "exists")
            return ast.DropMaterializedView(self._ident(), if_exists)
        if self._kw("drop", "source"):
            if_exists = self._kw("if", "exists")
            return ast.DropSource(self._ident(), if_exists)
        if self._kw("show", "tables"):
            return ast.Show("tables")
        if self._kw("show", "materialized", "views"):
            return ast.Show("materialized views")
        if self._kw("show", "sources"):
            return ast.Show("sources")
        if self._kw("show", "sinks"):
            return ast.Show("sinks")
        if self._kw("show"):
            # SHOW <session variable> ("all" is an ident — SHOW ALL
            # arrives as var:all and lists every variable)
            return ast.Show("var:" + self._ident())
        if self._kw("set"):
            name = self._ident()
            if not self._op("="):
                kind, text = self._next()
                if not (kind in ("kw", "ident")
                        and text.lower() == "to"):
                    raise ParseError(
                        f"expected = or TO after SET, got {text!r}")
            kind, text = self._next()
            if kind == "number":
                value = int(text) if "." not in text else float(text)
            elif kind == "string":
                # string tokens are quote-delimited with '' escapes
                # (same rule as _string())
                value = text[1:-1].replace("''", "'")
            elif kind in ("kw", "ident"):
                low = text.lower()
                value = {"true": True, "false": False,
                         "on": True, "off": False,
                         "default": None}.get(low, text)
            else:
                raise ParseError(f"bad SET value {text!r}")
            return ast.SetVar(name.lower(), value)
        if self._kw("flush"):
            return ast.Flush()
        if self._kw("explain"):
            return ast.Explain(self._select())
        if self._peek() == ("kw", "select"):
            return self._select()
        raise ParseError(f"unsupported statement at {self._peek()}")

    def _create_table(self) -> ast.CreateTable:
        name = self._ident()
        self._expect_op("(")
        columns, pk_cols = [], []
        while True:
            col = self._ident()
            words = [self._next()[1].lower()]
            while self._peek()[0] in ("ident", "kw") and \
                    self._peek()[1].lower() in (
                        "with", "time", "zone", "precision",
                        "varying"):
                words.append(self._next()[1].lower())
            columns.append((col, " ".join(words)))
            if self._kw("primary"):
                self._expect_kw("key")
                pk_cols.append(col)
            if not self._op(","):
                break
        self._expect_op(")")
        return ast.CreateTable(name, columns, pk_cols)

    def _create_source(self) -> ast.CreateSource:
        name = self._ident()
        columns = None
        if self._op("("):
            # explicit schema: (col type, ...) — external connectors
            # cannot infer one (the generators carry fixed schemas)
            columns = []
            while True:
                col = self._ident()
                words = [self._next()[1].lower()]
                while self._peek()[0] in ("ident", "kw") and \
                        self._peek()[1].lower() in (
                            "with", "time", "zone", "precision",
                            "varying"):
                    words.append(self._next()[1].lower())
                columns.append((col, " ".join(words)))
                if not self._op(","):
                    break
            self._expect_op(")")
        self._expect_kw("with")
        self._expect_op("(")
        options = {}
        while True:
            key = self._ident()
            while self._op("."):
                key += "." + self._ident()
            self._expect_op("=")
            kind, text = self._peek()
            if kind == "string":
                options[key] = self._string()
            elif kind == "number":
                options[key] = self._next()[1]
            else:
                raise ParseError(f"bad WITH value {text!r}")
            if not self._op(","):
                break
        self._expect_op(")")
        return ast.CreateSource(name, options, columns=columns)

    # -- SELECT ----------------------------------------------------------
    def _select(self) -> ast.Select:
        self._expect_kw("select")
        projections = [self._projection()]
        while self._op(","):
            projections.append(self._projection())
        from_item = None
        joins: List[ast.Join] = []
        if self._kw("from"):
            from_item = self._from_item()
            while True:
                kind = None
                if self._kw("join") or self._kw("inner", "join"):
                    kind = "inner"
                else:
                    for k in ("left", "right", "full"):
                        if self._kw(k, "outer", "join") \
                                or self._kw(k, "join"):
                            kind = k
                            break
                if kind is None:
                    break
                item = self._from_item()
                temporal = False
                if self._kw("for"):
                    # FOR SYSTEM_TIME AS OF PROCTIME()
                    if self._ident().lower() != "system_time":
                        raise ParseError(
                            "expected SYSTEM_TIME after FOR")
                    self._expect_kw("as")
                    if self._ident().lower() != "of":
                        raise ParseError("expected OF after AS")
                    if self._ident().lower() != "proctime":
                        raise ParseError(
                            "only AS OF PROCTIME() is supported")
                    self._expect_op("(")
                    self._expect_op(")")
                    temporal = True
                self._expect_kw("on")
                joins.append(ast.Join(item, self._expr(), kind,
                                      temporal=temporal))
        where = self._expr() if self._kw("where") else None
        group_by: List[ast.Expr] = []
        if self._kw("group", "by"):
            group_by.append(self._expr())
            while self._op(","):
                group_by.append(self._expr())
        having = self._expr() if self._kw("having") else None
        order_by: List[Tuple[ast.Expr, bool]] = []
        if self._kw("order", "by"):
            while True:
                e = self._expr()
                desc = bool(self._kw("desc"))
                if not desc:
                    self._kw("asc")
                order_by.append((e, desc))
                if not self._op(","):
                    break
        limit = offset = None
        if self._kw("limit"):
            limit = int(self._next()[1])
        if self._kw("offset"):
            offset = int(self._next()[1])
        return ast.Select(projections, from_item, joins, where, group_by,
                          order_by, limit, offset, having=having)

    def _projection(self) -> Tuple[ast.Expr, Optional[str]]:
        if self._op("*"):
            return (ast.ColRef("*"), None)
        e = self._expr()
        alias = None
        if self._kw("as"):
            alias = self._ident()
        elif self._peek()[0] == "ident":
            alias = self._ident()
        return (e, alias)

    def _from_item(self):
        if self._peek() == ("op", "(") and \
                self._peek(1) == ("kw", "select"):
            # derived table: FROM (SELECT ...) alias
            self._expect_op("(")
            sel = self._select()
            self._expect_op(")")
            if self._kw("as"):
                alias = self._ident()
            elif self._peek()[0] == "ident":
                alias = self._ident()
            else:
                raise ParseError(
                    "subquery in FROM must have an alias")
            return ast.Subquery(sel, alias)
        if self._kw("tumble"):
            self._expect_op("(")
            table = ast.TableRef(self._ident())
            self._expect_op(",")
            time_col = self._ident()
            self._expect_op(",")
            iv = self._expr()
            if not isinstance(iv, ast.IntervalLit):
                raise ParseError("TUMBLE needs an INTERVAL literal")
            self._expect_op(")")
            alias = self._ident() if self._kw("as") else None
            return ast.Tumble(table, time_col, iv.usecs, alias)
        if self._kw("hop"):
            self._expect_op("(")
            table = ast.TableRef(self._ident())
            self._expect_op(",")
            time_col = self._ident()
            self._expect_op(",")
            slide = self._expr()
            self._expect_op(",")
            size = self._expr()
            if not (isinstance(slide, ast.IntervalLit)
                    and isinstance(size, ast.IntervalLit)):
                raise ParseError("HOP needs two INTERVAL literals")
            self._expect_op(")")
            alias = self._ident() if self._kw("as") else None
            return ast.Hop(table, time_col, slide.usecs, size.usecs,
                           alias)
        name = self._ident()
        if self._op("("):
            # FROM-clause table function: generate_series(a, b [, s])
            args = []
            if not self._op(")"):
                args.append(self._expr())
                while self._op(","):
                    args.append(self._expr())
                self._expect_op(")")
            fn_alias = self._ident() if self._kw("as") else None
            return ast.TableFn(name.lower(), args, fn_alias)
        alias = None
        if self._kw("as"):
            alias = self._ident()
        elif self._peek()[0] == "ident":
            alias = self._ident()
        return ast.TableRef(name, alias)

    # -- expressions (precedence climbing) -------------------------------
    def _expr(self) -> ast.Expr:
        return self._or_expr()

    def _or_expr(self) -> ast.Expr:
        e = self._and_expr()
        while self._kw("or"):
            e = ast.Bin("or", e, self._and_expr())
        return e

    def _and_expr(self) -> ast.Expr:
        e = self._not_expr()
        while self._kw("and"):
            e = ast.Bin("and", e, self._not_expr())
        return e

    def _not_expr(self) -> ast.Expr:
        if self._kw("not"):
            return ast.Un("not", self._not_expr())
        return self._cmp_expr()

    _CMP = {"=", "<>", "!=", "<", "<=", ">", ">="}

    def _cmp_expr(self) -> ast.Expr:
        e = self._add_expr()
        if self._kw("between"):
            # e BETWEEN lo AND hi ⇒ e >= lo AND e <= hi
            lo = self._add_expr()
            self._expect_kw("and")
            hi = self._add_expr()
            return ast.Bin("and", ast.Bin(">=", e, lo),
                           ast.Bin("<=", e, hi))
        kind, text = self._peek()
        if kind == "op" and text in self._CMP:
            self.i += 1
            op = "<>" if text == "!=" else text
            return ast.Bin(op, e, self._add_expr())
        return e

    def _add_expr(self) -> ast.Expr:
        e = self._mul_expr()
        while True:
            if self._op("+"):
                e = ast.Bin("+", e, self._mul_expr())
            elif self._op("-"):
                e = ast.Bin("-", e, self._mul_expr())
            elif self._op("||"):
                e = ast.Bin("||", e, self._mul_expr())
            else:
                return e

    def _mul_expr(self) -> ast.Expr:
        e = self._unary_expr()
        while True:
            if self._op("*"):
                e = ast.Bin("*", e, self._unary_expr())
            elif self._op("/"):
                e = ast.Bin("/", e, self._unary_expr())
            elif self._op("%"):
                e = ast.Bin("%", e, self._unary_expr())
            else:
                return e

    def _unary_expr(self) -> ast.Expr:
        if self._op("-"):
            return ast.Un("neg", self._unary_expr())
        return self._primary()

    def _primary(self) -> ast.Expr:
        kind, text = self._peek()
        if kind == "number":
            self.i += 1
            return ast.Lit(text, "number")
        if kind == "string":
            return ast.Lit(self._string(), "string")
        if self._kw("null"):
            return ast.Lit(None, "null")
        if self._kw("true"):
            return ast.Lit(True, "bool")
        if self._kw("false"):
            return ast.Lit(False, "bool")
        if self._kw("interval"):
            text = self._string()
            n = int(text.strip())
            unit = self._next()[1].lower()
            if unit not in _INTERVAL_UNITS:
                raise ParseError(f"bad interval unit {unit!r}")
            return ast.IntervalLit(n * _INTERVAL_UNITS[unit])
        if self._kw("case"):
            return self._case()
        if self._kw("cast"):
            self._expect_op("(")
            e = self._expr()
            self._expect_kw("as")
            words = [self._next()[1].lower()]
            # multi-word type names (timestamp with time zone, etc.)
            while self._peek()[0] in ("ident", "kw") and \
                    self._peek()[1].lower() in ("with", "time", "zone",
                                                "precision", "varying"):
                words.append(self._next()[1].lower())
            self._expect_op(")")
            return ast.CastExpr(e, " ".join(words))
        if self._op("("):
            e = self._expr()
            self._expect_op(")")
            return e
        if kind == "kw" and text in RESERVED:
            raise ParseError(f"unexpected keyword {text!r}")
        if kind in ("ident", "kw"):
            name = self._ident()
            if self._op("("):           # function call
                if self._op("*"):
                    self._expect_op(")")
                    call = ast.Call(name.lower(), [], star=True)
                else:
                    distinct = self._kw("distinct")
                    args = []
                    if not self._op(")"):
                        args.append(self._expr())
                        while self._op(","):
                            args.append(self._expr())
                        self._expect_op(")")
                    call = ast.Call(name.lower(), args,
                                    distinct=distinct)
                nk, nt = self._peek()
                if nk in ("ident", "kw") and nt.lower() == "filter":
                    self._next()
                    self._expect_op("(")
                    self._expect_kw("where")
                    call.filter_where = self._expr()
                    self._expect_op(")")
                if self._kw("over"):
                    return self._over(call)
                return call
            if self._op("."):
                col = self._ident()
                return ast.ColRef(col, table=name)
            return ast.ColRef(name)
        raise ParseError(f"unexpected token {text!r}")

    def _over(self, call: ast.Call) -> ast.Expr:
        """OVER ( [PARTITION BY e, ...] [ORDER BY e [ASC|DESC], ...] )
        — explicit frame clauses are not supported yet."""
        self._expect_op("(")
        partition: list = []
        order: list = []
        if self._kw("partition"):
            self._expect_kw("by")
            partition.append(self._expr())
            while self._op(","):
                partition.append(self._expr())
        if self._kw("order"):
            self._expect_kw("by")
            while True:
                e = self._expr()
                desc = False
                if self._kw("desc"):
                    desc = True
                else:
                    self._kw("asc")
                order.append((e, desc))
                if not self._op(","):
                    break
        self._expect_op(")")
        return ast.Over(call, partition, order)

    def _case(self) -> ast.Expr:
        whens = []
        while self._kw("when"):
            cond = self._expr()
            self._expect_kw("then")
            whens.append((cond, self._expr()))
        else_ = self._expr() if self._kw("else") else ast.Lit(None, "null")
        self._expect_kw("end")
        # represented as nested Call for binder simplicity
        return ast.Call("case", [c for w in whens for c in w] + [else_])


def parse(sql: str):
    return Parser(sql).parse()


def parse_many(sql: str) -> list:
    """Split on top-level ';' → [(statement text, parsed stmt)].

    The text rides along so callers (the session's DDL log) can persist
    exactly what was executed.
    """
    out = []
    for part in _split_statements(sql):
        if part.strip():
            out.append((part.strip(), parse(part)))
    return out


def _split_statements(sql: str) -> List[str]:
    parts, cur, in_str = [], [], False
    i = 0
    while i < len(sql):
        c = sql[i]
        if in_str:
            cur.append(c)
            if c == "'":
                in_str = False
        elif c == "'":
            in_str = True
            cur.append(c)
        elif c == ";":
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    parts.append("".join(cur))
    return parts
