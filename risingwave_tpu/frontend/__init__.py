"""SQL frontend: parse → bind → plan → deploy/execute.

Reference parity: src/sqlparser/ (hand-written recursive-descent parser
with streaming extensions like TUMBLE), src/frontend/src/{binder,
planner,optimizer,handler}/ and the pgwire session loop
(src/utils/pgwire/src/pg_server.rs:53). Scaled to the supported
surface: CREATE SOURCE / CREATE MATERIALIZED VIEW (deployed as live
streaming pipelines), batch SELECT over committed MV snapshots,
DROP / SHOW, one process, one session.
"""

from risingwave_tpu.frontend.session import Frontend

__all__ = ["Frontend"]
